"""Capture an XLA op-level profile of one training microbatch and
print the top ops by self time. Ad hoc: python scripts/trace_step.py
"""

import collections
import glob
import sys

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from scripts.profile_mfu import _model_and_batch, _sync
from paddlefleetx_tpu.models.gpt.model import chunked_lm_loss

cfg, model, params, ids, labels, mask = _model_and_batch()


def loss_fn(p, ids, labels, mask):
    return chunked_lm_loss(model, p, ids, labels, mask,
                           chunks=cfg.loss_chunks, deterministic=True)


step = jax.jit(jax.value_and_grad(loss_fn))
out = step(params, ids, labels, mask)
_sync(out)

logdir = "/tmp/pfx_trace"
with jax.profiler.trace(logdir):
    for _ in range(3):
        out = step(params, ids, labels, mask)
    _sync(out)

path = sorted(glob.glob(logdir + "/**/*.xplane.pb", recursive=True))[-1]
pd = jax.profiler.ProfileData.from_file(path)
events = collections.Counter()
for plane in pd.planes:
    if "TPU" not in plane.name and "tpu" not in plane.name.lower():
        continue
    for line in plane.lines:
        for ev in line.events:
            dur = ev.duration_ns
            name = ev.name
            events[name] += dur

total = sum(events.values())
print(f"plane total: {total/1e6:.2f} ms over 3 steps")
for name, dur in events.most_common(40):
    print(f"{dur/3/1e6:9.3f} ms  {100*dur/total:5.1f}%  {name[:110]}")
