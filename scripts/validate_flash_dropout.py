#!/usr/bin/env python
"""Chip certification for in-kernel flash-attention dropout + the
bf16-exp lever (perf playbook levers #2/#3). MUST run on a real TPU:
``pltpu.prng_seed`` has no CPU interpret lowering, so this path cannot
even compile offline.

Checks, strongest last:
1. rate=0 equivalence: the dropout custom_vjp with rate 0 bit-matches
   the plain kernel (plumbing sanity).
2. determinism: same rng -> identical output; different rng ->
   different output.
3. expectation: averaging dropout outputs over many keys approaches
   the no-dropout output (dropout is identity in expectation), and
   the zero-fraction of the probability mass matches the rate.
4. gradient consistency: finite-difference vs jax.grad THROUGH the
   kernel at fixed seed — if the backward regenerated different masks
   than the forward, this fails loudly.
5. bf16-exp: with PFX_FLASH_BF16_EXP=1 the forward stays within bf16
   tolerance of the fp32-exp forward.

Exit 0 = certified — the script writes the certification artifact
(``ops/pallas/dropout_cert.json``) whose presence flips
``_kernel_dropout_enabled``'s default on (commit it as evidence);
nonzero = the gate stays closed.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    """On-chip statistical validation of in-kernel flash dropout
    (keep-rate and scaling against the XLA path)."""
    if jax.devices()[0].platform != "tpu":
        print("SKIP: needs a real TPU")
        return 2
    from paddlefleetx_tpu.ops.pallas.flash_attention import (
        flash_attention,
    )

    from paddlefleetx_tpu.ops.pallas.flash_attention import (
        _flash_lse_dropout, _to_bh, check_shapes,
    )

    b, s, h, d = 2, 512, 4, 64
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((b, s, h, d)),
                           jnp.float32) for _ in range(3))
    base = flash_attention(q, k, v, causal=True)

    # 1. rate-0 plumbing equivalence THROUGH the dropout custom_vjp:
    # same kernels, seed ignored — must bit-match the plain kernel
    bq, bkv = check_shapes(s, s, d)
    out0, _ = _flash_lse_dropout(
        _to_bh(q), _to_bh(k), _to_bh(v),
        jnp.zeros((1,), jnp.int32), d ** -0.5, True, bq, bkv, 0.0)
    np.testing.assert_array_equal(
        np.asarray(out0.reshape(b, h, s, d).transpose(0, 2, 1, 3)),
        np.asarray(base))
    print("rate-0 plumbing equivalence OK")

    key = jax.random.key(7)
    out_drop = flash_attention(q, k, v, causal=True, dropout_rate=0.1,
                               dropout_rng=key)
    assert out_drop.shape == base.shape
    assert bool(jnp.isfinite(out_drop).all()), "non-finite dropout out"

    # 1b. dropped-mass fraction: with v = ones, each no-dropout output
    # entry is exactly 1 (softmax rows sum to 1); with dropout the
    # kept-mass fraction is out*(1-rate), whose mean must equal
    # 1-rate -> mean(1 - out*(1-rate)) == rate up to MC noise
    rate = 0.3
    ones_v = jnp.ones_like(v)
    fracs = []
    for i in range(16):
        o = flash_attention(q, k, ones_v, causal=True,
                            dropout_rate=rate,
                            dropout_rng=jax.random.key(500 + i))
        fracs.append(1.0 - float(jnp.mean(o)) * (1.0 - rate))
    measured = float(np.mean(fracs))
    print(f"dropped-mass fraction {measured:.4f} (target {rate})")
    assert abs(measured - rate) < 0.02, measured

    # 2. determinism
    out_drop2 = flash_attention(q, k, v, causal=True, dropout_rate=0.1,
                                dropout_rng=key)
    np.testing.assert_array_equal(np.asarray(out_drop),
                                  np.asarray(out_drop2))
    out_other = flash_attention(q, k, v, causal=True, dropout_rate=0.1,
                                dropout_rng=jax.random.key(8))
    assert not np.array_equal(np.asarray(out_drop),
                              np.asarray(out_other)), \
        "different rngs produced identical dropout"
    print("determinism OK")

    # 3. expectation: mean over N independent masks -> no-dropout out
    N = 64
    acc = np.zeros(base.shape, np.float64)
    for i in range(N):
        acc += np.asarray(flash_attention(
            q, k, v, causal=True, dropout_rate=0.3,
            dropout_rng=jax.random.key(100 + i)), np.float64)
    mean = acc / N
    # row magnitudes vary; compare normalized error over all entries
    err = np.abs(mean - np.asarray(base, np.float64)).mean() / \
        (np.abs(np.asarray(base, np.float64)).mean() + 1e-9)
    print(f"expectation: mean rel err {err:.4f} over {N} masks")
    assert err < 0.08, err  # ~1/sqrt(N*keep-ish) Monte-Carlo noise

    # 4. gradient consistency (fwd/bwd mask identity) by central
    # finite differences on a scalar loss, small shape
    bs, ss, hs, ds = 1, 256, 2, 64
    q2, k2, v2 = (jnp.asarray(rng.standard_normal((bs, ss, hs, ds)),
                              jnp.float32) for _ in range(3))
    key2 = jax.random.key(42)
    co = jnp.asarray(rng.standard_normal(
        (bs, ss, hs, ds)), jnp.float32)  # fixed cotangent direction

    def loss(qq):
        out = flash_attention(qq, k2, v2, causal=True,
                              dropout_rate=0.2, dropout_rng=key2)
        return jnp.vdot(out, co)

    g = jax.grad(loss)(q2)
    # probe a handful of coordinates
    eps = 1e-2
    idxs = [(0, 3, 0, 5), (0, 100, 1, 10), (0, 255, 0, 63),
            (0, 17, 1, 31)]
    for idx in idxs:
        e = jnp.zeros_like(q2).at[idx].set(eps)
        fd = (loss(q2 + e) - loss(q2 - e)) / (2 * eps)
        an = g[idx]
        denom = max(abs(float(fd)), abs(float(an)), 1e-3)
        rel = abs(float(fd) - float(an)) / denom
        print(f"grad check {idx}: fd {float(fd):+.5f} "
              f"analytic {float(an):+.5f} rel {rel:.4f}")
        assert rel < 0.05, (idx, float(fd), float(an))
    print("gradient consistency OK")

    # 5. bf16-exp tolerance (forward only; flag read at trace time)
    os.environ["PFX_FLASH_BF16_EXP"] = "1"
    try:
        out_bf16 = jax.jit(lambda a, b_, c: flash_attention(
            a, b_, c, causal=True))(q, k, v)
    finally:
        del os.environ["PFX_FLASH_BF16_EXP"]
    rel = float(jnp.abs(out_bf16 - base).max() /
                (jnp.abs(base).max() + 1e-9))
    print(f"bf16-exp max rel dev {rel:.5f}")
    assert rel < 0.02, rel  # bf16 mantissa ~2^-8

    print("ALL CHECKS PASSED — in-kernel dropout certified")
    # write the certification artifact: its presence flips
    # _kernel_dropout_enabled's default on (self-certifying gate;
    # commit it as evidence). PFX_FLASH_DROPOUT=0 still force-disables.
    import datetime
    import json

    from paddlefleetx_tpu.ops.attention import DROPOUT_CERT_PATH
    d = jax.devices()[0]
    # atomic: a kill mid-write must not leave a truncated file that
    # still flips the gate (the gate reads and validates the JSON,
    # but a half-written valid prefix is cheap to rule out entirely)
    tmp = DROPOUT_CERT_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump({
            "device_kind": d.device_kind,
            "ts": datetime.datetime.now(
                datetime.timezone.utc).isoformat(timespec="seconds"),
            "checks": ["rate0_bitmatch", "determinism",
                       "expectation", "zero_fraction",
                       "grad_finite_difference", "bf16_exp_tolerance"],
            "grad_rel_tol": 0.05,
            "bf16_exp_rel_tol": 0.02,
        }, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, DROPOUT_CERT_PATH)
    print(f"certification artifact written: {DROPOUT_CERT_PATH}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
