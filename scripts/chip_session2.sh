#!/bin/sh
# Follow-up chip-session: the stages the first r5 session failed, after
# their fixes — dropout cert (seed-fold for Mosaic's 2-operand
# prng_seed limit), convergence oracle (init check at step 1), the
# near-capacity secondaries in fresh processes, and the tune sweep
# with data-dependency-chained timing. Safe to re-run.
cd "$(dirname "$0")/.." || exit 1
mkdir -p bench_log
log() { echo "[$(date -u +%FT%TZ)] $*" >> bench_log/session2.log; }

log "session2 start"
export PFX_BENCH_MAX_WAIT=600

log "stage: dropout certification (fixed seed fold)"
timeout -k 60 1200 python scripts/validate_flash_dropout.py \
    >> bench_log/dropout_cert2.log 2>&1
log "cert rc=$?"

log "stage: convergence (init check at step 1)"
timeout -k 60 1200 python bench.py --mode convergence \
    >> bench_log/bench_convergence2.log 2>&1
log "convergence rc=$?"

log "stage: 67b fresh-process"
timeout -k 60 2400 python bench.py --mode 67b \
    >> bench_log/bench_67b.log 2>&1
log "67b rc=$?"

log "stage: longctx fresh-process"
timeout -k 60 1800 python bench.py --mode longctx \
    >> bench_log/bench_longctx.log 2>&1
log "longctx rc=$?"

# lever #3 A/B: headline with bf16 exp in the online softmax — compare
# against the warm headline in bench_train.log; flip _bf16_exp's
# default only on a measured win (cert already bounds the numerics)
log "stage: bench train bf16-exp probe (headline only)"
PFX_FLASH_BF16_EXP=1 PFX_BENCH_SKIP_SECONDARIES=1 \
    timeout -k 60 1500 python bench.py \
    >> bench_log/bench_bf16exp.log 2>&1
log "bf16exp rc=$?"

log "stage: tune_flash (chained timing)"
timeout -k 60 1500 python scripts/tune_flash.py \
    >> bench_log/tune_flash2.log 2>&1
log "tune rc=$?"

# the stages session 1 lost to the tunnel outage
log "stage: moe"
timeout -k 60 1500 python bench.py --mode moe \
    >> bench_log/bench_moe.log 2>&1
log "moe rc=$?"

log "stage: generation"
timeout -k 60 1200 python bench.py --mode generation \
    >> bench_log/bench_generation.log 2>&1
log "generation rc=$?"

# one COMPLETE headline record (train + fresh-process secondaries)
log "stage: bench train complete"
timeout -k 60 3600 python bench.py \
    >> bench_log/bench_train.log 2>&1
log "bench train complete rc=$?"

# refresh the non-GPT family numbers if the window is still open
for fam in vit imagen ernie; do
    log "stage: family smoke $fam"
    timeout -k 60 900 python scripts/smoke_family_tpu.py "$fam" \
        >> "bench_log/family_$fam.log" 2>&1
    log "family $fam rc=$?"
done

log "session2 end"
