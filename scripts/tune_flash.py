"""One-shot flash-attention tuning sweep for the real chip.

Times the Pallas kernel at the bench operating points across block
sizes, against dense XLA attention, fwd and fwd+bwd — one run prints
the whole decision table, so a returning/scarce TPU allocation yields
the full tuning picture in a single session (VERDICT r3 #4: the d=64
exp path is the named single-chip MFU floor).

Usage (TPU): ``python scripts/tune_flash.py [--points 345m,longctx,67b]``
"""

import argparse
import functools
import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

POINTS = {
    # (batch, heads, seq, head_dim) per microbatch at the bench points
    "345m": (8, 16, 1024, 64),
    "longctx": (1, 16, 8192, 64),
    "67b": (2, 32, 2048, 128),
}


def _time(fn, q, k, v, reps=20):
    """Median-of-3 per-iteration ms, with reps CHAINED through a data
    dependency inside one jitted scan.

    Independent back-to-back dispatches under-measure badly here (the
    r5 chip session recorded 0.018 ms "forwards" at s=8192 — 40x the
    chip's peak FLOPs — because nothing forces iteration i to wait for
    i-1). Feeding a tiny function of output i into input i+1 makes the
    chain sequential on device; 1e-30*out is numerically negligible
    but cannot be dead-code-eliminated."""
    def body(qq, _):
        out = fn(qq, k, v)
        lead = out[0] if isinstance(out, tuple) else out
        bump = (1e-30 * lead.ravel()[0]).astype(qq.dtype)
        return qq + bump, None

    @jax.jit
    def run(q):
        final, _ = jax.lax.scan(body, q, None, length=reps)
        return final

    jax.block_until_ready(run(q))  # compile + warm
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(run(q))
        times.append((time.perf_counter() - t0) / reps * 1e3)
    return sorted(times)[1]


def sweep(point: str, b: int, h: int, s: int, d: int):
    """Print ms for kernel block-size variants + dense, fwd and
    value_and_grad, at one operating point."""
    from paddlefleetx_tpu.ops.attention import _xla_attention
    from paddlefleetx_tpu.ops.pallas import flash_attention as fa

    rng = np.random.default_rng(0)
    shape = (b, s, h, d)
    q = jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=shape), jnp.bfloat16)

    def flash_loss(q, k, v, bq, bkv):
        o = fa.flash_attention(q, k, v, causal=True, block_q=bq,
                               block_kv=bkv)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    def dense_loss(q, k, v):
        o = _xla_attention(q, k, v, None, True, 0, 0.0, None, True,
                           True, kv_cache_layout=False)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    try:
        from bench import causal_attn_flops, peak_flops
        floor_ms = causal_attn_flops(b, h, s, d) / peak_flops() * 1e3
    except Exception as e:
        floor_ms = None
        floor_err = f"{type(e).__name__}: {e}"
    print(f"== {point}: b={b} h={h} s={s} d={d} (bf16) ==")
    if floor_ms is not None:
        # self-check: any fwd below this is a measurement artifact
        # (the r5 session's unchained timing read 40x past peak)
        print(f"  roofline floor     : fwd {floor_ms:7.3f} ms "
              f"(peak-bound; trust nothing faster)")
    else:
        print(f"  roofline floor unavailable ({floor_err[:80]}) — "
              f"timings below are UNCHECKED against peak")
    blocks = sorted({min(512, s), min(1024, s), min(2048, s)})
    for bq in blocks:
        for bkv in blocks:
            if s % bq or s % bkv:
                continue
            try:
                # close over the config instead of jit(partial(...)):
                # the jit boundary then carries exactly q/k/v and no
                # unbound kernel param can ever arrive as a tracer
                fwd = _time(jax.jit(lambda q, k, v: fa.flash_attention(
                    q, k, v, causal=True, block_q=bq,
                    block_kv=bkv)), q, k, v)
                vag = _time(jax.jit(jax.grad(functools.partial(
                    flash_loss, bq=bq, bkv=bkv), argnums=(0, 1, 2))),
                    q, k, v)
                print(f"  flash bq={bq:5d} bkv={bkv:5d}: "
                      f"fwd {fwd:7.3f} ms   fwd+bwd {vag:7.3f} ms")
            except Exception as e:
                print(f"  flash bq={bq:5d} bkv={bkv:5d}: FAILED "
                      f"({type(e).__name__}: {str(e)[:80]})")
    try:
        fwd = _time(jax.jit(lambda q, k, v: _xla_attention(
            q, k, v, None, True, 0, 0.0, None, True, True,
            kv_cache_layout=False)), q, k, v)
        vag = _time(jax.jit(jax.grad(dense_loss, argnums=(0, 1, 2))),
                    q, k, v)
        print(f"  dense XLA          : fwd {fwd:7.3f} ms   "
              f"fwd+bwd {vag:7.3f} ms")
    except Exception as e:
        print(f"  dense XLA          : FAILED ({str(e)[:80]})")


def main():
    """Run the sweep at the selected operating points."""
    p = argparse.ArgumentParser()
    p.add_argument("--points", default="345m,longctx,67b")
    args = p.parse_args()
    d = jax.devices()[0]
    print(f"device: {d.platform} {d.device_kind}")
    for point in args.points.split(","):
        b, h, s, hd = POINTS[point.strip()]
        sweep(point.strip(), b, h, s, hd)


if __name__ == "__main__":
    main()
