"""MFU tuning harness: per-component timings at the bench operating
point (345M, b=8, s=1024) on the real chip.

Not part of the test suite — run ad hoc: python scripts/profile_mfu.py
[component ...].  Components: attn, ce, gemm, micro, opt, e2e.
"""

import functools
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np
import optax

from paddlefleetx_tpu.models.gpt import GPTConfig, GPTForPretraining
from paddlefleetx_tpu.models.gpt.model import chunked_lm_loss
from paddlefleetx_tpu.observability.flops import (
    causal_attn_flops, model_flops_per_token, peak_flops,
)
from paddlefleetx_tpu.ops.pallas.flash_attention import flash_attention

PEAK = peak_flops() or 197e12

B, S, H, L, NH, D, V, FFN = 8, 1024, 1024, 24, 16, 64, 50304, 4096


def _sync(out):
    # block_until_ready is unreliable on tunneled backends; fetching a
    # value forces the device queue (in-order execution) to drain.
    # Slice device-side first: transferring a whole array over the
    # tunnel costs ~ms/MB and poisons the measurement.
    leaf = jax.tree.leaves(out)[0]
    float(jnp.ravel(leaf)[0].astype(jnp.float32))


def timeit(fn, *args, n=20, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / n


def report(name, dt, flops):
    print(f"{name:<40s} {dt*1e3:8.3f} ms  {flops/dt/1e12:7.2f} TF/s "
          f"({flops/dt/PEAK*100:5.1f}% of peak)")


REPEAT = 30


def repeat_jit(fn):
    """Chain REPEAT dependent applications inside one jit so a single
    dispatch (tunnel RTT ~50ms) covers REPEAT device executions. fn
    must map its first arg to a same-shaped output."""
    @jax.jit
    def many(x, *rest):
        def body(x, _):
            return fn(x, *rest), None
        return jax.lax.scan(body, x, None, length=REPEAT)[0]
    return many


def timeit_rep(fn, x, *rest, n=3):
    many = repeat_jit(fn)
    out = many(x, *rest)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = many(x, *rest)
    _sync(out)
    return (time.perf_counter() - t0) / (n * REPEAT)


def bench_attn():
    """Sweep flash-attention block sizes and report TFLOP/s."""
    rng = np.random.default_rng(0)
    shape = (B, S, NH, D)
    q = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    fwd_flops = causal_attn_flops(B, NH, S, D)
    for bq, bkv in [(256, 256), (256, 512), (512, 512), (512, 1024),
                    (1024, 512), (1024, 1024), (512, 256)]:
        if bq > S or bkv > S:
            continue
        f = functools.partial(flash_attention, causal=True,
                              block_q=bq, block_kv=bkv)
        dt = timeit_rep(lambda q, k, v: f(q, k, v), q, k, v)
        report(f"attn fwd bq={bq} bkv={bkv}", dt, fwd_flops)

        def gstep(q, k, v, f=f):
            g = jax.grad(
                lambda q: jnp.sum(f(q, k, v).astype(jnp.float32)))(q)
            return g.astype(q.dtype)
        dt = timeit_rep(gstep, q, k, v)
        report(f"attn fwd+bwd(dq-chain) bq={bq} bkv={bkv}", dt,
               3.5 * fwd_flops)


def bench_ce():
    """Time the chunked cross-entropy head at several chunk counts."""
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.standard_normal((B, S, H)), jnp.bfloat16)
    emb = jnp.asarray(rng.standard_normal((V, H)) * 0.02, jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)
    mask = jnp.ones((B, S), jnp.float32)
    fwd_flops = 2 * B * S * H * V

    from paddlefleetx_tpu.models.gpt.model import (
        masked_nll_sums, tied_logits,
    )

    for chunks in [1, 4, 8, 16]:
        csz = S // chunks

        def ce(h, emb, labels, mask, chunks=chunks, csz=csz):
            """Chunk-scanned masked NLL over the tied LM head."""
            hc = h.reshape(B, chunks, csz, H).swapaxes(0, 1)
            lc = labels.reshape(B, chunks, csz).swapaxes(0, 1)
            mc = mask.reshape(B, chunks, csz).swapaxes(0, 1)

            @jax.checkpoint
            def body(carry, xs):
                hh, ll, mm = xs
                nll, ms = masked_nll_sums(tied_logits(hh, emb), ll, mm)
                return (carry[0] + nll, carry[1] + ms), None

            (nll, ms), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32),
                       jnp.zeros((), jnp.float32)), (hc, lc, mc))
            return nll / ms

        g = jax.jit(jax.grad(ce, argnums=(0, 1)))
        dt = timeit(g, h, emb, labels, mask)
        # fwd + recompute + 2 bwd matmuls = 4x fwd matmul flops
        report(f"CE fwd+bwd chunks={chunks}", dt, 4 * fwd_flops)


def bench_gemm():
    """Mimic of one layer's linear stack, fwd+bwd, x24."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((B * S, H)), jnp.bfloat16)
    wqkv = jnp.asarray(rng.standard_normal((H, 3 * H)) * .02, jnp.bfloat16)
    wo = jnp.asarray(rng.standard_normal((H, H)) * .02, jnp.bfloat16)
    w1 = jnp.asarray(rng.standard_normal((H, FFN)) * .02, jnp.bfloat16)
    w2 = jnp.asarray(rng.standard_normal((FFN, H)) * .02, jnp.bfloat16)

    def layer_stack(x, wqkv, wo, w1, w2):
        def body(x, _):
            a = x @ wqkv
            x = x + a[:, :H] @ wo
            x = x + jax.nn.gelu(x @ w1, approximate=True) @ w2
            return x, None
        x, _ = jax.lax.scan(body, x, None, length=L)
        return jnp.sum(x.astype(jnp.float32))

    g = jax.jit(jax.grad(layer_stack, argnums=(0, 1, 2, 3, 4)))
    flops = 3 * L * 2 * B * S * H * (3 * H + H + FFN + FFN)
    dt = timeit(g, x, wqkv, wo, w1, w2)
    report("24-layer linear mimic fwd+bwd", dt, flops)


def _model_and_batch(**kw):
    cfg = GPTConfig(
        vocab_size=V, hidden_size=H, num_layers=L,
        num_attention_heads=NH, ffn_hidden_size=FFN,
        max_position_embeddings=S, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, dtype="bfloat16",
        use_flash_attention=True, use_recompute=True,
        recompute_granularity="save_dots", loss_chunks=8, **kw)
    model = GPTForPretraining(cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    mask = jnp.ones((B, S), jnp.float32)
    params = jax.jit(model.init)({"params": jax.random.key(0)},
                                 ids[:1])["params"]
    return cfg, model, params, ids, labels, mask


def bench_micro():
    """Time one microbatch fwd and fwd+bwd against the MFU formula."""
    cfg, model, params, ids, labels, mask = _model_and_batch()

    def loss_fn(p, ids, labels, mask):
        return chunked_lm_loss(model, p, ids, labels, mask,
                               chunks=cfg.loss_chunks,
                               deterministic=True)

    fwd = jax.jit(loss_fn)
    dt = timeit(fwd, params, ids, labels, mask)
    tok = B * S
    # fwd-only = one third of the Megatron fwd+bwd count; derive it
    # from the shared formula rather than keeping a second copy
    fpt_fwd = model_flops_per_token(L, H, V, S) / 3.0
    report("microbatch fwd", dt, fpt_fwd * tok)

    g = jax.jit(jax.value_and_grad(loss_fn))
    dt = timeit(g, params, ids, labels, mask)
    report("microbatch fwd+bwd", dt, 3 * fpt_fwd * tok)


def bench_opt():
    """Time the optimizer update in isolation."""
    cfg, model, params, *_ = _model_and_batch()
    tx = optax.chain(optax.clip_by_global_norm(1.0),
                     optax.adamw(2e-4, weight_decay=0.01,
                                 mu_dtype=jnp.bfloat16))
    opt_state = tx.init(params)
    grads = jax.tree.map(lambda p: jnp.ones(p.shape, jnp.float32),
                         params)

    @jax.jit
    def upd(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    dt = timeit(lambda: upd(params, opt_state, grads), n=10)
    print(f"optimizer update: {dt*1e3:.3f} ms")


def main():
    which = set(sys.argv[1:]) or {"attn", "ce", "gemm", "micro", "opt"}
    print(f"device: {jax.devices()[0].device_kind}")
    for name in ["attn", "ce", "gemm", "micro", "opt"]:
        if name in which:
            print(f"--- {name} ---")
            globals()[f"bench_{name}"]()


if __name__ == "__main__":
    main()
