"""ViT input-pipeline microbench: images/sec through the full
train transform chain (decode -> random crop -> flip -> normalize ->
CHW) at num_workers in {1, 4, 8}.

Ad hoc: python scripts/bench_loader.py. Results recorded in
projects/vit/README.md.
"""

import io
import sys
import time

sys.path.insert(0, ".")

import numpy as np

from paddlefleetx_tpu.data.loader import DataLoader
from paddlefleetx_tpu.data.transforms.preprocess import build_transforms

N_IMAGES = 512
BATCH = 32


class JpegDataset:
    """In-memory JPEG blobs -> full ViT train transform per sample."""

    def __init__(self):
        from PIL import Image
        rng = np.random.default_rng(0)
        img = Image.fromarray(
            rng.integers(0, 255, (512, 384, 3), np.uint8).astype(np.uint8))
        buf = io.BytesIO()
        img.save(buf, format="JPEG", quality=90)
        self.blob = buf.getvalue()
        self.transform = build_transforms([
            {"DecodeImage": {"to_rgb": True, "channel_first": False}},
            {"RandCropImage": {"size": 224, "interpolation": "bilinear"}},
            {"RandFlipImage": {"flip_code": 1}},
            {"NormalizeImage": {
                "scale": 1.0 / 255.0,
                "mean": [0.485, 0.456, 0.406],
                "std": [0.229, 0.224, 0.225], "order": ""}},
            {"ToCHWImage": {}},
        ])

    def __len__(self):
        return N_IMAGES

    def __getitem__(self, i):
        return self.transform(self.blob), i % 1000


def collate(batch):
    xs, ys = zip(*batch)
    return np.stack(xs), np.asarray(ys)


def main():
    """Time the loader at several worker counts on synthetic JPEGs."""
    ds = JpegDataset()
    batches = [list(range(i, i + BATCH))
               for i in range(0, N_IMAGES, BATCH)]
    print(f"{N_IMAGES} images, batch {BATCH}, 512x384 JPEG -> 224x224")
    base = None
    for workers in (1, 4, 8):
        loader = DataLoader(ds, batches, collate_fn=collate,
                            num_workers=workers)
        n = sum(b[0].shape[0] for b in loader)  # warm pool/page cache
        t0 = time.perf_counter()
        n = sum(b[0].shape[0] for b in loader)
        dt = time.perf_counter() - t0
        ips = n / dt
        base = base or ips
        print(f"num_workers={workers}: {ips:7.1f} images/s "
              f"({ips / base:.2f}x)")


if __name__ == "__main__":
    main()
