"""End-to-end chaos drill: SIGKILL a real training run, resume, prove it.

The in-process resilience tests (tests/test_resilience.py) drill the
save -> die -> restore loop with ``PFX_FAULTS_MODE=raise``; this
script is the full-fidelity version the CI ``chaos-smoke`` job runs
(docs/robustness.md): three ``tools/train.py`` subprocesses on a tiny
CPU config with per-step telemetry —

1. **baseline** — runs to ``--steps``, recording every step's loss
   from the flight recorder's ``step_window`` events;
2. **chaos** — the same run with ``PFX_FAULTS=kill@step=K``: a real
   ``SIGKILL`` mid-training, after the checkpoint cadence has
   committed at least one manifest;
3. **resume** — the same command pointed back at the chaos output
   dir, no fault spec.

Asserted: the killed run durably recorded ``fault_injected``; resume
restores the last committed checkpoint (step continuity, no gap and
no replayed step windows); the resumed loss curve is IDENTICAL to the
baseline from the restore point on; the resumed event log contains no
``ckpt_fallback`` (the kill landed between saves, so the newest
checkpoint must verify). Exit 0 on success, 1 with a diagnosis on any
violation.

With ``--ptq`` a fourth leg runs ``scripts/quantize_checkpoint.py``
on the resumed output and drills the quantized artifact the same way
the training checkpoints are drilled: the int8 checkpoint must
verify, a single flipped byte in a payload file (the fp32
``kernel_scale`` arrays ride in the same ocdbt payload as the int8
kernels) must fail manifest verification AND drop the step dir out of
``latest_checkpoint`` (the resume fallback path), and restoring the
byte must verify again — proving the scale arrays are covered as
payload, not sidecar metadata (docs/quantization.md).

With ``--fleet`` a serving leg drills the fleet's availability story
(docs/fleet_serving.md) in-process: two paged interpret-mode
GenerationServer replicas — tiered, with a pinned-host spill pool and
the router's ``prefix_store_dir`` round-tripping each dying replica's
prefix store through disk — behind an ``async_workers=True``
FleetRouter (each replica served from its own worker thread,
docs/fleet_serving.md "Async router") serve a shared-prefix trace
while EVERY replica is rolling-restarted mid-stream under the
overlapped load. Asserted: every completion is token-identical to the
single-batch lockstep reference (zero dropped committed tokens),
nothing was shed (the peer always had capacity), at least one request
actually failed over, and events.jsonl ALONE reconstructs one trace
id per request — with two ``serving/request`` lifetimes bridged by a
``fleet/failover`` span for each failed-over stream. A second wave of
the same prompts then proves the warm restart: the restarted replicas
serve it with at least one ``serving_rehydrate``, and in the
post-restart event stream the first rehydrate precedes the first
``serving_prefill_chunk`` — host-DRAM hits beat re-prefill
(docs/inference.md "Hierarchical KV cache").

With ``--adapters`` a multi-tenant LoRA leg (docs/lora.md) rolling-
restarts a 2-replica fleet under MIXED-ADAPTER load: six requests
striped across adapter ids {1,2,3} while every replica goes down in
turn. Asserted: zero dropped tokens (every completion, first wave and
a warm second wave, token-identical to a single-server reference),
nothing shed, at least one request failed over, and the post-restart
adapter-cache re-warm reconstructs from events.jsonl ALONE — the
``serving_adapter_load`` events after the restart cover the full
adapter working set, proving the restarted replicas' cold banks
re-warmed rather than silently serving base weights. Run from the
repo root:

  python scripts/chaos_smoke.py [--workdir DIR] [--steps 12]
                                [--kill-step 7] [--save-steps 4]
                                [--ptq] [--fleet] [--adapters]
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

_CONFIG = """\
Global:
  device: cpu
  seed: 1024
  global_batch_size: null
  local_batch_size: 8
  micro_batch_size: 8
Engine:
  max_steps: {steps}
  num_train_epochs: 1
  logging_freq: 1
  eval_freq: 1000
  eval_iters: 1
  mix_precision:
    use_pure_fp16: False
  save_load:
    save_steps: {save_steps}
    output_dir: {out}
Model:
  module: GPTModule
  name: GPT
  vocab_size: 128
  hidden_size: 32
  num_layers: 2
  num_attention_heads: 4
  ffn_hidden_size: 64
  max_position_embeddings: 64
  hidden_dropout_prob: 0.0
  attention_probs_dropout_prob: 0.0
Distributed:
  dp_degree: 1
  mp_degree: 1
  pp_degree: 1
  sharding:
    sharding_degree: 1
    sharding_stage: 1
Optimizer:
  name: FusedAdamW
  weight_decay: 0.01
  beta1: 0.9
  beta2: 0.999
  epsilon: 1.0e-8
  lr:
    name: CosineAnnealingWithWarmupDecay
    decay_steps: 100
    warmup_rate: 0.1
    max_lr: 1.0e-2
    min_lr: 1.0e-3
  grad_clip:
    name: ClipGradByGlobalNorm
    clip_norm: 1.0
Data:
  Train:
    dataset:
      name: GPTDataset
      input_dir: {data}
      split: [1, 0, 0]
      max_seq_len: 32
      num_samples: 400
      mode: Train
      eos_id: 127
      build_data_file: True
    sampler:
      name: GPTBatchSampler
      batch_size: 8
      shuffle: False
      drop_last: True
    loader:
      collate_fn: gpt_collate_fn
Telemetry:
  enable: True
"""


def make_corpus(data_dir):
    """Synthetic corpus_ids.npy + corpus_idx.npz (quick_start shape)."""
    rng = np.random.default_rng(0)
    lens = rng.integers(20, 60, 80).astype(np.int32)
    ids = rng.integers(0, 128, int(lens.sum())).astype(np.int32)
    ids[np.cumsum(lens) - 1] = 127
    os.makedirs(data_dir, exist_ok=True)
    np.save(os.path.join(data_dir, "corpus_ids.npy"), ids)
    np.savez(os.path.join(data_dir, "corpus_idx.npz"), lens=lens)


def run_train(cfg_path, out_dir, faults=None, resume=False, timeout=600):
    """One tools/train.py subprocess; returns its returncode."""
    cmd = [sys.executable, os.path.join(REPO, "tools", "train.py"),
           "-c", cfg_path,
           "-o", f"Engine.save_load.output_dir={out_dir}"]
    if resume:
        cmd += ["-o", f"Engine.save_load.ckpt_dir={out_dir}"]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("PFX_FAULTS", None)
    if faults:
        env["PFX_FAULTS"] = faults
    proc = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout,
                          stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True)
    tag = "chaos" if faults else ("resume" if resume else "baseline")
    sys.stdout.write(f"--- {tag} run: rc={proc.returncode} ---\n")
    if proc.returncode not in (0, -signal.SIGKILL):
        sys.stdout.write(proc.stdout[-4000:] + "\n")
    return proc.returncode


def read_events(out_dir, skip_lines=0):
    """Parsed events.jsonl records, optionally past a line offset."""
    path = os.path.join(out_dir, "events.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        lines = f.readlines()
    out = []
    for line in lines[skip_lines:]:
        try:
            out.append(json.loads(line))
        except ValueError:
            pass  # torn tail line of a killed run
    return out


def count_lines(out_dir):
    """Line count of events.jsonl (0 when absent)."""
    path = os.path.join(out_dir, "events.jsonl")
    if not os.path.exists(path):
        return 0
    with open(path) as f:
        return sum(1 for _ in f)


def losses_by_step(events):
    """Map step -> loss from the step_window events."""
    return {e["step"]: e["loss"] for e in events
            if e.get("event") == "step_window"}


def fail(msg):
    """Print the diagnosis and exit nonzero."""
    sys.stdout.write(f"CHAOS SMOKE FAILED: {msg}\n")
    sys.exit(1)


def ptq_leg(work, chaos_out, cfg_path):
    """Quantize the resumed checkpoint and drill the int8 artifact:
    byte-flip a scale payload -> verify fails and latest_checkpoint
    falls back; restore the byte -> verifies again."""
    ptq_out = os.path.join(work, "ptq_out")
    cmd = [sys.executable,
           os.path.join(REPO, "scripts", "quantize_checkpoint.py"),
           "--checkpoint", chaos_out, "--output", ptq_out,
           "--config", cfg_path, "--max-rel-dev", "0.05"]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(cmd, cwd=REPO, env=env, timeout=600,
                          stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True)
    sys.stdout.write(f"--- ptq run: rc={proc.returncode} ---\n")
    if proc.returncode != 0:
        sys.stdout.write(proc.stdout[-4000:] + "\n")
        fail(f"quantize_checkpoint.py exited {proc.returncode}")
    if "QUANTIZE CHECKPOINT OK" not in proc.stdout:
        fail("quantize run missing its OK line")

    sys.path.insert(0, REPO)
    from paddlefleetx_tpu.core.checkpoint import (
        latest_checkpoint, verify_checkpoint,
    )
    step_dir = latest_checkpoint(ptq_out)
    if step_dir is None:
        fail(f"no verified quantized checkpoint under {ptq_out}")

    # pick a payload file holding the fp32 kernel scales if the store
    # names arrays in its paths, else the largest non-manifest payload
    payload = [os.path.join(root, name)
               for root, _, files in os.walk(step_dir)
               for name in files if name != "pfx_manifest.json"]
    if not payload:
        fail(f"quantized step dir {step_dir} holds no payload files")
    scales = [p for p in payload
              if "kernel_scale" in os.path.relpath(p, step_dir)]
    target = max(scales or payload, key=os.path.getsize)

    size = os.path.getsize(target)
    with open(target, "r+b") as f:
        f.seek(size // 2)
        orig = f.read(1)
        f.seek(size // 2)
        f.write(bytes([orig[0] ^ 0xFF]))
    rel = os.path.relpath(target, step_dir)
    reason = verify_checkpoint(step_dir)
    if reason is None:
        fail(f"flipped byte in {rel} still passed verification — "
             f"scale arrays are not covered as payload")
    if latest_checkpoint(ptq_out) == step_dir:
        fail(f"latest_checkpoint still resolves the corrupted "
             f"{step_dir} (resume would load a torn artifact)")
    with open(target, "r+b") as f:
        f.seek(size // 2)
        f.write(orig)
    if verify_checkpoint(step_dir) is not None:
        fail(f"restored byte in {rel} no longer verifies")
    sys.stdout.write(
        f"PTQ LEG OK: quantized {os.path.basename(chaos_out)} -> "
        f"{step_dir}; corrupting {rel} failed verify and fallback "
        f"skipped it; restored artifact verifies\n")


def fleet_leg(work):
    """In-process fleet drill: rolling-restart a 2-replica tiered
    ASYNC fleet mid-stream — each replica serving from its own worker
    thread, so the restart happens under genuinely overlapped load —
    and prove zero token loss + trace continuity from the event log
    alone, then a warm second wave that must rehydrate from the
    restart-persisted prefix store before it prefills anything."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("PFX_PALLAS_INTERPRET", "1")
    sys.path.insert(0, REPO)
    import jax
    import jax.numpy as jnp

    from paddlefleetx_tpu.core.fleet import FleetRouter
    from paddlefleetx_tpu.core.serving import GenerationServer
    from paddlefleetx_tpu.models.gpt import GPTConfig, GPTForPretraining
    from paddlefleetx_tpu.models.gpt.generation import (
        GenerationConfig, generate, left_pad_batch,
    )

    vocab, eos = 96, 95
    cfg = GPTConfig(vocab_size=vocab, hidden_size=32, num_layers=2,
                    num_attention_heads=4,
                    max_position_embeddings=256,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0)
    model = GPTForPretraining(cfg)
    params = model.init({"params": jax.random.key(0)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    gen_cfg = GenerationConfig(max_dec_len=8,
                               decode_strategy="greedy_search",
                               eos_token_id=eos, pad_token_id=eos)

    # the fleet workload shape: a few shared system prompts, many tails
    rng = np.random.default_rng(2)
    prefixes = [rng.integers(0, eos, 130).tolist() for _ in range(2)]
    prompts = [prefixes[i % 2] + rng.integers(0, eos, 8 + i).tolist()
               for i in range(6)]

    ids_arr, mask = left_pad_batch(prompts, eos)
    out = np.asarray(generate(model, params, jnp.asarray(ids_arr),
                              jnp.asarray(mask), jax.random.key(0),
                              gen_cfg))
    ref = []
    for row in out:
        toks = []
        for t in row:
            toks.append(int(t))
            if int(t) == eos:
                break
        ref.append(toks)

    events = os.path.join(work, "fleet_events.jsonl")

    def factory(name):
        return GenerationServer(model, params, gen_cfg, num_slots=2,
                                rng=jax.random.PRNGKey(7),
                                page_size=128, pool_pages=17,
                                prefill_chunk_pages=1,
                                prefix_sharing=True,
                                host_pool_bytes=4 << 20,
                                events_path=events)

    stores = os.path.join(work, "fleet_stores")
    fleet = FleetRouter(factory, 2, events_path=events,
                        prefix_store_dir=stores,
                        async_workers=True)
    gids = [fleet.submit(p) for p in prompts]
    done = {}
    # commit some tokens first — with async workers the router tick
    # commits nothing itself, so poll until the worker threads have
    # decoded mid-stream state worth failing over (~1 token/request)
    deadline = time.monotonic() + 120.0
    while (fleet.summary()["decode_tokens"] < len(prompts)
           and len(done) < len(prompts)
           and time.monotonic() < deadline):
        for c in fleet.step():
            done[c.request_id] = c
    # the drill: EVERY replica goes down in turn while serving
    for c in fleet.rolling_restart():
        done[c.request_id] = c
    while fleet.busy:
        for c in fleet.step():
            done[c.request_id] = c
    summ = fleet.summary()

    missing = [g for g in gids if g not in done]
    if missing:
        fail(f"fleet leg lost requests {missing}")
    got = [done[g].tokens for g in gids]
    if got != ref:
        bad = [i for i, (a, b) in enumerate(zip(got, ref)) if a != b]
        fail(f"fleet leg dropped committed tokens: requests {bad} "
             f"diverged from the lockstep reference after the "
             f"rolling restart")
    if summ["shed"] != 0:
        fail(f"fleet leg shed {summ['shed']} requests while the peer "
             f"had capacity")
    if summ["failovers"] < 1:
        fail("fleet leg exercised no failover — the restart landed "
             "on an idle replica, drill geometry is broken")
    if summ["restarts"] != 2:
        fail(f"expected 2 replica restarts, recorded "
             f"{summ['restarts']}")
    if not summ.get("async_workers"):
        fail("fleet leg ran lockstep — the drill must restart "
             "replicas under overlapped worker-thread load")

    # trace continuity, reconstructed from events.jsonl ALONE
    with open(events) as f:
        evs = [json.loads(line) for line in f if line.strip()]
    routes = {e["request"]: e["trace"] for e in evs
              if e.get("event") == "fleet_route"}
    if sorted(routes) != sorted(gids):
        fail(f"fleet_route events cover requests {sorted(routes)}, "
             f"expected {sorted(gids)}")
    if len(set(routes.values())) != len(gids):
        fail("trace ids are not unique per request")
    begins = [e for e in evs if e.get("event") == "span_begin"]
    for e in [e for e in evs if e.get("event") == "fleet_failover"]:
        tid = e["trace"]
        lives = [b for b in begins if b["name"] == "serving/request"
                 and b["trace"] == tid]
        bridges = [b for b in begins if b["name"] == "fleet/failover"
                   and b["trace"] == tid]
        if len(lives) < 2:
            fail(f"failed-over trace {tid} shows {len(lives)} "
                 f"serving/request lifetimes, expected >= 2")
        if not bridges:
            fail(f"failed-over trace {tid} has no fleet/failover span")

    # warm second wave: the restarted replicas carry the dying
    # replicas' prefix stores (round-tripped through prefix_store_dir
    # on disk), so resubmitting the SAME prompts must be served by
    # rehydrating spilled prefix pages from host DRAM — and the first
    # serving_rehydrate in the post-restart stream must land BEFORE
    # the first serving_prefill_chunk (docs/fleet_serving.md
    # "Warm starts").
    for i in range(2):
        if not os.path.exists(os.path.join(
                stores, f"replica{i}_prefix_store",
                "pfx_manifest.json")):
            fail(f"replica{i} left no committed prefix store under "
                 f"{stores}")
    mark = sum(1 for _ in open(events))
    gids2 = [fleet.submit(p) for p in prompts]
    done2 = {}
    while fleet.busy:
        for c in fleet.step():
            done2[c.request_id] = c
    summ2 = fleet.summary()
    fleet.close()
    got2 = [done2[g].tokens for g in gids2 if g in done2]
    if got2 != ref:
        fail("warm wave diverged from the lockstep reference — the "
             "imported prefix store corrupted decoding")
    rehydrates = sum(r.get("rehydrates", 0)
                     for r in summ2["per_replica"])
    if rehydrates < 1:
        fail("warm wave rehydrated nothing — the restarted replicas "
             "started cold despite the persisted prefix store")
    with open(events) as f:
        warm_evs = [json.loads(line)
                    for line in list(f)[mark:] if line.strip()]
    kinds = [e["event"] for e in warm_evs
             if e.get("event") in ("serving_rehydrate",
                                   "serving_prefill_chunk")]
    if "serving_rehydrate" not in kinds:
        fail("no serving_rehydrate event in the warm wave")
    if kinds.index("serving_rehydrate") != 0:
        fail(f"warm wave prefilled before it rehydrated "
             f"(event order {kinds[:4]}) — registry hits must be "
             f"served from the host tier first")

    sys.stdout.write(
        f"FLEET LEG OK: rolling restart of 2 tiered ASYNC replicas "
        f"under overlapped load — {len(gids)} requests "
        f"lockstep-exact, shed=0, "
        f"failovers={summ['failovers']}, per-request traces "
        f"reconstruct from {os.path.basename(events)}; warm wave "
        f"re-served {len(gids2)} prompts with {rehydrates} "
        f"rehydrates, first rehydrate ahead of any prefill chunk\n")


def adapters_leg(work):
    """Multi-tenant LoRA drill (docs/lora.md): rolling-restart a
    2-replica fleet under mixed-adapter load — zero dropped tokens,
    and the restarted replicas' adapter-cache re-warm proven from
    ``serving_adapter_load`` events alone."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("PFX_PALLAS_INTERPRET", "1")
    sys.path.insert(0, REPO)
    import jax
    import jax.numpy as jnp
    import flax.linen as nn

    from paddlefleetx_tpu.core.adapters import extract_adapter
    from paddlefleetx_tpu.core.fleet import FleetRouter
    from paddlefleetx_tpu.core.serving import GenerationServer
    from paddlefleetx_tpu.models.gpt import GPTConfig, GPTForPretraining
    from paddlefleetx_tpu.models.gpt.generation import GenerationConfig

    vocab, eos = 96, 95
    cfg = GPTConfig(vocab_size=vocab, hidden_size=32, num_layers=2,
                    num_attention_heads=4,
                    max_position_embeddings=128,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    fuse_attn_qkv=True, lora_rank=4,
                    lora_num_adapters=4)
    model = GPTForPretraining(cfg)
    params = nn.meta.unbox(
        model.init({"params": jax.random.key(0)},
                   jnp.zeros((1, 8), jnp.int32))["params"])
    gen_cfg = GenerationConfig(max_dec_len=6,
                               decode_strategy="greedy_search",
                               eos_token_id=eos, pad_token_id=eos)
    shapes = {k: np.asarray(v).shape
              for k, v in extract_adapter(params, 0).items()}

    def source(aid):
        rng = np.random.default_rng(1000 + int(aid))
        return {k: rng.normal(0.0, 0.2, s).astype(np.float32)
                for k, s in shapes.items()}

    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, eos, 6 + i).tolist() for i in range(6)]
    aids = [1, 2, 3, 1, 2, 3]    # the adapter working set, striped

    # greedy decode is deterministic whatever the batching, so one
    # reference server's completions are the fleet's token oracle
    ref_srv = GenerationServer(model, params, gen_cfg, num_slots=2,
                               adapter_source=source)
    ref = [c.tokens for c in ref_srv.run(prompts, adapter_ids=aids)]

    events = os.path.join(work, "adapter_events.jsonl")

    def factory(name):
        return GenerationServer(model, params, gen_cfg, num_slots=2,
                                adapter_source=source,
                                events_path=events)

    fleet = FleetRouter(factory, 2, events_path=events)
    gids = [fleet.submit(p, adapter_id=a)
            for p, a in zip(prompts, aids)]
    done = {}
    # commit mid-stream state worth restarting under
    while fleet.summary()["decode_tokens"] < 2 and len(done) < len(gids):
        for c in fleet.step():
            done[c.request_id] = c
    mark = sum(1 for _ in open(events))
    # the drill: EVERY replica goes down in turn under adapter load
    for c in fleet.rolling_restart():
        done[c.request_id] = c
    while fleet.busy:
        for c in fleet.step():
            done[c.request_id] = c
    summ = fleet.summary()

    missing = [g for g in gids if g not in done]
    if missing:
        fail(f"adapter leg lost requests {missing}")
    bad_reason = [g for g in gids
                  if done[g].finish_reason not in ("eos", "length")]
    if bad_reason:
        fail(f"adapter leg requests {bad_reason} finished "
             f"{[done[g].finish_reason for g in bad_reason]} — the "
             f"restart dropped adapters on the floor")
    got = [done[g].tokens for g in gids]
    if got != ref:
        bad = [i for i, (a, b) in enumerate(zip(got, ref)) if a != b]
        fail(f"adapter leg dropped committed tokens: requests {bad} "
             f"diverged from the single-server reference after the "
             f"rolling restart")
    if summ["shed"] != 0:
        fail(f"adapter leg shed {summ['shed']} requests while the "
             f"peer had capacity")
    if summ["failovers"] < 1:
        fail("adapter leg exercised no failover — the restart landed "
             "on an idle replica, drill geometry is broken")
    if summ["restarts"] != 2:
        fail(f"expected 2 replica restarts, recorded "
             f"{summ['restarts']}")

    # warm second wave: the same mixed-adapter trace again, served by
    # the restarted replicas
    gids2 = [fleet.submit(p, adapter_id=a)
             for p, a in zip(prompts, aids)]
    done2 = {}
    while fleet.busy:
        for c in fleet.step():
            done2[c.request_id] = c
    fleet.close()
    got2 = [done2[g].tokens for g in gids2 if g in done2]
    if got2 != ref:
        fail("adapter leg warm wave diverged from the single-server "
             "reference — the re-warmed banks served wrong weights")

    # the re-warm evidence must reconstruct from events ALONE: the
    # restarted replicas start with cold banks, so the post-restart
    # stream (failover re-admissions + the warm wave) must show
    # serving_adapter_load events covering the full working set — a
    # fleet that silently served base weights would show none
    with open(events) as f:
        warm_evs = [json.loads(line)
                    for line in list(f)[mark:] if line.strip()]
    reloaded = {e["adapter"] for e in warm_evs
                if e.get("event") == "serving_adapter_load"}
    if reloaded != set(aids):
        fail(f"post-restart stream re-warmed adapters "
             f"{sorted(reloaded)}, expected the full working set "
             f"{sorted(set(aids))} — the restarted banks stayed cold")

    sys.stdout.write(
        f"ADAPTER LEG OK: rolling restart of 2 LoRA replicas under "
        f"mixed-adapter load — {len(gids)} + {len(gids2)} requests "
        f"token-exact vs the single-server reference, shed=0, "
        f"failovers={summ['failovers']}, post-restart re-warm of "
        f"adapters {sorted(reloaded)} reconstructed from "
        f"{os.path.basename(events)}\n")


def main():
    """Run the baseline/chaos/resume triple and assert continuity."""
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--kill-step", type=int, default=7)
    ap.add_argument("--save-steps", type=int, default=4)
    ap.add_argument("--ptq", action="store_true",
                    help="also PTQ the resumed checkpoint and drill "
                         "the int8 artifact's manifest verification")
    ap.add_argument("--fleet", action="store_true",
                    help="also rolling-restart an in-process "
                         "2-replica serving fleet mid-stream and "
                         "assert zero token loss + trace continuity")
    ap.add_argument("--adapters", action="store_true",
                    help="also rolling-restart a 2-replica LoRA "
                         "fleet under mixed-adapter load and assert "
                         "zero token loss + adapter-cache re-warm "
                         "from events alone")
    args = ap.parse_args()

    work = args.workdir or tempfile.mkdtemp(prefix="pfx_chaos_")
    os.makedirs(work, exist_ok=True)
    data = os.path.join(work, "data")
    base_out = os.path.join(work, "base_out")
    chaos_out = os.path.join(work, "chaos_out")
    make_corpus(data)
    cfg_path = os.path.join(work, "chaos_smoke.yaml")
    with open(cfg_path, "w") as f:
        f.write(_CONFIG.format(steps=args.steps,
                               save_steps=args.save_steps,
                               out=base_out, data=data))
    last_save = (args.kill_step // args.save_steps) * args.save_steps
    if not 0 < last_save < args.kill_step:
        fail(f"bad drill geometry: kill step {args.kill_step} must "
             f"land strictly between save-cadence multiples of "
             f"{args.save_steps}")

    # 1. baseline
    rc = run_train(cfg_path, base_out)
    if rc != 0:
        fail(f"baseline run exited {rc}")
    base_losses = losses_by_step(read_events(base_out))
    missing = [s for s in range(1, args.steps + 1)
               if s not in base_losses]
    if missing:
        fail(f"baseline missing step_window for steps {missing}")

    # 2. chaos: a real SIGKILL at --kill-step
    rc = run_train(cfg_path, chaos_out,
                   faults=f"kill@step={args.kill_step}")
    if rc != -signal.SIGKILL:
        fail(f"chaos run expected SIGKILL exit, got rc={rc}")
    chaos_events = read_events(chaos_out)
    injected = [e for e in chaos_events
                if e.get("event") == "fault_injected"]
    if not injected:
        fail("killed run did not durably record fault_injected")
    chaos_losses = losses_by_step(chaos_events)
    for s in range(1, args.kill_step + 1):
        if chaos_losses.get(s) != base_losses[s]:
            fail(f"pre-kill divergence at step {s}: "
                 f"{chaos_losses.get(s)} != {base_losses[s]}")
    mark = count_lines(chaos_out)

    # 3. resume from the chaos output dir
    rc = run_train(cfg_path, chaos_out, resume=True)
    if rc != 0:
        fail(f"resume run exited {rc}")
    resumed = read_events(chaos_out, skip_lines=mark)
    fallbacks = [e for e in resumed if e.get("event") == "ckpt_fallback"]
    if fallbacks:
        fail(f"resume fell back past the newest checkpoint (the kill "
             f"landed between saves, so step {last_save} must "
             f"verify): {fallbacks}")
    res_losses = losses_by_step(resumed)
    expect = list(range(last_save + 1, args.steps + 1))
    if sorted(res_losses) != expect:
        fail(f"resume step continuity broken: trained steps "
             f"{sorted(res_losses)}, expected {expect} (restore at "
             f"step {last_save})")
    diverged = {s: (res_losses[s], base_losses[s]) for s in expect
                if res_losses[s] != base_losses[s]}
    if diverged:
        fail(f"resumed loss curve diverged from baseline: {diverged}")

    # 4. optional: PTQ the resumed checkpoint, drill the artifact
    if args.ptq:
        ptq_leg(work, chaos_out, cfg_path)

    # 5. optional: rolling-restart a serving fleet under load
    if args.fleet:
        fleet_leg(work)

    # 6. optional: rolling-restart a LoRA fleet under adapter load
    if args.adapters:
        adapters_leg(work)

    sys.stdout.write(
        f"CHAOS SMOKE OK: killed at step {args.kill_step}, restored "
        f"step {last_save}, steps {expect[0]}..{expect[-1]} "
        f"loss-identical to baseline ({work})\n")


if __name__ == "__main__":
    main()
