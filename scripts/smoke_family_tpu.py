"""Real-chip step-time smoke for the ViT, Imagen, and ERNIE families.

Ad hoc: python scripts/smoke_family_tpu.py [vit|imagen|ernie] —
measures a bf16 train step (fwd+bwd+adamw) at a production-shaped
operating point on the attached chip. Numbers are recorded in
projects/{vit,imagen}/README.md and projects/ernie/README.md.
"""

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root, from any cwd

import jax
import jax.numpy as jnp
import numpy as np
import optax


def _sync(x):
    float(jnp.ravel(jax.tree.leaves(x)[0])[0].astype(jnp.float32))


def _step_time(step, state, *batch, n=10):
    state = step(state, *batch)
    _sync(state)
    t0 = time.perf_counter()
    for _ in range(n):
        state = step(state, *batch)
    _sync(state)
    return (time.perf_counter() - t0) / n


def smoke_vit(batch=128):
    """One jitted ViT train step on chip; returns the images/s
    record."""
    from paddlefleetx_tpu.models.vit.vit import VISION_MODELS
    from paddlefleetx_tpu.models.vit.loss import ViTCELoss

    model = VISION_MODELS["ViT_base_patch16_224"](dtype="bfloat16")
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.uniform(-1, 1, (batch, 224, 224, 3)),
                         jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, 1000, (batch,)), jnp.int32)
    params = jax.jit(model.init)(jax.random.key(0), images[:1])["params"]
    tx = optax.adamw(1e-3, weight_decay=0.05, mu_dtype=jnp.bfloat16)
    opt = tx.init(params)
    criterion = ViTCELoss(epsilon=0.1)

    def loss_fn(p, x, y):
        return criterion(model.apply({"params": p}, x,
                                     deterministic=True), y)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, x, y):
        p, o = state
        loss, g = jax.value_and_grad(loss_fn)(p, x, y)
        up, o = tx.update(g, o, p)
        return optax.apply_updates(p, up), o

    dt = _step_time(step, (params, opt), images, labels)
    print(f"ViT-base/16 224 bf16 train step, bs={batch}: "
          f"{dt * 1e3:.1f} ms = {batch / dt:.0f} images/s")
    return {"metric": "vit_base16_224_train_images_per_sec",
            "value": round(batch / dt, 1), "unit": "images/s",
            "vs_baseline": None, "batch": batch}


def smoke_imagen(batch=16):
    """One jitted Imagen train step on chip; returns the images/s
    record."""
    from paddlefleetx_tpu.models.imagen.modeling import (
        build_imagen_model, imagen_criterion,
    )

    model = build_imagen_model("imagen_397M_text2im_64",
                               dtype="bfloat16")
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.uniform(0, 1, (batch, 3, 64, 64)),
                         jnp.float32)
    emb = jnp.asarray(rng.normal(size=(batch, 77, model.config.text_embed_dim)),
                      jnp.bfloat16)
    mask = jnp.ones((batch, 77), jnp.int32)
    variables = jax.jit(functools.partial(
        model.init))({"params": jax.random.key(0),
                      "diffusion": jax.random.key(1)},
                     images[:1], emb[:1], mask[:1])
    params = variables["params"]
    tx = optax.adamw(1e-4, mu_dtype=jnp.bfloat16)
    opt = tx.init(params)

    def loss_fn(p, x, e, m, key):
        pred, target, log_snr, gamma = model.apply(
            {"params": p}, x, e, m, rngs={"diffusion": key})
        return imagen_criterion(pred, target, log_snr, gamma)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, x, e, m):
        p, o, key = state
        key, sub = jax.random.split(key)
        loss, g = jax.value_and_grad(loss_fn)(p, x, e, m, sub)
        up, o = tx.update(g, o, p)
        return optax.apply_updates(p, up), o, key

    dt = _step_time(step, (params, opt, jax.random.key(2)),
                    images, emb, mask)
    print(f"Imagen base U-Net 397M text2im 64x64 bf16 train step, "
          f"bs={batch}: {dt * 1e3:.1f} ms = {batch / dt:.0f} images/s")
    return {"metric": "imagen_397M_text2im64_train_images_per_sec",
            "value": round(batch / dt, 1), "unit": "images/s",
            "vs_baseline": None, "batch": batch}


def smoke_ernie(batch=32, seq=512):
    """ERNIE-345M-class encoder MLM train step (the reference's
    ``pretrain_ernie_345M_single_card.yaml`` geometry: h=1024, 24
    layers, s=512)."""
    from paddlefleetx_tpu.models.ernie.config import ErnieConfig
    from paddlefleetx_tpu.models.ernie.model import (
        ErnieForPretraining, ernie_pretraining_loss,
    )
    from paddlefleetx_tpu.models.ernie.modules import apply_mlm_masking

    cfg = ErnieConfig(
        vocab_size=50304, hidden_size=1024, num_hidden_layers=24,
        num_attention_heads=16, max_position_embeddings=seq,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        dtype="bfloat16", use_flash_attention=True, scan_layers=False)
    model = ErnieForPretraining(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(1, cfg.vocab_size, (batch, seq)),
                         jnp.int32)
    params = jax.jit(model.init)(
        {"params": jax.random.key(0)}, tokens[:1])["params"]
    tx = optax.adamw(1e-4, mu_dtype=jnp.bfloat16)
    opt = tx.init(params)

    def loss_fn(p, masked, labels):
        scores, _ = model.apply({"params": p}, masked,
                                deterministic=True)
        return ernie_pretraining_loss(scores, labels,
                                      with_nsp_loss=False)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, tokens):
        p, o, key = state
        key, sub = jax.random.split(key)
        masked, labels = apply_mlm_masking(sub, tokens, cfg)
        loss, g = jax.value_and_grad(loss_fn)(p, masked, labels)
        up, o = tx.update(g, o, p)
        return optax.apply_updates(p, up), o, key

    dt = _step_time(step, (params, opt, jax.random.key(2)), tokens)
    print(f"ERNIE-345M MLM bf16 train step, bs={batch}/s={seq}: "
          f"{dt * 1e3:.1f} ms = {batch * seq / dt:.0f} tokens/s")
    return {"metric": "ernie_345M_mlm_train_tokens_per_sec",
            "value": round(batch * seq / dt, 1), "unit": "tokens/s",
            "vs_baseline": None, "batch": batch, "seq": seq}


if __name__ == "__main__":
    from paddlefleetx_tpu.utils.env import setup_compilation_cache
    setup_compilation_cache(os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".xla_cache"))   # the unrolled 24-layer ERNIE compiles slowly
    which = sys.argv[1:] or ["vit", "imagen", "ernie"]
    print("device:", jax.devices()[0].device_kind)
    # successful on-chip family numbers join the committed audit
    # trail (bench_log/runs.jsonl) like the GPT bench records — but
    # logging must NEVER cost a measurement (nor may a cwd that can't
    # import bench.py abort the smoke before it measures anything)
    def _audit(record):
        try:
            sys.path.insert(0, os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            from bench import _log_success
            _log_success(record)
        except Exception as e:
            print(f"audit-trail append skipped "
                  f"({type(e).__name__}: {e})", file=sys.stderr)
    if "vit" in which:
        _audit(smoke_vit())
    if "imagen" in which:
        _audit(smoke_imagen())
    if "ernie" in which:
        _audit(smoke_ernie())
