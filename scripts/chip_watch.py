#!/usr/bin/env python
"""Watch for the TPU tunnel to come up, without ever hanging.

Reuses ``bench.probe_once`` — PJRT client creation in a KILLABLE
subprocess (the tunnel's known failure shape is an indefinite hang at
client init; an in-process ``jax.devices()`` would wedge the watcher
itself) — every ``--interval`` seconds, for at most ``--budget``
seconds. Exits 0 the moment a probe reaches a real TPU (printing its
device_kind), 3 if the budget expires without one. Used by the builder
to trigger opportunistic ``bench.py`` runs (VERDICT r4 next #1) the
moment a chip window opens.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from bench import probe_once  # noqa: E402


def main():
    """Poll the TPU backend probe until it answers or the budget
    runs out; exit 0 only on a live chip."""
    p = argparse.ArgumentParser()
    p.add_argument("--budget", type=float, default=540.0,
                   help="total seconds to watch before giving up")
    p.add_argument("--interval", type=float, default=60.0)
    p.add_argument("--probe-timeout", type=float, default=75.0)
    args = p.parse_args()
    deadline = time.monotonic() + args.budget
    n = 0
    while time.monotonic() < deadline:
        n += 1
        info, err, _hang = probe_once(
            min(args.probe_timeout,
                max(10.0, deadline - time.monotonic())))
        if info is not None and info.get("platform") == "tpu":
            print(json.dumps({"up": True, "probes": n, **info}))
            return 0
        detail = (err.splitlines()[-1] if err
                  else f"non-tpu platform {info}")
        sys.stderr.write(f"probe {n}: {detail}\n")
        time.sleep(min(args.interval,
                       max(0.0, deadline - time.monotonic())))
    print(json.dumps({"up": False, "probes": n}))
    return 3


if __name__ == "__main__":
    sys.exit(main())
