"""PTQ a trained GPT checkpoint to weight-only int8, offline.

Reads the newest VERIFIED ``epoch_*_step_*`` checkpoint under
``--checkpoint`` (or an explicit step dir), rewrites its parameter
tree into the ``quant_execution="weight_only_int8"`` storage format
(``core/quantize.py``: int8 ``kernel`` + fp32 per-output-channel
``kernel_scale`` at every dense site, everything else untouched), and
writes it as a NEW manifest-verified checkpoint under ``--output`` —
same ``epoch_E_step_S`` layout, so ``latest_checkpoint`` /
``load_checkpoint`` and the serving loaders consume it unchanged.
The optimizer state is dropped: quantized kernels are frozen
inference artifacts (their VJP is a symbolic zero —
``ops/pallas/quantized_matmul.py``).

With ``--config`` (the training YAML) the script also builds the
model pair and runs a deterministic synthetic seed batch through
both: the fp forward records per-module activation abs-max into the
checkpoint meta (the QAT moving-average statistic at its per-batch
fixed point), and the quantized forward bounds the logit deviation —
printed, stored in meta, and enforced by ``--max-rel-dev`` when set.
Workflow docs: docs/quantization.md. Run from the repo root:

  python scripts/quantize_checkpoint.py \\
      --checkpoint out/ --output out_int8/ [--config cfg.yaml] \\
      [--calib-batch 4 --calib-seqlen 32] [--max-rel-dev 0.05]
"""

import argparse
import os
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def fail(msg):
    """Print the diagnosis and exit nonzero."""
    sys.stdout.write(f"QUANTIZE CHECKPOINT FAILED: {msg}\n")
    sys.exit(1)


def load_raw_state(path):
    """Restore ``(state, meta)`` exactly as saved (host arrays, no
    sharding template) — PTQ is tree surgery, not a mesh restore."""
    import orbax.checkpoint as ocp
    with ocp.Checkpointer(ocp.CompositeCheckpointHandler()) as ckptr:
        restored = ckptr.restore(
            path, args=ocp.args.Composite(
                state=ocp.args.StandardRestore(),
                meta=ocp.args.JsonRestore()))
    return restored.state, restored.meta or {}


def main():
    """Resolve, verify, quantize, (optionally) calibrate, save."""
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--checkpoint", required=True,
                    help="checkpoint root or explicit step dir")
    ap.add_argument("--output", required=True,
                    help="directory for the quantized step dir")
    ap.add_argument("--config", default=None,
                    help="training YAML; enables seed-batch "
                         "calibration + logit-deviation validation")
    ap.add_argument("--calib-batch", type=int, default=4)
    ap.add_argument("--calib-seqlen", type=int, default=32)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-rel-dev", type=float, default=None,
                    help="fail when quantized logits deviate more "
                         "than this relative to fp logits")
    args = ap.parse_args()

    from paddlefleetx_tpu.core.checkpoint import (
        _STEP_DIR, latest_checkpoint, save_checkpoint,
        verify_checkpoint,
    )
    from paddlefleetx_tpu.core.quantize import (
        calibrate_activation_absmax, quantization_meta,
        quantize_param_tree,
    )

    src = latest_checkpoint(args.checkpoint)
    if src is None:
        fail(f"no verified checkpoint under {args.checkpoint}")
    reason = verify_checkpoint(src)
    if reason is not None:
        fail(f"{src} failed verification: {reason}")
    m = _STEP_DIR.search(src)
    epoch, step = (int(m.group(1)), int(m.group(2))) if m else (0, 0)

    state, meta = load_raw_state(src)
    if "params" not in state:
        fail(f"{src} holds no 'params' subtree (keys: "
             f"{sorted(state)})")
    qparams, report = quantize_param_tree(state["params"])
    if not report:
        fail("no quantizable dense-site kernels found — is this a "
             "GPT checkpoint?")
    for row in report:
        sys.stdout.write(
            f"  quantized {row['path']} {row['shape']} "
            f"({row['bytes_fp']} -> {row['bytes_int8']} bytes)\n")

    calibration = None
    deviation = None
    if args.config:
        import jax
        import jax.numpy as jnp
        from paddlefleetx_tpu.models.gpt.config import GPTConfig
        from paddlefleetx_tpu.models.gpt.model import (
            GPTForPretraining, GPTModel,
        )
        from paddlefleetx_tpu.utils.config import get_config
        cfg = get_config(args.config)
        mcfg = GPTConfig.from_config(cfg)
        qcfg = GPTConfig(**{**mcfg.__dict__,
                            "quant_execution": "weight_only_int8"})
        # engine checkpoints carry the pretraining wrapper's scope
        # ("gpt/..."); bare GPTModel trees start at "embeddings"
        cls = GPTForPretraining if "gpt" in state["params"] else GPTModel
        ids = jax.random.randint(
            jax.random.PRNGKey(args.seed),
            (args.calib_batch, args.calib_seqlen), 0,
            mcfg.vocab_size)
        base = cls(mcfg).apply({"params": state["params"]}, ids)
        calibration = calibrate_activation_absmax(
            cls(mcfg), state["params"], ids)
        quant = cls(qcfg).apply({"params": qparams}, ids)
        err = float(jnp.max(jnp.abs(
            base.astype(jnp.float32) - quant.astype(jnp.float32))))
        denom = max(float(jnp.max(jnp.abs(base))), 1e-8)
        deviation = {"max_abs": err, "max_rel": err / denom}
        sys.stdout.write(
            f"  seed-batch logit deviation: abs {err:.5f} "
            f"rel {err / denom:.5f}\n")
        if args.max_rel_dev is not None \
                and deviation["max_rel"] > args.max_rel_dev:
            fail(f"quantized logits deviate {deviation['max_rel']:.5f}"
                 f" > --max-rel-dev {args.max_rel_dev}")

    qmeta = dict(meta)
    qmeta["quantization"] = quantization_meta(report, calibration)
    if deviation is not None:
        qmeta["quantization"]["seed_batch_deviation"] = deviation
    new_state = {"params": qparams}
    if "step" in state:
        new_state["step"] = state["step"]
    dropped = sorted(set(state) - set(new_state))
    if dropped:
        sys.stdout.write(f"  dropping {dropped} (frozen inference "
                         f"artifact)\n")
    path = save_checkpoint(args.output, epoch, step, new_state, qmeta)
    reason = verify_checkpoint(path)
    if reason is not None:
        fail(f"freshly saved {path} failed verification: {reason}")
    sys.stdout.write(
        f"QUANTIZE CHECKPOINT OK: {src} -> {path} "
        f"({len(report)} sites)\n")


if __name__ == "__main__":
    main()
