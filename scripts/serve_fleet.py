"""Thin fleet-serving entrypoint: a FleetRouter demo you can scrape.

Ad hoc: ``python scripts/serve_fleet.py --replicas 2 --requests 24``
builds N interpret-friendly GenerationServer replicas behind a
prefix-affinity FleetRouter (core/fleet.py), feeds them a seeded
mixed-prefix trace (a few "system prompts" shared by many requests —
the fleet workload shape), optionally performs a rolling restart
mid-run, and prints the fleet summary as JSON. Set
``PFX_METRICS_PORT`` to also expose the live ``/metrics`` +
aggregated ``/healthz`` endpoints while it runs
(docs/fleet_serving.md).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root, from any cwd


def build_trace(num_requests: int, num_prefixes: int, prefix_len: int,
                tail_len: int, vocab: int, seed: int):
    """A seeded fleet-shaped trace: every request is one of
    ``num_prefixes`` shared system prompts plus a per-request tail."""
    import numpy as np
    rng = np.random.default_rng(seed)
    prefixes = [rng.integers(1, vocab - 2, prefix_len).tolist()
                for _ in range(num_prefixes)]
    prompts = []
    for i in range(num_requests):
        tail = rng.integers(1, vocab - 2, tail_len).tolist()
        prompts.append(prefixes[i % num_prefixes] + tail)
    return prompts


def main() -> int:
    """Build the fleet, serve the trace, print the summary JSON."""
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--prefill-replicas", type=int, default=0,
                    help="first K replicas take the prefill role "
                         "(0 = mixed fleet)")
    ap.add_argument("--handoff", choices=("device", "host"),
                    default="device")
    ap.add_argument("--slots", type=int, default=2,
                    help="slots per replica")
    ap.add_argument("--page-size", type=int, default=128,
                    help="KV page size (0 = contiguous slots; paged "
                         "is required for prefix affinity and "
                         "prefill/decode split)")
    ap.add_argument("--pool-pages", type=int, default=0,
                    help="pages per replica pool (0 = server default)")
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--prefixes", type=int, default=2,
                    help="distinct shared system prompts in the trace")
    ap.add_argument("--prefix-len", type=int, default=128)
    ap.add_argument("--tail-len", type=int, default=16)
    ap.add_argument("--max-dec-len", type=int, default=16)
    ap.add_argument("--async-workers", action="store_true",
                    help="overlapped per-replica worker threads "
                         "(docs/fleet_serving.md \"Async router\")")
    ap.add_argument("--rolling-restart", action="store_true",
                    help="restart every replica mid-run (drain -> "
                         "failover -> fresh server)")
    ap.add_argument("--events", default="",
                    help="events.jsonl path shared by the router and "
                         "every replica")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    os.environ.setdefault("PFX_PALLAS_INTERPRET", "1")
    import jax
    import jax.numpy as jnp

    from paddlefleetx_tpu.core.fleet import FleetRouter
    from paddlefleetx_tpu.core.serving import GenerationServer
    from paddlefleetx_tpu.models.gpt import GPTConfig, GPTForPretraining
    from paddlefleetx_tpu.models.gpt.generation import GenerationConfig

    vocab = 96
    capacity = args.prefix_len + args.tail_len + args.max_dec_len
    if args.page_size:
        capacity = -(-capacity // args.page_size) * args.page_size
    cfg = GPTConfig(vocab_size=vocab, hidden_size=32, num_layers=2,
                    num_attention_heads=4,
                    max_position_embeddings=capacity,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0)
    model = GPTForPretraining(cfg)
    params = model.init({"params": jax.random.key(args.seed)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    gen_cfg = GenerationConfig(max_dec_len=args.max_dec_len,
                               decode_strategy="greedy_search",
                               eos_token_id=vocab - 1,
                               pad_token_id=vocab - 1)

    def factory(name: str) -> GenerationServer:
        kw = {}
        if args.page_size:
            kw["page_size"] = args.page_size
            if args.pool_pages:
                kw["pool_pages"] = args.pool_pages
        return GenerationServer(
            model, params, gen_cfg, num_slots=args.slots,
            rng=jax.random.PRNGKey(args.seed),
            events_path=args.events or None, **kw)

    fleet = FleetRouter(factory, args.replicas,
                        prefill_replicas=args.prefill_replicas,
                        events_path=args.events or None,
                        handoff=args.handoff,
                        async_workers=args.async_workers)
    prompts = build_trace(args.requests, args.prefixes,
                          args.prefix_len, args.tail_len, vocab,
                          args.seed)
    ids = [fleet.submit(p) for p in prompts]
    done = {}
    restarted = False
    while fleet.busy:
        for c in fleet.step():
            done[c.request_id] = c
        if args.rolling_restart and not restarted and \
                len(done) >= len(ids) // 4:
            for c in fleet.rolling_restart():
                done[c.request_id] = c
            restarted = True
    missing = [i for i in ids if i not in done]
    summary = fleet.summary()
    summary["requests"] = len(ids)
    summary["completed"] = len(ids) - len(missing)
    print(json.dumps(summary, default=str))
    fleet.close()
    return 1 if missing else 0


if __name__ == "__main__":
    sys.exit(main())
