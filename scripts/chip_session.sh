#!/bin/sh
# One-shot chip-session protocol (perf playbook "first 20 minutes"),
# safe to re-run. Each stage logs under bench_log/; successful bench
# runs also append their JSON + device_kind to bench_log/runs.jsonl
# (the audit trail). Stages are individually timed out so a dying
# tunnel cannot wedge the session; later stages still get their shot.
cd "$(dirname "$0")/.." || exit 1
mkdir -p bench_log
log() { echo "[$(date -u +%FT%TZ)] $*" >> bench_log/session.log; }

log "chip session start"
# keep per-stage probe budgets short: the chip was just probed up
export PFX_BENCH_MAX_WAIT=600

log "stage: tune_flash"
timeout 1500 python scripts/tune_flash.py \
    >> bench_log/tune_flash.log 2>&1
log "tune_flash rc=$?"

log "stage: bench train (cold, decomp)"
PFX_BENCH_DECOMP=1 timeout 2400 python bench.py \
    >> bench_log/bench_train.log 2>&1
log "bench train cold rc=$?"

log "stage: bench train (warm)"
timeout 1500 python bench.py >> bench_log/bench_train.log 2>&1
log "bench train warm rc=$?"

log "stage: dropout certification"
timeout 1200 python scripts/validate_flash_dropout.py \
    >> bench_log/dropout_cert.log 2>&1
log "dropout cert rc=$?"

log "stage: convergence oracle"
timeout 1200 python bench.py --mode convergence \
    >> bench_log/bench_convergence.log 2>&1
log "convergence rc=$?"

log "stage: moe"
timeout 1200 python bench.py --mode moe \
    >> bench_log/bench_moe.log 2>&1
log "moe rc=$?"

log "stage: generation"
timeout 1200 python bench.py --mode generation \
    >> bench_log/bench_generation.log 2>&1
log "generation rc=$?"

log "chip session end"
