#!/bin/sh
# One-shot chip-session protocol (perf playbook "first 20 minutes"),
# safe to re-run. Each stage logs under bench_log/; successful bench
# runs also append their JSON + device_kind to bench_log/runs.jsonl
# (the audit trail). Stages are individually timed out so a dying
# tunnel cannot wedge the session; later stages still get their shot.
# timeout -k: a stage wedged inside a native PJRT/compile call cannot
# run Python signal handlers, so TERM alone can hang the whole session
# (observed r5: moe stage 22 min past deadline) — KILL follows.
cd "$(dirname "$0")/.." || exit 1
mkdir -p bench_log
log() { echo "[$(date -u +%FT%TZ)] $*" >> bench_log/session.log; }

log "chip session start"
# keep per-stage probe budgets short: the chip was just probed up
export PFX_BENCH_MAX_WAIT=600

log "stage: tune_flash"
timeout -k 60 1500 python scripts/tune_flash.py \
    >> bench_log/tune_flash.log 2>&1
log "tune_flash rc=$?"

log "stage: bench train (cold, decomp, headline only)"
PFX_BENCH_DECOMP=1 PFX_BENCH_SKIP_SECONDARIES=1 \
    timeout -k 60 2400 python bench.py \
    >> bench_log/bench_train.log 2>&1
log "bench train cold rc=$?"

log "stage: bench train (warm, headline only)"
PFX_BENCH_SKIP_SECONDARIES=1 timeout -k 60 1500 python bench.py \
    >> bench_log/bench_train.log 2>&1
log "bench train warm rc=$?"

# the secondaries get DEDICATED stages with their own budgets (cold
# compiles of the 6.7B L=8 / s=8192 configs take minutes each): inside
# the train stage they would share its timeout and be TERM'd away
log "stage: 67b"
timeout -k 60 2400 python bench.py --mode 67b \
    >> bench_log/bench_67b.log 2>&1
log "67b rc=$?"

log "stage: longctx"
timeout -k 60 1800 python bench.py --mode longctx \
    >> bench_log/bench_longctx.log 2>&1
log "longctx rc=$?"

log "stage: dropout certification"
timeout -k 60 1200 python scripts/validate_flash_dropout.py \
    >> bench_log/dropout_cert.log 2>&1
log "dropout cert rc=$?"

log "stage: convergence oracle"
timeout -k 60 1200 python bench.py --mode convergence \
    >> bench_log/bench_convergence.log 2>&1
log "convergence rc=$?"

log "stage: moe"
timeout -k 60 1200 python bench.py --mode moe \
    >> bench_log/bench_moe.log 2>&1
log "moe rc=$?"

log "stage: generation"
timeout -k 60 1200 python bench.py --mode generation \
    >> bench_log/bench_generation.log 2>&1
log "generation rc=$?"

log "chip session end"
