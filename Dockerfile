# Container recipe for a Cloud TPU VM (counterpart of the reference's
# CUDA image build, reference `Dockerfile`; see
# docs/environment_install.md for the non-container path and
# docs/cluster_deployment.md for multi-host usage).
#
# Build:  docker build -t paddlefleetx-tpu .
# Run  :  sudo docker run -it --rm --privileged --network host \
#             paddlefleetx-tpu bash
# `--privileged --network host` exposes the TPU device files and the
# other hosts of a multi-host slice to the container (the equivalent
# of the reference's nvidia-container-runtime step; no device runtime
# is installed inside the image — the TPU driver lives on the VM).

FROM python:3.11-slim

WORKDIR /workspace

# native toolchain for the C++ data-index helpers (data_tools/cpp)
RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make && rm -rf /var/lib/apt/lists/*

RUN python -m pip install --no-cache-dir -U \
        "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
        flax optax orbax-checkpoint chex einops numpy pyyaml pytest

COPY . /workspace
RUN python -m pip install --no-cache-dir -e .

# sanity: import the package; TPU check happens at run time on the VM
RUN python -c "import paddlefleetx_tpu"

CMD ["python", "-c", "import jax; print(jax.devices())"]
