"""Cached artifact resolution and a retrying, hash-verified downloader.

Parity: reference ``utils/download.py`` — ``_download`` retries the
fetch up to a retry budget, verifies md5, writes to a temp file and
atomically moves into the cache (:71-114); ``download`` gates the fetch
on rank 0 while other ranks spin-wait on the cached file (:118-128).
This deployment is zero-egress, so network schemes fail fast with a
pre-staging hint, but the full retry/verify/atomic-move machinery runs
for any reachable URL (``file://`` included, which the tests use).
"""

from __future__ import annotations

import hashlib
import os
import shutil
import time
import urllib.error
import urllib.request
from typing import Optional

from .log import logger

CACHE_HOME = os.environ.get(
    "PFX_CACHE_HOME",
    os.path.join(os.path.expanduser("~"), ".cache", "paddlefleetx_tpu"))

DOWNLOAD_RETRY_LIMIT = 3


def cached_path(name_or_path: str,
                cache_subdir: str = "") -> Optional[str]:
    """Resolve ``name_or_path`` to a local file: as given, or under
    the cache home. Returns None if absent."""
    if os.path.exists(name_or_path):
        return name_or_path
    candidate = os.path.join(CACHE_HOME, cache_subdir,
                             os.path.basename(name_or_path))
    return candidate if os.path.exists(candidate) else None


def _md5check(fullname: str, md5sum: Optional[str]) -> bool:
    """Reference ``_md5check`` (:130-146): True when no sum is given."""
    if md5sum is None:
        return True
    md5 = hashlib.md5()
    with open(fullname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            md5.update(chunk)
    ok = md5.hexdigest() == md5sum
    if not ok:
        logger.warning("md5 mismatch for %s: %s != %s", fullname,
                       md5.hexdigest(), md5sum)
    return ok


def _download(url: str, path: str, md5sum: Optional[str] = None,
              retries: int = DOWNLOAD_RETRY_LIMIT,
              timeout: float = 30.0, backoff: float = 1.0) -> str:
    """Fetch ``url`` into directory ``path`` with retry + md5 verify +
    atomic move (reference ``_download`` :71-114). The hash is checked
    on the temp file BEFORE the move, so a truncated fetch never lands
    in the cache; permanent failure leaves a ``.failed`` sentinel so
    waiting ranks fail fast instead of spinning out their timeout."""
    os.makedirs(path, exist_ok=True)
    fname = os.path.basename(url)
    fullname = os.path.join(path, fname)
    sentinel = fullname + ".failed"
    if os.path.exists(sentinel):
        os.remove(sentinel)
    attempt = 0
    while not (os.path.exists(fullname) and _md5check(fullname, md5sum)):
        if attempt >= retries:
            with open(sentinel, "w") as f:
                f.write(url)
            raise RuntimeError(
                f"download of {url} failed after {retries} attempts")
        attempt += 1
        logger.info("downloading %s (attempt %d/%d)", url, attempt,
                    retries)
        tmp_fullname = fullname + "_tmp"
        try:
            with urllib.request.urlopen(url, timeout=timeout) as req, \
                    open(tmp_fullname, "wb") as f:
                shutil.copyfileobj(req, f)
            if _md5check(tmp_fullname, md5sum):
                shutil.move(tmp_fullname, fullname)
            else:
                os.remove(tmp_fullname)
        except (urllib.error.URLError, OSError) as e:
            logger.warning("fetch attempt %d for %s failed: %s",
                           attempt, url, e)
            if os.path.exists(tmp_fullname):
                os.remove(tmp_fullname)
            # second-scale backoff by default so transient blips can
            # clear; tests pass a small factor
            time.sleep(min(2 ** attempt, 8) * backoff)
    return fullname


def _process_rank() -> int:
    for var in ("PFX_RANK", "JAX_PROCESS_INDEX", "RANK"):
        if os.environ.get(var):
            return int(os.environ[var])
    return 0


def download(url: str, path: str, md5sum: Optional[str] = None) -> str:
    """Rank-0 downloads; other ranks spin-wait until the file exists
    AND passes the hash (reference ``download`` :118-128 waits on
    existence only, which would accept a stale file rank 0 is still
    re-fetching)."""
    fullname = os.path.join(path, os.path.basename(url))
    if _process_rank() != 0:
        t0 = time.time()
        sentinel = fullname + ".failed"
        # establish "now" in the FILESYSTEM's clock: sentinel mtimes
        # come from the file server, which may be skewed from
        # time.time() on shared storage
        fs_t0 = t0
        try:
            os.makedirs(path, exist_ok=True)
            probe = os.path.join(
                path, f".waitprobe.{os.getpid()}.{_process_rank()}")
            with open(probe, "w"):
                pass
            fs_t0 = os.path.getmtime(probe)
            os.remove(probe)
        except OSError:
            pass
        last_stat = last_ok = None
        while True:
            if os.path.exists(fullname):
                # re-hash only when the file changed — a multi-GB
                # artifact must not be fully re-read once per second
                # while rank 0 refetches
                try:
                    st = os.stat(fullname)
                    stat_key = (st.st_size, st.st_mtime_ns)
                except OSError:
                    stat_key = None
                if stat_key is not None:
                    if stat_key != last_stat:
                        last_stat = stat_key
                        last_ok = _md5check(fullname, md5sum)
                    if last_ok:
                        return fullname
            # fail fast ONLY on a sentinel written during this wait
            # (rank 0 failed just now and refreshed its mtime). A
            # stale sentinel is ignored: a healthy rank 0 may be busy
            # with other artifacts for minutes before clearing it, and
            # killing the job on leftovers from a previous run is the
            # worse failure mode — the loop timeout stays the backstop
            # for the rare rank-0-failed-before-we-started ordering.
            if os.path.exists(sentinel):
                try:
                    fresh = os.path.getmtime(sentinel) >= fs_t0 - 5.0
                except OSError:       # rank 0 removed it mid-check
                    fresh = False
                if fresh:
                    raise RuntimeError(
                        f"rank 0 failed to download {url} "
                        f"(sentinel {sentinel})")
            if time.time() - t0 > 3600.0:
                raise TimeoutError(
                    f"timed out waiting for verified {fullname}")
            time.sleep(1)
    return _download(url, path, md5sum)


def get_weights_path_from_url(url: str, md5sum: Optional[str] = None
                              ) -> str:
    """Resolve (or fetch) a weights artifact into the cache
    (reference ``get_weights_path_from_url`` → ``get_path_from_url``)."""
    weights_dir = os.path.join(CACHE_HOME, "weights")
    cached = cached_path(os.path.basename(url), "weights")
    if cached and _md5check(cached, md5sum):
        return cached
    try:
        return download(url, weights_dir, md5sum)
    except (RuntimeError, urllib.error.URLError, OSError) as e:
        raise FileNotFoundError(
            f"{os.path.basename(url)} not found under "
            f"{CACHE_HOME}/weights and could not be fetched ({e}); on "
            f"zero-egress deployments pre-stage the file there "
            f"(source: {url}).") from e


def wait_for_file(path: str, producer_rank: bool,
                  produce_fn=None, timeout: float = 3600.0) -> str:
    """Rank-0-produces / others-spin-wait (reference ``download.py``
    main-process gate; also the dataset index-build protocol,
    ``gpt_dataset.py:47-69``)."""
    if producer_rank:
        if not os.path.exists(path) and produce_fn is not None:
            produce_fn()
        return path
    t0 = time.time()
    while not os.path.exists(path):
        if time.time() - t0 > timeout:
            raise TimeoutError(f"timed out waiting for {path}")
        time.sleep(1)
    logger.debug("found %s after waiting", path)
    return path
