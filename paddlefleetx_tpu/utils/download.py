"""Cached artifact resolution (vocab files, pretrained weights).

Parity: reference ``utils/download.py`` — a retrying cached downloader
where process rank 0 fetches while other ranks spin-wait on the cached
file (:118+). This deployment is zero-egress: resolution covers the
explicit path, the cache directory (``PFX_CACHE_HOME``, default
``~/.cache/paddlefleetx_tpu``), and a same-process rank-0-writes /
others-wait protocol for locally *produced* artifacts; an actual URL
fetch raises with instructions instead of downloading.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from .log import logger

CACHE_HOME = os.environ.get(
    "PFX_CACHE_HOME",
    os.path.join(os.path.expanduser("~"), ".cache", "paddlefleetx_tpu"))


def cached_path(name_or_path: str,
                cache_subdir: str = "") -> Optional[str]:
    """Resolve ``name_or_path`` to a local file: as given, or under
    the cache home. Returns None if absent."""
    if os.path.exists(name_or_path):
        return name_or_path
    candidate = os.path.join(CACHE_HOME, cache_subdir,
                             os.path.basename(name_or_path))
    return candidate if os.path.exists(candidate) else None


def get_weights_path_from_url(url: str, md5sum: Optional[str] = None
                              ) -> str:
    """Reference API surface; zero-egress deployments must pre-stage
    the file into the cache."""
    cached = cached_path(os.path.basename(url), "weights")
    if cached:
        return cached
    raise FileNotFoundError(
        f"{os.path.basename(url)} not found under {CACHE_HOME}/weights "
        f"and downloading is disabled (zero egress). Pre-stage the "
        f"file there (source: {url}).")


def wait_for_file(path: str, producer_rank: bool,
                  produce_fn=None, timeout: float = 3600.0) -> str:
    """Rank-0-produces / others-spin-wait (reference ``download.py``
    main-process gate; also the dataset index-build protocol,
    ``gpt_dataset.py:47-69``)."""
    if producer_rank:
        if not os.path.exists(path) and produce_fn is not None:
            produce_fn()
        return path
    t0 = time.time()
    while not os.path.exists(path):
        if time.time() - t0 > timeout:
            raise TimeoutError(f"timed out waiting for {path}")
        time.sleep(1)
    logger.debug("found %s after waiting", path)
    return path
