"""YAML config system: ``_base_`` inheritance, dotted ``-o`` overrides,
distributed-topology derivation and batch-size algebra.

Behavior parity with reference ``ppfleetx/utils/config.py``:
  - ``parse_config`` (:163-202): single ``_base_`` inheritance with
    recursive dict merge; a child dict carrying ``_inherited_: False``
    replaces its base subtree instead of merging into it.
  - ``override/override_config`` (:248-310): repeated ``-o a.b.2.c=v``
    dotted paths, integer segments index lists, values literal-eval'd.
  - ``process_dist_config`` (:30-65): mp/pp/sharding degrees default to
    1; dp inferred as ``nranks // (mp*pp*sharding)``.
  - ``process_global_configs`` (:68-95): global/local/micro batch-size
    algebra over the dp x sharding dataflow axis.
  - ``process_engine_config`` (:98-117): save cadence defaults,
    ``test_iters = eval_iters * 10``,
    ``accumulate_steps = local_batch_size // micro_batch_size``.

The reference keeps two parallel config paths (hybrid vs auto). Here a
single path serves both: GSPMD partitioning *is* the auto engine, so
``process_auto_strategy`` collapses into the same topology processing.
"""

from __future__ import annotations

import argparse
import ast
import copy
import os
import sys
from typing import Any, Dict, List, Optional

import yaml

from .log import logger, advertise

__all__ = [
    "AttrDict", "parse_config", "override_config", "get_config",
    "process_configs", "parse_args", "print_config", "bf16_enabled",
]


def bf16_enabled(config) -> bool:
    """Single point of truth for the AMP-O2 policy: does this config
    ask for bf16 compute (with fp32 master params)? Model families
    consult this instead of re-sniffing the mix_precision section."""
    mix = (config.get("Engine", {}) or {}).get("mix_precision", {}) or {}
    return bool(mix.get("use_pure_fp16")
                or mix.get("dtype") == "bfloat16")


class AttrDict(dict):
    """Dict with attribute access; missing keys raise AttributeError."""

    def __getattr__(self, key):
        try:
            return self[key]
        except KeyError as e:
            raise AttributeError(key) from e

    def __setattr__(self, key, value):
        self[key] = value

    def __deepcopy__(self, memo):
        out = AttrDict()
        memo[id(self)] = out
        for k, v in self.items():
            out[k] = copy.deepcopy(v, memo)
        return out

def _attrify(obj: Any) -> Any:
    """Recursively convert dicts to AttrDict and literal-eval str leaves."""
    if isinstance(obj, dict):
        return AttrDict({k: _attrify(v) for k, v in obj.items()})
    if isinstance(obj, list):
        return [_attrify(v) for v in obj]
    if isinstance(obj, str):
        try:
            return ast.literal_eval(obj)
        except (ValueError, SyntaxError):
            return obj
    return obj


def _merge(child: Dict, base: Dict) -> Dict:
    """Merge ``child`` over ``base`` recursively (child wins).

    A child subtree with ``_inherited_: False`` replaces the base
    subtree wholesale.
    """
    if child.get("_inherited_", True) is False:
        out = dict(child)
        out.pop("_inherited_")
        return out
    out = dict(base)
    for key, val in child.items():
        if isinstance(val, dict) and isinstance(out.get(key), dict):
            out[key] = _merge(val, out[key])
        else:
            out[key] = val
    out.pop("_inherited_", None)
    return out


def parse_config(cfg_file: str) -> AttrDict:
    """Load a YAML file, resolving ``_base_`` inheritance relative to it."""

    def _load(path: str) -> Dict:
        with open(path, "r", encoding="utf-8") as f:
            dic = yaml.safe_load(f) or {}
        base = dic.pop("_base_", None)
        if base is not None:
            base_dic = _load(os.path.join(os.path.dirname(path), base))
            dic = _merge(dic, base_dic)
        return dic

    def _strip_markers(node):
        if isinstance(node, dict):
            node.pop("_inherited_", None)
            for v in node.values():
                _strip_markers(v)
        return node

    return _attrify(_strip_markers(_load(cfg_file)))


def _coerce(v: str) -> Any:
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def _override(node: Any, keys: List[str], value: str) -> None:
    key: Any = keys[0]
    if isinstance(node, list):
        key = int(key)
        if len(keys) == 1:
            node[key] = _coerce(value)
        else:
            _override(node[key], keys[1:], value)
        return
    if not isinstance(node, dict):
        raise TypeError(f"cannot override into leaf node with key {key!r}")
    if len(keys) == 1:
        if key not in node:
            logger.info("new config field introduced by override: %s", key)
        node[key] = _coerce(value)
    else:
        if key in node and not isinstance(node[key], (dict, list)):
            raise TypeError(
                f"override path descends through scalar {key!r} "
                f"(= {node[key]!r}); refusing to destroy it")
        if key not in node:
            node[key] = AttrDict()
        _override(node[key], keys[1:], value)


def override_config(config: AttrDict,
                    options: Optional[List[str]] = None) -> AttrDict:
    """Apply ``-o dotted.path=value`` overrides in order."""
    for opt in options or []:
        if "=" not in opt:
            raise ValueError(f"override {opt!r} must look like key=value")
        key, value = opt.split("=", 1)
        _override(config, key.split("."), value)
    return config


def _device_count() -> int:
    """World size: explicit env override, else jax.device_count()."""
    env = os.environ.get("PFX_WORLD_SIZE")
    if env:
        return int(env)
    import jax
    return jax.device_count()


def process_dist_config(config: AttrDict, nranks: Optional[int] = None) -> None:
    """Fill in degree defaults and infer dp_degree from the device count."""
    dist = config.setdefault("Distributed", AttrDict())
    nranks = nranks if nranks is not None else _device_count()
    for key in ("mp_degree", "pp_degree"):
        if not dist.get(key):
            dist[key] = 1
    sharding = dist.setdefault("sharding", AttrDict())
    if not sharding.get("sharding_degree"):
        sharding["sharding_degree"] = 1
    sharding.setdefault("sharding_stage", 1)
    sharding.setdefault("sharding_offload", False)
    if not dist.get("cp_degree"):
        dist["cp_degree"] = 1
    other = (dist["mp_degree"] * dist["pp_degree"] * dist["cp_degree"]
             * sharding["sharding_degree"])
    if nranks % other != 0:
        raise ValueError(
            f"device count {nranks} not divisible by "
            f"mp*pp*cp*sharding = {other}")
    if not dist.get("dp_degree"):
        dist["dp_degree"] = nranks // other
    elif dist["dp_degree"] * other != nranks:
        logger.warning(
            "dp_degree %s inconsistent with %s devices "
            "(mp=%s pp=%s sharding=%s); adjusting dp_degree to %s",
            dist["dp_degree"], nranks, dist["mp_degree"], dist["pp_degree"],
            sharding["sharding_degree"], nranks // other)
        dist["dp_degree"] = nranks // other
    dist["world_size"] = nranks


def process_global_configs(config: AttrDict) -> None:
    """Batch-size algebra over the dp x sharding dataflow axis."""
    dist = config["Distributed"]
    dataflow = dist["dp_degree"] * dist["sharding"]["sharding_degree"]
    g = config.setdefault("Global", AttrDict())
    gbs, lbs = g.get("global_batch_size"), g.get("local_batch_size")
    if gbs is None and lbs is None:
        raise ValueError("global_batch_size or local_batch_size must be set")
    if gbs is not None and lbs is not None:
        if gbs != lbs * dataflow:
            raise ValueError(
                f"global_batch_size {gbs} != local_batch_size {lbs} * "
                f"(dp*sharding) {dataflow}")
    elif gbs is not None:
        if gbs % dataflow != 0:
            raise ValueError(
                f"global_batch_size {gbs} not divisible by dp*sharding "
                f"{dataflow}")
        g["local_batch_size"] = gbs // dataflow
    else:
        g["global_batch_size"] = lbs * dataflow
    if not g.get("micro_batch_size"):
        g["micro_batch_size"] = g["local_batch_size"]
    if g["local_batch_size"] % g["micro_batch_size"] != 0:
        raise ValueError(
            f"local_batch_size {g['local_batch_size']} not divisible by "
            f"micro_batch_size {g['micro_batch_size']}")


def process_engine_config(config: AttrDict) -> None:
    """Fill Engine-section defaults (save/load, logging, run limits)
    in place, mirroring the reference's config normalization."""
    engine = config.setdefault("Engine", AttrDict())
    save_load = engine.setdefault("save_load", AttrDict())
    if save_load.get("save_steps") in (None, -1):
        save_load["save_steps"] = sys.maxsize
    if save_load.get("save_epoch") in (None, -1):
        save_load["save_epoch"] = 1
    save_load.setdefault("output_dir", "./output")
    save_load.setdefault("ckpt_dir", None)
    if engine.get("eval_iters") is None:
        engine["eval_iters"] = 10
    if engine.get("test_iters") is None:
        engine["test_iters"] = engine["eval_iters"] * 10
    engine["accumulate_steps"] = (
        config.Global.local_batch_size // config.Global.micro_batch_size)
    mp = engine.setdefault("mix_precision", AttrDict())
    # bf16 replaces fp16+GradScaler on TPU; keep the reference knobs as
    # accepted aliases so reference YAMLs run unchanged.
    # Auto-config schema (reference ``process_auto_strategy``,
    # ``utils/config.py:418-448``): ``level`` o1/o2/o3.
    #   o1 -> selective autocast: params fp32, compute bf16 (the
    #         black/white lists are XLA's problem, accepted+ignored)
    #   o2 -> pure bf16 compute + fp32 master weights (== use_pure_fp16)
    #   o3 -> o2 plus bf16 optimizer moments (reference
    #         use_optimizer_fp16); wired to the optimizer's mu_dtype
    level = mp.get("level")
    if level is not None:
        if level not in ("o0", "o1", "o2", "o3"):
            raise ValueError(
                f"mix_precision.level must be o0/o1/o2/o3, got {level!r}")
        mp.setdefault("use_pure_fp16", level in ("o1", "o2", "o3"))
        if level == "o3":
            opt = config.setdefault("Optimizer", AttrDict())
            opt.setdefault("state_dtype", "bfloat16")
    mp.setdefault("use_pure_fp16", False)
    mp.setdefault("dtype", "bfloat16" if mp.get("use_pure_fp16") else "float32")
    mp.setdefault("scale_loss", 1.0)
    mp.setdefault("custom_black_list", [])
    mp.setdefault("custom_white_list", [])


def process_configs(config: AttrDict, nranks: Optional[int] = None) -> AttrDict:
    process_dist_config(config, nranks=nranks)
    process_global_configs(config)
    process_engine_config(config)
    return config


def get_config(fname: str, overrides: Optional[List[str]] = None,
               show: bool = False, nranks: Optional[int] = None) -> AttrDict:
    if not os.path.exists(fname):
        raise FileNotFoundError(f"config file {fname} does not exist")
    config = parse_config(fname)
    override_config(config, overrides)
    process_configs(config, nranks=nranks)
    if show:
        print_config(config)
    return config


def _print_dict(d: Dict, indent: int = 0) -> None:
    for k in sorted(d.keys(), key=str):
        v = d[k]
        if isinstance(v, dict):
            logger.info("%s%s :", " " * indent, k)
            _print_dict(v, indent + 4)
        elif isinstance(v, list) and v and isinstance(v[0], dict):
            logger.info("%s%s :", " " * indent, k)
            for item in v:
                _print_dict(item, indent + 4)
        else:
            logger.info("%s%s : %s", " " * indent, k, v)
        if isinstance(k, str) and k.isupper():
            logger.info("-" * 60)


def print_config(config: AttrDict) -> None:
    advertise()
    _print_dict(config)


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser("paddlefleetx-tpu")
    parser.add_argument("-c", "--config", required=True, help="config file")
    parser.add_argument(
        "-o", "--override", action="append", default=[],
        help="override config options, e.g. -o Global.seed=1")
    return parser.parse_args(argv)
