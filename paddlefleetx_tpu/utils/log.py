"""Logging with extra TRAIN / EVAL levels and per-step throughput lines.

Behavior parity: reference ``ppfleetx/utils/log.py:30-118`` defines a
logger with custom TRAIN/EVAL levels whose output lines (``loss:``,
``ips:``) are grepped by the TIPC benchmark harness
(``benchmarks/test_tipc/.../run_benchmark.sh:17-21``). We keep the same
level names and line grammar so the same harness works unchanged.
"""

from __future__ import annotations

import logging
import sys
import time
from contextlib import contextmanager

TRAIN = 21
EVAL = 22
IMPORT = 23

logging.addLevelName(TRAIN, "TRAIN")
logging.addLevelName(EVAL, "EVAL")
logging.addLevelName(IMPORT, "IMPORT")

_COLORS = {
    "DEBUG": "\033[36m",      # cyan
    "INFO": "\033[32m",       # green
    "TRAIN": "\033[35m",      # magenta
    "EVAL": "\033[34m",       # blue
    "WARNING": "\033[33m",    # yellow
    "ERROR": "\033[31m",      # red
    "CRITICAL": "\033[31;1m",
}
_RESET = "\033[0m"


class _Formatter(logging.Formatter):
    def __init__(self, use_color: bool):
        super().__init__("[%(asctime)s] [%(levelname)8s] - %(message)s",
                         "%Y-%m-%d %H:%M:%S")
        self._use_color = use_color

    def format(self, record):
        msg = super().format(record)
        if self._use_color:
            color = _COLORS.get(record.levelname)
            if color:
                msg = f"{color}{msg}{_RESET}"
        return msg


class Logger(logging.Logger):
    """`logging.Logger` with `.train()` / `.eval()` convenience levels."""

    def train(self, msg, *args, **kwargs):
        if self.isEnabledFor(TRAIN):
            self._log(TRAIN, msg, args, **kwargs)

    def eval(self, msg, *args, **kwargs):
        if self.isEnabledFor(EVAL):
            self._log(EVAL, msg, args, **kwargs)


def _build_logger() -> Logger:
    logging.setLoggerClass(Logger)
    lg = logging.getLogger("paddlefleetx_tpu")
    logging.setLoggerClass(logging.Logger)
    if not lg.handlers:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(_Formatter(use_color=sys.stdout.isatty()))
        lg.addHandler(handler)
        lg.setLevel(logging.INFO)
        lg.propagate = False
    return lg  # type: ignore[return-value]


logger: Logger = _build_logger()

# -- TIPC line-grammar contract ------------------------------------------
# The benchmark harness greps the TRAIN/EVAL lines for these exact
# ``key:`` tokens (reference run_benchmark.sh:17-21 pipes through
# ``grep ips | awk -F 'ips:' ...``). The regexes pin the grammar so
# tests (tests/test_log_grammar.py) fail loudly if a logging change —
# e.g. a telemetry suffix — breaks the scrape, instead of silently
# zeroing the benchmark dashboards.
TRAIN_LINE_REQUIRED = ("loss:", "avg_batch_cost:", "speed:",
                       "ips_total:", "ips:", "learning rate:")
EVAL_LINE_REQUIRED = ("loss:", "avg_eval_cost:", "speed:")
TRAIN_LINE_RE = (
    r"\[train\] epoch: \d+, batch: \d+, loss: \d+\.\d{9}, "
    r"avg_batch_cost: \d+\.\d{5} sec, speed: \d+\.\d{2} step/s, "
    r"ips_total: \d+ tokens/s, ips: \d+ tokens/s, "
    r"learning rate: \d\.\d{5}e[+-]\d+")
EVAL_LINE_RE = (
    r"\[eval\] epoch: \d+, batch: \d+, loss: \d+\.\d{9}, "
    r"avg_eval_cost: \d+\.\d{5} sec, speed: \d+\.\d{2} step/s")


@contextmanager
def timed(name: str):
    """Log wall-clock time of a block at INFO level."""
    start = time.perf_counter()
    yield
    logger.info("%s took %.3fs", name, time.perf_counter() - start)


def advertise():
    banner = r"""
=======================================================================
    PaddleFleetX-TPU  —  TPU-native large-model training (JAX/XLA)
=======================================================================
"""
    logger.info(banner)
