"""Version info (reference ``utils/version.py``)."""

__version__ = "0.1.0"


def show() -> str:
    import jax
    line = (f"paddlefleetx_tpu {__version__} | jax {jax.__version__} | "
            f"backend {jax.default_backend()}")
    return line
