"""Environment/config sanity checks.

Parity: reference ``utils/check.py`` (GPU-version and config checks);
here the checks are TPU/JAX-shaped: device availability, topology vs
device count, batch-size algebra.
"""

from __future__ import annotations

from .log import logger


def check_device(expected: str = None) -> str:
    import jax
    platform = jax.devices()[0].platform
    if expected and expected not in ("gpu", platform):
        # reference configs say "gpu"; on this stack that means
        # "the accelerator" — only warn on real mismatches
        logger.warning("config requests device %r but jax is running "
                       "on %r", expected, platform)
    return platform


def check_config(config) -> None:
    """Cross-field invariants the reference asserts during
    ``process_configs`` (utils/config.py:54,95)."""
    import jax
    glob = config.get("Global", {})
    dist = config.get("Distributed", {})
    world = dist.get("world_size") or jax.device_count()
    lbs = glob.get("local_batch_size")
    mbs = glob.get("micro_batch_size")
    if lbs and mbs and lbs % mbs != 0:
        raise ValueError(
            f"local_batch_size {lbs} not divisible by "
            f"micro_batch_size {mbs}")
    degrees = [dist.get("mp_degree") or 1, dist.get("pp_degree") or 1,
               dist.get("cp_degree") or 1,
               (dist.get("sharding") or {}).get("sharding_degree") or 1,
               dist.get("dp_degree") or 1]
    prod = 1
    for d in degrees:
        prod *= d
    if prod != world:
        raise ValueError(
            f"topology product {prod} != world size {world}")
