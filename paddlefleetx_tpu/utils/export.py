"""AOT model export: the TPU-native replacement for the reference's
dygraph-to-static pipeline.

The reference exports with ``paddle.jit.to_static`` + program pruning
(reference ``utils/export.py:27-59``) into per-rank
``rank_{i}/model.pdmodel|pdiparams`` dirs consumed by the
``paddle.inference`` runtime (``core/engine/inference_engine.py``).
Here the jitted function itself is the deployable artifact: the traced
computation is serialized with ``jax.export`` (StableHLO, weights NOT
baked in), parameters are saved as an Orbax checkpoint next to it, and
a ``spec.json`` records the input signature. The artifact is
topology-portable — one directory regardless of the training mesh,
unlike the reference's per-rank dirs.

Layout::

    <dir>/model.jaxexport   serialized StableHLO computation
    <dir>/params/           Orbax checkpoint of the parameter pytree
    <dir>/spec.json         input shapes/dtypes + metadata
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Sequence, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from .log import logger

_MODEL_FILE = "model.jaxexport"
_SPEC_FILE = "spec.json"
_PARAMS_DIR = "params"


def export_inference_model(fn: Callable, params,
                           input_spec: Sequence[Tuple[Sequence, str]],
                           output_dir: str,
                           metadata: Dict[str, Any] = None) -> str:
    """Serialize ``fn(params, *inputs)`` + ``params`` to ``output_dir``.

    ``input_spec`` is the module contract's ``[(shape, dtype), ...]``
    (None dims become 1 — the exported program has static shapes).
    """
    os.makedirs(output_dir, exist_ok=True)
    abstract_inputs = [
        jax.ShapeDtypeStruct(
            tuple(1 if d is None else int(d) for d in shape),
            jax.numpy.dtype(dtype))
        for shape, dtype in input_spec]
    abstract_params = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params)
    exported = jax.export.export(jax.jit(fn))(
        abstract_params, *abstract_inputs)
    with open(os.path.join(output_dir, _MODEL_FILE), "wb") as f:
        f.write(exported.serialize())

    params_path = os.path.abspath(os.path.join(output_dir, _PARAMS_DIR))
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        ckptr.save(params_path, jax.device_get(params), force=True)

    spec = {
        "inputs": [[list(s.shape), s.dtype.name] for s in abstract_inputs],
        "metadata": metadata or {},
    }
    with open(os.path.join(output_dir, _SPEC_FILE), "w") as f:
        json.dump(spec, f, indent=2)
    logger.info("exported inference model to %s", output_dir)
    return output_dir


def load_inference_model(model_dir: str):
    """Returns ``(call, params, spec)``; ``call(params, *inputs)``
    executes the deserialized computation on the current backend."""
    with open(os.path.join(model_dir, _MODEL_FILE), "rb") as f:
        exported = jax.export.deserialize(f.read())
    params_path = os.path.abspath(os.path.join(model_dir, _PARAMS_DIR))
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        params = ckptr.restore(params_path)
    with open(os.path.join(model_dir, _SPEC_FILE)) as f:
        spec = json.load(f)

    def call(p, *inputs):
        return exported.call(p, *inputs)

    return call, params, spec


def pad_to_spec(arrays: List[np.ndarray], spec: Dict[str, Any],
                pad_values: Sequence[float],
                pad_sides: Sequence[str] = None) -> List[np.ndarray]:
    """Pad each input up to the exported static shape (the exported
    program cannot accept smaller batches/sequences).

    ``pad_sides[i]`` is "right" (default) or "left"; left applies to
    the LAST axis only (the sequence axis — generation prompts must be
    left-padded so the final slot holds the last real token, matching
    ``generate()``'s contract). Batch and leading axes always pad
    right.
    """
    out = []
    sides = pad_sides or ["right"] * len(arrays)
    for arr, (shape, dtype), pad, side in zip(arrays, spec["inputs"],
                                              pad_values, sides):
        arr = np.asarray(arr)
        if list(arr.shape) == shape:
            out.append(arr.astype(dtype))
            continue
        if arr.ndim != len(shape) or any(
                a > s for a, s in zip(arr.shape, shape)):
            raise ValueError(
                f"input shape {arr.shape} incompatible with exported "
                f"spec {shape}")
        widths = [(0, s - a) for a, s in zip(arr.shape, shape)]
        if side == "left" and arr.ndim >= 1:
            widths[-1] = (widths[-1][1], 0)
        out.append(np.pad(arr, widths,
                          constant_values=pad).astype(dtype))
    return out
