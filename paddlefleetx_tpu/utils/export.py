"""AOT model export: the TPU-native replacement for the reference's
dygraph-to-static pipeline.

The reference exports with ``paddle.jit.to_static`` + program pruning
(reference ``utils/export.py:27-59``) into per-rank
``rank_{i}/model.pdmodel|pdiparams`` dirs consumed by the
``paddle.inference`` runtime (``core/engine/inference_engine.py``).
Here the jitted function itself is the deployable artifact: the traced
computation is serialized with ``jax.export`` (StableHLO, weights NOT
baked in), parameters are saved as an Orbax checkpoint next to it, and
a ``spec.json`` records the input signature. The artifact is
topology-portable — one directory regardless of the training mesh,
unlike the reference's per-rank dirs.

Layout::

    <dir>/model.jaxexport   serialized StableHLO computation
    <dir>/params/           Orbax checkpoint of the parameter pytree
    <dir>/spec.json         input shapes/dtypes + metadata
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Sequence, Tuple

import jax
# on jax 0.4.x the export module exists but is not re-exported as a
# lazy `jax.export` attribute — the explicit submodule import binds it
import jax.export
import numpy as np
import orbax.checkpoint as ocp

from .log import logger

_MODEL_FILE = "model.jaxexport"
_SPEC_FILE = "spec.json"
_PARAMS_DIR = "params"


def _symbolic_abstract_inputs(input_spec):
    """``None`` dims become symbolic dimensions (shape polymorphism):
    the artifact then serves ANY size on those axes — the reference's
    ``InputSpec(shape=[None, ...])`` dynamic-batch semantics.

    ``None`` dims at the SAME axis index share one symbol across
    inputs: tokens+mask both shaped ``(None, s)`` trace as
    ``(b, s), (b, s)`` — distinct symbols would make their equality
    comparisons inconclusive and kill the symbolic export for every
    multi-input model (batch/sequence axes are shared in practice;
    the constraint is also enforced at call time, where it catches
    mismatched inputs early). Returns None when no dim is dynamic."""
    if not any(d is None for shape, _ in input_spec for d in shape):
        return None
    scope = jax.export.SymbolicScope()
    out = []
    for shape, dtype in input_spec:
        dims = [f"d{i}" if d is None else str(int(d))
                for i, d in enumerate(shape)]
        out.append(jax.ShapeDtypeStruct(
            jax.export.symbolic_shape(",".join(dims), scope=scope),
            jax.numpy.dtype(dtype)))
    return out


def export_inference_model(fn: Callable, params,
                           input_spec: Sequence[Tuple[Sequence, str]],
                           output_dir: str,
                           metadata: Dict[str, Any] = None) -> str:
    """Serialize ``fn(params, *inputs)`` + ``params`` to ``output_dir``.

    ``input_spec`` is the module contract's ``[(shape, dtype), ...]``.
    ``None`` dims export as SYMBOLIC dimensions where the traced
    computation allows it (plain forwards do; value-dependent loops
    like the generation scan may not) — the served artifact then
    accepts any size on those axes. When symbolic tracing fails — or
    for partitioned artifacts, where jax.export's polymorphism does
    not compose with baked shardings — ``None`` dims are concretized
    to 1 and the runtime pads to spec (``pad_to_spec``).
    """
    os.makedirs(output_dir, exist_ok=True)
    abstract_params = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params)
    exported = None
    dynamic_dims: List[List[int]] = []
    # partitioned params (any leaf actually SPLIT across devices —
    # dp-replicated leaves live on many devices but are not split;
    # the same replication-aware predicate engine.export uses to pick
    # its export mesh): jax export polymorphism does not compose with
    # baked shardings — derived from the params themselves, not a
    # caller convention
    def _split(x):
        s = getattr(x, "sharding", None)
        return (s is not None
                and getattr(s, "num_devices", 1) > 1
                and not s.is_fully_replicated)

    partitioned = any(_split(x) for x in jax.tree.leaves(params))
    has_dynamic = any(d is None for shape, _ in input_spec
                      for d in shape)
    symbolic = _symbolic_abstract_inputs(input_spec) \
        if has_dynamic and not partitioned else None
    if partitioned and has_dynamic:
        logger.warning(
            "partitioned export: dynamic (None) input dims are baked "
            "to 1 (jax export polymorphism does not compose with "
            "baked shardings); the artifact pads to spec instead of "
            "accepting any size")
    if symbolic is not None:
        try:
            exported = jax.export.export(jax.jit(fn))(
                abstract_params, *symbolic)
            dynamic_dims = [
                [i for i, d in enumerate(shape) if d is None]
                for shape, _ in input_spec]
        except Exception as e:
            # a capability downgrade of the shipped artifact (it will
            # only accept the concretized sizes) — say so loudly
            logger.warning(
                "symbolic-shape export unsupported for this function; "
                "baking dynamic dims to 1 (the artifact pads to spec "
                "instead of accepting any size). %s: %s",
                type(e).__name__, e)
    abstract_inputs = [
        jax.ShapeDtypeStruct(
            tuple(1 if d is None else int(d) for d in shape),
            jax.numpy.dtype(dtype))
        for shape, dtype in input_spec]
    if exported is None:
        exported = jax.export.export(jax.jit(fn))(
            abstract_params, *abstract_inputs)
        dynamic_dims = [[] for _ in input_spec]
    with open(os.path.join(output_dir, _MODEL_FILE), "wb") as f:
        f.write(exported.serialize())

    params_path = os.path.abspath(os.path.join(output_dir, _PARAMS_DIR))
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        ckptr.save(params_path, jax.device_get(params), force=True)

    spec = {
        # dynamic axes record null: the runtime accepts any size there
        "inputs": [
            [[None if i in dyn else int(d)
              for i, d in enumerate(s.shape)], s.dtype.name]
            for s, dyn in zip(abstract_inputs, dynamic_dims)],
        "metadata": metadata or {},
    }
    with open(os.path.join(output_dir, _SPEC_FILE), "w") as f:
        json.dump(spec, f, indent=2)
    logger.info("exported inference model to %s", output_dir)
    return output_dir


def serialize_param_specs(shardings) -> Dict[str, list]:
    """Flatten a params-tree of ``NamedSharding``s (or
    ``PartitionSpec``s) to ``{"a/b/c": [None, "mp", ["dp", "fsdp"]]}``
    — JSON-able, mesh-free; :func:`deserialize_param_specs` rebuilds
    ``NamedSharding``s against the *loader's* mesh."""
    import jax.sharding as js

    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(shardings)[0]:
        key = "/".join(str(getattr(p, "key", p)) for p in path)
        spec = leaf.spec if isinstance(leaf, js.NamedSharding) else leaf
        flat[key] = [list(e) if isinstance(e, tuple) else e
                     for e in tuple(spec)]
    return flat


def deserialize_param_specs(flat: Dict[str, list], params, mesh):
    """``{"a/b/c": serialized spec}`` -> params-shaped tree of
    ``NamedSharding`` on ``mesh`` (replicated for paths the artifact
    does not list)."""
    import jax.sharding as js
    P = js.PartitionSpec

    def build(path, _leaf):
        key = "/".join(str(getattr(p, "key", p)) for p in path)
        entries = flat.get(key)
        if entries is None:
            return js.NamedSharding(mesh, P())
        return js.NamedSharding(mesh, P(*[
            tuple(e) if isinstance(e, list) else e for e in entries]))

    return jax.tree_util.tree_map_with_path(build, params)


def load_spec(model_dir: str) -> Dict[str, Any]:
    """The artifact's ``spec.json`` (input shapes + metadata) alone —
    cheap; callers use it to resolve a mesh BEFORE loading weights."""
    with open(os.path.join(model_dir, _SPEC_FILE)) as f:
        return json.load(f)


def load_inference_model(model_dir: str, mesh=None):
    """Returns ``(call, params, spec)``; ``call(params, *inputs)``
    executes the deserialized computation on the current backend.

    With ``mesh`` and a spec that records ``param_specs``, each
    parameter is restored DIRECTLY into its ``NamedSharding`` (Orbax
    sharded read) — a model that only fits partitioned must never
    materialize whole in host RAM just to be re-sharded."""
    with open(os.path.join(model_dir, _MODEL_FILE), "rb") as f:
        exported = jax.export.deserialize(f.read())
    spec = load_spec(model_dir)
    params_path = os.path.abspath(os.path.join(model_dir, _PARAMS_DIR))
    flat_specs = spec["metadata"].get("param_specs")
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        if mesh is not None and flat_specs:
            meta = ckptr.metadata(params_path)
            # newer orbax wraps the metadata tree; 0.7.x returns the
            # pytree of ArrayMetadata (with .shape/.dtype) directly
            meta_tree = getattr(
                getattr(meta, "item_metadata", None), "tree", meta)
            shardings = deserialize_param_specs(flat_specs, meta_tree,
                                                mesh)
            abstract = jax.tree.map(
                lambda m, s: jax.ShapeDtypeStruct(m.shape, m.dtype,
                                                  sharding=s),
                meta_tree, shardings)
            params = ckptr.restore(
                params_path, args=ocp.args.StandardRestore(abstract))
        else:
            params = ckptr.restore(params_path)

    def call(p, *inputs):
        return exported.call(p, *inputs)

    return call, params, spec


def pad_to_spec(arrays: List[np.ndarray], spec: Dict[str, Any],
                pad_values: Sequence[float],
                pad_sides: Sequence[str] = None) -> List[np.ndarray]:
    """Pad each input up to the exported static shape (the exported
    program cannot accept smaller batches/sequences).

    ``pad_sides[i]`` is "right" (default) or "left"; left applies to
    the LAST axis only (the sequence axis — generation prompts must be
    left-padded so the final slot holds the last real token, matching
    ``generate()``'s contract). Batch and leading axes always pad
    right.
    """
    out = []
    sides = pad_sides or ["right"] * len(arrays)
    for arr, (shape, dtype), pad, side in zip(arrays, spec["inputs"],
                                              pad_values, sides):
        arr = np.asarray(arr)
        # None = symbolic (dynamic) axis: any size passes through
        target = [a if s is None else s
                  for a, s in zip(arr.shape, shape)] \
            if arr.ndim == len(shape) else shape
        if list(arr.shape) == target:
            out.append(arr.astype(dtype))
            continue
        if arr.ndim != len(shape) or any(
                a > s for a, s in zip(arr.shape, target)):
            raise ValueError(
                f"input shape {arr.shape} incompatible with exported "
                f"spec {shape}")
        widths = [(0, s - a) for a, s in zip(arr.shape, target)]
        if side == "left" and arr.ndim >= 1:
            widths[-1] = (widths[-1][1], 0)
        out.append(np.pad(arr, widths,
                          constant_values=pad).astype(dtype))
    return out
