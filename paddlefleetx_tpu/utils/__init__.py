"""Utils subpackage."""
from .config import (  # noqa: F401
    AttrDict, get_config, parse_config, override_config, process_configs,
    parse_args, print_config,
)
from .log import logger  # noqa: F401
from . import env  # noqa: F401
