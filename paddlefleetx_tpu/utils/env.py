"""Distributed environment init and the seed/RNG policy.

Parity with reference ``ppfleetx/utils/env.py``:
  - ``set_seed`` (:27-46): python/numpy seeds offset by the dataflow
    (dp x sharding) rank; a *global* dropout stream shared across mp
    ranks and a *local* stream offset by ``mp_rank*10 + pp_rank*1000``.
    On TPU the same guarantees come from ``jax.random`` key folding:
    dropout on TP-sharded activations is computed from one global key
    (so it is replicated-consistent by construction under GSPMD), and
    per-shard streams are derived with ``fold_in``.
  - ``init_dist_env`` (:49-69): builds the communicate topology; here
    that is mesh construction (see ``parallel.mesh``) plus optional
    ``jax.distributed.initialize`` for multi-host pods.
"""

from __future__ import annotations

import os
import random
from typing import Optional

import jax
import numpy as np

from .log import logger

GLOBAL_STREAM = "global_seed"
LOCAL_STREAM = "local_seed"


def init_dist_env(coordinator: Optional[str] = None,
                  num_processes: Optional[int] = None,
                  process_id: Optional[int] = None) -> None:
    """Initialize multi-host JAX if launched as part of a pod.

    Single-process runs (one host owning all chips, or CPU tests) need
    no rendezvous. On Cloud TPU pods ``jax.distributed.initialize()``
    auto-discovers peers from the metadata server.
    """
    if num_processes is None and os.environ.get("PFX_NUM_PROCESSES"):
        num_processes = int(os.environ["PFX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("PFX_PROCESS_ID"):
        process_id = int(os.environ["PFX_PROCESS_ID"])
    if num_processes is not None and num_processes > 1 or \
            os.environ.get("PFX_COORDINATOR") or coordinator:
        jax.distributed.initialize(
            coordinator_address=coordinator or os.environ.get(
                "PFX_COORDINATOR"),
            num_processes=num_processes, process_id=process_id)
        logger.info("jax.distributed initialized: process %d / %d",
                    jax.process_index(), jax.process_count())


def setup_compilation_cache(cache_dir: Optional[str]) -> None:
    """Point JAX's persistent compilation cache at ``cache_dir``
    (``Global.compilation_cache_dir``). TPU-native concern with no
    reference analogue: XLA compiles of big jitted train steps take
    minutes, and preempted-and-restarted jobs (see
    ``Engine.save_on_preemption``) would pay them again on every
    restart — with the cache on shared storage they are skipped.
    """
    if not cache_dir:
        return
    cache_dir = os.path.abspath(os.path.expanduser(cache_dir))
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache every program: the default thresholds skip fast compiles,
    # but a restart replays *all* of them, so small entries pay too
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    logger.info("persistent compilation cache at %s", cache_dir)


def set_seed(seed: int, data_rank: int = 0) -> jax.Array:
    """Seed host RNGs (offset by dataflow rank) and return the root key.

    The returned key is the single source of device-side randomness;
    the engine folds in step counts and stream names from it.
    """
    random.seed(seed + data_rank)
    np.random.seed(seed + data_rank)
    return jax.random.key(seed + data_rank)


def local_stream_key(root: jax.Array, mp_rank: int = 0,
                     pp_rank: int = 0) -> jax.Array:
    """Per-shard dropout stream, mirroring ``seed+123+mp*10+pp*1000``."""
    return jax.random.fold_in(root, 123 + mp_rank * 10 + pp_rank * 1000)


def get_local_rank() -> int:
    return jax.process_index()


def device_kind() -> str:
    return jax.devices()[0].device_kind


def is_tpu() -> bool:
    return jax.devices()[0].platform == "tpu"
