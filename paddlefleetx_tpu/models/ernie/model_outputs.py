"""Typed ERNIE model outputs (reference ``ernie/model_outputs.py``).

The reference ships HF-style ``ModelOutput`` dataclasses with optional
``hidden_states``/``attentions`` plumbing (reference
``model_outputs.py:229-627``). TPU-first differences:

- each class is a ``flax.struct.dataclass`` — a registered JAX pytree,
  so a jitted forward can return it directly (the reference's
  ``OrderedDict`` subclass with ``__post_init__`` reflection is a
  Python-side construct XLA could not trace through);
- optional fields are plain ``None`` when not requested (the pytree
  just has no leaves there), so ``jax.jit`` sees a different static
  structure per flag combination — which is exactly the XLA-friendly
  behavior: each requested output set compiles once;
- no ``past_key_values``/``cross_attentions`` content: ERNIE here is a
  pure encoder (the reference carries those fields from its
  transformers vendoring but its encoder never populates them); the
  fields exist for API parity and stay ``None``.

``to_tuple()`` matches the reference's tuple forms: non-``None``
fields in declaration order.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.struct

Array = Any
ArrayTuple = Tuple[Array, ...]


class _OutputMixin:
    def to_tuple(self) -> tuple:
        """Non-None fields in declaration order (the reference's
        ``ModelOutput.to_tuple`` contract)."""
        return tuple(getattr(self, f.name)
                     for f in self.__dataclass_fields__.values()
                     if getattr(self, f.name) is not None)

    def __getitem__(self, k):
        if isinstance(k, str):
            v = getattr(self, k)
            if v is None:
                raise KeyError(k)
            return v
        return self.to_tuple()[k]

    def keys(self):
        return [f for f in self.__dataclass_fields__
                if getattr(self, f) is not None]


@flax.struct.dataclass
class BaseModelOutputWithPoolingAndCrossAttentions(_OutputMixin):
    """``ErnieModel`` output (reference ``model_outputs.py:388-435``)."""
    last_hidden_state: Array = None
    pooler_output: Array = None
    past_key_values: Optional[ArrayTuple] = None
    hidden_states: Optional[ArrayTuple] = None
    attentions: Optional[ArrayTuple] = None
    cross_attentions: Optional[ArrayTuple] = None


@flax.struct.dataclass
class ErnieForPreTrainingOutput(_OutputMixin):
    """``ErnieForPretraining`` output. The reference declares this
    shape but its ``return_dict=True`` branch is commented out
    (``single_model.py:610-622`` falls through and returns ``None``);
    here it works."""
    loss: Optional[Array] = None
    prediction_logits: Array = None
    seq_relationship_logits: Array = None
    hidden_states: Optional[ArrayTuple] = None
    attentions: Optional[ArrayTuple] = None


@flax.struct.dataclass
class MaskedLMOutput(_OutputMixin):
    """``ErnieForMaskedLM`` output (reference :558-585)."""
    loss: Optional[Array] = None
    logits: Array = None
    hidden_states: Optional[ArrayTuple] = None
    attentions: Optional[ArrayTuple] = None


@flax.struct.dataclass
class MultipleChoiceModelOutput(_OutputMixin):
    """``ErnieForMultipleChoice`` output (reference :527-556)."""
    loss: Optional[Array] = None
    logits: Array = None
    hidden_states: Optional[ArrayTuple] = None
    attentions: Optional[ArrayTuple] = None


@flax.struct.dataclass
class SequenceClassifierOutput(_OutputMixin):
    """Reference :437-464 (declared for downstream heads)."""
    loss: Optional[Array] = None
    logits: Array = None
    hidden_states: Optional[ArrayTuple] = None
    attentions: Optional[ArrayTuple] = None


@flax.struct.dataclass
class TokenClassifierOutput(_OutputMixin):
    """Reference :466-493 (declared for downstream heads)."""
    loss: Optional[Array] = None
    logits: Array = None
    hidden_states: Optional[ArrayTuple] = None
    attentions: Optional[ArrayTuple] = None


@flax.struct.dataclass
class QuestionAnsweringModelOutput(_OutputMixin):
    """Reference :495-525 (declared for downstream heads)."""
    loss: Optional[Array] = None
    start_logits: Array = None
    end_logits: Array = None
    hidden_states: Optional[ArrayTuple] = None
    attentions: Optional[ArrayTuple] = None
