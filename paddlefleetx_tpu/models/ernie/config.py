"""ERNIE hyper-parameter container.

Field names and defaults follow the reference's ``ErnieModel``
constructor (reference ``ernie/single_model.py:193-238``): 12 post-LN
encoder layers, hidden 768, intermediate 3072, gelu, learned
word/position/token-type embeddings with pad_token_id 0, optional
task-type embeddings.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class ErnieConfig:
    """Static (hashable) ERNIE architecture hyperparameters."""

    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: Optional[int] = None
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    pad_token_id: int = 0
    task_type_vocab_size: int = 3
    task_id: int = 0
    use_task_id: bool = False
    use_recompute: bool = False
    # MLM objective knobs (the module's dynamic masking; reference
    # BERT/ERNIE semantics — see modules.ErnieModule)
    masked_lm_prob: float = 0.15
    mask_token_id: Optional[int] = None    # default: vocab_size - 1
    with_nsp_loss: bool = False            # reference ErnieModule uses False
    # TPU-specific knobs (absent in reference):
    scan_layers: bool = True
    use_flash_attention: bool = False
    dtype: str = "float32"
    param_dtype: str = "float32"

    def __post_init__(self):
        if self.intermediate_size is None:
            object.__setattr__(self, "intermediate_size",
                               4 * self.hidden_size)
        if self.mask_token_id is None:
            object.__setattr__(self, "mask_token_id", self.vocab_size - 1)
        if self.hidden_size % self.num_attention_heads != 0:
            raise ValueError(
                f"num_attention_heads ({self.num_attention_heads}) must "
                f"divide hidden_size ({self.hidden_size})")

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def from_config(cls, config) -> "ErnieConfig":
        """Build from a parsed YAML tree (Model + Engine sections)."""
        model = dict(config.get("Model", {}))
        # YAML may use the GPT-style spelling
        if "num_layers" in model and "num_hidden_layers" not in model:
            model["num_hidden_layers"] = model.pop("num_layers")
        if "ffn_hidden_size" in model and "intermediate_size" not in model:
            model["intermediate_size"] = model.pop("ffn_hidden_size")
        from ...utils.config import bf16_enabled
        fields = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in model.items()
                  if k in fields and v is not None}
        if bf16_enabled(config):
            kwargs.setdefault("dtype", "bfloat16")
        return cls(**kwargs)
