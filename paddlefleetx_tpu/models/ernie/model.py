"""TPU-native ERNIE: sharding-annotated bidirectional encoder LM.

Behavior parity with the reference encoder stack
(``ernie/single_model.py``):
  - embeddings = word + position + token-type (+ optional task-type),
    then LayerNorm and dropout (:37-118; the snapshot's ``forward``
    short-circuits after the word lookup — clearly a leftover debug
    ``return`` — so this implements the constructor's documented sum)
  - post-LN encoder blocks (``normalize_before=False``, :226-236):
    ``x = LN(x + attn(x)); x = LN(x + ffn(x))``, erf-gelu, no
    activation dropout
  - pooler = dense + tanh over the first token (:120-133)
  - MLM head: dense transform + act + LN, decoder matmul against the
    tied word-embedding table plus a vocab bias (:419-459)
  - NSP head: dense ``hidden -> 2`` over the pooled output (:461-481)
  - criterion: masked-LM CE (ignore_index -1) + optional NSP CE
    (:640-694)
  - task heads for API parity: ``ErnieForMaskedLM`` (:710-» ) and
    ``ErnieForMultipleChoice`` (:845-»)

Same TPU-first choices as the GPT model: logical-axis annotations on
every weight so one definition serves every topology, ``nn.scan`` over
layers, fp32 softmax/criterion under bf16 compute.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...ops.attention import dot_product_attention
from ...parallel.sharding import with_logical_constraint
from .config import ErnieConfig


def _init(cfg: ErnieConfig):
    # the reference uses TruncatedNormal(std=initializer_range)
    return nn.initializers.truncated_normal(stddev=cfg.initializer_range)


def _act(name: str):
    if name == "gelu":
        return lambda x: nn.gelu(x, approximate=False)
    return getattr(nn, name)


def _ln(cfg: ErnieConfig, name: str) -> nn.LayerNorm:
    return nn.LayerNorm(
        epsilon=1e-5, dtype=jnp.dtype(cfg.dtype),
        param_dtype=jnp.dtype(cfg.param_dtype), name=name,
        scale_init=nn.with_logical_partitioning(
            nn.initializers.ones_init(), ("norm",)),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros_init(), ("norm",)))


def _dense(cfg: ErnieConfig, features, name: str, in_axes, out_axes,
           axis=-1):
    return nn.DenseGeneral(
        features, axis=axis, name=name, dtype=jnp.dtype(cfg.dtype),
        param_dtype=jnp.dtype(cfg.param_dtype),
        kernel_init=nn.with_logical_partitioning(
            _init(cfg), in_axes + out_axes),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros_init(), out_axes))


class ErnieEmbeddings(nn.Module):
    """Word + position + token-type (+ task-type) embeddings, LN,
    dropout (reference ``single_model.py:37-118``)."""
    config: ErnieConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, position_ids=None,
                 task_type_ids=None, deterministic: bool = True):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        word_emb = self.param(
            "word_embeddings",
            nn.with_logical_partitioning(_init(cfg), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), jnp.dtype(cfg.param_dtype))
        pos_emb = self.param(
            "position_embeddings",
            nn.with_logical_partitioning(_init(cfg), ("pos", "embed")),
            (cfg.max_position_embeddings, cfg.hidden_size),
            jnp.dtype(cfg.param_dtype))
        if position_ids is None:
            position_ids = jnp.broadcast_to(
                jnp.arange(input_ids.shape[-1], dtype=jnp.int32)[None, :],
                input_ids.shape)
        x = jnp.take(word_emb, input_ids, axis=0).astype(dtype) + \
            jnp.take(pos_emb, position_ids, axis=0).astype(dtype)

        if cfg.type_vocab_size > 0:
            type_emb = self.param(
                "token_type_embeddings",
                nn.with_logical_partitioning(_init(cfg), (None, "embed")),
                (cfg.type_vocab_size, cfg.hidden_size),
                jnp.dtype(cfg.param_dtype))
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + jnp.take(type_emb, token_type_ids, axis=0).astype(dtype)
        if cfg.use_task_id:
            task_emb = self.param(
                "task_type_embeddings",
                nn.with_logical_partitioning(_init(cfg), (None, "embed")),
                (cfg.task_type_vocab_size, cfg.hidden_size),
                jnp.dtype(cfg.param_dtype))
            if task_type_ids is None:
                task_type_ids = jnp.full_like(input_ids, cfg.task_id)
            x = x + jnp.take(task_emb, task_type_ids, axis=0).astype(dtype)

        x = _ln(cfg, "layer_norm")(x)
        x = nn.Dropout(cfg.hidden_dropout_prob)(
            x, deterministic=deterministic)
        return with_logical_constraint(x, ("batch", "seq", "act_embed"))


class ErnieSelfAttention(nn.Module):
    """Bidirectional multi-head attention with an additive mask.

    ``output_attentions`` (reference ``single_model.py:256``) returns
    the post-softmax probabilities alongside the output; that path
    computes attention densely (the flash kernel never materializes
    probabilities — asking for them IS asking for the dense
    [b, h, s, s] tensor)."""
    config: ErnieConfig
    output_attentions: bool = False

    @nn.compact
    def __call__(self, x, attn_bias=None, deterministic: bool = True):
        cfg = self.config
        nh, hd = cfg.num_attention_heads, cfg.head_dim
        q = _dense(cfg, (nh, hd), "q_proj", ("embed",), ("heads", "kv"))(x)
        k = _dense(cfg, (nh, hd), "k_proj", ("embed",), ("heads", "kv"))(x)
        v = _dense(cfg, (nh, hd), "v_proj", ("embed",), ("heads", "kv"))(x)
        q, k, v = (with_logical_constraint(
            t, ("batch", None, "act_heads", None)) for t in (q, k, v))
        dropout_rng = None
        if cfg.attention_probs_dropout_prob > 0.0 and not deterministic:
            dropout_rng = self.make_rng("dropout")
        probs = None
        if self.output_attentions:
            scores = jnp.einsum(
                "bqhd,bkhd->bhqk", q, k).astype(jnp.float32) \
                / jnp.sqrt(jnp.float32(hd))
            if attn_bias is not None:
                scores = scores + attn_bias.astype(jnp.float32)
            probs = jax.nn.softmax(scores, axis=-1)
            weights = probs.astype(v.dtype)
            if dropout_rng is not None:
                keep = jax.random.bernoulli(
                    dropout_rng, 1.0 - cfg.attention_probs_dropout_prob,
                    weights.shape)
                weights = jnp.where(
                    keep, weights / (1.0 - cfg.attention_probs_dropout_prob),
                    0.0).astype(v.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
        else:
            out = dot_product_attention(
                q, k, v, bias=attn_bias, causal=False,
                dropout_rate=cfg.attention_probs_dropout_prob,
                dropout_rng=dropout_rng, deterministic=deterministic,
                use_flash=cfg.use_flash_attention)
        out = nn.DenseGeneral(
            cfg.hidden_size, axis=(-2, -1), name="out_proj",
            dtype=jnp.dtype(cfg.dtype),
            param_dtype=jnp.dtype(cfg.param_dtype),
            kernel_init=nn.with_logical_partitioning(
                _init(cfg), ("heads", "kv", "embed")),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("embed",)))(out)
        return out, probs


class ErnieEncoderLayer(nn.Module):
    """Post-LN encoder block (``normalize_before=False``, reference
    ``single_model.py:226-236``).

    ``collect_hidden``/``output_attentions`` are STATIC module fields
    (not call args) so they survive ``nn.scan``/``nn.remat`` without
    touching the traced signature; the scanned form emits per-layer
    ``(hidden?, attention?)`` as scan ys, which the model splits into
    the reference's tuples.

    Return type (non-scanned): a bare ``[b, s, h]`` array — the
    original public contract — unless ``output_attentions=True``, in
    which case ``(x, probs)`` (opt-in, so existing callers are
    unaffected)."""
    config: ErnieConfig
    scanned: bool = False
    collect_hidden: bool = False
    output_attentions: bool = False

    @nn.compact
    def __call__(self, x, attn_bias=None, deterministic: bool = True):
        cfg = self.config
        y, probs = ErnieSelfAttention(
            cfg, name="self_attn",
            output_attentions=self.output_attentions)(
            x, attn_bias, deterministic)
        y = nn.Dropout(cfg.hidden_dropout_prob, name="dropout1")(
            y, deterministic=deterministic)
        x = _ln(cfg, "norm1")(x + y)
        x = with_logical_constraint(x, ("batch", "seq", "act_embed"))

        y = _dense(cfg, cfg.intermediate_size, "linear1",
                   ("embed",), ("mlp",))(x)
        y = _act(cfg.hidden_act)(y)
        y = with_logical_constraint(y, ("batch", None, "act_mlp"))
        y = _dense(cfg, cfg.hidden_size, "linear2", ("mlp",), ("embed",))(y)
        y = nn.Dropout(cfg.hidden_dropout_prob, name="dropout2")(
            y, deterministic=deterministic)
        x = _ln(cfg, "norm2")(x + y)
        x = with_logical_constraint(x, ("batch", "seq", "act_embed"))
        if self.scanned:
            return x, (x if self.collect_hidden else None, probs)
        if self.output_attentions:
            return x, probs
        return x


class ErniePooler(nn.Module):
    """Dense + tanh over the first ([CLS]) token (reference :120-133)."""
    config: ErnieConfig

    @nn.compact
    def __call__(self, hidden_states):
        first = hidden_states[:, 0]
        return jnp.tanh(_dense(self.config, self.config.hidden_size,
                               "dense", ("embed",), (None,))(first))


def attention_mask_bias(attention_mask: Optional[jax.Array],
                        dtype=jnp.float32) -> Optional[jax.Array]:
    """``[b, s]`` 1/0 padding mask -> additive ``[b, 1, 1, s]`` bias
    (the reference builds the same -1e4-style additive mask from
    ``pad_token_id`` positions)."""
    if attention_mask is None:
        return None
    return jnp.where(attention_mask[:, None, None, :] > 0, 0.0,
                     -1e4).astype(dtype)


class ErnieModel(nn.Module):
    """Embeddings -> N post-LN encoder blocks -> (sequence, pooled).

    Output plumbing matches reference ``single_model.py:255-257``:
    ``output_hidden_states`` adds the reference/HF tuple of L+1 states
    (embedding output + every block output), ``output_attentions`` the
    per-layer post-softmax probabilities, ``return_dict`` wraps them in
    :class:`BaseModelOutputWithPoolingAndCrossAttentions`."""
    config: ErnieConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, position_ids=None,
                 attention_mask=None, task_type_ids=None,
                 deterministic: bool = True,
                 output_hidden_states: bool = False,
                 output_attentions: bool = False,
                 return_dict: bool = False):
        cfg = self.config
        if attention_mask is None:
            # No mask: treat the batch as unpadded, on BOTH attention
            # paths. On pretraining streams token id 0 is a legitimate
            # vocab token, so inferring the mask from pad_token_id
            # (what the reference does) silently drops those positions
            # — and would make flash vs XLA attention disagree on the
            # same batch. Padded batches must pass an explicit mask.
            bias = None
        else:
            bias = attention_mask_bias(attention_mask,
                                       jnp.dtype(cfg.dtype))
        x = ErnieEmbeddings(cfg, name="embeddings")(
            input_ids, token_type_ids, position_ids, task_type_ids,
            deterministic)

        all_hidden = [x] if output_hidden_states else None
        all_attn = [] if output_attentions else None
        block = ErnieEncoderLayer
        if cfg.use_recompute:
            # argnums count from self: (self, x, attn_bias, deterministic)
            block = nn.remat(block, static_argnums=(3,),
                             prevent_cse=not cfg.scan_layers)
        if cfg.scan_layers:
            x, (h_stack, a_stack) = nn.scan(
                block,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=nn.broadcast,
                length=cfg.num_hidden_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, scanned=True, collect_hidden=output_hidden_states,
              output_attentions=output_attentions,
              name="encoder")(x, bias, deterministic)
            if output_hidden_states:
                all_hidden += [h_stack[i]
                               for i in range(cfg.num_hidden_layers)]
            if output_attentions:
                all_attn = [a_stack[i]
                            for i in range(cfg.num_hidden_layers)]
        else:
            for i in range(cfg.num_hidden_layers):
                out = block(
                    cfg, output_attentions=output_attentions,
                    name=f"encoder_{i}")(x, bias, deterministic)
                x, probs = out if output_attentions else (out, None)
                if output_hidden_states:
                    all_hidden.append(x)
                if output_attentions:
                    all_attn.append(probs)

        pooled = ErniePooler(cfg, name="pooler")(x)
        hidden_states = tuple(all_hidden) if output_hidden_states \
            else None
        attentions = tuple(all_attn) if output_attentions else None
        if not return_dict:
            out = (x, pooled)
            if output_hidden_states:
                out = out + (hidden_states,)
            if output_attentions:
                out = out + (attentions,)
            return out
        from .model_outputs import (
            BaseModelOutputWithPoolingAndCrossAttentions,
        )
        return BaseModelOutputWithPoolingAndCrossAttentions(
            last_hidden_state=x, pooler_output=pooled,
            hidden_states=hidden_states, attentions=attentions)


class ErnieLMPredictionHead(nn.Module):
    """Transform -> act -> LN -> tied-embedding decoder + bias
    (reference :419-459)."""
    config: ErnieConfig

    @nn.compact
    def __call__(self, hidden_states, word_embeddings,
                 masked_positions: Optional[jax.Array] = None):
        cfg = self.config
        if masked_positions is not None:
            flat = hidden_states.reshape(-1, hidden_states.shape[-1])
            hidden_states = jnp.take(flat, masked_positions, axis=0)
        h = _dense(cfg, cfg.hidden_size, "transform",
                   ("embed",), (None,))(hidden_states)
        h = _act(cfg.hidden_act)(h)
        h = _ln(cfg, "layer_norm")(h)
        bias = self.param(
            "decoder_bias",
            nn.with_logical_partitioning(nn.initializers.zeros_init(),
                                         ("vocab",)),
            (cfg.vocab_size,), jnp.dtype(cfg.param_dtype))
        logits = jnp.einsum("...h,vh->...v", h,
                            word_embeddings.astype(h.dtype))
        logits = logits + bias.astype(h.dtype)
        return with_logical_constraint(
            logits, ("batch", "seq", "act_vocab")
            if logits.ndim == 3 else (None, "act_vocab"))


class ErniePretrainingHeads(nn.Module):
    """MLM scores + NSP scores (reference :461-481)."""
    config: ErnieConfig

    @nn.compact
    def __call__(self, sequence_output, pooled_output, word_embeddings,
                 masked_positions=None):
        scores = ErnieLMPredictionHead(self.config, name="predictions")(
            sequence_output, word_embeddings, masked_positions)
        seq_rel = _dense(self.config, 2, "seq_relationship",
                         ("embed",), (None,))(pooled_output)
        return scores, seq_rel


def _tied_word_embeddings(variables) -> jax.Array:
    emb = variables["params"]["ernie"]["embeddings"]["word_embeddings"]
    if isinstance(emb, nn.Partitioned):
        emb = emb.value
    return emb


def _mean_ce_ignore(logits: jax.Array, labels: jax.Array,
                    ignore_index: int) -> jax.Array:
    """Mean softmax CE over positions with ``label != ignore_index``
    (the reference heads use ``paddle.nn.CrossEntropyLoss`` whose
    default ignore_index is -100; the pretraining criterion uses -1)."""
    logits = logits.astype(jnp.float32).reshape(-1, logits.shape[-1])
    labels = labels.reshape(-1)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    safe = jnp.maximum(labels, 0)
    picked = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
    mask = (labels != ignore_index).astype(jnp.float32)
    return jnp.sum((logz - picked) * mask) / jnp.maximum(
        jnp.sum(mask), 1.0)


class ErnieForPretraining(nn.Module):
    """ERNIE with MLM + NSP heads (reference :513-637); returns
    ``(prediction_scores, seq_relationship_score)`` — prefixed by the
    total loss when both label sets are given, or an
    :class:`ErnieForPreTrainingOutput` under ``return_dict=True``
    (which the reference declares but leaves commented out, returning
    ``None``; here it works)."""
    config: ErnieConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, position_ids=None,
                 attention_mask=None, masked_positions=None,
                 labels=None, next_sentence_label=None,
                 deterministic: bool = True,
                 output_hidden_states: bool = False,
                 output_attentions: bool = False,
                 return_dict: bool = False):
        outputs = ErnieModel(self.config, name="ernie")(
            input_ids, token_type_ids, position_ids, attention_mask,
            deterministic=deterministic,
            output_hidden_states=output_hidden_states,
            output_attentions=output_attentions, return_dict=True)
        scores, seq_rel = ErniePretrainingHeads(
            self.config, name="heads")(
            outputs.last_hidden_state, outputs.pooler_output,
            _tied_word_embeddings(self.variables), masked_positions)
        total_loss = None
        if labels is not None and next_sentence_label is not None:
            # reference :600-609: CrossEntropyLoss() on both heads
            # (default ignore_index -100)
            total_loss = _mean_ce_ignore(scores, labels, -100) + \
                _mean_ce_ignore(seq_rel, next_sentence_label, -100)
        if not return_dict:
            out = (scores, seq_rel)
            if output_hidden_states:
                out = out + (outputs.hidden_states,)
            if output_attentions:
                out = out + (outputs.attentions,)
            return ((total_loss,) + out) if total_loss is not None \
                else out
        from .model_outputs import ErnieForPreTrainingOutput
        return ErnieForPreTrainingOutput(
            loss=total_loss, prediction_logits=scores,
            seq_relationship_logits=seq_rel,
            hidden_states=outputs.hidden_states,
            attentions=outputs.attentions)


class ErnieForMaskedLM(nn.Module):
    """MLM-only head (reference ``ErnieOnlyMLMHead``/``ErnieForMaskedLM``
    :696-843); returns prediction scores, with loss/typed-output forms
    matching the reference's ``labels``/``return_dict`` branches."""
    config: ErnieConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, position_ids=None,
                 attention_mask=None, labels=None,
                 deterministic: bool = True,
                 output_hidden_states: bool = False,
                 output_attentions: bool = False,
                 return_dict: bool = False):
        outputs = ErnieModel(self.config, name="ernie")(
            input_ids, token_type_ids, position_ids, attention_mask,
            deterministic=deterministic,
            output_hidden_states=output_hidden_states,
            output_attentions=output_attentions, return_dict=True)
        scores = ErnieLMPredictionHead(self.config, name="predictions")(
            outputs.last_hidden_state,
            _tied_word_embeddings(self.variables))
        loss = None
        if labels is not None:
            # reference :794-800: CrossEntropyLoss() — "-100 index =
            # padding token"
            loss = _mean_ce_ignore(scores, labels, -100)
        if not return_dict:
            out = (scores,)
            if output_hidden_states:
                out = out + (outputs.hidden_states,)
            if output_attentions:
                out = out + (outputs.attentions,)
            if loss is not None:
                return (loss,) + out
            return out[0] if len(out) == 1 else out
        from .model_outputs import MaskedLMOutput
        return MaskedLMOutput(
            loss=loss, logits=scores,
            hidden_states=outputs.hidden_states,
            attentions=outputs.attentions)


class ErnieForMultipleChoice(nn.Module):
    """[b, num_choices, s] inputs -> per-choice scores (reference
    :845-962): run the encoder per choice, score the pooled output."""
    config: ErnieConfig
    num_choices: int = 2

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, position_ids=None,
                 attention_mask=None, labels=None,
                 deterministic: bool = True,
                 output_hidden_states: bool = False,
                 output_attentions: bool = False,
                 return_dict: bool = False):
        b, c, s = input_ids.shape
        flat = lambda t: None if t is None else t.reshape(b * c, s)  # noqa: E731
        outputs = ErnieModel(self.config, name="ernie")(
            flat(input_ids), flat(token_type_ids), flat(position_ids),
            flat(attention_mask), deterministic=deterministic,
            output_hidden_states=output_hidden_states,
            output_attentions=output_attentions, return_dict=True)
        pooled = nn.Dropout(self.config.hidden_dropout_prob)(
            outputs.pooler_output, deterministic=deterministic)
        logits = _dense(self.config, 1, "classifier",
                        ("embed",), (None,))(pooled)
        logits = logits.reshape(b, c)
        loss = None
        if labels is not None:
            loss = _mean_ce_ignore(logits, labels, -100)
        if not return_dict:
            out = (logits,)
            if output_hidden_states:
                out = out + (outputs.hidden_states,)
            if output_attentions:
                out = out + (outputs.attentions,)
            if loss is not None:
                return (loss,) + out
            return out[0] if len(out) == 1 else out
        from .model_outputs import MultipleChoiceModelOutput
        return MultipleChoiceModelOutput(
            loss=loss, logits=logits,
            hidden_states=outputs.hidden_states,
            attentions=outputs.attentions)


def ernie_pretraining_loss(
        prediction_scores: jax.Array,
        masked_lm_labels: jax.Array,
        seq_relationship_score: Optional[jax.Array] = None,
        next_sentence_labels: Optional[jax.Array] = None,
        with_nsp_loss: bool = True) -> Any:
    """Pretraining criterion (reference ``ErniePretrainingCriterion``,
    ``single_model.py:640-694``): mean masked-LM CE over positions with
    label != -1 (``ignore_index=-1``), plus mean NSP CE when enabled.
    Returns the MLM loss alone or a ``(mlm, nsp)`` tuple, matching the
    reference's two return shapes.
    """
    logits = prediction_scores.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    safe_labels = jnp.maximum(masked_lm_labels, 0)
    label_logits = jnp.take_along_axis(
        logits, safe_labels[..., None], axis=-1)[..., 0]
    mask = (masked_lm_labels >= 0).astype(jnp.float32)
    mlm_loss = jnp.sum((logz - label_logits) * mask) / \
        jnp.maximum(jnp.sum(mask), 1.0)
    if not with_nsp_loss:
        return mlm_loss
    nsp_logits = seq_relationship_score.astype(jnp.float32)
    nsp_logz = jax.scipy.special.logsumexp(nsp_logits, axis=-1)
    nsp_label_logits = jnp.take_along_axis(
        nsp_logits, next_sentence_labels[..., None], axis=-1)[..., 0]
    nsp_loss = jnp.mean(nsp_logz - nsp_label_logits)
    return mlm_loss, nsp_loss
