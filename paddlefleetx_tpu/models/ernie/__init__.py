"""ERNIE model family (encoder LM with MLM/NSP pretraining heads)."""

from .config import ErnieConfig
from .model import (
    ErnieEmbeddings,
    ErnieEncoderLayer,
    ErnieForMaskedLM,
    ErnieForMultipleChoice,
    ErnieForPretraining,
    ErnieModel,
    ErniePretrainingHeads,
    ernie_pretraining_loss,
)

__all__ = [
    "ErnieConfig",
    "ErnieEmbeddings",
    "ErnieEncoderLayer",
    "ErnieForMaskedLM",
    "ErnieForMultipleChoice",
    "ErnieForPretraining",
    "ErnieModel",
    "ErniePretrainingHeads",
    "ernie_pretraining_loss",
]
