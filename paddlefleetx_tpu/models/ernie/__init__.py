"""ERNIE model family (encoder LM with MLM/NSP pretraining heads)."""

from .config import ErnieConfig
from .model import (
    ErnieEmbeddings,
    ErnieEncoderLayer,
    ErnieForMaskedLM,
    ErnieForMultipleChoice,
    ErnieForPretraining,
    ErnieModel,
    ErniePretrainingHeads,
    ernie_pretraining_loss,
)
from .model_outputs import (
    BaseModelOutputWithPoolingAndCrossAttentions,
    ErnieForPreTrainingOutput,
    MaskedLMOutput,
    MultipleChoiceModelOutput,
    QuestionAnsweringModelOutput,
    SequenceClassifierOutput,
    TokenClassifierOutput,
)

__all__ = [
    "BaseModelOutputWithPoolingAndCrossAttentions",
    "ErnieConfig",
    "ErnieForPreTrainingOutput",
    "MaskedLMOutput",
    "MultipleChoiceModelOutput",
    "QuestionAnsweringModelOutput",
    "SequenceClassifierOutput",
    "TokenClassifierOutput",
    "ErnieEmbeddings",
    "ErnieEncoderLayer",
    "ErnieForMaskedLM",
    "ErnieForMultipleChoice",
    "ErnieForPretraining",
    "ErnieModel",
    "ErniePretrainingHeads",
    "ernie_pretraining_loss",
]
