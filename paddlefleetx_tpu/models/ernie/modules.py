"""ERNIE task module implementing the BasicModule contract.

Parity: reference ``ernie/ernie_module.py`` — ``ErnieModule`` trains
``ErnieForPretraining`` on GPTDataset token streams with the MLM-only
criterion (``ErniePretrainingCriterion(with_nsp_loss=False)``,
:56-94). The snapshot's ``training_step`` is a placeholder that feeds
*random* labels (:85-88); this module implements the objective that
criterion is written for: BERT-style dynamic masking — select
``masked_lm_prob`` of positions each step, replace 80% with [MASK],
10% with a random token, keep 10%, and predict the original ids at the
selected positions (ignore_index -1 elsewhere).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from .. import register_module
from ...core.module import LanguageModule
from .config import ErnieConfig
from .model import ErnieForPretraining, ernie_pretraining_loss


def apply_mlm_masking(rng: jax.Array, tokens: jax.Array,
                      cfg: ErnieConfig):
    """Dynamic MLM corruption: returns ``(masked_tokens, labels)`` with
    labels == -1 at unmasked positions (the criterion's ignore_index).
    Pad positions are never selected."""
    select_rng, kind_rng, rand_rng = jax.random.split(rng, 3)
    selectable = tokens != cfg.pad_token_id
    selected = (jax.random.uniform(select_rng, tokens.shape) <
                cfg.masked_lm_prob) & selectable
    kind = jax.random.uniform(kind_rng, tokens.shape)
    random_tokens = jax.random.randint(rand_rng, tokens.shape, 0,
                                       cfg.vocab_size, tokens.dtype)
    corrupted = jnp.where(kind < 0.8, cfg.mask_token_id,
                          jnp.where(kind < 0.9, random_tokens, tokens))
    masked_tokens = jnp.where(selected, corrupted, tokens)
    labels = jnp.where(selected, tokens, -1)
    return masked_tokens, labels


@register_module("ErnieModule")
class ErnieModule(LanguageModule):
    """ERNIE masked-LM pretraining module (MLM + SOP heads)."""

    def __init__(self, configs):
        from ..language_utils import process_data_configs
        process_data_configs(configs)
        super().__init__(configs)

    def get_model(self):
        self.model_config = ErnieConfig.from_config(self.configs)
        return ErnieForPretraining(self.model_config)

    def loss_fn(self, params, batch, rng, train: bool = True):
        """MLM+NSP pretraining loss on dynamically masked GPTDataset
        batches (reference ``ernie_module.py:56-102`` semantics)."""
        tokens, _position_ids, _labels, _loss_mask = batch
        cfg = self.model_config
        mask_rng, dropout_rng = jax.random.split(rng)
        masked_tokens, mlm_labels = apply_mlm_masking(mask_rng, tokens,
                                                      cfg)
        deterministic = not train or (
            cfg.hidden_dropout_prob == 0.0
            and cfg.attention_probs_dropout_prob == 0.0)
        rngs = None if deterministic else {"dropout": dropout_rng}
        scores, seq_rel = self.model.apply(
            {"params": params}, masked_tokens,
            deterministic=deterministic, rngs=rngs)
        if cfg.with_nsp_loss:
            # GPTDataset streams carry no sentence-pair labels; NSP
            # training requires a pairing dataset (reference uses
            # with_nsp_loss=False on this data for the same reason)
            raise ValueError("with_nsp_loss requires sentence-pair data")
        return ernie_pretraining_loss(scores, mlm_labels,
                                      with_nsp_loss=False)

    def input_spec(self):
        section = self._data_section()
        seq = section.dataset.max_seq_len if section \
            else self.model_config.max_position_embeddings
        micro = self.configs.Global.micro_batch_size
        return [((micro, seq), "int32")]

    def training_step_end(self, log_dict: Dict[str, Any]) -> None:
        log_dict.setdefault(
            "max_seq_len", self.configs.Data.Train.dataset.max_seq_len)
        super().training_step_end(log_dict)
