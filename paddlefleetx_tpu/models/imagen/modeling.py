"""Imagen: cascaded continuous-time DDPM over efficient U-Nets.

Behavior parity with the reference ``imagen/modeling.py``:
  - per-unet noise schedules (cosine for base, linear for
    super-resolution stages, :176-193), continuous times in [0, 1]
  - training ``forward`` picks one unet of the cascade
    (``unet_number``), draws random times/noise, builds the low-res
    conditioning image for upsampler stages (resize down then up,
    noised by the low-res augmentation schedule, :707-795), and
    returns ``(pred, target, log_snr, p2_gamma)`` for the criterion
  - ``ImagenCriterion``: per-sample reduced l1/l2/huber with p2
    reweighting ``(k + exp(log_snr))^-gamma`` (:89-131)
  - ancestral sampling with classifier-free guidance
    (``forward_with_cond_scale``), dynamic thresholding by the
    |x0| percentile (:319-368), posterior step per (t, t_next) pair
    (:369-411); the sampling loop is a ``lax.scan`` under jit instead
    of a Python timestep loop

TPU-first: NHWC activations (NCHW batches are transposed at the
boundary), explicit jax PRNG threading (flax rng collection
"diffusion"), one jitted program per cascade stage.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from .diffusion import GaussianDiffusionContinuousTimes
from .unet import UNET_ZOO, Unet, UnetConfig


def _resize(x: jax.Array, size: int) -> jax.Array:
    b, h, w, c = x.shape
    if h == size:
        return x
    return jax.image.resize(x, (b, size, size, c), "bilinear")


@dataclasses.dataclass(frozen=True)
class ImagenConfig:
    """Static config for the Imagen cascade (one entry per U-Net
    stage where a field is a tuple)."""

    unets: Tuple[str, ...] = ("Unet64_397M",)
    image_sizes: Tuple[int, ...] = (64,)
    text_embed_dim: int = 1024
    in_chans: int = 3
    timesteps: Union[int, Tuple[int, ...]] = 1000
    cond_drop_prob: float = 0.1
    noise_schedules: Union[str, Tuple[str, ...]] = "cosine"
    pred_objectives: Union[str, Tuple[str, ...]] = "noise"
    lowres_noise_schedule: str = "linear"
    lowres_sample_noise_level: float = 0.2
    condition_on_text: bool = True
    auto_normalize_img: bool = True
    #: SR stages: True draws one aug-noise level per sample, False one
    #: per batch (reference ``modeling.py`` per_sample_random_aug_noise_level)
    per_sample_random_aug_noise_level: bool = False
    #: U-Net compute dtype (AMP-O2 -> bfloat16). The diffusion schedule
    #: math stays fp32; unet inputs are cast at the call boundary so
    #: promotion doesn't silently drag the net back to fp32.
    dtype: str = "float32"
    #: spatial self-attention through the flash kernel on TPU — the SR
    #: U-Nets' deepest stages attend over 16K tokens (see UnetConfig)
    use_flash_attention: bool = False
    p2_loss_weight_gamma: float = 0.5
    dynamic_thresholding: bool = True
    dynamic_thresholding_percentile: float = 0.95
    unet_overrides: Tuple[Tuple[str, Any], ...] = ()

    def __post_init__(self):
        if len(self.unets) != len(self.image_sizes):
            raise ValueError("one image size per unet")


def _per_unet(v, n):
    if isinstance(v, (list, tuple)):
        assert len(v) == n
        return tuple(v)
    return (v,) * n


class ImagenModel(nn.Module):
    """Holds the unet cascade; training forward runs ONE stage."""
    config: ImagenConfig

    def setup(self):
        """Instantiate the per-stage U-Nets and noise schedulers."""
        cfg = self.config
        n = len(cfg.unets)
        schedules = list(_per_unet(cfg.noise_schedules, n))
        # reference default: cosine for the first two, linear beyond
        if not isinstance(cfg.noise_schedules, (list, tuple)):
            schedules = [cfg.noise_schedules] * min(n, 2) + \
                ["linear"] * max(0, n - 2)
        self.schedules = [
            GaussianDiffusionContinuousTimes(s, t) for s, t in
            zip(schedules, _per_unet(cfg.timesteps, n))]
        self.lowres_schedule = GaussianDiffusionContinuousTimes(
            cfg.lowres_noise_schedule)
        self.objectives = _per_unet(cfg.pred_objectives, n)
        self.p2_gammas = _per_unet(cfg.p2_loss_weight_gamma, n)

        unets = []
        overrides = dict(cfg.unet_overrides)
        for i, name in enumerate(cfg.unets):
            kw = dict(UNET_ZOO[name]) if isinstance(name, str) else {}
            kw.update(overrides)
            kw["channels"] = cfg.in_chans
            kw["text_embed_dim"] = cfg.text_embed_dim
            kw.setdefault("use_flash_attention",
                          cfg.use_flash_attention)
            if i > 0:
                kw["lowres_cond"] = True  # cascade stages condition on
                #                           the previous resolution
            unets.append(Unet(UnetConfig(**kw), name=f"unet_{i}"))
        self.unets = unets

    def _normalize(self, img):
        # [0, 1] -> [-1, 1] (reference auto_normalize_img)
        return img * 2 - 1 if self.config.auto_normalize_img else img

    def _unnormalize(self, img):
        return (img + 1) * 0.5 if self.config.auto_normalize_img else img

    def __call__(self, images, text_embeds=None, text_masks=None,
                 unet_number: int = 1):
        """Training step math for cascade stage ``unet_number``
        (1-based, like the reference). ``images`` NHWC or NCHW in
        [0, 1]. Returns (pred, target, log_snr, p2_gamma)."""
        cfg = self.config
        if images.shape[1] == cfg.in_chans and \
                images.shape[-1] != cfg.in_chans:
            images = jnp.transpose(images, (0, 2, 3, 1))
        i = unet_number - 1
        scheduler = self.schedules[i]
        size = cfg.image_sizes[i]
        b = images.shape[0]

        if cfg.condition_on_text:
            assert text_embeds is not None, \
                "text embeds required (condition_on_text)"
            if text_masks is None:
                text_masks = jnp.any(text_embeds != 0, axis=-1) \
                    .astype(jnp.int32)

        rng = self.make_rng("diffusion")
        t_rng, n_rng, drop_rng, lr_rng, lrt_rng = jax.random.split(rng, 5)
        times = scheduler.sample_random_times(t_rng, b)

        lowres_cond_img = lowres_aug_times = None
        # gate on the unet's own flag, not cascade position: the
        # standalone SR zoo entries (imagen_SR256/512/1024) are
        # lowres-conditioned single-unet models whose conditioning
        # image is synthesized from the training image at 1/4
        # resolution (no previous cascade stage to take it from)
        if self.unets[i].config.lowres_cond:
            prev = cfg.image_sizes[i - 1] if i > 0 else \
                max(1, size // 4)
            lowres_cond_img = _resize(_resize(images, prev), size)
            if cfg.per_sample_random_aug_noise_level:
                lowres_aug_times = \
                    self.lowres_schedule.sample_random_times(lrt_rng, b)
            else:
                lowres_aug_times = jnp.broadcast_to(
                    self.lowres_schedule.sample_random_times(lrt_rng, 1),
                    (b,))

        x_start = self._normalize(_resize(images, size))
        noise = jax.random.normal(n_rng, x_start.shape, x_start.dtype)
        x_noisy, log_snr = scheduler.q_sample(x_start, times, noise)

        lowres_noisy = None
        lowres_times_cond = None
        if lowres_cond_img is not None:
            lr = self._normalize(lowres_cond_img)
            lr_noise = jax.random.normal(lr_rng, lr.shape, lr.dtype)
            lowres_noisy, _ = self.lowres_schedule.q_sample(
                lr, lowres_aug_times, lr_noise)
            lowres_times_cond = self.lowres_schedule.get_condition(
                lowres_aug_times)

        cond_drop_mask = None
        if cfg.condition_on_text and cfg.cond_drop_prob > 0:
            cond_drop_mask = jax.random.uniform(drop_rng, (b,)) < \
                cfg.cond_drop_prob

        cdt = jnp.dtype(cfg.dtype)

        def _c(v):
            return v.astype(cdt) if v is not None and \
                jnp.issubdtype(v.dtype, jnp.floating) else v

        pred = self.unets[i](
            _c(x_noisy), _c(scheduler.get_condition(times)),
            text_embeds=_c(text_embeds) if cfg.condition_on_text
            else None,
            text_mask=text_masks if cfg.condition_on_text else None,
            lowres_cond_img=_c(lowres_noisy),
            lowres_noise_times=_c(lowres_times_cond),
            cond_drop_mask=cond_drop_mask)

        target = noise if self.objectives[i] == "noise" else x_start
        return pred, target, log_snr, self.p2_gammas[i]

    def _pred_with_cond_scale(self, i, x, time_cond, text_embeds,
                              text_masks, lowres_noisy, lowres_times,
                              cond_scale):
        """Classifier-free guidance: cond + scale*(cond - uncond)
        (reference ``forward_with_cond_scale``)."""
        b = x.shape[0]
        unet = self.unets[i]
        cond = unet(x, time_cond, text_embeds=text_embeds,
                    text_mask=text_masks, lowres_cond_img=lowres_noisy,
                    lowres_noise_times=lowres_times,
                    cond_drop_mask=jnp.zeros((b,), bool))
        if cond_scale == 1.0 or text_embeds is None:
            return cond
        uncond = unet(x, time_cond, text_embeds=text_embeds,
                      text_mask=text_masks,
                      lowres_cond_img=lowres_noisy,
                      lowres_noise_times=lowres_times,
                      cond_drop_mask=jnp.ones((b,), bool))
        return uncond + (cond - uncond) * cond_scale

    def sample_stage(self, unet_number: int, shape,
                     text_embeds=None, text_masks=None,
                     lowres_img=None, cond_scale: float = 1.0,
                     skip_steps: int = 0):
        """Ancestral sampling for one cascade stage; returns images in
        [0, 1]. Call via ``model.apply(..., method="sample_stage",
        rngs={"diffusion": key})``. ``skip_steps`` drops the first
        (noisiest) timestep pairs (reference ``p_sample_loop``
        ``timesteps[skip_steps:]``, ``modeling.py:451-452``) — a
        static slice, so each skip count is its own compiled
        program."""
        cfg = self.config
        i = unet_number - 1
        scheduler = self.schedules[i]
        b = shape[0]
        rng = self.make_rng("diffusion")
        init_rng, loop_rng, lr_rng = jax.random.split(rng, 3)

        lowres_noisy = lowres_times = None
        if lowres_img is not None:
            lr = self._normalize(_resize(lowres_img,
                                         cfg.image_sizes[i]))
            noise_level = cfg.lowres_sample_noise_level
            lr_t = self.lowres_schedule.get_times(b, noise_level)
            lowres_noisy, _ = self.lowres_schedule.q_sample(
                lr, lr_t, jax.random.normal(lr_rng, lr.shape, lr.dtype))
            lowres_times = self.lowres_schedule.get_condition(lr_t)

        x0 = jax.random.normal(init_rng, tuple(shape), jnp.float32)
        time_pairs = scheduler.get_sampling_timesteps(b)  # [T, 2, b]
        if skip_steps:
            skip_steps = int(skip_steps)
            if not 0 <= skip_steps < time_pairs.shape[0]:
                # a silent negative/oversized slice would return
                # shape-valid garbage (raw or one-step-denoised noise)
                raise ValueError(
                    f"skip_steps={skip_steps} out of range for "
                    f"{time_pairs.shape[0]} sampling steps")
            time_pairs = time_pairs[skip_steps:]

        def step(mdl, carry, tp):
            """One DDPM sampling step (t -> t_next)."""
            x, k = carry
            t, t_next = tp[0], tp[1]
            pred = mdl._pred_with_cond_scale(
                i, x, scheduler.get_condition(t), text_embeds,
                text_masks, lowres_noisy, lowres_times, cond_scale)
            if self.objectives[i] == "noise":
                x_start = scheduler.predict_start_from_noise(x, t, pred)
            else:
                x_start = pred
            if cfg.dynamic_thresholding:
                s = jnp.quantile(
                    jnp.abs(x_start.reshape(b, -1)),
                    cfg.dynamic_thresholding_percentile, axis=-1)
                s = jnp.clip(s, min=1.0).reshape(b, 1, 1, 1)
                x_start = jnp.clip(x_start, -s, s) / s
            else:
                x_start = jnp.clip(x_start, -1.0, 1.0)
            mean, _var, log_var = scheduler.q_posterior(
                x_start, x, t, t_next)
            k, nk = jax.random.split(k)
            noise = jax.random.normal(nk, x.shape, x.dtype)
            not_last = (t_next > 0).astype(x.dtype) \
                .reshape(b, 1, 1, 1)
            x = mean + not_last * jnp.exp(0.5 * log_var) * noise
            return (x, k), None

        # nn.scan, not jax.lax.scan: the body calls bound submodules
        # (the stage U-Net), whose scope must be threaded through the
        # scan legally — a raw lax.scan trips flax's trace-level check
        # (linen scopes are pinned to the trace they were bound at).
        # Params broadcast (read-only per step); no rng is drawn inside
        # the body — the noise keys ride in the carry.
        scanned = nn.scan(step, variable_broadcast="params",
                          split_rngs={}, in_axes=0, out_axes=0)
        (x, _), _ = scanned(self, (x0, loop_rng), time_pairs)
        return self._unnormalize(jnp.clip(x, -1.0, 1.0))

    def sample(self, text_embeds=None, text_masks=None,
               batch_size: int = 1, cond_scale=1.0,
               skip_steps=None,
               stop_at_unet_number: int = None,
               return_all_unet_outputs: bool = False):
        """Full-cascade text->image sampling (reference
        ``modeling.py:506-580``): walk every stage in order, feeding
        each stage's output into the next stage's low-res conditioning
        (``sample_stage`` resizes it to the stage resolution,
        normalizes, and applies the ``lowres_sample_noise_level``
        augmentation noise exactly as the reference does before the
        denoising loop). ``cond_scale`` is a scalar or a per-stage
        sequence (reference ``cast_tuple(cond_scale, num_unets)``);
        ``stop_at_unet_number`` truncates the cascade; by default the
        final stage's image (in [0, 1], NHWC — the TPU-native layout
        every stage here samples in; the reference returns NCHW)
        returns, or every stage's with ``return_all_unet_outputs``.

        ``skip_steps`` (scalar or per-stage) drops the noisiest
        timestep pairs per stage like the reference's
        ``timesteps[skip_steps:]``.

        Call via ``model.apply(..., method="sample",
        rngs={"diffusion": key})``. The loop over stages is a Python
        loop over distinct compiled programs (each stage has its own
        resolution — static shapes per stage is the XLA-friendly
        structure; the reference loops the same way, swapping unets
        onto the GPU per stage).

        Deliberately NOT ported from the reference ``sample()``
        signature: ``init_images`` (accepted but never read by the
        reference — ``p_sample_loop`` ignores it and always starts
        from noise, ``modeling.py:425,432``), ``cond_images``
        (channel-concat image conditioning; ``cond_images_channels``
        is 0 in every shipped reference config, so no recipe can
        exercise it) and inpainting (same: no shipped config/task
        drives ``inpaint_images``)."""
        cfg = self.config
        if cfg.condition_on_text and text_embeds is None:
            raise ValueError(
                "text embeddings must be passed when the cascade is "
                "text-conditional (reference sample() asserts the "
                "same)")
        if not cfg.condition_on_text and text_embeds is not None:
            raise ValueError(
                "imagen specified not to be conditioned on text, yet "
                "text embeddings were passed")
        if text_embeds is not None:
            if text_embeds.shape[-1] != cfg.text_embed_dim:
                raise ValueError(
                    f"text embedding dim {text_embeds.shape[-1]} != "
                    f"configured {cfg.text_embed_dim}")
            batch_size = text_embeds.shape[0]
            if text_masks is None:
                # reference: default mask = any(embed != 0)
                text_masks = jnp.any(text_embeds != 0.0, axis=-1)
        n = len(self.unets)
        if stop_at_unet_number is not None:
            n = min(n, int(stop_at_unet_number))
        scales = _per_unet(cond_scale, len(self.unets))
        skips = _per_unet(skip_steps if skip_steps is not None else 0,
                          len(self.unets))
        img = None
        outputs = []
        for u in range(1, n + 1):
            size = cfg.image_sizes[u - 1]
            shape = (batch_size, size, size, cfg.in_chans)
            img = self.sample_stage(
                u, shape, text_embeds=text_embeds,
                text_masks=text_masks, lowres_img=img,
                cond_scale=scales[u - 1],
                skip_steps=int(skips[u - 1]))
            outputs.append(img)
        return outputs if return_all_unet_outputs else img


def imagen_criterion(pred, target, log_snr, p2_gamma,
                     name: str = "mse_loss", p2_loss_weight_k: float = 1.0):
    """Reference ``ImagenCriterion`` (``modeling.py:89-131``)."""
    pred = pred.astype(jnp.float32)
    target = target.astype(jnp.float32)
    if name == "l1_loss":
        losses = jnp.abs(pred - target)
    elif name == "mse_loss":
        losses = (pred - target) ** 2
    elif name == "smooth_l1_loss":
        d = jnp.abs(pred - target)
        losses = jnp.where(d < 1.0, 0.5 * d ** 2, d - 0.5)
    else:
        raise NotImplementedError(name)
    losses = jnp.mean(losses.reshape(losses.shape[0], -1), axis=-1)
    if p2_gamma > 0:
        weight = (p2_loss_weight_k + jnp.exp(log_snr)) ** -p2_gamma
        losses = losses * weight
    return jnp.mean(losses)


def _zoo(**kw):
    def build(**overrides):
        merged = {**kw, **overrides}
        merged.pop("use_recompute", None)
        merged.pop("fused_linear", None)   # XLA fuses; config parity
        tuple_overrides = tuple(
            dict(merged.pop("unet_overrides", {})).items())
        return ImagenModel(ImagenConfig(
            unet_overrides=tuple_overrides, **merged))
    return build


# reference zoo (modeling.py:796-827)
IMAGEN_MODELS = {
    "imagen_397M_text2im_64": _zoo(unets=("Unet64_397M",),
                                   image_sizes=(64,)),
    "imagen_2B_text2im_64": _zoo(unets=("BaseUnet64",),
                                 image_sizes=(64,)),
    "imagen_text2im_64_SR256": _zoo(unets=("BaseUnet64", "SRUnet256"),
                                    image_sizes=(64, 256)),
    "imagen_SR256": _zoo(unets=("SRUnet256",), image_sizes=(256,)),
    "imagen_SR512": _zoo(unets=("SRUnet1024",), image_sizes=(512,)),
    "imagen_SR1024": _zoo(unets=("SRUnet1024",), image_sizes=(1024,)),
}


def build_imagen_model(name: str, **kwargs) -> ImagenModel:
    if name not in IMAGEN_MODELS:
        raise ValueError(
            f"unknown imagen model {name!r}; available: "
            f"{sorted(IMAGEN_MODELS)}")
    return IMAGEN_MODELS[name](**kwargs)
