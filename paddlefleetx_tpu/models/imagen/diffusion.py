"""Continuous-time gaussian diffusion (v-diffusion parameterization).

Parity: reference ``imagen/utils.py:321-424``
(``GaussianDiffusionContinuousTimes`` and its log-SNR helpers, credited
there to crowsonkb's v-diffusion-jax — this implementation returns to
jax natively). Times are continuous in [0, 1]; the noise level is
``log_snr(t)`` with either the cosine or the linear-beta schedule, and
``alpha = sqrt(sigmoid(log_snr))``, ``sigma = sqrt(sigmoid(-log_snr))``.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _log(t, eps=1e-12):
    return jnp.log(jnp.clip(t, min=eps))


def beta_linear_log_snr(t: jax.Array) -> jax.Array:
    return -_log(jnp.expm1(1e-4 + 10 * (t ** 2)))


def alpha_cosine_log_snr(t: jax.Array, s: float = 0.008) -> jax.Array:
    return -_log(
        jnp.cos((t + s) / (1 + s) * math.pi * 0.5) ** -2 - 1, eps=1e-5)


def log_snr_to_alpha_sigma(log_snr: jax.Array
                           ) -> Tuple[jax.Array, jax.Array]:
    return (jnp.sqrt(jax.nn.sigmoid(log_snr)),
            jnp.sqrt(jax.nn.sigmoid(-log_snr)))


def _pad_like(x: jax.Array, t: jax.Array) -> jax.Array:
    """Right-pad ``t``'s dims to broadcast against image-shaped ``x``."""
    return t.reshape(t.shape + (1,) * (x.ndim - t.ndim))


class GaussianDiffusionContinuousTimes:
    """Stateless schedule object (no parameters — unlike the reference
    nn.Layer, it needs no device registration)."""

    def __init__(self, noise_schedule: str = "cosine",
                 timesteps: int = 1000):
        if noise_schedule == "linear":
            self.log_snr = beta_linear_log_snr
        elif noise_schedule == "cosine":
            self.log_snr = alpha_cosine_log_snr
        else:
            raise ValueError(f"invalid noise schedule {noise_schedule}")
        self.num_timesteps = timesteps

    def get_times(self, batch_size: int, noise_level: float) -> jax.Array:
        return jnp.full((batch_size,), noise_level, jnp.float32)

    def sample_random_times(self, rng: jax.Array, batch_size: int,
                            max_thres: float = 0.999) -> jax.Array:
        return jax.random.uniform(rng, (batch_size,), jnp.float32, 0,
                                  max_thres)

    def get_condition(self, times: Optional[jax.Array]):
        return self.log_snr(times) if times is not None else None

    def get_sampling_timesteps(self, batch: int) -> jax.Array:
        """[T, 2, b]: (t, t_next) pairs from 1 -> 0."""
        times = jnp.linspace(1.0, 0.0, self.num_timesteps + 1)
        pairs = jnp.stack([times[:-1], times[1:]], axis=1)  # [T, 2]
        return jnp.broadcast_to(pairs[:, :, None],
                                (self.num_timesteps, 2, batch))

    def q_sample(self, x_start: jax.Array, t: jax.Array,
                 noise: jax.Array) -> Tuple[jax.Array, jax.Array]:
        log_snr = self.log_snr(t)
        alpha, sigma = log_snr_to_alpha_sigma(_pad_like(x_start, log_snr))
        return alpha * x_start + sigma * noise, log_snr

    def q_posterior(self, x_start: jax.Array, x_t: jax.Array,
                    t: jax.Array, t_next: Optional[jax.Array] = None):
        """Posterior q(x_{t_next} | x_t, x_start); eq. 33 of the
        variational-diffusion supplement (as in the reference)."""
        if t_next is None:
            t_next = jnp.clip(t - 1.0 / self.num_timesteps, min=0.0)
        log_snr = _pad_like(x_t, self.log_snr(t))
        log_snr_next = _pad_like(x_t, self.log_snr(t_next))
        alpha, _sigma = log_snr_to_alpha_sigma(log_snr)
        alpha_next, sigma_next = log_snr_to_alpha_sigma(log_snr_next)
        c = -jnp.expm1(log_snr - log_snr_next)
        posterior_mean = alpha_next * (x_t * (1 - c) / alpha
                                       + c * x_start)
        posterior_variance = (sigma_next ** 2) * c
        return posterior_mean, posterior_variance, \
            _log(posterior_variance, eps=1e-20)

    def predict_start_from_noise(self, x_t: jax.Array, t: jax.Array,
                                 noise: jax.Array) -> jax.Array:
        log_snr = _pad_like(x_t, self.log_snr(t))
        alpha, sigma = log_snr_to_alpha_sigma(log_snr)
        return (x_t - sigma * noise) / jnp.clip(alpha, min=1e-8)
