"""Imagen model family: cascaded text-to-image continuous-time
diffusion."""

from .diffusion import GaussianDiffusionContinuousTimes
from .modeling import (
    IMAGEN_MODELS,
    ImagenModel,
    build_imagen_model,
    imagen_criterion,
)
from .unet import Unet, UnetConfig

__all__ = [
    "GaussianDiffusionContinuousTimes",
    "IMAGEN_MODELS",
    "ImagenModel",
    "Unet",
    "UnetConfig",
    "build_imagen_model",
    "imagen_criterion",
]
