"""Imagen task module (reference
``multimodal_model/multimodal_module.py:103-137``): build the cascade
from the ``Model`` section, criterion from ``Loss``, train on
(image, text_embed, text_mask) batches."""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from .. import register_module
from ...core.module import BasicModule
from ...utils.log import logger
from .modeling import build_imagen_model, imagen_criterion


@register_module("ImagenModule")
class ImagenModule(BasicModule):
    """Imagen diffusion training module (one cascade stage per
    run)."""

    #: forward draws times/noise/cond-drop from this rng collection
    init_rng_collections = ("diffusion",)

    def __init__(self, configs):
        loss_cfg = dict(configs.get("Loss", {}) or {})
        self.loss_name = loss_cfg.get("name", "mse_loss")
        self.p2_loss_weight_k = loss_cfg.get("p2_loss_weight_k", 1)
        # reference SR configs name the knob only_train_unet_number
        self.unet_number = configs.Model.get("unet_number") or \
            configs.Model.get("only_train_unet_number") or 1
        # AMP-O2: bf16 compute + fp32 master params. The U-Net layers
        # follow input/param promotion, so casting both at the apply
        # boundary runs the whole cascade in bf16 while the optimizer
        # keeps fp32 masters; the criterion upcasts before the loss.
        from ...utils.config import bf16_enabled
        self.bf16_compute = bf16_enabled(configs)
        super().__init__(configs)

    def get_model(self):
        model_setting = dict(self.configs.Model)
        for compat in ("module", "unet_number", "only_train_unet_number",
                       "text_encoder_name"):  # embeds are precomputed
            model_setting.pop(compat, None)
        name = model_setting.pop("name")
        if self.bf16_compute:
            model_setting.setdefault("dtype", "bfloat16")
        return build_imagen_model(name, **model_setting)

    def init_model_variables(self, model, rngs, samples):
        # init must visit the SAME cascade stage loss_fn trains, or
        # that stage's params would not exist in the tree
        return model.init(rngs, *samples, unet_number=self.unet_number)

    def loss_fn(self, params, batch, rng, train: bool = True):
        """Denoising regression loss for the configured stage."""
        images, text_embeds, text_masks = batch
        if self.bf16_compute:
            # bf16 master->compute cast of params ONLY: images stay
            # fp32 so the diffusion schedule and the regression target
            # (noise is drawn in x_start.dtype) keep full precision;
            # ImagenModel casts the U-Net inputs at its call boundary
            params = jax.tree.map(
                lambda x: x.astype(jnp.bfloat16)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
        pred, target, log_snr, gamma = self.model.apply(
            {"params": params}, images, text_embeds, text_masks,
            unet_number=self.unet_number, rngs={"diffusion": rng})
        return imagen_criterion(pred, target, log_snr, gamma,
                                name=self.loss_name,
                                p2_loss_weight_k=self.p2_loss_weight_k)

    def input_spec(self):
        cfg = self.configs.Model
        size = (cfg.get("image_sizes") or [64])[self.unet_number - 1]
        chans = cfg.get("in_chans", 3)
        embed_dim = cfg.get("text_embed_dim", 1024)
        micro = self.configs.Global.micro_batch_size
        # __call__(images, text_embeds, ...) — init needs all three
        return [((micro, chans, size, size), "float32"),
                ((micro, 128, embed_dim), "float32"),
                ((micro, 128), "int32")]

    def training_step_end(self, log_dict: Dict[str, Any]) -> None:
        bs = self.configs.Global.global_batch_size
        logger.train(
            "[train] epoch: %d, batch: %d, loss: %.9f, avg_batch_cost: "
            "%.5f sec, ips: %.2f images/sec, learning rate: %.5e",
            log_dict["epoch"], log_dict["batch"], log_dict["loss"],
            log_dict["train_cost"], bs / log_dict["train_cost"],
            log_dict["lr"])
