"""Efficient U-Net for Imagen, in flax (NHWC).

Behavior parity with the reference U-Net (``imagen/unet.py:814-1250``
plus its layer zoo): learned-sinusoidal time embedding, text
conditioning through a Perceiver resampler + pooled text embedding,
classifier-free-guidance null embeddings, cross-embed initial conv,
per-level ResNet blocks with time scale-shift conditioning, optional
self-attention TransformerBlock and cross-attention per level,
skip-connected up path, optional low-resolution conditioning image
(cascade upsamplers). The zoo configs ``Unet64_397M / BaseUnet64 /
SRUnet256 / SRUnet1024`` mirror reference ``modeling.py:32-88``.

TPU-first: channel-last convs (XLA's native TPU layout), fp32 softmax,
one flax module — parallelism comes from the mesh rules, not model
surgery.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...ops.attention import dot_product_attention


def _conv(features: int, kernel, name: str, *, strides=None,
          padding="SAME", kernel_init=None, shard: bool = True):
    """``nn.Conv`` whose OUT-channel dim carries the logical ``embed``
    axis (→ fsdp under ZeRO-3, ``parallel/sharding.py:43``): the SR
    U-Nets' wide channel dims (up to dim x8 = 1024) shard instead of
    replicating per device (VERDICT r4 #7; reference SR zoo
    ``modeling.py:796-827`` relies on its sharding stage for the same
    models). ``shard=False`` for tiny fan-outs (RGB head)."""
    k_init = kernel_init or nn.linear.default_kernel_init
    b_init = nn.initializers.zeros_init()
    if shard:
        k_init = nn.with_logical_partitioning(
            k_init, (None, None, None, "embed"))
        b_init = nn.with_logical_partitioning(b_init, ("embed",))
    return nn.Conv(features, kernel, strides=strides, padding=padding,
                   kernel_init=k_init, bias_init=b_init, name=name)


def _attn_dense(features, name: str, axis=-1, use_bias: bool = False,
                logical=("embed", "heads", "kv")):
    """``nn.DenseGeneral`` with logical param axes (same idiom as
    ``models/vit/vit.py:91-112``): ``heads`` → mp, and any ``embed``
    axis → fsdp under ZeRO-3."""
    return nn.DenseGeneral(
        features, axis=axis, use_bias=use_bias, name=name,
        kernel_init=nn.with_logical_partitioning(
            nn.linear.default_kernel_init, logical))


def _cond_dense(features: int, name: str):
    """Dense for the time/text conditioning paths: the OUT dim carries
    ``embed`` (fsdp under ZeRO-3); the IN dim stays unsharded — it can
    be narrow (the 33-wide learned-sinusoidal embedding) where an fsdp
    split would be uneven."""
    return nn.Dense(
        features, name=name,
        kernel_init=nn.with_logical_partitioning(
            nn.linear.default_kernel_init, (None, "embed")),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros_init(), ("embed",)))


def _t(v, n: int) -> Tuple:
    """cast_tuple: scalar-or-seq -> length-n tuple."""
    if isinstance(v, (list, tuple)):
        assert len(v) == n
        return tuple(v)
    return (v,) * n


@dataclasses.dataclass(frozen=True)
class UnetConfig:
    """Static architecture hyperparameters for one U-Net stage."""

    dim: int = 128
    dim_mults: Sequence[int] = (1, 2, 4, 8)
    num_resnet_blocks: Union[int, Sequence[int]] = 2
    layer_attns: Union[bool, Sequence[bool]] = False
    layer_cross_attns: Union[bool, Sequence[bool]] = False
    attn_heads: int = 8
    attn_dim_head: int = 64
    ff_mult: float = 2.0
    channels: int = 3
    channels_out: Optional[int] = None
    cond_dim: Optional[int] = None
    text_embed_dim: int = 1024
    num_latents: int = 32          # perceiver resampler latents
    learned_sinu_dim: int = 16
    cross_embed_kernel_sizes: Sequence[int] = (3, 7, 15)
    lowres_cond: bool = False      # cascade upsampler conditioning
    memory_efficient: bool = False
    #: route spatial self-attention through ops.dot_product_attention
    #: (Pallas flash kernel on TPU for 2048+ tokens). The SR U-Nets'
    #: deepest stages attend over 128x128 = 16K tokens, where dense
    #: [b, h, s, s] scores are not materializable.
    use_flash_attention: bool = False
    dtype: str = "float32"
    param_dtype: str = "float32"

    @property
    def n_levels(self) -> int:
        return len(self.dim_mults)


class LearnedSinusoidalPosEmb(nn.Module):
    """Learned-frequency sinusoidal embedding (reference :567-585)."""
    dim: int

    @nn.compact
    def __call__(self, t):
        w = self.param("weights", nn.initializers.normal(1.0),
                       (self.dim // 2,))
        f = t[:, None] * w[None, :] * 2 * math.pi
        return jnp.concatenate([t[:, None], jnp.sin(f), jnp.cos(f)],
                               axis=-1)


class PerceiverResampler(nn.Module):
    """Fixed-size latents cross-attend to text tokens (reference
    :86-208): the variable-length T5 sequence becomes ``num_latents``
    conditioning tokens."""
    config: UnetConfig
    depth: int = 2

    @nn.compact
    def __call__(self, x, mask=None):
        cfg = self.config
        dim = cfg.cond_dim or cfg.dim
        n_latents = cfg.num_latents
        latents = self.param("latents",
                             nn.initializers.normal(0.02),
                             (n_latents, dim))
        latents = jnp.broadcast_to(latents[None],
                                   (x.shape[0],) + latents.shape)
        for i in range(self.depth):
            latents = latents + PerceiverAttention(
                cfg, name=f"attn_{i}")(x, latents, mask)
            latents = latents + _ff(dim, cfg.ff_mult,
                                    name=f"ff_{i}")(
                nn.LayerNorm(name=f"ff_norm_{i}")(latents))
        return latents


class PerceiverAttention(nn.Module):
    """Latents-attend-to-tokens block of the Perceiver resampler."""

    config: UnetConfig

    @nn.compact
    def __call__(self, x, latents, mask=None):
        cfg = self.config
        dim = cfg.cond_dim or cfg.dim
        h, dh = cfg.attn_heads, cfg.attn_dim_head
        x = nn.LayerNorm(name="norm_media")(x)
        latents = nn.LayerNorm(name="norm_latents")(latents)
        q = _attn_dense((h, dh), "to_q")(latents)
        # keys/values attend over media AND latents (reference :116)
        kv_in = jnp.concatenate([x, latents], axis=1)
        k = _attn_dense((h, dh), "to_k")(kv_in)
        v = _attn_dense((h, dh), "to_v")(kv_in)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * dh ** -0.5
        if mask is not None:
            full_mask = jnp.concatenate(
                [mask, jnp.ones((x.shape[0], latents.shape[1]),
                                mask.dtype)], axis=1)
            scores = jnp.where(full_mask[:, None, None, :] > 0, scores,
                               -1e9)
        attn = jax.nn.softmax(scores.astype(jnp.float32), axis=-1) \
            .astype(scores.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", attn, v)
        return _attn_dense(dim, "to_out", axis=(-2, -1),
                           logical=("heads", "kv", "embed"))(out)


def _ff(dim: int, mult: float, name: str):
    zeros = nn.initializers.zeros_init()
    return nn.Sequential([
        nn.Dense(int(dim * mult), name=f"{name}_in",
                 kernel_init=nn.with_logical_partitioning(
                     nn.linear.default_kernel_init, ("embed", "mlp")),
                 bias_init=nn.with_logical_partitioning(
                     zeros, ("mlp",))),
        nn.gelu,
        nn.Dense(dim, name=f"{name}_out",
                 kernel_init=nn.with_logical_partitioning(
                     nn.linear.default_kernel_init, ("mlp", "embed")),
                 bias_init=nn.with_logical_partitioning(
                     zeros, ("embed",))),
    ])


class CrossAttention(nn.Module):
    """Image tokens attend to conditioning tokens (reference :209-287),
    with learned null KV for classifier-free guidance."""
    config: UnetConfig
    dim: int

    @nn.compact
    def __call__(self, x, context, mask=None):
        cfg = self.config
        h, dh = cfg.attn_heads, cfg.attn_dim_head
        b = x.shape[0]
        xn = nn.LayerNorm(name="norm")(x)
        cn = nn.LayerNorm(name="norm_context")(context)
        q = _attn_dense((h, dh), "to_q")(xn)
        k = _attn_dense((h, dh), "to_k")(cn)
        v = _attn_dense((h, dh), "to_v")(cn)
        null_kv = self.param("null_kv", nn.initializers.normal(0.02),
                             (2, dh))
        nk = jnp.broadcast_to(null_kv[0], (b, 1, h, dh))
        nv = jnp.broadcast_to(null_kv[1], (b, 1, h, dh))
        k = jnp.concatenate([nk, k], axis=1)
        v = jnp.concatenate([nv, v], axis=1)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * dh ** -0.5
        if mask is not None:
            full = jnp.concatenate(
                [jnp.ones((b, 1), mask.dtype), mask], axis=1)
            scores = jnp.where(full[:, None, None, :] > 0, scores, -1e9)
        attn = jax.nn.softmax(scores.astype(jnp.float32), axis=-1) \
            .astype(scores.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", attn, v)
        return _attn_dense(self.dim, "to_out", axis=(-2, -1),
                           logical=("heads", "kv", "embed"))(out)


class SelfAttention(nn.Module):
    """Full self-attention over flattened spatial tokens
    (reference ``Attention`` :434-522)."""
    config: UnetConfig
    dim: int

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        h, dh = cfg.attn_heads, cfg.attn_dim_head
        xn = nn.LayerNorm(name="norm")(x)
        q = _attn_dense((h, dh), "to_q")(xn)
        k = _attn_dense((h, dh), "to_k")(xn)
        v = _attn_dense((h, dh), "to_v")(xn)
        out = dot_product_attention(
            q, k, v, causal=False,
            use_flash=cfg.use_flash_attention)
        return _attn_dense(self.dim, "to_out", axis=(-2, -1),
                           logical=("heads", "kv", "embed"))(out)


class TransformerBlock(nn.Module):
    """Self-attn + FF over the spatial grid (reference :532-566)."""
    config: UnetConfig
    dim: int

    @nn.compact
    def __call__(self, x):
        b, hh, ww, c = x.shape
        t = x.reshape(b, hh * ww, c)
        t = t + SelfAttention(self.config, c, name="attn")(t)
        t = t + _ff(c, self.config.ff_mult, name="ff")(
            nn.LayerNorm(name="ff_norm")(t))
        return t.reshape(b, hh, ww, c)


class ResnetBlock(nn.Module):
    """GroupNorm-SiLU-conv x2 with time scale-shift and optional
    cross-attention conditioning (reference :329-407)."""
    config: UnetConfig
    dim_out: int
    use_cross_attn: bool = False

    @nn.compact
    def __call__(self, x, time_emb=None, context=None):
        cfg = self.config
        groups = min(8, self.dim_out)
        scale_shift = None
        if time_emb is not None:
            t = nn.Dense(self.dim_out * 2, name="time_mlp",
                         kernel_init=nn.with_logical_partitioning(
                             nn.linear.default_kernel_init,
                             (None, "embed")),
                         bias_init=nn.with_logical_partitioning(
                             nn.initializers.zeros_init(), ("embed",))
                         )(nn.silu(time_emb))
            scale_shift = jnp.split(t[:, None, None, :], 2, axis=-1)

        h = nn.GroupNorm(num_groups=groups, name="norm1")(x)
        h = nn.silu(h)
        h = _conv(self.dim_out, (3, 3), "conv1")(h)

        if self.use_cross_attn:
            assert context is not None
            b, hh, ww, c = h.shape
            flat = h.reshape(b, hh * ww, c)
            flat = flat + CrossAttention(cfg, c, name="cross_attn")(
                flat, context)
            h = flat.reshape(b, hh, ww, c)

        h = nn.GroupNorm(num_groups=groups, name="norm2")(h)
        if scale_shift is not None:
            scale, shift = scale_shift
            h = h * (scale + 1) + shift
        h = nn.silu(h)
        h = _conv(self.dim_out, (3, 3), "conv2")(h)

        if x.shape[-1] != self.dim_out:
            x = _conv(self.dim_out, (1, 1), "res_conv")(x)
        return h + x


class CrossEmbedLayer(nn.Module):
    """Multi-kernel stem conv (reference :707-734)."""
    dim_out: int
    kernel_sizes: Sequence[int]

    @nn.compact
    def __call__(self, x):
        n = len(self.kernel_sizes)
        dims = [self.dim_out // (2 ** (i + 1)) for i in range(n)]
        dims[-1] = self.dim_out - sum(dims[:-1])
        outs = [
            _conv(d, (k, k), f"conv_{k}")(x)
            for d, k in zip(dims, sorted(self.kernel_sizes))]
        return jnp.concatenate(outs, axis=-1)


def _downsample(x, dim, name):
    return _conv(dim, (4, 4), name, strides=(2, 2),
                 padding=((1, 1), (1, 1)))(x)


def _upsample(x, dim, name):
    b, h, w, c = x.shape
    x = jax.image.resize(x, (b, h * 2, w * 2, c), "nearest")
    return _conv(dim, (3, 3), name)(x)


class Unet(nn.Module):
    """The efficient U-Net (reference :814-1250)."""
    config: UnetConfig

    @nn.compact
    def __call__(self, x, time, *, text_embeds=None, text_mask=None,
                 lowres_cond_img=None, lowres_noise_times=None,
                 cond_drop_mask=None):
        """``x`` NHWC in [-1, 1]; ``time`` = log-SNR condition [b];
        ``cond_drop_mask`` [b] True = drop text conditioning
        (classifier-free guidance)."""
        cfg = self.config
        n = cfg.n_levels
        dims = [cfg.dim * m for m in cfg.dim_mults]
        blocks_per = _t(cfg.num_resnet_blocks, n)
        attns = _t(cfg.layer_attns, n)
        cross = _t(cfg.layer_cross_attns, n)
        cond_dim = cfg.cond_dim or cfg.dim
        time_cond_dim = cfg.dim * 4

        if cfg.lowres_cond:
            assert lowres_cond_img is not None
            x = jnp.concatenate([x, lowres_cond_img], axis=-1)

        # -- time conditioning -----------------------------------------
        t = LearnedSinusoidalPosEmb(cfg.learned_sinu_dim,
                                    name="sinu_pos_emb")(time)
        t = _cond_dense(time_cond_dim, "time_mlp_in")(t)
        t = nn.silu(t)
        t = _cond_dense(time_cond_dim, "time_mlp_out")(t)
        if cfg.lowres_cond:
            lt = LearnedSinusoidalPosEmb(
                cfg.learned_sinu_dim, name="lowres_sinu_pos_emb")(
                lowres_noise_times)
            lt = _cond_dense(time_cond_dim, "lowres_time_in")(lt)
            lt = nn.silu(lt)
            lt = _cond_dense(time_cond_dim, "lowres_time_out")(lt)
            t = t + lt

        # -- text conditioning (+ null embeddings for CFG) --------------
        context = None
        if text_embeds is not None:
            te = _cond_dense(cond_dim, "text_to_cond")(text_embeds)
            tokens = PerceiverResampler(cfg, name="resampler")(
                te, text_mask)
            null_tokens = self.param(
                "null_text_embed", nn.initializers.normal(0.02),
                (cfg.num_latents, cond_dim))
            null_hidden = self.param(
                "null_text_hidden", nn.initializers.normal(0.02),
                (time_cond_dim,))
            if text_mask is not None:
                denom = jnp.maximum(
                    jnp.sum(text_mask, -1, keepdims=True), 1)
                pooled = jnp.sum(
                    te * text_mask[..., None], axis=1) / denom
            else:
                pooled = jnp.mean(te, axis=1)
            pooled = nn.LayerNorm(name="text_pool_norm")(pooled)
            pooled = _cond_dense(time_cond_dim,
                                 "text_pool_proj")(pooled)
            if cond_drop_mask is not None:
                keep = (~cond_drop_mask)[:, None]
                tokens = jnp.where(keep[..., None], tokens,
                                   null_tokens[None])
                pooled = jnp.where(keep, pooled, null_hidden[None])
            t = t + pooled
            context = tokens

        # -- down path --------------------------------------------------
        x = CrossEmbedLayer(cfg.dim, cfg.cross_embed_kernel_sizes,
                            name="init_conv")(x)
        hiddens = []
        for i in range(n):
            for j in range(blocks_per[i]):
                x = ResnetBlock(
                    cfg, dims[i],
                    use_cross_attn=cross[i] and j == 0
                    and context is not None,
                    name=f"down_{i}_block_{j}")(x, t, context)
            if attns[i]:
                x = TransformerBlock(cfg, dims[i],
                                     name=f"down_{i}_attn")(x)
            hiddens.append(x)
            if i < n - 1:
                x = _downsample(x, dims[i + 1], f"down_{i}_ds")

        # -- middle -----------------------------------------------------
        x = ResnetBlock(cfg, dims[-1],
                        use_cross_attn=cross[-1] and context is not None,
                        name="mid_block1")(x, t, context)
        x = TransformerBlock(cfg, dims[-1], name="mid_attn")(x)
        x = ResnetBlock(cfg, dims[-1],
                        use_cross_attn=cross[-1] and context is not None,
                        name="mid_block2")(x, t, context)

        # -- up path ----------------------------------------------------
        for i in reversed(range(n)):
            x = jnp.concatenate([x, hiddens[i]], axis=-1)
            for j in range(blocks_per[i]):
                x = ResnetBlock(
                    cfg, dims[i],
                    use_cross_attn=cross[i] and j == 0
                    and context is not None,
                    name=f"up_{i}_block_{j}")(x, t, context)
            if attns[i]:
                x = TransformerBlock(cfg, dims[i],
                                     name=f"up_{i}_attn")(x)
            if i > 0:
                x = _upsample(x, dims[i - 1], f"up_{i}_us")

        x = ResnetBlock(cfg, cfg.dim, name="final_block")(x, t)
        out_ch = cfg.channels_out or cfg.channels
        return _conv(out_ch, (3, 3), "final_conv",
                     kernel_init=nn.initializers.zeros_init(),
                     shard=False)(x)


# reference zoo (modeling.py:32-88)
UNET_ZOO = {
    "Unet64_397M": dict(dim=256, dim_mults=(1, 2, 3, 4),
                        num_resnet_blocks=3,
                        layer_attns=(False, True, True, True),
                        layer_cross_attns=(False, True, True, True),
                        attn_heads=8, ff_mult=2.0,
                        memory_efficient=False),
    "BaseUnet64": dict(dim=512, dim_mults=(1, 2, 3, 4),
                       num_resnet_blocks=3,
                       layer_attns=(False, True, True, True),
                       layer_cross_attns=(False, True, True, True),
                       attn_heads=8, ff_mult=2.0,
                       memory_efficient=False),
    "SRUnet256": dict(dim=128, dim_mults=(1, 2, 4, 8),
                      num_resnet_blocks=(2, 4, 8, 8),
                      layer_attns=(False, False, False, True),
                      layer_cross_attns=(False, False, False, True),
                      attn_heads=8, ff_mult=2.0, memory_efficient=True,
                      lowres_cond=True),
    "SRUnet1024": dict(dim=128, dim_mults=(1, 2, 4, 8),
                       num_resnet_blocks=(2, 4, 8, 8),
                       layer_attns=False,
                       layer_cross_attns=(False, False, False, True),
                       attn_heads=8, ff_mult=2.0, memory_efficient=True,
                       lowres_cond=True),
}


def build_unet(name_or_cfg: Any, **overrides) -> Unet:
    if isinstance(name_or_cfg, UnetConfig):
        return Unet(dataclasses.replace(name_or_cfg, **overrides))
    kwargs = dict(UNET_ZOO[name_or_cfg])
    kwargs.update(overrides)
    return Unet(UnetConfig(**kwargs))
