"""Classification metrics (reference ``vision_model/metrics/accuracy.py``).

``TopkAcc`` returns ``{"top1": ..., "top5": ..., "metric": top-first}``
like the reference's dict contract (:19-43).
"""

from __future__ import annotations

from typing import Dict, Sequence, Union

import jax
import jax.numpy as jnp


class TopkAcc:
    """Top-k classification accuracy (``top1``/``top5`` keys)."""

    def __init__(self, topk: Union[int, Sequence[int]] = (1, 5)):
        self.topk = [topk] if isinstance(topk, int) else list(topk)

    def __call__(self, logits: jax.Array,
                 labels: jax.Array) -> Dict[str, jax.Array]:
        labels = labels.reshape(-1)
        k_max = max(self.topk)
        _, top_idx = jax.lax.top_k(logits, k_max)
        hits = top_idx == labels[:, None]
        out: Dict[str, jax.Array] = {}
        for i, k in enumerate(self.topk):
            acc = jnp.mean(jnp.any(hits[:, :k], axis=-1)
                           .astype(jnp.float32))
            out[f"top{k}"] = acc
            if i == 0:
                out["metric"] = acc
        return out


METRICS = {"TopkAcc": TopkAcc}


def build_metric(cfg):
    cfg = dict(cfg)
    name = cfg.pop("name")
    if name not in METRICS:
        raise ValueError(
            f"unknown metric {name!r}; available: {sorted(METRICS)}")
    return METRICS[name](**cfg)
