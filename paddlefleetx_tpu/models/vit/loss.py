"""Classification losses (reference ``vision_model/loss/cross_entropy.py``).

``CELoss``: softmax CE with optional label smoothing; accepts hard int
labels or soft ``[b, C]`` targets (:25-61). ``ViTCELoss``: sigmoid
(binary) CE summed over classes with the ViT-style smoothing
``label*(1-eps)+eps`` (:64-95). Both reduce by mean over the batch and
compute in fp32.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _one_hot_if_needed(labels: jax.Array, class_num: int) -> jax.Array:
    if labels.ndim >= 2 and labels.shape[-1] == class_num:
        return labels.astype(jnp.float32)
    return jax.nn.one_hot(labels.reshape(-1), class_num,
                          dtype=jnp.float32)


class CELoss:
    """Softmax cross entropy with optional label smoothing."""

    def __init__(self, epsilon: Optional[float] = None):
        if epsilon is not None and not 0 <= epsilon <= 1:
            raise ValueError("epsilon must be in [0, 1]")
        self.epsilon = epsilon

    def __call__(self, logits: jax.Array, labels: jax.Array) -> jax.Array:
        logits = logits.astype(jnp.float32)
        class_num = logits.shape[-1]
        log_probs = jax.nn.log_softmax(logits, axis=-1)
        soft = labels.ndim >= 2 and labels.shape[-1] == class_num
        if self.epsilon is not None:
            target = _one_hot_if_needed(labels, class_num)
            # paddle.nn.functional.label_smooth
            target = target * (1 - self.epsilon) + self.epsilon / class_num
            loss = -jnp.sum(target * log_probs, axis=-1)
        elif soft:
            loss = -jnp.sum(labels.astype(jnp.float32) * log_probs,
                            axis=-1)
        else:
            loss = -jnp.take_along_axis(
                log_probs, labels.reshape(-1, 1).astype(jnp.int32),
                axis=-1)[..., 0]
        return jnp.mean(loss)


class ViTCELoss:
    """Sigmoid CE summed over classes (ViT pretraining recipe)."""

    def __init__(self, epsilon: Optional[float] = None):
        if epsilon is not None and not 0 <= epsilon <= 1:
            raise ValueError("epsilon must be in [0, 1]")
        self.epsilon = epsilon

    def __call__(self, logits: jax.Array, labels: jax.Array) -> jax.Array:
        logits = logits.astype(jnp.float32)
        class_num = logits.shape[-1]
        target = _one_hot_if_needed(labels, class_num)
        if self.epsilon is not None:
            target = target * (1.0 - self.epsilon) + self.epsilon
        # binary_cross_entropy_with_logits, reduction none -> sum classes
        loss = jnp.maximum(logits, 0) - logits * target + \
            jnp.log1p(jnp.exp(-jnp.abs(logits)))
        return jnp.mean(jnp.sum(loss, axis=-1))


LOSSES = {"CELoss": CELoss, "ViTCELoss": ViTCELoss}


def build_loss(cfg):
    cfg = dict(cfg)
    name = cfg.pop("name")
    if name not in LOSSES:
        raise ValueError(
            f"unknown loss {name!r}; available: {sorted(LOSSES)}")
    return LOSSES[name](**cfg)
