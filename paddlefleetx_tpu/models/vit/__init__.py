"""ViT model family (vision transformer classification)."""

from .loss import CELoss, ViTCELoss
from .metrics import TopkAcc
from .vit import (
    VISION_MODELS,
    ViT,
    ViTConfig,
    build_vision_model,
    interpolate_pos_embed,
)

__all__ = [
    "CELoss",
    "TopkAcc",
    "VISION_MODELS",
    "ViT",
    "ViTCELoss",
    "ViTConfig",
    "build_vision_model",
    "interpolate_pos_embed",
]
