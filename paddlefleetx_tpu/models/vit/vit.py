"""TPU-native ViT with the reference's architecture and model zoo.

Behavior parity (reference ``vision_model/vit/vit.py``):
  - conv patch embedding, prepended [CLS] token, learned pos embed
    (truncated-normal .02), embedding dropout (:127-139)
  - pre-LN blocks: ``x + DropPath(attn(LN(x)))`` then
    ``x + DropPath(mlp(LN(x)))`` (:93-96); stochastic-depth rates
    linspaced 0..drop_path_rate over depth (:140)
  - attention with optional qkv bias / qk scale, xavier-uniform
    weights (:70-79 of ``layers/attention.py``)
  - final LN, take [CLS], optional representation head (dense+tanh,
    head bias init -10) else zero-init classifier head (:158-177)
  - model zoo builders ``ViT_base_patch16_224`` ... ``ViT_6B_patch14``
    (:261-434) and pos-embed interpolation for resolution transfer
    (:207-259)

TPU-first: NHWC layout (images arrive CHW from the reference's
``ToCHWImage`` pipelines and are transposed once at the module
boundary), logical sharding axes like the GPT/ERNIE models, python
loop over blocks (per-layer drop-path rates; depth is small).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ...parallel.sharding import with_logical_constraint


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    """Static ViT architecture hyperparameters."""

    img_size: int = 224
    patch_size: int = 16
    in_chans: int = 3
    class_num: int = 1000
    embed_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_ratio: float = 4.0
    qkv_bias: bool = False
    qk_scale: Optional[float] = None
    drop_rate: float = 0.0
    attn_drop_rate: float = 0.0
    drop_path_rate: float = 0.0
    epsilon: float = 1e-5
    representation_size: Optional[int] = None
    dtype: str = "float32"
    param_dtype: str = "float32"

    @property
    def num_patches(self) -> int:
        return (self.img_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads


def _xavier():
    return nn.initializers.xavier_uniform()


def drop_path(x: jax.Array, rate: float, deterministic: bool,
              rng: Optional[jax.Array]) -> jax.Array:
    """Stochastic depth: drop the whole residual branch per sample
    (reference ``layers/droppath.py``)."""
    if deterministic or rate == 0.0:
        return x
    keep = 1.0 - rate
    shape = (x.shape[0],) + (1,) * (x.ndim - 1)
    mask = jax.random.bernoulli(rng, keep, shape)
    return jnp.where(mask, x / keep, 0.0)


class ViTAttention(nn.Module):
    """Qkv (optional bias) -> scaled softmax -> proj (reference
    ``layers/attention.py:21-60``)."""
    config: ViTConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        nh, hd = cfg.num_heads, cfg.head_dim
        dtype = jnp.dtype(cfg.dtype)
        qkv = nn.DenseGeneral(
            (3, nh, hd), axis=-1, name="qkv", use_bias=cfg.qkv_bias,
            dtype=dtype, param_dtype=jnp.dtype(cfg.param_dtype),
            kernel_init=nn.with_logical_partitioning(
                _xavier(), ("embed", None, "heads", "kv")),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), (None, "heads", "kv")))(x)
        q, k, v = (qkv[..., i, :, :] for i in range(3))
        scale = cfg.qk_scale or hd ** -0.5
        attn = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        attn = jax.nn.softmax(attn.astype(jnp.float32), axis=-1)
        attn = attn.astype(dtype)
        attn = nn.Dropout(cfg.attn_drop_rate)(
            attn, deterministic=deterministic)
        out = jnp.einsum("bhqk,bkhd->bqhd", attn, v)
        out = nn.DenseGeneral(
            cfg.embed_dim, axis=(-2, -1), name="proj", dtype=dtype,
            param_dtype=jnp.dtype(cfg.param_dtype),
            kernel_init=nn.with_logical_partitioning(
                _xavier(), ("heads", "kv", "embed")),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("embed",)))(out)
        return nn.Dropout(cfg.drop_rate)(out,
                                         deterministic=deterministic)


class ViTMLP(nn.Module):
    """Transformer MLP block (GELU, ``mlp_ratio`` expansion)."""

    config: ViTConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        hidden = int(cfg.embed_dim * cfg.mlp_ratio)
        dtype = jnp.dtype(cfg.dtype)
        x = nn.DenseGeneral(
            hidden, name="fc1", dtype=dtype,
            param_dtype=jnp.dtype(cfg.param_dtype),
            kernel_init=nn.with_logical_partitioning(
                _xavier(), ("embed", "mlp")),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("mlp",)))(x)
        x = nn.gelu(x, approximate=False)
        x = nn.Dropout(cfg.drop_rate)(x, deterministic=deterministic)
        x = with_logical_constraint(x, ("batch", None, "act_mlp"))
        x = nn.DenseGeneral(
            cfg.embed_dim, name="fc2", dtype=dtype,
            param_dtype=jnp.dtype(cfg.param_dtype),
            kernel_init=nn.with_logical_partitioning(
                _xavier(), ("mlp", "embed")),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("embed",)))(x)
        return nn.Dropout(cfg.drop_rate)(x, deterministic=deterministic)


class ViTBlock(nn.Module):
    """Pre-LN block with stochastic depth (reference ``Block``)."""
    config: ViTConfig
    drop_path_rate: float = 0.0

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=cfg.epsilon, dtype=jnp.dtype(cfg.dtype),
            param_dtype=jnp.dtype(cfg.param_dtype), name=name,
            scale_init=nn.with_logical_partitioning(
                nn.initializers.ones_init(), ("norm",)),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("norm",)))
        dp_rng = None
        if not deterministic and self.drop_path_rate > 0.0:
            dp_rng = self.make_rng("dropout")
        y = ViTAttention(cfg, name="attn")(ln("norm1")(x), deterministic)
        x = x + drop_path(y, self.drop_path_rate, deterministic, dp_rng)
        if dp_rng is not None:
            dp_rng = self.make_rng("dropout")
        y = ViTMLP(cfg, name="mlp")(ln("norm2")(x), deterministic)
        x = x + drop_path(y, self.drop_path_rate, deterministic, dp_rng)
        return with_logical_constraint(x, ("batch", "seq", "act_embed"))


class ViT(nn.Module):
    """Vision Transformer classifier; input NHWC (a CHW batch from the
    reference's ``ToCHWImage`` pipeline is accepted and transposed)."""
    config: ViTConfig

    @nn.compact
    def __call__(self, images, deterministic: bool = True):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        if images.ndim != 4:
            raise ValueError(f"expected [b,h,w,c] images, got "
                             f"{images.shape}")
        if images.shape[1] == cfg.in_chans and \
                images.shape[-1] != cfg.in_chans:
            images = jnp.transpose(images, (0, 2, 3, 1))  # NCHW -> NHWC

        x = nn.Conv(
            cfg.embed_dim, (cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size), padding="VALID",
            name="patch_embed", dtype=dtype,
            param_dtype=jnp.dtype(cfg.param_dtype),
            kernel_init=nn.with_logical_partitioning(
                _xavier(), (None, None, None, "embed")),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("embed",)))(
            images.astype(dtype))
        b = x.shape[0]
        x = x.reshape(b, -1, cfg.embed_dim)

        cls_token = self.param(
            "cls_token",
            nn.with_logical_partitioning(nn.initializers.zeros_init(),
                                         (None, None, "embed")),
            (1, 1, cfg.embed_dim), jnp.dtype(cfg.param_dtype))
        pos_embed = self.param(
            "pos_embed",
            nn.with_logical_partitioning(
                nn.initializers.truncated_normal(stddev=0.02),
                (None, "pos", "embed")),
            (1, cfg.num_patches + 1, cfg.embed_dim),
            jnp.dtype(cfg.param_dtype))
        x = jnp.concatenate(
            [jnp.broadcast_to(cls_token.astype(dtype),
                              (b, 1, cfg.embed_dim)), x], axis=1)
        x = x + pos_embed.astype(dtype)
        x = nn.Dropout(cfg.drop_rate)(x, deterministic=deterministic)
        x = with_logical_constraint(x, ("batch", "seq", "act_embed"))

        rates = np.linspace(0.0, cfg.drop_path_rate, cfg.depth)
        for i in range(cfg.depth):
            x = ViTBlock(cfg, drop_path_rate=float(rates[i]),
                         name=f"blocks_{i}")(x, deterministic)

        x = nn.LayerNorm(
            epsilon=cfg.epsilon, dtype=dtype,
            param_dtype=jnp.dtype(cfg.param_dtype), name="norm",
            scale_init=nn.with_logical_partitioning(
                nn.initializers.ones_init(), ("norm",)),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("norm",)))(x)
        x = x[:, 0]

        if cfg.representation_size is not None:
            x = jnp.tanh(nn.Dense(
                cfg.representation_size, name="head0", dtype=dtype,
                param_dtype=jnp.dtype(cfg.param_dtype),
                kernel_init=nn.with_logical_partitioning(
                    _xavier(), ("embed", None)),
                bias_init=nn.with_logical_partitioning(
                    nn.initializers.zeros_init(), (None,)))(x))
            # reference inits this head's bias to -10 (minus_tens_)
            head_bias_init = nn.initializers.constant(-10.0)
            head_kernel_init = _xavier()
        else:
            head_bias_init = nn.initializers.zeros_init()
            head_kernel_init = nn.initializers.zeros_init()
        if cfg.class_num > 0:
            # classifier head stays replicated: class_num rarely
            # divides the mp axis and the FLOPs are negligible
            x = nn.Dense(
                cfg.class_num, name="head", dtype=dtype,
                param_dtype=jnp.dtype(cfg.param_dtype),
                kernel_init=nn.with_logical_partitioning(
                    head_kernel_init, ("embed", None)),
                bias_init=nn.with_logical_partitioning(
                    head_bias_init, (None,)))(x)
        return x


def interpolate_pos_embed(pos_embed: np.ndarray,
                          new_num_patches: int) -> np.ndarray:
    """Bicubic-resize the grid part of a ``[1, 1+N, D]`` pos embed to a
    new patch count (reference ``load_pretrained`` :221-259)."""
    pos_embed = np.asarray(pos_embed)
    n = pos_embed.shape[1] - 1
    if n == new_num_patches:
        return pos_embed
    cls_tok, grid = pos_embed[:, :1], pos_embed[:, 1:]
    old = int(round(np.sqrt(n)))
    new = int(round(np.sqrt(new_num_patches)))
    d = grid.shape[-1]
    grid = grid.reshape(old, old, d)
    grid_j = jax.image.resize(jnp.asarray(grid), (new, new, d),
                              method="bicubic")
    grid = np.asarray(grid_j).reshape(1, new * new, d)
    return np.concatenate([cls_tok, grid], axis=1)


def _zoo(**kw) -> Any:
    def build(**overrides):
        merged = {**kw, **overrides}
        merged.pop("pretrained", None)  # checkpoint loading is explicit
        return ViT(ViTConfig(**merged))
    return build


# reference zoo, mirrored builder-for-builder (vit.py:261-434): the
# 224-res variants carry a representation head sized to embed_dim,
# the 384-res transfer variants drop it; base/large/g/G/6B use
# epsilon=1e-6 + qkv_bias while huge keeps the class defaults.
# tiny/small are repo extras (timm-standard shapes) for cheap tests.
VISION_MODELS = {
    "ViT": lambda **kw: ViT(ViTConfig(**kw)),
    "ViT_tiny_patch16_224": _zoo(patch_size=16, embed_dim=192, depth=12,
                                 num_heads=3),
    "ViT_small_patch16_224": _zoo(patch_size=16, embed_dim=384, depth=12,
                                  num_heads=6),
    "ViT_base_patch16_224": _zoo(patch_size=16, embed_dim=768, depth=12,
                                 num_heads=12, qkv_bias=True,
                                 epsilon=1e-6, representation_size=768),
    "ViT_base_patch16_384": _zoo(img_size=384, patch_size=16,
                                 embed_dim=768, depth=12, num_heads=12,
                                 qkv_bias=True, epsilon=1e-6),
    "ViT_base_patch32_224": _zoo(patch_size=32, embed_dim=768, depth=12,
                                 num_heads=12, qkv_bias=True,
                                 epsilon=1e-6, representation_size=768),
    "ViT_base_patch32_384": _zoo(img_size=384, patch_size=32,
                                 embed_dim=768, depth=12, num_heads=12,
                                 qkv_bias=True, epsilon=1e-6),
    "ViT_large_patch16_224": _zoo(patch_size=16, embed_dim=1024,
                                  depth=24, num_heads=16, qkv_bias=True,
                                  epsilon=1e-6,
                                  representation_size=1024),
    "ViT_large_patch16_384": _zoo(img_size=384, patch_size=16,
                                  embed_dim=1024, depth=24, num_heads=16,
                                  qkv_bias=True, epsilon=1e-6),
    "ViT_large_patch32_224": _zoo(patch_size=32, embed_dim=1024,
                                  depth=24, num_heads=16, qkv_bias=True,
                                  epsilon=1e-6,
                                  representation_size=1024),
    "ViT_large_patch32_384": _zoo(img_size=384, patch_size=32,
                                  embed_dim=1024, depth=24, num_heads=16,
                                  qkv_bias=True, epsilon=1e-6),
    "ViT_huge_patch14_224": _zoo(patch_size=14, embed_dim=1280,
                                 depth=32, num_heads=16,
                                 representation_size=1280),
    "ViT_huge_patch14_384": _zoo(img_size=384, patch_size=14,
                                 embed_dim=1280, depth=32, num_heads=16),
    "ViT_g_patch14_224": _zoo(patch_size=14, embed_dim=1408, depth=40,
                              num_heads=16, mlp_ratio=4.364,
                              qkv_bias=True, epsilon=1e-6,
                              representation_size=1408),
    "ViT_G_patch14_224": _zoo(patch_size=14, embed_dim=1664, depth=48,
                              num_heads=16, mlp_ratio=4.9231,
                              qkv_bias=True, epsilon=1e-6,
                              representation_size=1664),
    "ViT_6B_patch14_224": _zoo(patch_size=14, embed_dim=2320, depth=80,
                               num_heads=16, mlp_ratio=4.955,
                               qkv_bias=True, epsilon=1e-6,
                               representation_size=2320),
}


def build_vision_model(cfg) -> nn.Module:
    """``Model.model`` YAML section -> model instance."""
    cfg = dict(cfg)
    name = cfg.pop("name")
    if name not in VISION_MODELS:
        raise ValueError(
            f"unknown vision model {name!r}; available: "
            f"{sorted(VISION_MODELS)}")
    return VISION_MODELS[name](**cfg)
