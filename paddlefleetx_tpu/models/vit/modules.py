"""General classification module (reference
``vision_model/general_classification_module.py:38-161``): builds
model / train+eval losses / metrics from the ``Model`` YAML section,
logs images/sec, and tracks the best eval metric.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from .. import register_module
from ...core.module import BasicModule
from ...utils.log import logger
from .loss import build_loss
from .metrics import build_metric
from .vit import build_vision_model


@register_module("GeneralClsModule")
class GeneralClsModule(BasicModule):
    """Image-classification training module (ViT et al.): configured
    loss heads plus top-k eval metrics."""

    def __init__(self, configs):
        model_cfg = configs.Model
        if "train" not in model_cfg.get("loss", {}):
            raise ValueError("Model.loss.train is required")
        self.train_loss = build_loss(model_cfg.loss.train)
        self.eval_loss = build_loss(model_cfg.loss.eval) \
            if "eval" in model_cfg.get("loss", {}) else self.train_loss
        metric_cfg = model_cfg.get("metric", {})
        self.train_metric = build_metric(metric_cfg["train"]) \
            if "train" in metric_cfg else None
        self.eval_metric = build_metric(metric_cfg["eval"]) \
            if "eval" in metric_cfg else None
        super().__init__(configs)
        self.best_metric = 0.0
        self.acc_list = []

    def get_model(self):
        model_cfg = dict(self.configs.Model.model)
        # AMP-O2 (the fp16o2 recipes): bf16 compute + fp32 params —
        # the reference decorates the model via paddle.amp (O2); here
        # the dtype policy flows into the flax modules directly
        from ...utils.config import bf16_enabled
        if bf16_enabled(self.configs):
            model_cfg.setdefault("dtype", "bfloat16")
        return build_vision_model(model_cfg)

    def loss_fn(self, params, batch, rng, train: bool = True):
        images, labels = batch
        deterministic = not train
        rngs = None if deterministic else {"dropout": rng}
        logits = self.model.apply({"params": params}, images,
                                  deterministic=deterministic, rngs=rngs)
        loss = self.train_loss if train else self.eval_loss
        return loss(logits, labels)

    def eval_outputs_fn(self, params, batch):
        """Loss + metrics from a single forward (the engine's combined
        eval-step contract)."""
        images, labels = batch
        logits = self.model.apply({"params": params}, images,
                                  deterministic=True)
        out = {"loss": self.eval_loss(logits, labels)}
        if self.eval_metric is not None:
            out.update(self.eval_metric(logits, labels))
        return out

    def input_spec(self):
        model = self.configs.Model.model
        size = model.get("img_size", 224)
        return [((None, 3, size, size), "float32")]

    def training_step_end(self, log_dict: Dict[str, Any]) -> None:
        bs = self.configs.Global.global_batch_size
        ips = bs / log_dict["train_cost"]
        logger.train(
            "[train] epoch: %d, step: %d, learning rate: %.7f, loss: "
            "%.9f, batch_cost: %.5f sec, ips: %.2f images/sec",
            log_dict["epoch"], log_dict["batch"], log_dict["lr"],
            log_dict["loss"], log_dict["train_cost"], ips)

    def validation_step_end(self, log_dict: Dict[str, Any]) -> None:
        if "metric" in log_dict:
            self.acc_list.append(
                {k: float(v) for k, v in log_dict.items()
                 if k.startswith("top") or k == "metric"})
        logger.eval(
            "[eval] epoch: %d, step: %d, loss: %.9f, batch_cost: %.5f "
            "sec", log_dict["epoch"], log_dict["batch"],
            log_dict["loss"], log_dict["eval_cost"])

    def validation_epoch_end(self, log_dict: Dict[str, Any]) -> None:
        """Aggregate epoch top-k accuracy and track the best metric
        (reference ``general_classification_module.py:86-127``)."""
        msg = ""
        if self.acc_list:
            keys = [k for k in self.acc_list[0] if k != "metric"]
            means = {k: float(np.mean([a[k] for a in self.acc_list]))
                     for k in keys}
            metric = float(np.mean([a["metric"] for a in self.acc_list]))
            self.acc_list = []
            if metric > self.best_metric:
                self.best_metric = metric
            msg = ", ".join(f"{k}: {v:.5f}" for k, v in means.items())
            msg += f", best_metric: {self.best_metric:.5f}, "
            self.metrics = {**means, "best_metric": self.best_metric}
        logger.info("[eval] epoch: %d, %stotal time: %.5f sec",
                    log_dict["epoch"], msg, log_dict["eval_cost"])
