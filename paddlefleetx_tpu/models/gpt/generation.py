"""Jit-compiled autoregressive generation with a fixed-capacity cache.

Parity: reference ``GPTForGeneration(Hybrid).forward/sample``
(``hybrid_model.py:1208-1433``): left-padded prompts, temperature /
top-k / top-p sampling, min-length + repetition-penalty processors,
KV-cached decode. The reference fights dygraph-to-static conversion
with a growing cache and a Python while-loop (:1322-1347); here the
whole generate is ONE compiled program: prefill + ``lax.scan`` over a
static number of decode steps, cache preallocated at
``max_position_embeddings`` slots, finished rows emit ``pad`` tokens.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from collections.abc import Mapping
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...parallel.sharding import with_logical_constraint
from .config import GPTConfig
from .processors import (
    hamming_diversity_processor, min_length_processor,
    repetition_penalty_processor, top_k_top_p_filter, NEG_INF,
)


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    """Knobs named as in the reference YAML ``Generation`` section."""
    max_dec_len: int = 20
    min_dec_len: int = 0
    #: sampling | greedy_search | beam_search — beam search goes
    #: BEYOND the reference, whose generation raises for any strategy
    #: but sampling (``hybrid_model.py:1432``)
    decode_strategy: str = "sampling"
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    num_beams: int = 1
    #: diverse (group) beam search: beams split into this many groups,
    #: decoded group-by-group within each step; later groups pay a
    #: Hamming penalty on tokens earlier groups just chose (drives
    #: ``hamming_diversity_processor``; the reference carries the
    #: processor, ``gpt/dygraph/processor.py:106-155``, but nothing
    #: invokes it). 1 = vanilla beam search.
    num_beam_groups: int = 1
    #: Hamming penalty strength for ``num_beam_groups > 1`` (the
    #: reference processor's ``diversity_rate``)
    diversity_rate: float = 0.0
    #: GNMT length penalty exponent (0 = pure log-prob)
    length_penalty: float = 0.0
    repetition_penalty: float = 1.0
    #: sampling/greedy: tile each prompt this many times before
    #: sampling — every copy samples an independent continuation
    #: (reference ``expand_inputs_for_generation``,
    #: ``hybrid_model.py:1422-1426``). beam_search: return this many
    #: best beams per prompt (must be <= num_beams).
    num_return_sequences: int = 1
    eos_token_id: int = 50256
    pad_token_id: int = 50256
    #: TPU-native: sample with the binned approximate top-k kernel
    #: instead of the full-vocab sort XLA:TPU lowers exact top_k to
    #: (~6x the rest of the sampling math at V=50k). Recall 0.99 — a
    #: bin miss lowers the k-th-value cutoff, so the candidate set
    #: can only WIDEN by a few tail tokens, never lose a
    #: high-probability one; temperature sampling cannot distinguish
    #: that from its own noise. Set False for sort-exact candidate
    #: sets. Beam search ignores this and always scores exactly.
    approx_top_k: bool = True
    #: speculative decoding on the slot server (core/serving.py):
    #: None = off; "ngram" = draft-model-free self-speculation — each
    #: request's own emitted history proposes ``spec_tokens`` draft
    #: tokens by suffix match (core/spec.py) and ONE verify forward
    #: scores the whole run (verify_step). The interface is a draft
    #: SOURCE, so a small draft-model method can slot in later.
    spec_method: Optional[str] = None
    #: drafted tokens per verify tick (k); each tick commits
    #: 1..k+1 tokens. Only read when spec_method is set.
    spec_tokens: int = 4

    def __post_init__(self):
        if self.spec_method is not None:
            if self.spec_method not in ("ngram",):
                raise ValueError(
                    f"unknown spec_method {self.spec_method!r} "
                    f"(supported: 'ngram')")
            if self.spec_tokens < 1:
                raise ValueError(
                    f"spec_tokens must be >= 1, got "
                    f"{self.spec_tokens}")
            if self.decode_strategy == "beam_search":
                raise ValueError(
                    "speculative decoding (spec_method) serves "
                    "sampling/greedy_search only; beam search scores "
                    "every candidate exactly and stays on the "
                    "lockstep generate() path")
        if self.num_return_sequences < 1:
            raise ValueError(
                f"num_return_sequences must be >= 1, got "
                f"{self.num_return_sequences}")
        if self.decode_strategy not in ("sampling", "greedy_search",
                                        "beam_search"):
            raise ValueError(
                f"unknown decode_strategy {self.decode_strategy!r}")
        if self.decode_strategy == "beam_search":
            if self.num_beams < 1:
                raise ValueError("num_beams must be >= 1")
            if self.num_return_sequences > self.num_beams:
                raise ValueError(
                    f"num_return_sequences ({self.num_return_sequences})"
                    f" cannot exceed num_beams ({self.num_beams})")
            if self.num_beam_groups < 1:
                raise ValueError("num_beam_groups must be >= 1")
            if self.num_beams % self.num_beam_groups:
                raise ValueError(
                    f"num_beams ({self.num_beams}) must be divisible "
                    f"by num_beam_groups ({self.num_beam_groups})")
            if self.num_beam_groups > 1 and self.diversity_rate <= 0.0:
                raise ValueError(
                    "num_beam_groups > 1 requires diversity_rate > 0 "
                    "(otherwise the groups search identically)")
            # YAML integers ("diversity_rate: 1") must not crash the
            # processor's strict float check at trace time
            object.__setattr__(self, "diversity_rate",
                               float(self.diversity_rate))

    @classmethod
    def from_config(cls, section) -> "GenerationConfig":
        import dataclasses as dc
        fields = {f.name for f in dc.fields(cls)}
        kwargs = {k: v for k, v in dict(section or {}).items()
                  if k in fields and v is not None}
        return cls(**kwargs)


def _decode_bias(valid_keys: jax.Array, dtype=jnp.float32) -> jax.Array:
    """[b, kv] validity -> additive [b, 1, 1, kv] bias."""
    return jnp.where(valid_keys, 0.0, NEG_INF)[:, None, None, :].astype(
        dtype)


def _unstack_layer_params(tree, num_layers: int):
    """Expand every ``decoder`` nn.scan stack (leaves with a leading
    ``num_layers`` axis) into ``decoder_0 .. decoder_{L-1}`` subtrees
    — the parameter layout the unrolled (``scan_layers=False``) model
    expects."""
    if not isinstance(tree, Mapping):
        return tree
    out = {}
    for key, sub in tree.items():
        if key == "decoder":
            for i in range(num_layers):
                out[f"decoder_{i}"] = jax.tree.map(
                    lambda x, i=i: x[i], dict(sub))
        else:
            out[key] = _unstack_layer_params(sub, num_layers)
    return out


def _has_decoder_stack(tree) -> bool:
    if not isinstance(tree, Mapping):
        return False
    return any(k == "decoder" or _has_decoder_stack(v)
               for k, v in tree.items())


def _unrolled_twin(model, params):
    """Decode-path twin with the layer loop UNROLLED.

    Training wants ``nn.scan`` over layers (one compiled layer body).
    Cached decode wants the opposite: under the scan, each step must
    dynamic-slice every layer's [b, h, d, capacity] K/V out of the
    stacked cache carry and dynamic-update-slice it back, and XLA
    materializes those as full-buffer copies — measured ~40% of decode
    step time at 345M/bs8 (projects/gpt/docs/inference analysis).
    Unrolled, each layer owns a plain cache buffer that XLA updates in
    place. One up-front unstack of the scanned params replaces the
    per-step stacked-cache traffic."""
    cfg = model.config
    if not cfg.scan_layers or not _has_decoder_stack(params):
        return model, params
    twin = type(model)(dataclasses.replace(cfg, scan_layers=False))
    return twin, _unstack_layer_params(params, cfg.num_layers)


@partial(jax.jit, static_argnames=("model", "gen_cfg"))
def generate(model, params, input_ids: jax.Array,
             attention_mask: Optional[jax.Array], rng: jax.Array,
             gen_cfg: GenerationConfig) -> jax.Array:
    """Returns generated token ids ``[b * num_return_sequences,
    max_dec_len]`` — prompt-major when ``num_return_sequences > 1``
    (rows ``i*n .. i*n + n - 1`` are prompt ``i``'s copies).

    ``input_ids`` is left-padded ``[b, prompt_len]``;
    ``attention_mask`` marks real tokens (1) vs pads (0), or None for
    unpadded prompts.
    """
    model, params = _unrolled_twin(model, params)
    cfg: GPTConfig = model.config
    beam = gen_cfg.decode_strategy == "beam_search"
    # beam search keeps num_beams rows per prompt live; sampling tiles
    # by num_return_sequences (reference expand_inputs_for_generation,
    # hybrid_model.py:1422-1426 — tile BEFORE prefill: the copies
    # prefill redundantly, the reference's cost profile; re-tiling the
    # scan-stacked cache after one prefill would be fragile)
    tile = gen_cfg.num_beams if beam else gen_cfg.num_return_sequences
    if tile > 1:
        input_ids = jnp.repeat(input_ids, tile, axis=0)
        if attention_mask is not None:
            attention_mask = jnp.repeat(attention_mask, tile, axis=0)
    b, prompt_len = input_ids.shape
    # the cache allocates cache_capacity slots (max_position_embeddings
    # rounded up to a 128 multiple — config.py) so the decode-kernel
    # tiling never rejects the cache length; the validity map must
    # cover every allocated slot, while the LENGTH bound below stays
    # at max_position_embeddings (the position-embedding table size)
    capacity = cfg.cache_capacity
    compute_dtype = jnp.dtype(cfg.dtype)
    if compute_dtype != jnp.float32:
        # flax casts fp32 params to the compute dtype inside every op,
        # so the decode loop would stream fp32 bytes each token; one
        # up-front cast is numerically identical and halves the
        # per-token parameter bandwidth (the decode bottleneck).
        # int8 kernels (non-floating) and their fp32 dequant scales
        # (quant_execution, docs/quantization.md) pass through — the
        # scale grid is part of the PTQ artifact's numerics.
        def _cast(path, p):
            name = getattr(path[-1], "key", "")
            if name == "kernel_scale" or not jnp.issubdtype(
                    p.dtype, jnp.floating):
                return p
            return p.astype(compute_dtype)
        params = jax.tree_util.tree_map_with_path(_cast, params)
    if prompt_len + gen_cfg.max_dec_len > cfg.max_position_embeddings:
        raise ValueError(
            f"prompt ({prompt_len}) + max_dec_len "
            f"({gen_cfg.max_dec_len}) exceeds the cache capacity "
            f"(max_position_embeddings "
            f"{cfg.max_position_embeddings})")
    if attention_mask is None:
        attention_mask = jnp.ones((b, prompt_len), jnp.int32)
    attention_mask = attention_mask.astype(jnp.int32)
    lengths = attention_mask.sum(axis=-1)                      # [b]
    position_ids = jnp.clip(
        jnp.cumsum(attention_mask, axis=-1) - 1, 0)

    # key-slot validity over the cache: prompt slots follow the pad
    # mask, decode slots become valid as they are written
    pad_cols = jnp.zeros((b, capacity - prompt_len), jnp.int32)
    base_valid = jnp.concatenate([attention_mask, pad_cols], axis=-1)

    # -- prefill -------------------------------------------------------
    # keys span the full preallocated cache during cached prefill, so
    # the pad bias covers all capacity slots (causality masks the rest)
    logits, mutated = model.apply(
        {"params": params}, input_ids, position_ids=position_ids,
        attn_bias=_decode_bias(base_valid.astype(bool)),
        use_cache=True, deterministic=True, mutable=["cache"])
    cache = mutated["cache"]
    last_logits = logits[:, -1, :].astype(jnp.float32)

    appeared0 = jnp.zeros((b, cfg.vocab_size), bool)
    appeared0 = appeared0.at[
        jnp.arange(b)[:, None], input_ids].set(attention_mask > 0)

    def sample_token(logits, appeared, step_idx, step_rng):
        """Pick the next token per row (greedy or filtered sample)."""
        logits = repetition_penalty_processor(
            logits, appeared, gen_cfg.repetition_penalty)
        # step_idx == tokens generated before this sample: EOS stays
        # banned until min_dec_len tokens exist (reference
        # MinLengthLogitsProcessor counts the same way)
        logits = min_length_processor(
            logits, step_idx, gen_cfg.min_dec_len,
            gen_cfg.eos_token_id)
        if gen_cfg.decode_strategy == "greedy_search":
            return jnp.argmax(logits, axis=-1)
        logits = logits / jnp.maximum(gen_cfg.temperature, 1e-6)
        logits = top_k_top_p_filter(logits, gen_cfg.top_k,
                                    gen_cfg.top_p,
                                    approx=gen_cfg.approx_top_k)
        return jax.random.categorical(step_rng, logits, axis=-1)

    def body(carry, step_idx):
        """One greedy/sampling decode step of the scan."""
        cache, logits, appeared, finished, valid = carry
        step_rng = jax.random.fold_in(rng, step_idx)
        token = sample_token(logits, appeared, step_idx, step_rng)
        token = jnp.where(finished, gen_cfg.pad_token_id, token)
        finished = finished | (token == gen_cfg.eos_token_id)
        appeared = appeared.at[jnp.arange(b), token].set(True)

        # the new key lands at slot prompt_len + step_idx
        slot = prompt_len + step_idx
        valid = valid.at[:, slot].set(1)
        step_pos = (lengths + step_idx)[:, None]               # [b, 1]
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, token[:, None],
            position_ids=step_pos,
            attn_bias=_decode_bias(valid.astype(bool)),
            use_cache=True, deterministic=True, mutable=["cache"])
        cache = mutated["cache"]
        next_logits = logits[:, -1, :].astype(jnp.float32)
        return (cache, next_logits, appeared, finished, valid), token

    if beam:
        return _beam_search(model, params, cache, last_logits,
                            base_valid, lengths, prompt_len, gen_cfg,
                            appeared0)

    finished0 = jnp.zeros((b,), bool)
    (_, _, _, _, _), tokens = jax.lax.scan(
        body, (cache, last_logits, appeared0, finished0, base_valid),
        jnp.arange(gen_cfg.max_dec_len))
    return tokens.T  # [b, max_dec_len]


def _gather_cache(cache, gidx):
    """Reorder the decode cache's batch axis to beam assignments.

    The KV leaves are ``[b, h, d, S]`` (or ``[L, b, h, d, S]`` under
    the layer scan) — the batch axis is always ``ndim - 4``;
    ``cache_index`` is batch-free and passes through."""
    def g(path, leaf):
        name = getattr(path[-1], "key", "")
        if name in ("cached_key", "cached_value",
                    "cached_key_scale", "cached_value_scale"):
            return jnp.take(leaf, gidx, axis=leaf.ndim - 4)
        return leaf
    return jax.tree_util.tree_map_with_path(g, cache)


def _length_penalty(length, alpha):
    """GNMT: ``((5 + len) / 6) ** alpha`` (alpha 0 = pure log-prob)."""
    return ((5.0 + length.astype(jnp.float32)) / 6.0) ** alpha


def _beam_search(model, params, cache, last_logits, base_valid,
                 lengths, prompt_len, gen_cfg, appeared0):
    """Beam search over the tiled ``b0 * k`` batch (beyond the
    reference, which supports sampling only — its processor file
    carries beam machinery the model never drives).

    Two-pool fixed-width search inside one ``lax.scan`` (the t5x
    shape): per step the top ``2k`` of the ``k * V`` candidates per
    prompt split into EOS hypotheses — inserted, length-penalized,
    into a separate finished pool they can never be evicted from by
    live beams — and the ``k`` best non-EOS continuations, which the
    KV cache is reordered to follow. The final ranking merges the
    finished pool with the length-penalized live beams and returns the
    ``num_return_sequences`` best per prompt, prompt-major. Applies
    min-length and repetition-penalty processing like the sampling
    path.

    NOTE: beam scores accumulate the PROCESSED log-probs (after
    repetition-penalty / min-length / Hamming shaping), matching the
    reference's and HF's beam semantics — so with
    ``repetition_penalty != 1.0`` the ranking deviates from raw model
    likelihood by design. Pinned at k=1 by
    ``test_beam_search_repetition_penalty_k1_equals_greedy`` and at
    k>1 by ``test_beam_search_processed_score_semantics_k_gt_1``
    (an independent teacher-forced replay of the processor pipeline
    must reproduce the returned beam ordering).

    With ``num_beam_groups > 1`` this becomes diverse (group) beam
    search: each group of ``k/G`` beams runs the same two-pool update,
    but groups are scored sequentially within a step and every group
    after the first pays ``hamming_diversity_processor``'s penalty on
    the tokens earlier groups just chose. One ``model.apply`` still
    serves all ``k`` beams per step — only the selection loop is
    per-group.
    """
    k = gen_cfg.num_beams
    G = gen_cfg.num_beam_groups
    kg = k // G
    V = last_logits.shape[-1]
    b = last_logits.shape[0]
    b0 = b // k
    eos, pad = gen_cfg.eos_token_id, gen_cfg.pad_token_id
    dec = gen_cfg.max_dec_len

    # only the first beam OF EACH GROUP is live at step 0 (all k rows
    # are prompt copies; a dead group would never start)
    alive0 = jnp.tile(
        jnp.asarray(([0.0] + [NEG_INF] * (kg - 1)) * G, jnp.float32),
        (b0, 1))
    seqs0 = jnp.full((b, dec), pad, jnp.int32)
    fin_scores0 = jnp.full((b0, G, kg), NEG_INF, jnp.float32)
    fin_seqs0 = jnp.full((b0, G, kg, dec), pad, jnp.int32)
    # appeared0 carries the prompt tokens (same repetition-penalty
    # seeding as the sampling path)

    def body(carry, step_idx):
        """One beam-search expansion step of the scan."""
        (cache, logits, alive, seqs, appeared, fin_scores,
         fin_seqs, valid) = carry
        logits = repetition_penalty_processor(
            logits.astype(jnp.float32), appeared,
            gen_cfg.repetition_penalty)
        logits = min_length_processor(logits, step_idx,
                                      gen_cfg.min_dec_len, eos)
        logp = jax.nn.log_softmax(logits, -1).reshape(b0, k, V)

        cur_tokens = jnp.zeros((b0, k), jnp.int32)
        galive, gtokens, gsrc = [], [], []
        gfin_scores, gfin_seqs = [], []
        for g in range(G):
            sl = slice(g * kg, (g + 1) * kg)
            glogp = logp[:, sl]                        # [b0, kg, V]
            if g > 0 and gen_cfg.diversity_rate > 0.0:
                shaped = hamming_diversity_processor(
                    glogp.reshape(b0 * kg, V),
                    cur_tokens.reshape(-1), g,
                    gen_cfg.diversity_rate, k, G)
                glogp = shaped.reshape(b0, kg, V)
            cand = alive[:, sl][..., None] + glogp
            n_top = min(2 * kg, kg * V)
            top_scores, top_idx = jax.lax.top_k(
                cand.reshape(b0, kg * V), n_top)
            src_beam = top_idx // V + g * kg           # absolute beam
            token = (top_idx % V).astype(jnp.int32)
            is_eos = token == eos

            # group finished pool: EOS candidates enter
            # length-penalized and compete only against other finished
            # hypotheses of the same group
            cand_fin = jnp.where(
                is_eos,
                top_scores / _length_penalty(
                    jnp.full_like(top_scores, step_idx + 1.0),
                    gen_cfg.length_penalty),
                NEG_INF)
            # materialize each candidate's sequence (prefix + eos)
            cand_rows = jnp.arange(b0)[:, None] * k + src_beam
            cand_seqs = seqs[cand_rows.reshape(-1)].reshape(
                b0, n_top, dec)
            cand_seqs = cand_seqs.at[:, :, step_idx].set(token)
            merged_scores = jnp.concatenate(
                [fin_scores[:, g], cand_fin], axis=1)
            merged_seqs = jnp.concatenate(
                [fin_seqs[:, g], cand_seqs], axis=1)
            fs, keep = jax.lax.top_k(merged_scores, kg)
            gfin_scores.append(fs)
            gfin_seqs.append(jnp.take_along_axis(
                merged_seqs, keep[..., None], axis=1))

            # group alive pool: best kg non-EOS continuations
            alive_cand = jnp.where(is_eos, NEG_INF, top_scores)
            al, pick = jax.lax.top_k(alive_cand, kg)   # [b0, kg]
            tok = jnp.take_along_axis(token, pick, axis=1)
            galive.append(al)
            gtokens.append(tok)
            gsrc.append(jnp.take_along_axis(src_beam, pick, axis=1))
            cur_tokens = cur_tokens.at[:, sl].set(tok)

        alive = jnp.concatenate(galive, axis=1)        # [b0, k]
        token_k = jnp.concatenate(gtokens, axis=1)
        src_k = jnp.concatenate(gsrc, axis=1)
        fin_scores = jnp.stack(gfin_scores, axis=1)    # [b0, G, kg]
        fin_seqs = jnp.stack(gfin_seqs, axis=1)
        gidx = (jnp.arange(b0)[:, None] * k + src_k).reshape(-1)

        seqs = seqs[gidx].at[:, step_idx].set(token_k.reshape(-1))
        appeared = appeared[gidx].at[
            jnp.arange(b), token_k.reshape(-1)].set(True)
        cache = _gather_cache(cache, gidx)
        valid = valid[gidx].at[:, prompt_len + step_idx].set(1)
        step_pos = (lengths + step_idx)[:, None]     # equal per group
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            token_k.reshape(-1)[:, None], position_ids=step_pos,
            attn_bias=_decode_bias(valid.astype(bool)),
            use_cache=True, deterministic=True, mutable=["cache"])
        return (mutated["cache"], logits[:, -1].astype(jnp.float32),
                alive, seqs, appeared, fin_scores, fin_seqs,
                valid), None

    (_, _, alive, seqs, _, fin_scores, fin_seqs, _), _ = jax.lax.scan(
        body, (cache, last_logits, alive0, seqs0, appeared0,
               fin_scores0, fin_seqs0, base_valid), jnp.arange(dec))
    fin_scores = fin_scores.reshape(b0, k)
    fin_seqs = fin_seqs.reshape(b0, k, dec)

    # merge live beams (length-penalized at full length) with the
    # finished pool and pick the n best per prompt
    alive_final = alive / _length_penalty(
        jnp.full_like(alive, float(dec)), gen_cfg.length_penalty)
    all_scores = jnp.concatenate([fin_scores, alive_final], axis=1)
    all_seqs = jnp.concatenate(
        [fin_seqs, seqs.reshape(b0, k, dec)], axis=1)
    _, best = jax.lax.top_k(all_scores,
                            gen_cfg.num_return_sequences)
    out = jnp.take_along_axis(all_seqs, best[..., None], axis=1)
    return out.reshape(b0 * gen_cfg.num_return_sequences, dec)


# -- continuous-batching slot primitives -------------------------------
#
# The lockstep generate() above advances every row at one shared cache
# index. The serving path (core/serving.py) instead keeps a persistent
# [slots, ...] KV cache whose rows are independent requests at
# independent lengths: prefill_into_slots admits new requests into free
# slot rows (one compiled shape per prompt-length bucket), decode_step
# advances ALL slots one token with per-slot lengths/sampling state via
# the ragged attention dispatch (cache_lengths -> flash_decode_ragged
# or the XLA per-row-offset fallback — docs/inference.md).


class SlotState(NamedTuple):
    """Per-slot decode state carried across serving ticks.

    One row per KV-cache slot; a pytree so the whole state threads
    through the jitted ``decode_step`` unchanged in structure.
    """
    #: [slots] int32 — valid cache positions (the slot's token count)
    lengths: jax.Array
    #: [slots] int32 — tokens generated so far (the per-request
    #: step_idx of the lockstep loop)
    dec_count: jax.Array
    #: [slots] int32 — per-request rng stream id (folded into the
    #: server rng so a request's sample stream is independent of slot
    #: assignment and neighbours)
    nonce: jax.Array
    #: [slots, V] bool — repetition-penalty token set
    appeared: jax.Array
    #: [slots] bool — emitted EOS
    finished: jax.Array
    #: [slots] bool — slot holds a live request
    active: jax.Array
    #: [slots, V] f32 — logits the next tick samples from
    last_logits: jax.Array
    #: [slots] int32 — draft token the previous verify tick REJECTED
    #: under sampling (-1 = none): the standard rejection-sampling
    #: residual excludes it, so the next tick's sample from
    #: ``last_logits`` masks it out post-filter (verify_step). Always
    #: -1 under greedy and with speculation off.
    rejected: jax.Array


def init_slot_state(num_slots: int, vocab_size: int) -> SlotState:
    """All-free slot state (no request admitted anywhere)."""
    z = jnp.zeros((num_slots,), jnp.int32)
    f = jnp.zeros((num_slots,), bool)
    return SlotState(
        lengths=z, dec_count=z, nonce=z,
        appeared=jnp.zeros((num_slots, vocab_size), bool),
        finished=f, active=f,
        last_logits=jnp.zeros((num_slots, vocab_size), jnp.float32),
        rejected=jnp.full((num_slots,), -1, jnp.int32))


def init_slot_cache(model, params, num_slots: int):
    """Zeroed persistent ``[slots, ...]`` KV-cache tree, shaped by
    ``jax.eval_shape`` over a cached apply (no compile, no FLOPs)."""
    shapes = jax.eval_shape(
        lambda p: model.apply(
            {"params": p}, jnp.zeros((num_slots, 1), jnp.int32),
            use_cache=True, deterministic=True,
            mutable=["cache"])[1]["cache"],
        params)
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)


def _constrain_slot_cache(cache):
    """Pin the serving cache's logical layout: slots over the dataflow
    plane, heads over mp (``cache_slots`` rule in parallel/sharding.py).
    A no-op without an active mesh/rules context."""
    def g(path, leaf):
        name = getattr(path[-1], "key", "")
        if name in ("cached_key", "cached_value",
                    "cached_key_scale", "cached_value_scale"):
            axes = (None,) * (leaf.ndim - 4) + (
                "cache_slots", "act_heads", None, None)
            return with_logical_constraint(leaf, axes)
        return leaf
    return jax.tree_util.tree_map_with_path(g, cache)


def _scatter_slot_rows(cache, rows, slot_ids):
    """Write per-request cache rows (batch = len(slot_ids)) into the
    persistent slot cache at ``slot_ids``. KV leaves are
    ``[..., b, h, d, S]`` with the batch axis at ``ndim - 4`` (matching
    ``_gather_cache``); the scalar ``cache_index`` leaves keep the
    persistent cache's value — slot lengths live in ``SlotState``."""
    def put(path, pleaf, rleaf):
        name = getattr(path[-1], "key", "")
        if name in ("cached_key", "cached_value",
                    "cached_key_scale", "cached_value_scale"):
            ax = pleaf.ndim - 4
            idx = (slice(None),) * ax + (slot_ids,)
            return pleaf.at[idx].set(rleaf.astype(pleaf.dtype))
        return pleaf
    return jax.tree_util.tree_map_with_path(put, cache, rows)


@partial(jax.jit, static_argnames=("model",))
def prefill_into_slots(model, params, cache, state: SlotState,
                       slot_ids: jax.Array, input_ids: jax.Array,
                       true_lengths: jax.Array,
                       nonce: jax.Array, adapter_ids=None):
    """Admit requests into free slots: prefill + scatter.

    ``input_ids`` is RIGHT-padded ``[n, bucket]`` (prompts start at
    cache position 0 of their slot; the pad tail past each row's
    ``true_lengths`` is never read — causality masks it during prefill
    and the per-slot length masks it during decode, so bucketing
    prompt lengths to a few compiled shapes costs nothing but the
    padded prefill FLOPs). Runs the ordinary scalar-cache-index
    prefill over the ``n`` new requests, gathers each row's
    last-real-token logits, and scatters the fresh cache rows and
    sampling state into the persistent ``[slots, ...]`` cache /
    ``SlotState`` at ``slot_ids``. One compiled shape per
    ``(n, bucket)`` pair.
    """
    n, bucket = input_ids.shape
    pos = jnp.broadcast_to(
        jnp.arange(bucket, dtype=jnp.int32)[None, :], (n, bucket))
    logits, mutated = model.apply(
        {"params": params}, input_ids, position_ids=pos,
        use_cache=True, deterministic=True, adapter_ids=adapter_ids,
        mutable=["cache"])
    last = jnp.take_along_axis(
        logits.astype(jnp.float32),
        jnp.maximum(true_lengths, 1)[:, None, None] - 1, axis=1)[:, 0]
    real = pos < true_lengths[:, None]                    # [n, bucket]
    appeared = jnp.zeros((n, model.config.vocab_size), bool)
    # scatter-max: True (a real occurrence) wins over the pad tail's
    # False even when a token id shows up in both regions
    appeared = appeared.at[jnp.arange(n)[:, None], input_ids].max(real)

    cache = _scatter_slot_rows(cache, mutated["cache"], slot_ids)
    cache = _constrain_slot_cache(cache)
    state = SlotState(
        lengths=state.lengths.at[slot_ids].set(true_lengths),
        dec_count=state.dec_count.at[slot_ids].set(0),
        nonce=state.nonce.at[slot_ids].set(nonce),
        appeared=state.appeared.at[slot_ids].set(appeared),
        finished=state.finished.at[slot_ids].set(False),
        active=state.active.at[slot_ids].set(True),
        last_logits=state.last_logits.at[slot_ids].set(last),
        rejected=state.rejected.at[slot_ids].set(-1))
    return cache, state


def _decode_tick_impl(model, params, cache, state: SlotState,
                      rng: jax.Array, gen_cfg: GenerationConfig,
                      page_table=None, adapter_ids=None):
    """Trace-level body of one plain decode tick — the SHARED step
    function of the standalone :func:`decode_step` jit and the fused
    :func:`decode_loop` ``lax.while_loop``; both paths trace exactly
    this code, so the loop at any T commits the same tokens the
    one-tick-per-round-trip server does."""
    slots = state.lengths.shape[0]
    logits = repetition_penalty_processor(
        state.last_logits, state.appeared, gen_cfg.repetition_penalty)
    logits = min_length_processor(
        logits, state.dec_count[:, None], gen_cfg.min_dec_len,
        gen_cfg.eos_token_id)
    if gen_cfg.decode_strategy == "greedy_search":
        token = jnp.argmax(logits, axis=-1)
    elif gen_cfg.decode_strategy == "sampling":
        logits = logits / jnp.maximum(gen_cfg.temperature, 1e-6)
        logits = top_k_top_p_filter(logits, gen_cfg.top_k,
                                    gen_cfg.top_p,
                                    approx=gen_cfg.approx_top_k)
        # per-slot streams: (request nonce, request step) fold so a
        # request samples the same continuation whichever slot it
        # lands in and whenever it was admitted
        step_keys = jax.vmap(
            lambda n, c: jax.random.fold_in(
                jax.random.fold_in(rng, n), c))(
            state.nonce, state.dec_count)
        token = jax.vmap(
            lambda kk, lg: jax.random.categorical(kk, lg))(
            step_keys, logits)
    else:
        raise ValueError(
            f"decode_step supports sampling/greedy_search, got "
            f"{gen_cfg.decode_strategy!r} (beam search stays on the "
            f"lockstep generate() path)")
    token = jnp.where(state.finished | ~state.active,
                      gen_cfg.pad_token_id, token).astype(jnp.int32)
    finished = state.finished | (
        state.active & (token == gen_cfg.eos_token_id))
    appeared = state.appeared.at[jnp.arange(slots), token].set(True)

    step_pos = jnp.clip(state.lengths, 0,
                        model.config.max_position_embeddings - 1)
    logits2, mutated = model.apply(
        {"params": params, "cache": cache}, token[:, None],
        position_ids=step_pos[:, None], use_cache=True,
        deterministic=True, cache_lengths=state.lengths,
        page_table=page_table, adapter_ids=adapter_ids,
        mutable=["cache"])
    cache = _constrain_slot_cache(mutated["cache"])
    new_state = SlotState(
        lengths=jnp.where(state.active, state.lengths + 1,
                          state.lengths),
        dec_count=jnp.where(state.active, state.dec_count + 1,
                            state.dec_count),
        nonce=state.nonce,
        appeared=appeared,
        finished=finished,
        active=state.active,
        last_logits=logits2[:, -1].astype(jnp.float32),
        rejected=state.rejected)
    return cache, new_state, token


@partial(jax.jit, static_argnames=("model", "gen_cfg"))
def decode_step(model, params, cache, state: SlotState,
                rng: jax.Array, gen_cfg: GenerationConfig,
                page_table=None, adapter_ids=None):
    """One shared decode tick over the whole slot batch.

    Mirrors the lockstep ``body`` of :func:`generate` slot-for-slot —
    sample from ``last_logits`` through the same processor pipeline
    (repetition penalty over ``appeared``, min-length over the
    PER-SLOT ``dec_count``), then advance the model one token with
    per-slot cache writes and ragged attention (``cache_lengths``).
    Greedy decoding therefore reproduces ``generate()`` exactly,
    whatever mix of lengths/admission times the slots hold. Inactive
    (free) slots ride along as pad tokens with frozen lengths; their
    writes land at their stale position and are overwritten before any
    later read (prefill rewrites the full row at admission).

    Returns ``(cache, state, tokens)`` — ``tokens [slots]`` is what
    each slot emitted this tick (pad for finished/inactive slots).
    """
    return _decode_tick_impl(model, params, cache, state, rng,
                             gen_cfg, page_table, adapter_ids)


#: fold_in salt separating a verify tick's ACCEPT uniform at request
#: step c+j from the categorical the NEXT tick draws at the same step
#: when that draft is rejected (the correction token) — without it the
#: two draws would share a key and correlate, breaking the
#: rejection-sampling guarantee.
SPEC_ACCEPT_SALT = 7919


def _verify_tick_impl(model, params, cache, state: SlotState,
                      drafts: jax.Array, rng: jax.Array,
                      gen_cfg: GenerationConfig, page_table=None,
                      adapter_ids=None):
    """Trace-level body of one speculative verify tick — the SHARED
    step function of the standalone :func:`verify_step` jit and the
    fused :func:`verify_loop`; see :func:`verify_step` for the full
    commit semantics."""
    slots, k = drafts.shape
    vocab = model.config.vocab_size
    eos, pad = gen_cfg.eos_token_id, gen_cfg.pad_token_id
    arange_s = jnp.arange(slots)

    def processed(raw, appeared, dec_count):
        lg = repetition_penalty_processor(
            raw, appeared, gen_cfg.repetition_penalty)
        return min_length_processor(
            lg, dec_count[:, None], gen_cfg.min_dec_len, eos)

    def step_keys(dec_count, salt=None):
        def one(n, c):
            kk = jax.random.fold_in(jax.random.fold_in(rng, n), c)
            return kk if salt is None else jax.random.fold_in(kk, salt)
        return jax.vmap(one)(state.nonce, dec_count)

    # -- t0: decode_step's sampling pipeline, residual-masked ---------
    logits = processed(state.last_logits, state.appeared,
                       state.dec_count)
    if gen_cfg.decode_strategy == "greedy_search":
        t0 = jnp.argmax(logits, axis=-1)
    elif gen_cfg.decode_strategy == "sampling":
        lg = logits / jnp.maximum(gen_cfg.temperature, 1e-6)
        lg = top_k_top_p_filter(lg, gen_cfg.top_k, gen_cfg.top_p,
                                approx=gen_cfg.approx_top_k)
        # rejection-sampling residual: the draft the PREVIOUS tick
        # rejected is excluded from this draw (-1 matches nothing, so
        # spec-off slots sample bit-identically to decode_step)
        lg = jnp.where(
            jnp.arange(vocab)[None, :] == state.rejected[:, None],
            NEG_INF, lg)
        t0 = jax.vmap(
            lambda kk, row: jax.random.categorical(kk, row))(
            step_keys(state.dec_count), lg)
    else:
        raise ValueError(
            f"verify_step supports sampling/greedy_search, got "
            f"{gen_cfg.decode_strategy!r}")
    t0 = jnp.where(state.finished | ~state.active,
                   pad, t0).astype(jnp.int32)

    # -- one forward over the [slots, k+1] window ---------------------
    window = jnp.concatenate(
        [t0[:, None], jnp.asarray(drafts, jnp.int32)], axis=1)
    mpe = model.config.max_position_embeddings
    pos = jnp.clip(
        state.lengths[:, None] +
        jnp.arange(k + 1, dtype=jnp.int32)[None, :], 0, mpe - 1)
    logits2, mutated = model.apply(
        {"params": params, "cache": cache}, window,
        position_ids=pos, use_cache=True, deterministic=True,
        cache_lengths=state.lengths, page_table=page_table,
        adapter_ids=adapter_ids, mutable=["cache"])
    cache = _constrain_slot_cache(mutated["cache"])
    logits_w = logits2.astype(jnp.float32)     # [slots, k+1, V]

    # -- vectorized accept/reject, left to right ----------------------
    fin = state.finished | (state.active & (t0 == eos))
    appeared = state.appeared.at[arange_s, t0].set(True)
    commit = jnp.ones((slots,), bool)          # t0 always emits
    counts = jnp.ones((slots,), jnp.int32)
    rejected_new = jnp.full((slots,), -1, jnp.int32)
    mmax = gen_cfg.max_dec_len - state.dec_count
    for j in range(1, k + 1):
        dj = window[:, j]
        lg = processed(logits_w[:, j - 1], appeared,
                       state.dec_count + j)
        if gen_cfg.decode_strategy == "greedy_search":
            ok = dj == jnp.argmax(lg, axis=-1)
        else:
            lg = lg / jnp.maximum(gen_cfg.temperature, 1e-6)
            lg = top_k_top_p_filter(lg, gen_cfg.top_k, gen_cfg.top_p,
                                    approx=gen_cfg.approx_top_k)
            p = jax.nn.softmax(lg, axis=-1)
            pj = jnp.take_along_axis(p, dj[:, None], axis=1)[:, 0]
            u = jax.vmap(jax.random.uniform)(
                step_keys(state.dec_count + j, SPEC_ACCEPT_SALT))
            ok = u < pj
        can = commit & ~fin & state.active & (j < mmax)
        cj = can & ok
        if gen_cfg.decode_strategy == "sampling":
            # at most one (can & ~ok) per slot — commit chains stop at
            # the first rejection
            rejected_new = jnp.where(can & ~ok, dj, rejected_new)
        commit = cj
        counts = counts + cj
        appeared = appeared.at[arange_s, dj].max(cj)
        fin = fin | (cj & (dj == eos))

    new_state = SlotState(
        lengths=jnp.where(state.active, state.lengths + counts,
                          state.lengths),
        dec_count=jnp.where(state.active, state.dec_count + counts,
                            state.dec_count),
        nonce=state.nonce,
        appeared=appeared,
        finished=fin,
        active=state.active,
        # the logits AFTER the last committed token — the next tick's
        # t0 distribution (on a rejection this is the residual's
        # source distribution; combined with the `rejected` mask it
        # completes the rejection-sampling rule)
        last_logits=jnp.take_along_axis(
            logits_w, (counts - 1)[:, None, None], axis=1)[:, 0],
        rejected=rejected_new)
    return cache, new_state, window, counts


@partial(jax.jit, static_argnames=("model", "gen_cfg"))
def verify_step(model, params, cache, state: SlotState,
                drafts: jax.Array, rng: jax.Array,
                gen_cfg: GenerationConfig, page_table=None,
                adapter_ids=None):
    """One SPECULATIVE tick: score ``k`` drafted tokens per slot in a
    single forward and commit the accepted prefix (+1 sampled token).

    ``drafts [slots, k]`` are the host draft source's guesses for each
    request's NEXT k tokens AFTER the one this tick samples
    (``core/spec.py``; draft content only affects throughput, never
    output). The tick:

    1. samples ``t0`` from ``last_logits`` through exactly
       :func:`decode_step`'s processor/sampling pipeline (same
       ``(nonce, dec_count)`` key fold — the spec-off stream), with
       the previous tick's ``rejected`` draft masked out post-filter
       (the rejection-sampling residual);
    2. runs the model ONCE over the ``[slots, k+1]`` window
       ``[t0, d_1..d_k]`` at positions ``lengths .. lengths + k``
       (ragged multi-token cache writes + the within-window causal
       verify mask — ``flash_decode_ragged``/``flash_decode_paged``
       or the XLA fallback, docs/inference.md);
    3. walks the drafts left to right: draft ``d_j`` is committed iff
       every earlier window token committed, none of them was EOS,
       the per-request budget allows it (``dec_count + j <
       max_dec_len`` — the sequential server would have evicted), and
       it passes the accept test — greedy: ``d_j`` equals the argmax
       of the processed logits at its position (teacher-forced logits
       are the sequential logits, so greedy output is token-exact
       spec-off); sampling: a salted per-step uniform under the
       draft's model probability (deterministic draft proposal ⇒ the
       standard rejection rule accepts with prob ``p(d_j)`` and the
       residual excludes ``d_j``, recorded in ``rejected`` for the
       next tick).

    Rejected KV needs no device-side undo: lengths only advance by the
    committed count, so the next window overwrites the stale columns
    before any masked read reaches them (paged: the server frees/nulls
    pages past the accepted point).

    Returns ``(cache, state, window, counts)`` — ``window [slots,
    k+1]`` holds the tick's token run (entry 0 = ``t0``), ``counts
    [slots]`` how many of them committed (1..k+1; the host appends
    ``window[slot, :counts[slot]]``).
    """
    return _verify_tick_impl(model, params, cache, state, drafts,
                             rng, gen_cfg, page_table, adapter_ids)


# -- device-resident decode: T ticks per host round-trip ---------------
#
# decode_step/verify_step return control to Python after every tick, so
# small-batch decode pays host->device dispatch, result fetch, and host
# scheduling per committed token group — the latency-bound (not
# FLOP-bound) regime. The fused loops below wrap the SAME tick bodies
# (_decode_tick_impl/_verify_tick_impl) in a lax.while_loop that runs
# up to `loop_ticks` ticks on-device, buffering each tick's committed
# tokens in a [slots, T]-shaped ring the host replays afterwards, and
# exits early the moment host scheduling actually has work to do:
# any active slot finished (eviction pending), any slot's decode budget
# expired, or the host flagged pending work (admission / drain /
# preemption risk) at launch. Exit reasons are reported so the server
# can count serving/loop_exit/{finished,admission,budget,drain}
# (docs/inference.md "Device-resident decode").

#: a slot emitted EOS — the host must evict before the next tick
LOOP_EXIT_FINISHED = 1
#: a slot's decode budget expired (dec_count hit max_dec_len), or the
#: loop ran its full `loop_ticks` tick budget with nothing else to do
LOOP_EXIT_BUDGET = 2
#: the host-signaled flag was set at launch (pending admission, drain,
#: or page-pool preemption risk) — the loop ran exactly one tick
LOOP_EXIT_HOST = 3


def _ring_write(buf: jax.Array, vals: jax.Array, tick: jax.Array,
                loop_ticks: int) -> jax.Array:
    """Write one tick's row block into the per-tick ring buffer at
    position ``tick % loop_ticks`` along axis 1 (``buf`` is
    ``[slots, T]`` or ``[slots, T, k+1]``; ``vals`` drops the T axis).
    The fused loops never wrap (they run at most ``loop_ticks`` ticks
    per launch), but the modulo keeps the helper total for any tick
    counter a caller carries across launches."""
    return jax.lax.dynamic_update_index_in_dim(
        buf, vals, jnp.mod(tick, loop_ticks), axis=1)


def _loop_exit_flags(state: SlotState, gen_cfg: GenerationConfig):
    """``(fin_any, bud_any)`` — does any ACTIVE slot need host
    attention: emitted EOS (eviction), or decode budget spent
    (``dec_count >= max_dec_len``, the server's length eviction)."""
    fin_any = jnp.any(state.active & state.finished)
    bud_any = jnp.any(state.active & ~state.finished &
                      (state.dec_count >= gen_cfg.max_dec_len))
    return fin_any, bud_any


def _loop_exit_reason(state: SlotState, gen_cfg: GenerationConfig,
                      host_flag: jax.Array) -> jax.Array:
    """Why the fused loop stopped, by priority: a finished slot beats
    a spent budget beats the host flag; a full-T run with none of the
    above reads as the tick budget expiring (LOOP_EXIT_BUDGET)."""
    fin_any, bud_any = _loop_exit_flags(state, gen_cfg)
    return jnp.where(
        fin_any, LOOP_EXIT_FINISHED,
        jnp.where(bud_any, LOOP_EXIT_BUDGET,
                  jnp.where(host_flag != 0, LOOP_EXIT_HOST,
                            LOOP_EXIT_BUDGET))).astype(jnp.int32)


@partial(jax.jit, static_argnames=("model", "gen_cfg", "loop_ticks"))
def decode_loop(model, params, cache, state: SlotState,
                rng: jax.Array, gen_cfg: GenerationConfig,
                host_flag: jax.Array, page_table=None,
                adapter_ids=None, *, loop_ticks: int = 1):
    """Up to ``loop_ticks`` plain decode ticks in ONE device program.

    Each iteration runs exactly :func:`decode_step`'s tick body, so
    the committed token stream is identical to ``loop_ticks``
    sequential ``decode_step`` calls (the T=1/T>1 parity pin in
    tests/test_serving.py). The ``lax.while_loop`` always executes at
    least one tick, then keeps going while ticks remain AND no exit
    condition holds: an active slot finished, a slot's budget expired,
    or ``host_flag`` (a traced int32 scalar — nonzero means the host
    has pending admission/drain/preemption work and wants control back
    after one tick; traced so flag flips never recompile).

    Returns ``(cache, state, tokens_buf, ticks_run, exit_reason)`` —
    ``tokens_buf [slots, loop_ticks]`` holds tick ``j``'s emitted
    token per slot in column ``j`` (pad beyond ``ticks_run``),
    ``ticks_run`` int32 how many ticks executed (1..loop_ticks), and
    ``exit_reason`` one of the ``LOOP_EXIT_*`` codes.
    """
    if loop_ticks < 1:
        raise ValueError(f"loop_ticks must be >= 1, got {loop_ticks}")
    slots = state.lengths.shape[0]
    tokens_buf = jnp.full((slots, loop_ticks), gen_cfg.pad_token_id,
                          jnp.int32)
    host_flag = jnp.asarray(host_flag, jnp.int32)

    def cond(carry):
        _, st, _, tick = carry
        fin_any, bud_any = _loop_exit_flags(st, gen_cfg)
        return (tick == 0) | ((tick < loop_ticks) & ~fin_any &
                              ~bud_any & (host_flag == 0))

    def body(carry):
        cache, st, buf, tick = carry
        cache, st, tok = _decode_tick_impl(
            model, params, cache, st, rng, gen_cfg, page_table,
            adapter_ids)
        buf = _ring_write(buf, tok, tick, loop_ticks)
        return cache, st, buf, tick + 1

    cache, state, tokens_buf, ticks = jax.lax.while_loop(
        cond, body, (cache, state, tokens_buf, jnp.int32(0)))
    return (cache, state, tokens_buf, ticks,
            _loop_exit_reason(state, gen_cfg, host_flag))


@partial(jax.jit, static_argnames=("model", "gen_cfg", "loop_ticks"))
def verify_loop(model, params, cache, state: SlotState,
                drafts: jax.Array, rng: jax.Array,
                gen_cfg: GenerationConfig, host_flag: jax.Array,
                page_table=None, adapter_ids=None, *,
                loop_ticks: int = 1):
    """Up to ``loop_ticks`` speculative verify ticks in ONE device
    program — the spec twin of :func:`decode_loop`.

    ``drafts [slots, loop_ticks, k]`` carries k·T host-proposed draft
    tokens per slot per round-trip; tick ``j`` verifies slice
    ``drafts[:, j]`` through exactly :func:`verify_step`'s tick body.
    Drafts for every tick are proposed from the PRE-loop history (the
    host cannot see mid-loop commits), which never affects correctness
    — acceptance re-scores every draft against the model — only the
    accept rate; greedy output stays token-exact vs spec-off at any T.
    Exit conditions and the ``host_flag`` contract match
    :func:`decode_loop`.

    Returns ``(cache, state, window_buf, counts_buf, ticks_run,
    exit_reason)`` — tick ``j``'s token run is
    ``window_buf[:, j] [slots, k+1]`` of which
    ``counts_buf[:, j]`` committed per slot (0 beyond ``ticks_run``).
    """
    if loop_ticks < 1:
        raise ValueError(f"loop_ticks must be >= 1, got {loop_ticks}")
    slots, t_axis, k = drafts.shape
    if t_axis != loop_ticks:
        raise ValueError(
            f"drafts tick axis ({t_axis}) != loop_ticks "
            f"({loop_ticks})")
    window_buf = jnp.full((slots, loop_ticks, k + 1),
                          gen_cfg.pad_token_id, jnp.int32)
    counts_buf = jnp.zeros((slots, loop_ticks), jnp.int32)
    host_flag = jnp.asarray(host_flag, jnp.int32)
    drafts = jnp.asarray(drafts, jnp.int32)

    def cond(carry):
        _, st, _, _, tick = carry
        fin_any, bud_any = _loop_exit_flags(st, gen_cfg)
        return (tick == 0) | ((tick < loop_ticks) & ~fin_any &
                              ~bud_any & (host_flag == 0))

    def body(carry):
        cache, st, wbuf, cbuf, tick = carry
        d = jax.lax.dynamic_index_in_dim(
            drafts, jnp.mod(tick, loop_ticks), axis=1, keepdims=False)
        cache, st, window, counts = _verify_tick_impl(
            model, params, cache, st, d, rng, gen_cfg, page_table,
            adapter_ids)
        wbuf = _ring_write(wbuf, window, tick, loop_ticks)
        cbuf = _ring_write(cbuf, counts, tick, loop_ticks)
        return cache, st, wbuf, cbuf, tick + 1

    cache, state, window_buf, counts_buf, ticks = jax.lax.while_loop(
        cond, body,
        (cache, state, window_buf, counts_buf, jnp.int32(0)))
    return (cache, state, window_buf, counts_buf, ticks,
            _loop_exit_reason(state, gen_cfg, host_flag))


# -- paged KV primitives (core/paging.py owns the host bookkeeping) ----
#
# With cfg.kv_page_size/kv_pool_pages set, the serving cache stops
# being [slots, h, d, capacity] rows and becomes ONE global page pool
# [kv_pool_pages, h, d, kv_page_size] per layer that every slot reaches
# through a [slots, max_kv_pages] page table (model.py paged branch).
# The jitted pieces below are deliberately dumb — shape-stable scatter/
# copy/activate kernels — while allocation, refcounts, COW decisions
# and prefix sharing stay host-side in core/serving.py + core/paging.py.


def init_page_pool(model, params, num_slots: int):
    """Zeroed global KV page-pool tree for a paged server, shaped by
    ``jax.eval_shape`` over a paged decode apply (no compile, no
    FLOPs). ``model.config`` must carry ``kv_page_size`` /
    ``kv_pool_pages`` (the server builds that twin config)."""
    cfg = model.config
    shapes = jax.eval_shape(
        lambda p: model.apply(
            {"params": p}, jnp.zeros((num_slots, 1), jnp.int32),
            use_cache=True, deterministic=True,
            cache_lengths=jnp.zeros((num_slots,), jnp.int32),
            page_table=jnp.zeros((num_slots, cfg.max_kv_pages),
                                 jnp.int32),
            mutable=["cache"])[1]["cache"],
        params)
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)


@partial(jax.jit, static_argnames=("model",))
def prefill_chunk_paged(model, params, cache, input_chunk: jax.Array,
                        chunk_start: jax.Array, page_table: jax.Array,
                        adapter_ids=None):
    """One page-aligned chunk of a chunked prefill.

    ``input_chunk`` is ``[n, chunk]`` token ids (the tail past the
    prompt right-padded with any token — its KV lands beyond the
    prompt length, where the per-slot ragged masking never reads and
    the first decode writes overwrite); ``chunk_start`` ``[n]`` is each
    row's absolute position of the chunk's first token (a multiple of
    ``kv_page_size``); ``page_table`` ``[n, max_kv_pages]`` carries
    just the prefilling rows. The chunk's KV scatters straight into
    its physical pages (model.py ``chunk_start`` branch) while the
    queries attend every earlier position through the page-table
    gather. Returns ``(cache, logits)`` with fp32 ``[n, chunk, V]``
    logits — the server picks row ``prompt_len - 1 - chunk_start`` of
    the final chunk as the first sampling distribution. One compiled
    shape per ``(n, chunk)``.
    """
    n, c = input_chunk.shape
    mpe = model.config.max_position_embeddings
    pos = jnp.clip(
        jnp.asarray(chunk_start, jnp.int32)[:, None] +
        jnp.arange(c, dtype=jnp.int32)[None, :], 0, mpe - 1)
    logits, mutated = model.apply(
        {"params": params, "cache": cache}, input_chunk,
        position_ids=pos, use_cache=True, deterministic=True,
        chunk_start=chunk_start, page_table=page_table,
        adapter_ids=adapter_ids, mutable=["cache"])
    return (_constrain_slot_cache(mutated["cache"]),
            logits.astype(jnp.float32))


@jax.jit
def copy_kv_pages(cache, src: jax.Array, dst: jax.Array):
    """Device-side copy of physical pages ``src -> dst`` (both
    ``[k]`` int32) in every KV pool leaf — the copy half of a
    copy-on-write split; the host (server) rewires the page table and
    refcounts around it."""
    def cp(path, leaf):
        name = getattr(path[-1], "key", "")
        if name in ("cached_key", "cached_value",
                    "cached_key_scale", "cached_value_scale"):
            ax = leaf.ndim - 4
            sel = (slice(None),) * ax
            return leaf.at[sel + (dst,)].set(leaf[sel + (src,)])
        return leaf
    return jax.tree_util.tree_map_with_path(cp, cache)


@jax.jit
def gather_kv_pages(cache, pids: jax.Array):
    """Pull physical pages ``pids`` (``[k]`` int32) out of every KV
    pool leaf — the export half of a cross-server KV handoff
    (``core/fleet.py``) and of the hierarchical-cache spill path
    (``core/serving.py`` issues this gather asynchronously at the
    yield point; the writer thread ``device_get``\\ s the result into
    the host tier). Non-pool leaves pass through untouched, so the
    result has the cache's own tree structure and
    :func:`scatter_kv_pages` consumes it directly; int8 pools carry
    their fp32 ``cached_*_scale`` pages alongside automatically (the
    same four leaf names :func:`copy_kv_pages` copies)."""
    def g(path, leaf):
        name = getattr(path[-1], "key", "")
        if name in ("cached_key", "cached_value",
                    "cached_key_scale", "cached_value_scale"):
            ax = leaf.ndim - 4
            sel = (slice(None),) * ax
            return leaf[sel + (pids,)]
        return leaf
    return jax.tree_util.tree_map_with_path(g, cache)


def split_kv_pages(page_data, num_pages: int):
    """Split an N-page :func:`gather_kv_pages` tree into ``num_pages``
    single-page trees (page axis ``ndim - 4`` of every KV leaf,
    non-pool leaves shared). Pure indexing — it works on device
    arrays and ``device_get``'d numpy alike, so the spill writer can
    carve one batched host transfer back into per-page byte-store
    entries (``core/serving.py``)."""
    def cut(i):
        def g(path, leaf):
            name = getattr(path[-1], "key", "")
            if name in ("cached_key", "cached_value",
                        "cached_key_scale", "cached_value_scale"):
                ax = leaf.ndim - 4
                sel = (slice(None),) * ax
                return leaf[sel + (slice(i, i + 1),)]
            return leaf
        return jax.tree_util.tree_map_with_path(g, page_data)
    return [cut(i) for i in range(num_pages)]


def stack_kv_pages(page_trees):
    """Concatenate single-page trees back into one N-page tree along
    the page axis — the inverse of :func:`split_kv_pages`, built so a
    batched rehydrate issues ONE :func:`scatter_kv_pages` dispatch
    for all N pages instead of N. Host-side concatenation (numpy):
    the inputs are staged host pages and the single scatter uploads
    the stacked result."""
    if len(page_trees) == 1:
        return page_trees[0]
    def cat(path, *leaves):
        name = getattr(path[-1], "key", "")
        if name in ("cached_key", "cached_value",
                    "cached_key_scale", "cached_value_scale"):
            ax = leaves[0].ndim - 4
            return np.concatenate(
                [np.asarray(leaf) for leaf in leaves], axis=ax)
        return leaves[0]
    return jax.tree_util.tree_map_with_path(cat, *page_trees)


@jax.jit
def scatter_kv_pages(cache, page_data, pids: jax.Array):
    """Write gathered page contents into pages ``pids`` of THIS pool —
    the import half of a cross-server KV handoff, and the rehydrate
    half of the hierarchical cache (host-tier numpy pages re-enter
    HBM under fresh page ids). ``page_data`` is a
    :func:`gather_kv_pages` result: device arrays for a same-devices
    transfer, or host-staged numpy (``jax.device_get`` of the gather)
    when the two pools' meshes don't share devices. The destination's
    page ids are free to differ from the source's — the host page
    table remap happens in the importer's allocator, this op only
    moves bytes."""
    def s(path, pleaf, dleaf):
        name = getattr(path[-1], "key", "")
        if name in ("cached_key", "cached_value",
                    "cached_key_scale", "cached_value_scale"):
            ax = pleaf.ndim - 4
            sel = (slice(None),) * ax
            return pleaf.at[sel + (pids,)].set(
                jnp.asarray(dleaf, pleaf.dtype))
        return pleaf
    return jax.tree_util.tree_map_with_path(s, cache, page_data)


@jax.jit
def activate_slot(state: SlotState, slot: jax.Array,
                  length: jax.Array, dec_count: jax.Array,
                  nonce: jax.Array, appeared_row: jax.Array,
                  last_logits_row: jax.Array,
                  rejected: jax.Array) -> SlotState:
    """Flip one slot live from host-computed state — the paged
    admission paths (chunked-prefill completion, whole-prompt registry
    hit, preempted-request resume) activate through here instead of
    ``prefill_into_slots``'s scatter. ``dec_count`` is nonzero only
    for resumes, so a requeued request's min-length processing and
    sampling stream continue exactly where they stopped; ``rejected``
    (-1 outside resumes of a speculative sampling server) likewise
    restores a pending rejection-residual exclusion (verify_step)."""
    slot = jnp.asarray(slot, jnp.int32)
    return SlotState(
        lengths=state.lengths.at[slot].set(
            jnp.asarray(length, jnp.int32)),
        dec_count=state.dec_count.at[slot].set(
            jnp.asarray(dec_count, jnp.int32)),
        nonce=state.nonce.at[slot].set(jnp.asarray(nonce, jnp.int32)),
        appeared=state.appeared.at[slot].set(appeared_row),
        finished=state.finished.at[slot].set(False),
        active=state.active.at[slot].set(True),
        last_logits=state.last_logits.at[slot].set(last_logits_row),
        rejected=state.rejected.at[slot].set(
            jnp.asarray(rejected, jnp.int32)))


def left_pad_batch(sequences, pad_id: int):
    """Left-pad a list of id lists to the max length
    (reference ``language_module.py:221-243`` left_padding)."""
    import numpy as np
    max_len = max(len(s) for s in sequences)
    ids = np.full((len(sequences), max_len), pad_id, np.int32)
    mask = np.zeros((len(sequences), max_len), np.int32)
    for i, s in enumerate(sequences):
        if len(s) == 0:
            raise ValueError("empty prompt")
        ids[i, max_len - len(s):] = s
        mask[i, max_len - len(s):] = 1
    return ids, mask
