"""Jit-compiled autoregressive generation with a fixed-capacity cache.

Parity: reference ``GPTForGeneration(Hybrid).forward/sample``
(``hybrid_model.py:1208-1433``): left-padded prompts, temperature /
top-k / top-p sampling, min-length + repetition-penalty processors,
KV-cached decode. The reference fights dygraph-to-static conversion
with a growing cache and a Python while-loop (:1322-1347); here the
whole generate is ONE compiled program: prefill + ``lax.scan`` over a
static number of decode steps, cache preallocated at
``max_position_embeddings`` slots, finished rows emit ``pad`` tokens.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from .config import GPTConfig
from .processors import (
    min_length_processor, repetition_penalty_processor,
    top_k_top_p_filter, NEG_INF,
)


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    """Knobs named as in the reference YAML ``Generation`` section."""
    max_dec_len: int = 20
    min_dec_len: int = 0
    decode_strategy: str = "sampling"   # sampling | greedy_search
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    repetition_penalty: float = 1.0
    #: tile each prompt this many times before sampling — every copy
    #: samples an independent continuation (reference
    #: ``expand_inputs_for_generation``, ``hybrid_model.py:1422-1426``)
    num_return_sequences: int = 1
    eos_token_id: int = 50256
    pad_token_id: int = 50256

    def __post_init__(self):
        if self.num_return_sequences < 1:
            raise ValueError(
                f"num_return_sequences must be >= 1, got "
                f"{self.num_return_sequences}")

    @classmethod
    def from_config(cls, section) -> "GenerationConfig":
        import dataclasses as dc
        fields = {f.name for f in dc.fields(cls)}
        kwargs = {k: v for k, v in dict(section or {}).items()
                  if k in fields and v is not None}
        return cls(**kwargs)


def _decode_bias(valid_keys: jax.Array, dtype=jnp.float32) -> jax.Array:
    """[b, kv] validity -> additive [b, 1, 1, kv] bias."""
    return jnp.where(valid_keys, 0.0, NEG_INF)[:, None, None, :].astype(
        dtype)


@partial(jax.jit, static_argnames=("model", "gen_cfg"))
def generate(model, params, input_ids: jax.Array,
             attention_mask: Optional[jax.Array], rng: jax.Array,
             gen_cfg: GenerationConfig) -> jax.Array:
    """Returns generated token ids ``[b * num_return_sequences,
    max_dec_len]`` — prompt-major when ``num_return_sequences > 1``
    (rows ``i*n .. i*n + n - 1`` are prompt ``i``'s copies).

    ``input_ids`` is left-padded ``[b, prompt_len]``;
    ``attention_mask`` marks real tokens (1) vs pads (0), or None for
    unpadded prompts.
    """
    cfg: GPTConfig = model.config
    if gen_cfg.num_return_sequences > 1:
        # reference expand_inputs_for_generation
        # (hybrid_model.py:1422-1426): tile the batch BEFORE prefill so
        # each prompt samples N independent continuations. The N copies
        # prefill redundantly — same cost profile as the reference;
        # tiling the cache after one prefill would be cheaper for long
        # prompts but the scan-stacked cache puts batch at axis 1,
        # making that transform fragile for no current need.
        n = gen_cfg.num_return_sequences
        input_ids = jnp.repeat(input_ids, n, axis=0)
        if attention_mask is not None:
            attention_mask = jnp.repeat(attention_mask, n, axis=0)
    b, prompt_len = input_ids.shape
    capacity = cfg.max_position_embeddings
    compute_dtype = jnp.dtype(cfg.dtype)
    if compute_dtype != jnp.float32:
        # flax casts fp32 params to the compute dtype inside every op,
        # so the decode loop would stream fp32 bytes each token; one
        # up-front cast is numerically identical and halves the
        # per-token parameter bandwidth (the decode bottleneck)
        params = jax.tree.map(
            lambda p: p.astype(compute_dtype)
            if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
    if prompt_len + gen_cfg.max_dec_len > capacity:
        raise ValueError(
            f"prompt ({prompt_len}) + max_dec_len "
            f"({gen_cfg.max_dec_len}) exceeds the cache capacity "
            f"{capacity} (= max_position_embeddings)")
    if attention_mask is None:
        attention_mask = jnp.ones((b, prompt_len), jnp.int32)
    attention_mask = attention_mask.astype(jnp.int32)
    lengths = attention_mask.sum(axis=-1)                      # [b]
    position_ids = jnp.clip(
        jnp.cumsum(attention_mask, axis=-1) - 1, 0)

    # key-slot validity over the cache: prompt slots follow the pad
    # mask, decode slots become valid as they are written
    pad_cols = jnp.zeros((b, capacity - prompt_len), jnp.int32)
    base_valid = jnp.concatenate([attention_mask, pad_cols], axis=-1)

    # -- prefill -------------------------------------------------------
    # keys span the full preallocated cache during cached prefill, so
    # the pad bias covers all capacity slots (causality masks the rest)
    logits, mutated = model.apply(
        {"params": params}, input_ids, position_ids=position_ids,
        attn_bias=_decode_bias(base_valid.astype(bool)),
        use_cache=True, deterministic=True, mutable=["cache"])
    cache = mutated["cache"]
    last_logits = logits[:, -1, :].astype(jnp.float32)

    appeared0 = jnp.zeros((b, cfg.vocab_size), bool)
    appeared0 = appeared0.at[
        jnp.arange(b)[:, None], input_ids].set(attention_mask > 0)

    def sample_token(logits, appeared, step_idx, step_rng):
        logits = repetition_penalty_processor(
            logits, appeared, gen_cfg.repetition_penalty)
        # step_idx == tokens generated before this sample: EOS stays
        # banned until min_dec_len tokens exist (reference
        # MinLengthLogitsProcessor counts the same way)
        logits = min_length_processor(
            logits, step_idx, gen_cfg.min_dec_len,
            gen_cfg.eos_token_id)
        if gen_cfg.decode_strategy == "greedy_search":
            return jnp.argmax(logits, axis=-1)
        logits = logits / jnp.maximum(gen_cfg.temperature, 1e-6)
        logits = top_k_top_p_filter(logits, gen_cfg.top_k,
                                    gen_cfg.top_p)
        return jax.random.categorical(step_rng, logits, axis=-1)

    def body(carry, step_idx):
        cache, logits, appeared, finished, valid = carry
        step_rng = jax.random.fold_in(rng, step_idx)
        token = sample_token(logits, appeared, step_idx, step_rng)
        token = jnp.where(finished, gen_cfg.pad_token_id, token)
        finished = finished | (token == gen_cfg.eos_token_id)
        appeared = appeared.at[jnp.arange(b), token].set(True)

        # the new key lands at slot prompt_len + step_idx
        slot = prompt_len + step_idx
        valid = valid.at[:, slot].set(1)
        step_pos = (lengths + step_idx)[:, None]               # [b, 1]
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, token[:, None],
            position_ids=step_pos,
            attn_bias=_decode_bias(valid.astype(bool)),
            use_cache=True, deterministic=True, mutable=["cache"])
        cache = mutated["cache"]
        next_logits = logits[:, -1, :].astype(jnp.float32)
        return (cache, next_logits, appeared, finished, valid), token

    finished0 = jnp.zeros((b,), bool)
    (_, _, _, _, _), tokens = jax.lax.scan(
        body, (cache, last_logits, appeared0, finished0, base_valid),
        jnp.arange(gen_cfg.max_dec_len))
    return tokens.T  # [b, max_dec_len]


def left_pad_batch(sequences, pad_id: int):
    """Left-pad a list of id lists to the max length
    (reference ``language_module.py:221-243`` left_padding)."""
    import numpy as np
    max_len = max(len(s) for s in sequences)
    ids = np.full((len(sequences), max_len), pad_id, np.int32)
    mask = np.zeros((len(sequences), max_len), np.int32)
    for i, s in enumerate(sequences):
        if len(s) == 0:
            raise ValueError("empty prompt")
        ids[i, max_len - len(s):] = s
        mask[i, max_len - len(s):] = 1
    return ids, mask
