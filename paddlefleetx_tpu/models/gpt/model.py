"""TPU-native GPT: one sharding-annotated flax model for every topology.

The reference maintains three GPT implementations — single-card
(``gpt/dygraph/single_model.py``), hybrid TP/PP/SP
(``gpt/dygraph/hybrid_model.py``) and auto-parallel
(``gpt/auto/auto_model.py``). Under GSPMD one definition covers all of
them: parameters and activations carry *logical* axis names
(``parallel/sharding.py``) and the partitioner inserts the collectives
the hybrid model wrote by hand (ColumnParallelLinear all-reduces,
sequence-parallel all-gather/reduce-scatter, vocab-parallel logits).

Architecture parity (reference ``single_model.py``):
  - learned word + position embeddings, dropout (:435-473)
  - pre-LayerNorm decoder blocks, eps 1e-5, tanh-approx GELU (:340-427)
  - fused QKV projection option (:86-87), causal fused-mask softmax
    (:198), attention-prob dropout
  - final LayerNorm (:278-279); logits tied to the word embedding
    (:608-611); masked cross-entropy criterion (:619-653)

TPU-first choices: batch-major ``[b, s, h]`` activations; compute in
bf16 with fp32 params/softmax; ``nn.scan`` over layers (one compiled
block, weights stacked on a ``layers`` axis — compile time independent
of depth); ``jax.checkpoint`` policies reproduce the reference's
recompute granularities full / full_attn / core_attn
(``hybrid_model.py:406-408,537-539,332-333``).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name

from ...ops.attention import dot_product_attention
from ...parallel.sharding import with_logical_constraint
from .config import GPTConfig

Dtype = Any


def _dense_init(cfg: GPTConfig):
    return nn.initializers.normal(stddev=cfg.initializer_range)


class _CollectiveDense(nn.Module):
    """``nn.DenseGeneral`` twin that dispatches its matmul to the
    overlapped mp rings (``ops/collective_matmul.py``) when viable.

    Parameters are created exactly as the DenseGeneral call sites
    create them — same names ("kernel"/"bias"), shapes, logical axes
    and init streams — so checkpoints and the abstract-init parameter
    tree are identical whether the knob is on or off and whether a
    given call falls back (the engine's batch-1 abstract-init sample
    always does). Only the compute dispatches:

    - ``mode="column"`` ("embed" contraction, qkv / fc1):
      :func:`all_gather_matmul` — x arrives sequence-sharded
      (Megatron-SP layout), output feature-sharded over mp.
    - ``mode="row"`` (mp-sharded contraction, out-proj / fc2):
      :func:`matmul_reduce_scatter` — output arrives sequence-sharded.

    The fallback is the DenseGeneral ``dot_general`` + bias with the
    usual GSPMD lowering — numerically identical (the dispatch matrix
    lives in docs/tensor_parallel.md; conditions pinned by
    tests/test_collective_matmul.py).
    """
    config: GPTConfig
    features: Tuple[int, ...]
    kernel_axes: Tuple[Optional[str], ...]
    mode: str                       # "column" | "row"
    contract_ndim: int = 1

    @nn.compact
    def __call__(self, x):
        from flax.linen.dtypes import promote_dtype
        cfg = self.config
        cn = self.contract_ndim
        kshape = tuple(x.shape[-cn:]) + tuple(self.features)
        kernel = self.param(
            "kernel",
            nn.with_logical_partitioning(_dense_init(cfg),
                                         self.kernel_axes),
            kshape, jnp.dtype(cfg.param_dtype))
        bias = self.param(
            "bias",
            nn.with_logical_partitioning(nn.initializers.zeros_init(),
                                         self.kernel_axes[cn:]),
            tuple(self.features), jnp.dtype(cfg.param_dtype))
        x, kernel, bias = promote_dtype(x, kernel, bias,
                                        dtype=jnp.dtype(cfg.dtype))

        mesh = None
        if cfg.use_collective_matmul and cfg.sequence_parallel:
            from ...parallel.mesh import get_mesh
            mesh = get_mesh()
        if mesh is not None:
            from ...ops.collective_matmul import (
                all_gather_matmul, matmul_reduce_scatter, mp_ring_viable,
            )
            from ...parallel.sharding import MP_WEIGHT_AXES
            from ...observability import metrics
            if self.mode == "column":
                shard_idx = next(
                    (i for i, a in enumerate(self.kernel_axes[cn:])
                     if a in MP_WEIGHT_AXES), None)
                if shard_idx is not None and cn == 1 and x.ndim == 3 \
                        and mp_ring_viable(
                            mesh, x.shape[0], x.shape[1],
                            (self.features[shard_idx],)):
                    metrics.inc("mp_linear/rings")
                    y = all_gather_matmul(x, kernel, mesh,
                                          w_shard_dim=shard_idx)
                    return y + bias
            else:
                if self.kernel_axes[0] in MP_WEIGHT_AXES \
                        and x.ndim == 2 + cn and mp_ring_viable(
                            mesh, x.shape[0], x.shape[1], (kshape[0],)):
                    metrics.inc("mp_linear/rings")
                    y = matmul_reduce_scatter(x, kernel, mesh,
                                              contract_ndim=cn)
                    return y + bias
            # the knob was on but this call site fell off the ring
            # conditions (docs/tensor_parallel.md) — count it so a
            # "rings enabled but silently all-GSPMD" run is visible
            metrics.inc("mp_linear/gspmd_fallback")

        y = jax.lax.dot_general(
            x, kernel,
            ((tuple(range(x.ndim - cn, x.ndim)), tuple(range(cn))),
             ((), ())))
        return y + bias


class _QuantDense(nn.Module):
    """Weight-only int8 twin of the DenseGeneral/_CollectiveDense call
    sites (``quant_execution="weight_only_int8"``,
    docs/quantization.md).

    Parameter contract: ``kernel`` keeps the fp sites' name, shape and
    logical axes but stores int8 — the frozen PTQ artifact
    ``core/quantize.py`` emits; ``kernel_scale`` is its fp32
    per-output-channel dequant scale (shape = the kernel's output
    dims, axes = the kernel axes past the contraction); ``bias`` is
    unchanged. A fresh ``init()`` therefore yields zero weights and
    unit scales — real values come from quantizing a trained
    checkpoint (scripts/quantize_checkpoint.py), and the abstract
    tree this init builds is exactly what the quantized checkpoint
    restores into.

    Dispatch: flatten the site to ``[M, K] @ [K, N]``, try the Pallas
    weight-only GEMM (``quant/matmul``), fall back PER SITE to the
    XLA dequantize-then-dot (``quant/fallback/kernel_rejected``) —
    the same per-site contract as the attention/moe/mp_linear
    families. When ``use_collective_matmul`` is also on, this module
    replaces ``_CollectiveDense`` at the shared sites: the rings
    stream fp weight chunks and cannot consume frozen int8 kernels,
    so quantization wins (warned at config construction; dispatch
    matrix in docs/quantization.md).
    """
    config: GPTConfig
    features: Tuple[int, ...]
    kernel_axes: Tuple[Optional[str], ...]
    contract_ndim: int = 1

    @nn.compact
    def __call__(self, x):
        from ...observability import metrics
        cfg = self.config
        cn = self.contract_ndim
        kshape = tuple(x.shape[-cn:]) + tuple(self.features)
        kernel = self.param(
            "kernel",
            nn.with_logical_partitioning(nn.initializers.zeros_init(),
                                         self.kernel_axes),
            kshape, jnp.int8)
        scale = self.param(
            "kernel_scale",
            nn.with_logical_partitioning(nn.initializers.ones_init(),
                                         self.kernel_axes[cn:]),
            tuple(self.features), jnp.float32)
        bias = self.param(
            "bias",
            nn.with_logical_partitioning(nn.initializers.zeros_init(),
                                         self.kernel_axes[cn:]),
            tuple(self.features), jnp.dtype(cfg.param_dtype))
        dtype = jnp.dtype(cfg.dtype)
        x = x.astype(dtype)
        k_dim = int(np.prod(kshape[:cn]))
        n_dim = int(np.prod(self.features))
        x2 = x.reshape(-1, k_dim)
        w2 = kernel.reshape(k_dim, n_dim)
        s = scale.reshape(n_dim)
        try:
            from ...ops.pallas.quantized_matmul import quantized_matmul
            y = quantized_matmul(x2, w2, s)
            metrics.inc("quant/matmul")
        except (ImportError, NotImplementedError):
            # XLA dequantize-then-dot: numerically the kernel's oracle
            # (same int8 grid, scale applied outside the contraction)
            metrics.inc("quant/fallback/kernel_rejected")
            w_deq = (w2.astype(jnp.float32) * s[None, :]).astype(dtype)
            y = jax.lax.dot_general(x2, w_deq, (((1,), (0,)), ((), ())))
        y = y.reshape(x.shape[:-cn] + tuple(self.features))
        return y + bias.astype(dtype)


class _LoRADelta(nn.Module):
    """Stacked multi-adapter LoRA delta for one dense site
    (``lora_rank > 0``, docs/lora.md).

    Parameter contract — the additive twin of the ``_CollectiveDense``
    knob-off convention: the base site's ``kernel``/``bias`` (and the
    int8 ``kernel_scale``) are created by the base modules exactly as
    ever, so knob-off is param-tree-identical; this module adds ONLY
    the sibling pair ``lora_a [A, K, r]`` (normal init) / ``lora_b
    [A, r, N]`` (zero init — a fresh bank is a zero delta for every
    adapter). A = ``lora_num_adapters`` resident bank rows; row 0 is
    the reserved zero adapter and is masked structurally, so adapter
    id 0 reproduces the base model token-exactly whatever the bank
    holds. Adapter checkpoints save exactly these ``*_lora`` subtrees
    (core/checkpoint.py ``save_adapter``), base weights absent.

    Compute dispatch mirrors ``_QuantDense``: flatten the site to
    ``[M, K]`` rows keyed by per-row adapter ids, try the grouped
    Pallas GEMM pair (sort by id → scalar-prefetched group boundaries
    → grouped A/B GEMMs — adapters instead of experts; counted
    ``lora/grouped``), fall back PER SITE to the XLA gather-einsum
    form (``lora/fallback``). ``adapter_ids=None`` (training the base
    model, abstract init, export) skips the compute entirely and
    returns a zero delta — the params still materialize so the tree
    shape never depends on the call.
    """
    config: GPTConfig
    features: Tuple[int, ...]
    contract_ndim: int = 1

    @nn.compact
    def __call__(self, x, adapter_ids=None):
        from ...observability import metrics
        cfg = self.config
        cn = self.contract_ndim
        num_adapters, rank = cfg.lora_num_adapters, cfg.lora_rank
        k_dim = int(np.prod(x.shape[-cn:]))
        n_dim = int(np.prod(self.features))
        lora_a = self.param(
            "lora_a",
            nn.with_logical_partitioning(
                _dense_init(cfg), ("adapters", "lora_in", "lora_rank")),
            (num_adapters, k_dim, rank), jnp.dtype(cfg.param_dtype))
        lora_b = self.param(
            "lora_b",
            nn.with_logical_partitioning(
                nn.initializers.zeros_init(),
                ("adapters", "lora_rank", "lora_out")),
            (num_adapters, rank, n_dim), jnp.dtype(cfg.param_dtype))
        out_shape = x.shape[:-cn] + tuple(self.features)
        if adapter_ids is None:
            return jnp.zeros(out_shape, x.dtype)
        dtype = jnp.dtype(cfg.dtype)
        x2 = x.astype(dtype).reshape(-1, k_dim)        # [M, K]
        # one id per leading batch row, repeated over the flattened
        # row-major positions (M = batch * seq)
        ids = jnp.repeat(jnp.asarray(adapter_ids, jnp.int32),
                         x2.shape[0] // x.shape[0])
        live = ids != 0
        x2 = jnp.where(live[:, None], x2, 0)
        a = lora_a.astype(dtype)
        b = lora_b.astype(dtype)
        try:
            from ...ops.lora import grouped_lora_delta
            d = grouped_lora_delta(x2, ids, a, b)
            metrics.inc("lora/grouped")
        except (ImportError, NotImplementedError):
            from ...ops.lora import fallback_lora_delta
            metrics.inc("lora/fallback")
            d = fallback_lora_delta(x2, ids, a, b)
        d = d * jnp.asarray(cfg.lora_scale, dtype)
        d = jnp.where(live[:, None], d, 0)
        return d.reshape(out_shape).astype(x.dtype)


def _quantize_kv(t):
    """Symmetric per-(row, token, head) abs-max int8 quantization of a
    ``[b, W, h, d]`` K/V tensor: ``(int8 values, [b, W, h, 1] fp32
    scales)``. Per-token scales keep every cache write independent —
    a page- or slot-granular scale would force requantizing already
    written positions on each incremental decode write. The scale is
    clamped away from zero so all-zero rows round-trip exactly."""
    f = t.astype(jnp.float32)
    amax = jnp.max(jnp.abs(f), axis=-1, keepdims=True)
    sc = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(f / sc), -127, 127).astype(jnp.int8)
    return q, sc


def _remat_policy(granularity: str):
    """Map reference recompute granularities onto checkpoint policies.

    ``full`` recomputes the whole block; ``full_attn`` saves everything
    except attention internals (tagged "attn"/"core_attn"); ``core_attn``
    saves everything except the softmax(QK)V internals ("core_attn").
    """
    cp = jax.checkpoint_policies
    if granularity == "full":
        return None  # nothing saveable
    if granularity == "full_attn":
        return cp.save_anything_except_these_names("attn", "core_attn")
    if granularity == "core_attn":
        return cp.save_anything_except_these_names("core_attn")
    if granularity == "save_dots":
        # TPU-native granularity (no reference analogue): keep only the
        # named matmul outputs — qkv/core-attn ("attn"), out_proj
        # ("attn_out"), both MLP projections ("mlp1"/"mlp2") — and
        # recompute the elementwise rest (norms, gelu, residuals) in
        # backward. Near-zero recompute FLOPs at a fraction of
        # full_attn's residency: the middle ground the 16G v5e needs
        # between "full" (33% FLOP overhead) and policies that OOM.
        return cp.save_only_these_names("attn", "attn_out", "mlp1",
                                        "mlp2")
    raise ValueError(granularity)


class MultiHeadAttention(nn.Module):
    """Self-attention with fused QKV and a fixed-capacity decode cache.

    The reference grows its KV cache by concatenation
    (``single_model.py:179-184``), which would retrace under jit; here
    the cache is a preallocated ``[b, max_len, h, d]`` buffer updated
    with ``dynamic_update_slice`` — the dy2static-friendly design the
    reference approximates in ``hybrid_model.py:1322-1347``.
    """
    config: GPTConfig

    @nn.compact
    def __call__(self, x, attn_bias=None, use_cache: bool = False,
                 deterministic: bool = True, cache_lengths=None,
                 page_table=None, chunk_start=None, adapter_ids=None):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        h, nh, hd = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim
        dense = lambda feats, name, axes: nn.DenseGeneral(  # noqa: E731
            feats, axis=-1, name=name, dtype=dtype,
            param_dtype=jnp.dtype(cfg.param_dtype),
            kernel_init=nn.with_logical_partitioning(
                _dense_init(cfg), ("embed",) + axes),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), axes))

        quant = cfg.quant_execution == "weight_only_int8"
        if cfg.fuse_attn_qkv:
            if quant:
                # quantization wins over the rings at shared sites
                # (config.py warns; docs/quantization.md matrix)
                qkv = _QuantDense(
                    cfg, features=(3, nh, hd),
                    kernel_axes=("embed", None, "heads", "kv"),
                    name="qkv_proj")(x)
            elif cfg.use_collective_matmul:
                qkv = _CollectiveDense(
                    cfg, features=(3, nh, hd),
                    kernel_axes=("embed", None, "heads", "kv"),
                    mode="column", name="qkv_proj")(x)
            else:
                qkv = dense((3, nh, hd), "qkv_proj",
                            (None, "heads", "kv"))(x)
            if cfg.lora_rank:
                qkv = qkv + _LoRADelta(
                    cfg, features=(3, nh, hd),
                    name="qkv_proj_lora")(x, adapter_ids)
            q, k, v = (qkv[..., i, :, :] for i in range(3))
        elif quant:
            q = _QuantDense(cfg, features=(nh, hd),
                            kernel_axes=("embed", "heads", "kv"),
                            name="q_proj")(x)
            k = _QuantDense(cfg, features=(nh, hd),
                            kernel_axes=("embed", "heads", "kv"),
                            name="k_proj")(x)
            v = _QuantDense(cfg, features=(nh, hd),
                            kernel_axes=("embed", "heads", "kv"),
                            name="v_proj")(x)
        else:
            # non-fused qkv stays on the plain GSPMD path: three
            # narrow column projections are not worth three rings
            # (docs/tensor_parallel.md fallback matrix)
            q = dense((nh, hd), "q_proj", ("heads", "kv"))(x)
            k = dense((nh, hd), "k_proj", ("heads", "kv"))(x)
            v = dense((nh, hd), "v_proj", ("heads", "kv"))(x)
        q = checkpoint_name(q, "attn")
        k = checkpoint_name(k, "attn")
        v = checkpoint_name(v, "attn")
        q, k, v = (with_logical_constraint(
            t, ("batch", None, "act_heads", None)) for t in (q, k, v))

        query_offset = 0
        kv_cache_layout = False
        page_table_arg = None
        k_scale = v_scale = None
        # int8 KV cache (kv_cache_dtype="int8", docs/quantization.md):
        # values quantize per (row, token, head) on the way into the
        # cache; fp32 scales live in rank-4 lookalike variables whose
        # feature axis is a dummy 1 ([b, h, 1, S] / [P, h, 1, page]) so
        # every write expression, page gather and slot helper
        # (generation.py) applies to scales exactly as to values.
        kv_int8 = cfg.kv_cache_dtype == "int8"
        if use_cache and page_table is not None:
            # Paged KV (core/paging.py): the cache variables hold the
            # GLOBAL page pool [kv_pool_pages, h, d, kv_page_size] —
            # one pool shared by every slot — and each batch row
            # reaches its tokens through its page_table row (logical
            # page j of row i lives in physical page page_table[i, j]).
            # Page layout keeps the [h, d, S-minor] tiling of the
            # contiguous cache, just cut into kv_page_size columns.
            # Two write modes:
            #   - ragged decode (cache_lengths): one token per row at
            #     that row's position — look up the physical page of
            #     position//page_size and scatter the column at
            #     position%page_size. Inactive slots' page-table rows
            #     are all NULL_PAGE, so their dead writes land in the
            #     reserved garbage page.
            #   - chunked prefill (chunk_start): the chunk is
            #     page-aligned and spans whole pages, so the fresh
            #     chunk KV drops straight into its physical pages with
            #     one scatter — no gather/modify/scatter round trip.
            # Reads go through ops/attention.py's page_table
            # indirection (flash_decode_paged walks the table via
            # scalar prefetch; the dense fallback gathers).
            page = cfg.kv_page_size
            if not page or not cfg.kv_pool_pages:
                raise ValueError(
                    "page_table passed but kv_page_size/kv_pool_pages "
                    "are not configured (GPTConfig)")
            cache_k = self.variable(
                "cache", "cached_key", jnp.zeros,
                (cfg.kv_pool_pages, nh, hd, page),
                jnp.int8 if kv_int8 else dtype)
            cache_v = self.variable(
                "cache", "cached_value", jnp.zeros,
                (cfg.kv_pool_pages, nh, hd, page),
                jnp.int8 if kv_int8 else dtype)
            writes = [(cache_k, k), (cache_v, v)]
            if kv_int8:
                kq, ks = _quantize_kv(k)
                vq, vs = _quantize_kv(v)
                cache_ks = self.variable(
                    "cache", "cached_key_scale", jnp.zeros,
                    (cfg.kv_pool_pages, nh, 1, page), jnp.float32)
                cache_vs = self.variable(
                    "cache", "cached_value_scale", jnp.zeros,
                    (cfg.kv_pool_pages, nh, 1, page), jnp.float32)
                writes = [(cache_k, kq), (cache_v, vq),
                          (cache_ks, ks), (cache_vs, vs)]
            pt = jnp.asarray(page_table, jnp.int32)
            if cache_lengths is not None:
                base = jnp.clip(
                    jnp.asarray(cache_lengths, jnp.int32), 0,
                    cfg.cache_capacity - 1)
                if x.shape[1] == 1:
                    pid = jnp.take_along_axis(
                        pt, (base // page)[:, None], axis=1)[:, 0]
                    for var, t in writes:
                        var.value = var.value.at[pid, :, :,
                                                 base % page].set(
                            t.transpose(0, 2, 3, 1)[..., 0])
                else:
                    # speculative verify window: row i's W tokens land
                    # at positions lengths[i] .. lengths[i] + W - 1,
                    # each resolved through the page table (the server
                    # pre-maps/COWs every page the window touches —
                    # _page_maintenance(window)). Positions clipped at
                    # capacity land in the last column, which is never
                    # read before eviction (commit clamp). Advanced
                    # indexing on dims 0 and 3 puts the index dims
                    # first, so the value IS k/v's native [b, W, h, d].
                    wpos = jnp.clip(
                        jnp.asarray(cache_lengths, jnp.int32)[:, None]
                        + jnp.arange(x.shape[1], dtype=jnp.int32)[
                            None, :], 0, cfg.cache_capacity - 1)
                    pid = jnp.take_along_axis(pt, wpos // page, axis=1)
                    for var, t in writes:
                        var.value = var.value.at[
                            pid, :, :, wpos % page].set(t)
                query_offset = base                     # [b]
            elif chunk_start is not None:
                c = x.shape[1]
                if c % page:
                    raise ValueError(
                        f"chunked prefill length {c} must be a "
                        f"multiple of kv_page_size {page}")
                cp = c // page
                c0 = jnp.asarray(chunk_start, jnp.int32)
                pids = jnp.take_along_axis(
                    pt, (c0 // page)[:, None] +
                    jnp.arange(cp, dtype=jnp.int32)[None, :], axis=1)
                # [b, h, dd, c] -> [b, cp, h, dd, page] page-major
                # blocks (dd = head_dim for values, 1 for scales)
                def chunk_kv(t):
                    tt = t.transpose(0, 2, 3, 1)
                    return tt.reshape(
                        x.shape[0], nh, tt.shape[2], cp,
                        page).transpose(0, 3, 1, 2, 4)
                for var, t in writes:
                    var.value = var.value.at[pids].set(chunk_kv(t))
                query_offset = c0                       # [b]
            else:
                raise ValueError(
                    "page_table requires cache_lengths (ragged decode)"
                    " or chunk_start (chunked prefill)")
            k, v = cache_k.value, cache_v.value
            if kv_int8:
                k_scale, v_scale = cache_ks.value, cache_vs.value
            kv_cache_layout = True
            page_table_arg = pt
        elif use_cache:
            # Decode: roll the new keys/values into the preallocated
            # cache. Capacity is cache_capacity (max_position_embeddings
            # rounded up to a 128 multiple so the minor dim always
            # tiles — config.py); the caller (generation loop / serving
            # server) must bound prompt+decode length by
            # max_position_embeddings — dynamic_update_slice clamps
            # rather than raises on overrun.
            # Layout [b, h, d, S]: the minor tile dims (d, S) =
            # (64, capacity) fill TPU (8,128) tiles exactly. The
            # alternatives both waste 2x HBM to lane padding (any
            # layout with d=64 minor) — measured: the padded cache
            # additionally provokes XLA into per-step compress/
            # uncompress copies of the whole stacked cache, which OOMs
            # at batch 64. As a bonus k arrives pre-transposed for the
            # q @ k^T decode matmul.
            capacity = cfg.cache_capacity
            cache_k = self.variable(
                "cache", "cached_key", jnp.zeros,
                (x.shape[0], nh, hd, capacity),
                jnp.int8 if kv_int8 else dtype)
            cache_v = self.variable(
                "cache", "cached_value", jnp.zeros,
                (x.shape[0], nh, hd, capacity),
                jnp.int8 if kv_int8 else dtype)
            writes = [(cache_k, k), (cache_v, v)]
            if kv_int8:
                kq, ks = _quantize_kv(k)
                vq, vs = _quantize_kv(v)
                cache_ks = self.variable(
                    "cache", "cached_key_scale", jnp.zeros,
                    (x.shape[0], nh, 1, capacity), jnp.float32)
                cache_vs = self.variable(
                    "cache", "cached_value_scale", jnp.zeros,
                    (x.shape[0], nh, 1, capacity), jnp.float32)
                writes = [(cache_k, kq), (cache_v, vq),
                          (cache_ks, ks), (cache_vs, vs)]
            cache_index = self.variable(
                "cache", "cache_index",
                lambda: jnp.zeros((), jnp.int32))
            if cache_lengths is not None:
                # Ragged slot decode (continuous batching): each batch
                # row is a server slot advancing at its OWN length, so
                # the single dynamic_update_slice index cannot serve —
                # scatter every row's new key/value column at that
                # row's position and hand the per-row offsets to the
                # attention dispatch (flash_decode_ragged or the XLA
                # per-row-offset fallback). cache_index is left
                # untouched: the slot lengths live with the server's
                # SlotState, not in the cache collection.
                rows = jnp.arange(x.shape[0])
                base = jnp.clip(
                    jnp.asarray(cache_lengths, jnp.int32), 0,
                    capacity - 1)
                if x.shape[1] == 1:
                    for var, t in writes:
                        var.value = var.value.at[
                            rows, :, :, base].set(
                            t.transpose(0, 2, 3, 1)[..., 0])
                else:
                    # speculative verify window (see the paged branch
                    # above): scatter row i's W columns at
                    # lengths[i] .. lengths[i] + W - 1; rejected
                    # columns are overwritten by the next window
                    # before any read (the next tick's window starts
                    # at the accepted length)
                    wpos = jnp.clip(
                        jnp.asarray(cache_lengths, jnp.int32)[:, None]
                        + jnp.arange(x.shape[1], dtype=jnp.int32)[
                            None, :], 0, capacity - 1)
                    for var, t in writes:
                        var.value = var.value.at[
                            rows[:, None], :, :, wpos].set(t)
                query_offset = base                     # [b]
            else:
                idx = cache_index.value
                for var, t in writes:
                    var.value = jax.lax.dynamic_update_slice(
                        var.value, t.transpose(0, 2, 3, 1),
                        (0, 0, 0, idx))
                query_offset = idx
                cache_index.value = idx + x.shape[1]
            k, v = cache_k.value, cache_v.value
            if kv_int8:
                k_scale, v_scale = cache_ks.value, cache_vs.value
            kv_cache_layout = True

        dropout_rng = None
        if cfg.attention_probs_dropout_prob > 0.0 and not deterministic:
            dropout_rng = self.make_rng("dropout")

        # Ulysses all-to-all CP (beyond-reference; DeepSpeed-Ulysses
        # semantics expressed as GSPMD reshards): for the attention
        # itself the seq dim gathers while heads shard over cp x mp —
        # the two constraints below make XLA emit the token
        # all-to-alls. Exact attention per head-shard, so dropout and
        # biases work unchanged (unlike the ring path).
        use_ulysses = (cfg.context_parallel and not use_cache
                       and cfg.context_parallel_algo == "ulysses")
        if use_ulysses:
            q, k, v = (with_logical_constraint(
                t, ("batch", None, "act_heads_cp", None))
                for t in (q, k, v))

        ring_mesh = None
        if cfg.context_parallel and not use_cache and attn_bias is None \
                and cfg.context_parallel_algo == "ring" \
                and (deterministic
                     or cfg.attention_probs_dropout_prob == 0.0):
            from ...parallel.mesh import (
                CP_AXIS, DATA_AXES, MP_AXIS, get_mesh,
            )
            mesh = get_mesh()
            if mesh is not None and mesh.shape.get(CP_AXIS, 1) > 1:
                # shard_map needs exact divisibility; undersized
                # shapes (e.g. the batch-1 abstract-init sample) take
                # the dense path — parameters are unaffected
                bsz = int(np.prod([mesh.shape[a] for a in DATA_AXES]))
                if q.shape[0] % bsz == 0 and \
                        q.shape[1] % mesh.shape[CP_AXIS] == 0 and \
                        q.shape[2] % mesh.shape[MP_AXIS] == 0:
                    ring_mesh = mesh
        if ring_mesh is not None:
            from ...ops.ring_attention import ring_attention_sharded
            out = ring_attention_sharded(q, k, v, ring_mesh,
                                         causal=True)
        else:
            out = dot_product_attention(
                q, k, v, bias=attn_bias, causal=True,
                query_offset=query_offset,
                dropout_rate=cfg.attention_probs_dropout_prob,
                dropout_rng=dropout_rng, deterministic=deterministic,
                use_flash=cfg.use_flash_attention,
                kv_cache_layout=kv_cache_layout,
                page_table=page_table_arg,
                k_scale=k_scale, v_scale=v_scale)
        if use_ulysses:
            # all-to-all back: seq re-shards over cp, heads gather
            out = with_logical_constraint(
                out, ("batch", "seq", "act_heads", None))
        out = checkpoint_name(out, "attn")

        attn_inner = out
        if quant:
            out = _QuantDense(
                cfg, features=(h,),
                kernel_axes=("heads", "kv", "embed"),
                contract_ndim=2, name="out_proj")(out)
        elif cfg.use_collective_matmul:
            out = _CollectiveDense(
                cfg, features=(h,),
                kernel_axes=("heads", "kv", "embed"),
                mode="row", contract_ndim=2, name="out_proj")(out)
        else:
            out = nn.DenseGeneral(
                h, axis=(-2, -1), name="out_proj", dtype=dtype,
                param_dtype=jnp.dtype(cfg.param_dtype),
                kernel_init=nn.with_logical_partitioning(
                    _dense_init(cfg), ("heads", "kv", "embed")),
                bias_init=nn.with_logical_partitioning(
                    nn.initializers.zeros_init(), ("embed",)))(out)
        if cfg.lora_rank:
            out = out + _LoRADelta(
                cfg, features=(h,), contract_ndim=2,
                name="out_proj_lora")(attn_inner, adapter_ids)
        return checkpoint_name(out, "attn_out")


class TransformerDecoderLayer(nn.Module):
    """Pre-LN decoder block (reference ``single_model.py:340-427``).

    With ``scanned=True`` the call returns ``(x, aux)`` — the
    ``(carry, ys)`` pair ``nn.scan`` requires, where ``aux`` is the
    MoE router auxiliary loss (None for the dense FFN). Non-scanned,
    the return is the bare ``x`` for dense configs and ``(x, aux)``
    when ``moe_num_experts > 0``.
    """
    config: GPTConfig
    scanned: bool = False

    @nn.compact
    def __call__(self, x, attn_bias=None, use_cache: bool = False,
                 deterministic: bool = True, cache_lengths=None,
                 page_table=None, chunk_start=None, adapter_ids=None):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        pdtype = jnp.dtype(cfg.param_dtype)
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=1e-5, dtype=dtype, param_dtype=pdtype, name=name,
            scale_init=nn.with_logical_partitioning(
                nn.initializers.ones_init(), ("norm",)),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("norm",)))

        residual = x
        y = ln("norm1")(x)
        y = MultiHeadAttention(cfg, name="self_attn")(
            y, attn_bias, use_cache, deterministic, cache_lengths,
            page_table, chunk_start, adapter_ids)
        y = nn.Dropout(cfg.hidden_dropout_prob, name="dropout1")(
            y, deterministic=deterministic)
        x = residual + y
        x = with_logical_constraint(x, ("batch", "seq", "act_embed"))

        residual = x
        y = ln("norm2")(x)
        moe_aux = None
        if cfg.moe_num_experts:
            from .moe import MoEMLP
            y, moe_aux = MoEMLP(cfg, name="moe_mlp")(y, deterministic)
        else:
            mlp_in = y
            if cfg.quant_execution == "weight_only_int8":
                y = _QuantDense(cfg, features=(cfg.ffn_hidden_size,),
                                kernel_axes=("embed", "mlp"),
                                name="linear1")(y)
            elif cfg.use_collective_matmul:
                y = _CollectiveDense(
                    cfg, features=(cfg.ffn_hidden_size,),
                    kernel_axes=("embed", "mlp"), mode="column",
                    name="linear1")(y)
            else:
                y = nn.DenseGeneral(
                    cfg.ffn_hidden_size, name="linear1", dtype=dtype,
                    param_dtype=pdtype,
                    kernel_init=nn.with_logical_partitioning(
                        _dense_init(cfg), ("embed", "mlp")),
                    bias_init=nn.with_logical_partitioning(
                        nn.initializers.zeros_init(), ("mlp",)))(y)
            if cfg.lora_rank:
                y = y + _LoRADelta(
                    cfg, features=(cfg.ffn_hidden_size,),
                    name="linear1_lora")(mlp_in, adapter_ids)
            y = checkpoint_name(y, "mlp1")
            y = nn.gelu(y, approximate=True)
            y = with_logical_constraint(y, ("batch", None, "act_mlp"))
            mlp_mid = y
            if cfg.quant_execution == "weight_only_int8":
                y = _QuantDense(cfg, features=(cfg.hidden_size,),
                                kernel_axes=("mlp", "embed"),
                                name="linear2")(y)
            elif cfg.use_collective_matmul:
                y = _CollectiveDense(
                    cfg, features=(cfg.hidden_size,),
                    kernel_axes=("mlp", "embed"), mode="row",
                    name="linear2")(y)
            else:
                y = nn.DenseGeneral(
                    cfg.hidden_size, name="linear2", dtype=dtype,
                    param_dtype=pdtype,
                    kernel_init=nn.with_logical_partitioning(
                        _dense_init(cfg), ("mlp", "embed")),
                    bias_init=nn.with_logical_partitioning(
                        nn.initializers.zeros_init(), ("embed",)))(y)
            if cfg.lora_rank:
                y = y + _LoRADelta(
                    cfg, features=(cfg.hidden_size,),
                    name="linear2_lora")(mlp_mid, adapter_ids)
            y = checkpoint_name(y, "mlp2")
        y = nn.Dropout(cfg.hidden_dropout_prob, name="dropout2")(
            y, deterministic=deterministic)
        x = residual + y
        x = with_logical_constraint(x, ("batch", "seq", "act_embed"))
        if self.scanned:
            return x, moe_aux
        return (x, moe_aux) if cfg.moe_num_experts else x


class GPTEmbeddings(nn.Module):
    """Word + learned position embeddings (reference :435-473)."""
    config: GPTConfig

    @nn.compact
    def __call__(self, input_ids, position_ids, deterministic: bool = True):
        cfg = self.config
        word_emb = self.param(
            "word_embeddings",
            nn.with_logical_partitioning(_dense_init(cfg),
                                         ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), jnp.dtype(cfg.param_dtype))
        pos_emb = self.param(
            "position_embeddings",
            nn.with_logical_partitioning(_dense_init(cfg),
                                         ("pos", "embed")),
            (cfg.max_position_embeddings, cfg.hidden_size),
            jnp.dtype(cfg.param_dtype))
        dtype = jnp.dtype(cfg.dtype)
        x = jnp.take(word_emb, input_ids, axis=0).astype(dtype) + \
            jnp.take(pos_emb, position_ids, axis=0).astype(dtype)
        x = nn.Dropout(cfg.hidden_dropout_prob)(
            x, deterministic=deterministic)
        return with_logical_constraint(x, ("batch", "seq", "act_embed"))


class GPTModel(nn.Module):
    """Embeddings -> N decoder blocks -> final LayerNorm."""
    config: GPTConfig

    @nn.compact
    def __call__(self, input_ids, position_ids=None, attn_bias=None,
                 use_cache: bool = False, deterministic: bool = True,
                 position_offset=0, cache_lengths=None,
                 page_table=None, chunk_start=None, adapter_ids=None):
        cfg = self.config
        static_offset = position_offset if isinstance(position_offset, int) \
            else 0
        if input_ids.shape[-1] + static_offset > \
                cfg.max_position_embeddings:
            raise ValueError(
                f"sequence length {input_ids.shape[-1]} (+offset "
                f"{static_offset}) exceeds max_position_embeddings "
                f"{cfg.max_position_embeddings}; with a traced offset the "
                f"generation loop must bound prompt+decode length itself")
        if position_ids is None:
            position_ids = position_offset + jnp.arange(
                input_ids.shape[-1], dtype=jnp.int32)[None, :]
            position_ids = jnp.broadcast_to(position_ids, input_ids.shape)
        x = GPTEmbeddings(cfg, name="embeddings")(
            input_ids, position_ids, deterministic)

        block = TransformerDecoderLayer
        if cfg.use_recompute:
            block = nn.remat(
                block, policy=_remat_policy(cfg.recompute_granularity),
                prevent_cse=not cfg.scan_layers,
                static_argnums=(3, 4))
        if cfg.scan_layers:
            x, aux_stack = nn.scan(
                block,
                variable_axes={"params": 0, "cache": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=nn.broadcast,
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, scanned=True, name="decoder")(
                x, attn_bias, use_cache, deterministic, cache_lengths,
                page_table, chunk_start, adapter_ids)
            moe_aux = aux_stack.sum() if cfg.moe_num_experts else None
        else:
            moe_aux = jnp.zeros((), jnp.float32) \
                if cfg.moe_num_experts else None
            for i in range(cfg.num_layers):
                x = block(cfg, name=f"decoder_{i}")(
                    x, attn_bias, use_cache, deterministic,
                    cache_lengths, page_table, chunk_start,
                    adapter_ids)
                if cfg.moe_num_experts:
                    x, aux = x
                    moe_aux = moe_aux + aux

        if moe_aux is not None:
            # picked up by loss paths via mutable=["losses"]; silently
            # dropped (flax sow semantics) by eval/generation/export
            # applies that don't request the collection
            self.sow("losses", "moe_aux", moe_aux)
        return _final_norm(cfg, name="final_norm")(x)


def _final_norm(cfg: GPTConfig, name: Optional[str] = None) -> nn.LayerNorm:
    """The decoder-output LayerNorm — single definition shared by the
    plain and pipelined forward paths."""
    return nn.LayerNorm(
        epsilon=1e-5, dtype=jnp.dtype(cfg.dtype),
        param_dtype=jnp.dtype(cfg.param_dtype), name=name,
        scale_init=nn.with_logical_partitioning(
            nn.initializers.ones_init(), ("norm",)),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros_init(), ("norm",)))


class GPTForPretraining(nn.Module):
    """GPT with tied-embedding LM head (reference :577-616).

    The hybrid reference computes tied logits through
    ``parallel_matmul`` with an mp all-gather (``hybrid_model.py:45-66``);
    here the einsum against the vocab-sharded embedding produces
    vocab-sharded logits and GSPMD inserts the same collective exactly
    where needed (only if the consumer demands replication).
    """
    config: GPTConfig

    @nn.compact
    def __call__(self, input_ids, position_ids=None, attn_bias=None,
                 use_cache: bool = False, deterministic: bool = True,
                 position_offset=0, cache_lengths=None,
                 page_table=None, chunk_start=None, adapter_ids=None):
        x = GPTModel(self.config, name="gpt")(
            input_ids, position_ids, attn_bias, use_cache, deterministic,
            position_offset, cache_lengths, page_table, chunk_start,
            adapter_ids)
        word_emb = _word_embedding(
            self.variables["params"]["gpt"]["embeddings"])
        return tied_logits(x, word_emb)


def _word_embedding(emb_params) -> jax.Array:
    """The (possibly Partitioned-boxed) tied embedding table from an
    embeddings param subtree — single unboxing point for the LM head,
    the pipelined loss, and the chunked loss."""
    word_emb = emb_params["word_embeddings"]
    if isinstance(word_emb, nn.Partitioned):
        word_emb = word_emb.value
    return word_emb


def tied_logits(x: jax.Array, word_emb: jax.Array) -> jax.Array:
    """LM head against the (vocab-sharded) embedding table; GSPMD
    keeps the logits vocab-sharded (reference ``parallel_matmul``,
    ``hybrid_model.py:45-66``)."""
    logits = jnp.einsum("bsh,vh->bsv", x, word_emb.astype(x.dtype))
    return with_logical_constraint(logits, ("batch", "seq", "act_vocab"))


def masked_nll_sums(logits: jax.Array, labels: jax.Array,
                    loss_mask: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Fp32 masked token NLL: ``(sum of nll over unmasked, mask sum)``.

    The shared core of the pretraining criterion and the offline-eval
    scorer; with vocab-sharded logits GSPMD turns the log-sum-exp and
    gather into the psum-based sharded softmax the reference's
    ``ParallelCrossEntropy`` (``hybrid_model.py:799``) hand-writes.
    """
    logits = logits.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(
        logits, labels[..., None], axis=-1)[..., 0]
    mask = loss_mask.astype(jnp.float32).reshape(logz.shape)
    return jnp.sum((logz - label_logits) * mask), jnp.sum(mask)


def _pipeline_parts(cfg: GPTConfig, input_ids, position_ids,
                    deterministic: bool, rng):
    """Shared setup for the pipelined loss paths: embedding output,
    the per-layer apply fn (remat-wrapped), final norm + tied head
    pieces, the split rngs, and whether each layer emits an aux loss
    (MoE router aux — the pipeline schedules thread it through as an
    explicit output with its own cotangent)."""
    if not cfg.scan_layers:
        raise ValueError("pipeline parallelism requires scan_layers=True "
                         "(stacked decoder params)")
    has_aux = bool(cfg.moe_num_experts)
    if position_ids is None:
        position_ids = jnp.broadcast_to(
            jnp.arange(input_ids.shape[-1], dtype=jnp.int32)[None, :],
            input_ids.shape)
    rng = rng if rng is not None else jax.random.key(0)
    emb_rng, pipe_rng = jax.random.split(rng)

    def emb_fwd(ep):
        return GPTEmbeddings(cfg).apply(
            {"params": ep}, input_ids, position_ids, deterministic,
            rngs=None if deterministic else {"dropout": emb_rng})

    def layer_apply(lp, h, key):
        return TransformerDecoderLayer(cfg, scanned=False).apply(
            {"params": lp}, h, None, False, deterministic,
            rngs=None if deterministic else {"dropout": key})
    if cfg.use_recompute:
        layer_apply = jax.checkpoint(
            layer_apply, policy=_remat_policy(cfg.recompute_granularity))

    return emb_fwd, layer_apply, pipe_rng, has_aux


def pipelined_lm_loss(cfg: GPTConfig, params, input_ids, labels,
                      loss_mask, *, pp: int, num_microbatches: int,
                      vpp: int = 1, rng=None, position_ids=None,
                      deterministic: bool = True) -> jax.Array:
    """Masked-CE pretraining loss with the decoder stack pipelined
    over the ``pp`` mesh axis.

    The pipe twin of ``GPTForPretraining`` — but unlike the
    reference's ``GPTForPretrainingPipe`` (a different module class
    with per-rank ``LayerDesc`` params, ``hybrid_model.py:862-962``)
    this consumes the *same* parameter tree as the non-pipe model:
    embeddings and final norm run replicated over ``pp``, the stacked
    ``[L, ...]`` decoder params are pipelined, and the LM head + loss
    run per-microbatch on the last stage's output (the reference
    computes per-microbatch loss inside ``train_batch`` the same way).
    The tied-embedding logits need no ``SharedLayerDesc``: the single
    embedding table serves both ends.
    """
    from ...parallel.pipeline import pipeline_forward

    emb_fwd, layer_apply, pipe_rng, has_aux = _pipeline_parts(
        cfg, input_ids, position_ids, deterministic, rng)
    emb_params = params["gpt"]["embeddings"]
    x = emb_fwd(emb_params)

    ln = _final_norm(cfg)
    fn_params = params["gpt"]["final_norm"]
    word_emb = _word_embedding(emb_params)

    def head_and_loss(acc, y, ex):
        # per-microbatch masked mean, averaged over microbatches below —
        # the same weighting as the engine's accumulation scan and the
        # reference's 1F1B micro-loss averaging (masks that vary across
        # microbatches weight identically with and without pp)
        labels_mb, mask_mb = ex
        h = ln.apply({"params": fn_params}, y)
        nll, msum = masked_nll_sums(tied_logits(h, word_emb),
                                    labels_mb, mask_mb)
        return acc + nll / jnp.maximum(msum, 1.0)

    # forward-only path drops the MoE router aux (pure CE — matching
    # the non-pipelined eval criterion, which also excludes aux)
    loss_sum = pipeline_forward(
        layer_apply, params["gpt"]["decoder"], x,
        pp=pp, num_microbatches=num_microbatches, vpp=vpp,
        out_fn=head_and_loss, out_init=jnp.zeros((), jnp.float32),
        extras=(labels, loss_mask), rng=pipe_rng,
        layer_has_aux=has_aux)
    return loss_sum / num_microbatches


def pipelined_lm_loss_and_grad(
        cfg: GPTConfig, params, input_ids, labels, loss_mask, *,
        pp: int, num_microbatches: int, vpp: int = 1, rng=None,
        position_ids=None, deterministic: bool = True,
        schedule: str = "1F1B", h2_depth: int = -1):
    """Loss AND parameter gradients under the explicit 1F1B (or
    zero-bubble ``"zb"``/``"zb_h2"``; ``h2_depth`` is the ZB-H2
    warm-up depth, -1 = full) schedule.

    ``jax.grad(pipelined_lm_loss)`` differentiates through the GPipe
    scan, which stashes every microbatch's stage activations before any
    backward runs; this path drives ``pipeline_value_and_grad`` so the
    activation ring holds at most ``2*pp*vpp`` microbatch slots — the
    1F1B memory profile the reference defaults to
    (``hybrid_model.py:962`` area, ``eager_engine.py:406-415``).

    Returns ``(loss, grads)`` where ``grads`` matches the
    ``{"gpt": {embeddings, decoder, final_norm}}`` parameter tree and
    both are per-microbatch-mean averaged — exactly what
    ``jax.value_and_grad(pipelined_lm_loss)`` would return.
    """
    from ...parallel.pipeline import pipeline_value_and_grad

    emb_fwd, layer_apply, pipe_rng, has_aux = _pipeline_parts(
        cfg, input_ids, position_ids, deterministic, rng)
    emb_params = params["gpt"]["embeddings"]
    extra = set(params["gpt"]) - {"embeddings", "decoder", "final_norm"}
    if extra:
        raise ValueError(f"unexpected GPT param subtrees: {extra}")
    x, emb_pull = jax.vjp(emb_fwd, emb_params)

    ln = _final_norm(cfg)
    fn_params = params["gpt"]["final_norm"]
    word_emb = _word_embedding(emb_params)

    def head_loss_and_grad(y, ex):
        """LM-head loss and its grads w.r.t. hidden states + head params."""
        labels_mb, mask_mb = ex

        def head(hp, yy):
            h = ln.apply({"params": hp["fn"]}, yy)
            nll, msum = masked_nll_sums(tied_logits(h, hp["we"]),
                                        labels_mb, mask_mb)
            return nll / jnp.maximum(msum, 1.0)

        loss_mb, pull = jax.vjp(head, {"fn": fn_params, "we": word_emb}, y)
        dhp, dy = pull(jnp.ones((), jnp.float32))
        return loss_mb, dy, dhp

    loss_sum, d_stacked, dhead, dx = pipeline_value_and_grad(
        layer_apply, params["gpt"]["decoder"], x,
        pp=pp, num_microbatches=num_microbatches, vpp=vpp,
        loss_and_grad=head_loss_and_grad,
        extras=(labels, loss_mask), rng=pipe_rng,
        schedule=schedule, h2_depth=h2_depth, layer_has_aux=has_aux)

    (demb,) = emb_pull(dx.astype(x.dtype))
    # fold the tied LM head's word-embedding gradient into the
    # embedding-table gradient (the reference ties them through
    # SharedLayerDesc's allreduce; here it is a plain add)
    we_leaf = demb["word_embeddings"]
    dwe = dhead["we"]
    if isinstance(we_leaf, nn.Partitioned):
        we_leaf = we_leaf.replace(
            value=we_leaf.value + dwe.astype(we_leaf.value.dtype))
    else:
        we_leaf = we_leaf + dwe.astype(we_leaf.dtype)
    demb = dict(demb)
    demb["word_embeddings"] = we_leaf

    inv = 1.0 / num_microbatches
    scale = lambda t: jax.tree.map(  # noqa: E731
        lambda g: (g * inv).astype(g.dtype), t)
    grads = {"gpt": {"embeddings": scale(demb),
                     "decoder": scale(d_stacked),
                     "final_norm": scale(dhead["fn"])}}
    return loss_sum * inv, grads


def cross_entropy_loss(logits: jax.Array, labels: jax.Array,
                       loss_mask: jax.Array) -> jax.Array:
    """Masked LM criterion (reference ``GPTPretrainingCriterion``,
    ``single_model.py:619-653``): mean NLL over unmasked positions.

    Computed in fp32 regardless of compute dtype (``masked_nll_sums``).
    """
    nll_sum, mask_sum = masked_nll_sums(logits, labels, loss_mask)
    return nll_sum / jnp.maximum(mask_sum, 1.0)


def chunked_lm_loss(model: "GPTForPretraining", params, input_ids,
                    labels, loss_mask, *, chunks: int,
                    position_ids=None, deterministic: bool = True,
                    rngs=None, include_moe_aux: bool = True) -> jax.Array:
    """Masked-CE pretraining loss with the LM head + softmax computed
    over ``chunks`` sequence chunks inside a rematerialized scan.

    Under ``deterministic=True`` this is numerically identical to
    ``cross_entropy_loss(model.apply(...))`` — the per-token NLL sums
    are exact, not chunk-mean-of-means. (With dropout the two paths
    draw different masks: flax folds the module path into dropout
    keys, and here ``GPTModel`` is the top-level module.) But
    the ``[b, s, V]`` logits — the largest single activation of
    GPT-class training (1.6 GB fp32 at bs8/s1024/V50304) — never
    materialize beyond ``[b, s/chunks, V]``. ``jax.checkpoint`` makes
    the backward recompute each chunk's logits instead of saving them:
    one extra head matmul per chunk buys O(s/chunks) logits memory.
    """
    cfg = model.config
    b, s = input_ids.shape
    if s % chunks:
        raise ValueError(
            f"loss_chunks ({chunks}) must divide the sequence length "
            f"({s})")
    moe_aux = jnp.zeros((), jnp.float32)
    if cfg.moe_num_experts and include_moe_aux:
        h, mods = GPTModel(cfg).apply(
            {"params": params["gpt"]}, input_ids, position_ids, None,
            False, deterministic, rngs=rngs, mutable=["losses"])
        moe_aux = sum(jax.tree.leaves(mods["losses"]))
    else:
        h = GPTModel(cfg).apply({"params": params["gpt"]}, input_ids,
                                position_ids, None, False,
                                deterministic, rngs=rngs)
    word_emb = _word_embedding(params["gpt"]["embeddings"])

    csz = s // chunks
    hc = h.reshape(b, chunks, csz, h.shape[-1]).swapaxes(0, 1)
    lc = labels.reshape(b, chunks, csz).swapaxes(0, 1)
    mc = loss_mask.reshape(b, chunks, csz).swapaxes(0, 1)

    @jax.checkpoint
    def body(carry, xs):
        hh, ll, mm = xs
        nll, msum = masked_nll_sums(tied_logits(hh, word_emb), ll, mm)
        return (carry[0] + nll, carry[1] + msum), None

    (nll, msum), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (hc, lc, mc))
    return nll / jnp.maximum(msum, 1.0) + moe_aux
