"""Logits processors for generation, as pure jnp transforms.

Parity: reference ``gpt/dygraph/processor.py:22-192`` (HF-style
min-length, repetition penalty, forced BOS/EOS; Hamming diversity is
beam-search-only and beams are out of scope for the sampling path).
Each processor maps ``(logits [b, V], state) -> logits`` and composes
inside the jitted decode loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e9


def min_length_processor(logits: jax.Array, cur_len, min_length: int,
                         eos_token_id: int) -> jax.Array:
    """Suppress EOS while the generated length is below ``min_length``
    (reference ``MinLengthLogitsProcessor``)."""
    suppress = cur_len < min_length
    eos_mask = jnp.arange(logits.shape[-1]) == eos_token_id
    return jnp.where(suppress & eos_mask[None, :], NEG_INF, logits)


def repetition_penalty_processor(logits: jax.Array, appeared: jax.Array,
                                 penalty: float) -> jax.Array:
    """Penalize already-generated tokens (reference
    ``RepetitionPenaltyLogitsProcessor``): positive scores divided by
    the penalty, negative scores multiplied."""
    if penalty == 1.0:
        return logits
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(appeared, penalized, logits)


def forced_token_processor(logits: jax.Array, force: jax.Array,
                           token_id: int) -> jax.Array:
    """Force ``token_id`` where ``force`` is set (reference
    ``ForcedBOS/EOSTokenLogitsProcessor``)."""
    vocab = jnp.arange(logits.shape[-1]) == token_id
    forced = jnp.where(vocab[None, :], 0.0, NEG_INF)
    return jnp.where(force[:, None], forced, logits)


def top_k_filter(logits: jax.Array, top_k: int) -> jax.Array:
    """Keep the k highest-scoring tokens (reference ``TopKProcess``,
    ``hybrid_model.py:1150-1160``)."""
    if top_k <= 0:
        return logits
    top_k = min(top_k, logits.shape[-1])
    kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def top_k_top_p_filter(logits: jax.Array, top_k: int,
                       top_p: float, approx: bool = False) -> jax.Array:
    """Fused TopK + TopP: ONE top-k scan of the vocabulary serves both
    the k-th-value cutoff and the nucleus threshold (the separate
    filters would each run their own O(V) scan per decoded token).
    Semantics with ``approx=False`` (the default): identical to
    ``top_p_filter(top_k_filter(x))``.

    ``approx=True`` uses ``lax.approx_max_k`` (recall 0.99): XLA:TPU
    lowers exact ``top_k`` to a full-vocabulary SORT — measured 0.4 ms
    of a 3.5 ms decode step at V=50k — while the binned approximate
    kernel takes ~0.07 ms. When the bins miss a true top-k value, the
    k-th-value cutoff lands LOWER, so the filter keeps a slight
    SUPERSET of the exact candidate set (and the nucleus threshold
    loosens with it) — it never drops a high-probability token.
    Harmless for temperature sampling; keep it off where the
    candidate set must never widen (beam scoring does).
    """
    vocab = logits.shape[-1]
    if top_k <= 0 or top_k >= vocab:
        return top_p_filter(top_k_filter(logits, top_k), top_p)
    if approx:
        sorted_logits = jax.lax.approx_max_k(
            logits, top_k, recall_target=0.99)[0]
    else:
        sorted_logits = jax.lax.top_k(logits, top_k)[0]
    filtered = jnp.where(logits < sorted_logits[..., -1:], NEG_INF,
                         logits)
    if top_p >= 1.0:
        return filtered
    denom = jax.scipy.special.logsumexp(filtered, axis=-1,
                                        keepdims=True)
    probs = jnp.exp(sorted_logits - denom)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (cum - probs) < top_p
    threshold = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1,
        keepdims=True)
    return jnp.where(filtered < threshold, NEG_INF, filtered)


def top_p_filter(logits: jax.Array, top_p: float,
                 already_top_k: int = 0) -> jax.Array:
    """Nucleus filtering (reference ``TopPProcess``,
    ``hybrid_model.py:1163-1187``): keep the smallest set of tokens
    whose cumulative probability exceeds ``top_p``.

    ``already_top_k > 0`` promises the caller has applied
    :func:`top_k_filter` with that k, so at most k entries are finite
    — the nucleus threshold is then computed from ``lax.top_k`` over
    k values instead of a full-vocabulary sort (the sort over 50k
    logits otherwise dominates the per-token sampling cost).
    """
    if top_p >= 1.0:
        return logits
    if 0 < already_top_k < logits.shape[-1]:
        # top_k returns values sorted descending. The probability
        # denominator must still come from the FULL filtered vector
        # (one sort-free logsumexp pass): ties at the k-th value keep
        # extra copies finite beyond the k returned here, and a
        # denominator over only k values would shift the nucleus
        # boundary. With the full-mass denominator the kept set is
        # identical to the full-sort path's (the final `logits <
        # threshold` compare re-admits every tie copy either way).
        sorted_logits = jax.lax.top_k(logits, already_top_k)[0]
        denom = jax.scipy.special.logsumexp(logits, axis=-1,
                                            keepdims=True)
        probs = jnp.exp(sorted_logits - denom)
    else:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # mask tokens once the cumulative mass *before* them exceeds top_p
    keep_sorted = (cum - probs) < top_p
    threshold = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1,
        keepdims=True)
    return jnp.where(logits < threshold, NEG_INF, logits)


def hamming_diversity_processor(scores: jax.Array,
                                current_tokens: jax.Array,
                                beam_group_idx: int,
                                diversity_rate: float, num_beams: int,
                                num_beam_groups: int) -> jax.Array:
    """Diverse (group) beam search penalty (reference
    ``HammingDiversityLogitsProcessor``, ``processor.py:106-155``):
    subtract ``diversity_rate`` times the frequency with which earlier
    groups already chose each token at this step.

    ``scores``: [batch * group_size, V] for the group being scored;
    ``current_tokens``: [batch * num_beams] this-step choices of all
    beams (only beams before this group are read).
    """
    if not isinstance(diversity_rate, float) or diversity_rate <= 0.0:
        raise ValueError(
            "`diversity_rate` should be a float strictly larger than 0.")
    if not isinstance(num_beams, int) or num_beams < 2:
        raise ValueError(
            "`num_beams` should be an integer strictly larger than 1.")
    if not isinstance(num_beam_groups, int) or num_beam_groups < 2:
        raise ValueError(
            "`num_beam_groups` should be an integer strictly larger "
            "than 1.")
    num_sub = num_beams // num_beam_groups
    group_start = beam_group_idx * num_sub
    if group_start == 0:
        return scores
    group_size = min(group_start + num_sub, num_beams) - group_start
    vocab = scores.shape[-1]
    batch = current_tokens.shape[0] // num_beams
    prev = current_tokens.reshape(batch, num_beams)[:, :group_start]
    # bincount over earlier groups' tokens, vectorized as one-hot sums
    freq = jnp.sum(jax.nn.one_hot(prev, vocab, dtype=scores.dtype),
                   axis=1)
    return scores - diversity_rate * jnp.repeat(freq, group_size, axis=0)
