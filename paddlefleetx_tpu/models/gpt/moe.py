"""Mixture-of-Experts FFN with expert parallelism (beyond-reference).

The reference has no MoE (SURVEY §2.2: "EP … not present"); this adds
it the TPU-native way — the GShard/Switch design expressed so that
GSPMD partitions it:

  - a fp32 router picks top-k experts per token;
  - tokens reach their per-expert capacity slots through one of the
    ``Config.moe_dispatch`` lowerings (matrix in docs/moe.md):

    * ``"einsum"`` — the one-hot *dispatch* / gate-weighted *combine*
      tensors ``[b, s, E, C]`` of the original GShard formulation.
      All static shapes and dense batched matmuls, but the pack and
      unpack einsums cost ``O(b·s·E·C·h)`` FLOPs — at the shipped ep8
      config that dwarfs the expert GEMMs themselves. Kept as the
      parity/fallback reference.
    * ``"sort"`` — counting-sort routing: each kept (token, choice)
      gets a destination slot ``e·C + position``; a static-shape
      inverse-permutation gather packs tokens into the contiguous
      ``[E, b, C, h]`` grouped buffer, and a second gather + gate
      weighting combines the expert outputs back. ``O(b·s·k·h)`` data
      movement, no ``[b, s, E, C]`` tensor ever materializes, and the
      dropped-token set is IDENTICAL to the einsum path's by
      construction (same cumsum slot positions).
    * ``"sort_pallas"`` — ``"sort"`` dispatch with the expert matmuls
      lowered to the Pallas grouped GEMM
      (``ops/pallas/grouped_matmul.py``), which skips (expert, row)
      groups no token routed to using the routing counts.

  - expert weights are stacked on a leading ``expert`` logical axis.
    Expert parallelism = sharding that axis over the dataflow mesh
    axes (``Distributed.ep_degree`` → dp/fsdp; a *dedicated* mesh
    axis would replicate the attention compute ep-fold, which is why
    EP classically rides the data-parallel groups). XLA inserts the
    token all-to-alls at the dispatch/combine boundaries — the einsum
    contraction or the sort path's ``[b, E, C, h] → [E, b, C, h]``
    resharding transpose; either way the sharding constraints, not
    hand-written collectives, place the communication.
    The ``expert_mlp`` inner dim still shards over mp, composing
    EP x TP.

Load balancing follows Switch/GShard: an auxiliary loss
``E * sum_e f_e * P_e`` (f = fraction of tokens whose top-1 choice is
expert e, P = mean router probability) plus an optional router z-loss
``mean(logsumexp(logits)^2)``. The layer returns the already-weighted
auxiliary total; the model sows it into the ``losses`` collection and
the training loss adds it.

Each compiled shape records its chosen lowering in the trace-time
dispatch counters (``moe/einsum``, ``moe/sort``, ``moe/sort_pallas``,
``moe/fallback/pallas_rejected`` — same contract as ``attention/*``
and ``mp_linear/*``, docs/observability.md).
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...observability import metrics
from ...parallel.sharding import with_logical_constraint
from .config import GPTConfig


def _dense_init(cfg: GPTConfig):
    # single source of truth lives in model.py (which imports this
    # module lazily, so the import is cycle-safe)
    from .model import _dense_init as impl
    return impl(cfg)


def expert_capacity(cfg: GPTConfig, seq_len: int) -> int:
    """Per-expert capacity slots for one routing group (= one batch
    row): ``ceil(top_k * seq * capacity_factor / num_experts)``."""
    return max(1, int(math.ceil(
        cfg.moe_top_k * seq_len * cfg.moe_capacity_factor
        / cfg.moe_num_experts)))


def _routing_plan(probs: jax.Array, top_k: int, capacity: int):
    """Routing decisions shared by EVERY dispatch lowering.

    Single source of truth for which (token, choice) keeps its slot —
    the einsum and sort paths both consume these exact positions, so
    their dropped-token sets cannot diverge.

    Returns ``(gate, idx, pos, keep, flat, aux_frac)``:
      gate: fp32 ``[b, s, k]`` top-k gate probabilities (renormalized
        for k>1).
      idx: int32 ``[b, s, k]`` chosen expert ids.
      pos: int32 ``[b, s*k]`` position of each flat (token, choice) in
        its expert's slot queue — lexicographic (s, k) priority, all
        of a token's choices adjacent, earlier tokens win slots
        (the reference-free GShard formulation).
      keep: bool ``[b, s*k]`` — position fits under ``capacity``.
      flat: int32 ``[b, s*k, E]`` one-hot expert choice.
      aux_frac: fp32 ``[E]`` fraction of tokens whose *first* choice
        is each expert (the f_e of the Switch load-balance loss,
        computed before capacity drops, as in GShard).
    """
    b, s, E = probs.shape
    gate, idx = jax.lax.top_k(probs, top_k)            # [b, s, k]
    if top_k > 1:
        gate = gate / jnp.maximum(
            gate.sum(axis=-1, keepdims=True), 1e-9)
    onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)   # [b, s, k, E]
    flat = onehot.reshape(b, s * top_k, E)
    pos = jnp.sum((jnp.cumsum(flat, axis=1) - flat) * flat,
                  axis=-1)                             # [b, s*k]
    keep = pos < capacity
    aux_frac = onehot[:, :, 0, :].astype(jnp.float32).mean(axis=(0, 1))
    return gate, idx, pos, keep, flat, aux_frac


def router_dispatch(probs: jax.Array, top_k: int, capacity: int):
    """Token-choice routing as dense one-hot tensors (einsum path).

    Args:
      probs: fp32 router probabilities ``[b, s, E]``.
      top_k: experts per token.
      capacity: slots per expert per batch row.

    Returns ``(dispatch, combine, aux_frac)``:
      dispatch: 0/1 ``[b, s, E, C]`` — token (b,s) occupies slot c of
        expert e. Tokens overflowing an expert's capacity are dropped
        (their dispatch row is zero → they pass through the residual
        only, the standard Switch overflow behavior).
      combine: fp32 ``[b, s, E, C]`` — dispatch weighted by the
        (renormalized, for k>1) gate probabilities.
      aux_frac: fp32 ``[E]`` — see :func:`_routing_plan`.
    """
    b, s, E = probs.shape
    gate, _, pos, keep, flat, aux_frac = _routing_plan(
        probs, top_k, capacity)
    kept = keep[..., None] * flat                      # [b, s*k, E]
    slot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)
    dispatch = jnp.einsum("bte,btc->btec", kept.astype(jnp.float32),
                          slot)
    dispatch = dispatch.reshape(b, s, top_k, E, capacity)
    combine = jnp.einsum("bskec,bsk->bsec", dispatch, gate)
    dispatch = dispatch.sum(axis=2)                    # [b, s, E, C]
    return dispatch, combine, aux_frac


def sort_routing(probs: jax.Array, top_k: int, capacity: int):
    """Counting-sort routing plan (sort / sort_pallas paths).

    The cumsum slot positions of :func:`_routing_plan` ARE a counting
    sort of the token→expert assignment: ``dest = e·C + pos`` is a
    unique grouped-buffer slot per kept choice, and scattering the
    choice index through it yields the inverse permutation ``src`` a
    static-shape gather needs. No ``[b, s, E, C]`` one-hot tensor is
    ever built.

    Returns ``(gate, dest, src, counts, aux_frac)``:
      gate: fp32 ``[b, s, k]`` renormalized gates.
      dest: int32 ``[b, s*k]`` grouped-buffer slot of each (token,
        choice); ``E*C`` (one past the end) for capacity-dropped
        choices — the combine gather reads the zero pad row there.
      src: int32 ``[b, E*C]`` source token row feeding each slot;
        ``s`` (the zero pad row) for unoccupied slots.
      counts: int32 ``[b, E]`` kept tokens per (batch row, expert) —
        the group boundaries the Pallas grouped GEMM iterates.
      aux_frac: fp32 ``[E]`` — see :func:`_routing_plan`.
    """
    b, s, E = probs.shape
    C = capacity
    gate, idx, pos, keep, flat, aux_frac = _routing_plan(
        probs, top_k, C)
    T = s * top_k
    flat_e = idx.reshape(b, T)
    dest = jnp.where(keep, flat_e * C + pos, E * C).astype(jnp.int32)
    # inverse permutation: which flat choice occupies each slot. The
    # in-range dest values are unique (one choice per slot), so the
    # scatter is deterministic; dropped choices aim one past the end
    # and mode="drop" discards them.
    src_choice = jnp.full((b, E * C), T, jnp.int32)
    src_choice = src_choice.at[
        jnp.arange(b)[:, None], dest].set(
        jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (b, T)),
        mode="drop")
    # choice t came from token t // k; empty slots hold T, and
    # T // k == s is exactly the zero pad row the gather wants
    src = src_choice // top_k
    counts = jnp.minimum(flat.sum(axis=1), C).astype(jnp.int32)
    return gate, dest, src, counts, aux_frac


class MoEMLP(nn.Module):
    """Drop-in replacement for the decoder block's dense FFN.

    Returns ``(y, aux)`` where ``aux`` is the weighted auxiliary loss
    (load balance + router z-loss) as an fp32 scalar. The parameter
    tree ("router_kernel"/"wi"/"wi_bias"/"wo"/"wo_bias", shapes,
    logical axes, init streams) is identical across every
    ``moe_dispatch`` mode — checkpoints move freely between them.
    """
    config: GPTConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        E, k = cfg.moe_num_experts, cfg.moe_top_k
        b, s, h = x.shape
        m = cfg.ffn_hidden_size
        dtype = jnp.dtype(cfg.dtype)
        pdtype = jnp.dtype(cfg.param_dtype)

        # router runs in fp32 (bf16 logits make top-k ties and the
        # z-loss noisy); its params are tiny and stay replicated
        wr = self.param(
            "router_kernel",
            nn.with_logical_partitioning(_dense_init(cfg),
                                         ("embed", None)),
            (h, E), pdtype)
        logits = jnp.einsum("bsh,he->bse", x.astype(jnp.float32),
                            wr.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)

        w1 = self.param(
            "wi", nn.with_logical_partitioning(
                _dense_init(cfg), ("expert", "expert_embed",
                                   "expert_mlp")),
            (E, h, m), pdtype)
        b1 = self.param(
            "wi_bias", nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("expert", "expert_mlp")),
            (E, m), pdtype)
        w2 = self.param(
            "wo", nn.with_logical_partitioning(
                _dense_init(cfg), ("expert", "expert_mlp",
                                   "expert_embed")),
            (E, m, h), pdtype)
        b2 = self.param(
            "wo_bias", nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("expert",
                                               "expert_embed")),
            (E, h), pdtype)

        C = expert_capacity(cfg, s)
        mode = cfg.moe_dispatch
        if mode == "einsum":
            metrics.inc("moe/einsum")
            dispatch, combine, aux_frac = router_dispatch(probs, k, C)
            # pack tokens into expert slots: [b,s,h] -> [E,b,C,h]; the
            # E axis is ep-sharded, so this einsum IS the all-to-all
            xe = jnp.einsum("bsec,bsh->ebch", dispatch.astype(dtype),
                            x)
            xe = with_logical_constraint(
                xe, ("act_expert", "act_expert_batch", None, None))
            y = self._expert_ffn(xe, w1, b1, w2, b2, None,
                                 deterministic)
            # unpack + gate-weight: the return all-to-all
            out = jnp.einsum("ebch,bsec->bsh", y,
                             combine.astype(dtype))
        else:
            gate, dest, src, counts, aux_frac = sort_routing(
                probs, k, C)
            # gather the routed tokens into per-(row, expert) groups:
            # [b, s, h] -> [b, E*C, h]; the appended zero row feeds
            # every unoccupied capacity slot
            x_pad = jnp.concatenate(
                [x, jnp.zeros((b, 1, h), x.dtype)], axis=1)
            xs = jnp.take_along_axis(x_pad, src[..., None], axis=1)
            xs = with_logical_constraint(
                xs, ("batch", "act_expert_slot", None))
            # reshard to expert-major: with E ep-sharded this
            # transpose is where GSPMD places the token all-to-all
            xe = xs.reshape(b, E, C, h).transpose(1, 0, 2, 3)
            xe = with_logical_constraint(
                xe, ("act_expert", "act_expert_batch", None, None))
            y = self._expert_ffn(
                xe, w1, b1, w2, b2,
                counts if mode == "sort_pallas" else None,
                deterministic)
            if mode == "sort":
                metrics.inc("moe/sort")
            # combine: per-choice gather of the expert outputs, gate
            # weighted; dropped choices read the zero pad slot, so a
            # fully-dropped token contributes nothing (pure residual)
            yf = y.transpose(1, 0, 2, 3).reshape(b, E * C, h)
            yf = jnp.concatenate(
                [yf, jnp.zeros((b, 1, h), y.dtype)], axis=1)
            yc = jnp.take_along_axis(yf, dest[..., None], axis=1)
            out = jnp.einsum("bskh,bsk->bsh", yc.reshape(b, s, k, h),
                             gate.astype(y.dtype))
        out = with_logical_constraint(out, ("batch", None, "act_embed"))

        aux = jnp.asarray(0.0, jnp.float32)
        if cfg.moe_aux_loss_weight:
            load_balance = E * jnp.sum(aux_frac * probs.mean(axis=(0, 1)))
            aux = aux + cfg.moe_aux_loss_weight * load_balance
        if cfg.moe_z_loss_weight:
            z = jnp.mean(
                jax.scipy.special.logsumexp(logits, axis=-1) ** 2)
            aux = aux + cfg.moe_z_loss_weight * z
        return out, aux

    def _expert_ffn(self, xe, w1, b1, w2, b2, counts, deterministic):
        """Expert MLP over the grouped ``[E, b, C, *]`` buffer.

        ``counts`` (int32 ``[b, E]``, sort_pallas only) routes the two
        matmuls to the Pallas grouped GEMM, which skips empty
        (expert, row) groups; ``None`` keeps the XLA batched einsums.
        Biases, gelu and dropout stay OUTSIDE the kernel, so every
        mode shares one definition of the non-matmul math and the
        kernel's group-skip zeros are exactly the zeros the einsum
        produces for unrouted slots.
        """
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        E, bb, C, h = xe.shape
        m = cfg.ffn_hidden_size
        from jax.ad_checkpoint import checkpoint_name
        gmm = None
        if counts is not None:
            try:
                from ...ops.pallas.grouped_matmul import grouped_matmul
                # groups ordered (e, row): group e*b + i holds batch
                # row i's slice of expert e's capacity block
                g_counts = counts.T.reshape(E * bb)
                y = grouped_matmul(xe.reshape(E * bb, C, h),
                                   w1.astype(dtype), g_counts)
                y = y.reshape(E, bb, C, m)
                gmm = grouped_matmul
                metrics.inc("moe/sort_pallas")
            except (ImportError, NotImplementedError):
                # kernel rejected the shape — expert compute falls
                # back to the XLA einsums on the same grouped buffer
                # (the dispatch stays sort-based; docs/moe.md)
                metrics.inc("moe/fallback/pallas_rejected")
                metrics.inc("moe/sort")
        if gmm is None:
            y = jnp.einsum("ebch,ehm->ebcm", xe, w1.astype(dtype))
        y = y + b1.astype(dtype)[:, None, None, :]
        y = checkpoint_name(y, "mlp1")
        y = nn.gelu(y, approximate=True)
        # hidden dropout inside the expert MLP (the dense FFN's
        # hidden_dropout_prob; parity note in docs/parity_matrix.md).
        # nn.Dropout folds the "dropout" rng on the module path, and
        # flax replays lifted rngs across a remat recompute, so the
        # keys are stable under use_recompute; the mask rides on the
        # mode-independent [E, b, C, m] slot layout, so all three
        # dispatch modes drop the same activations for the same rng.
        if cfg.hidden_dropout_prob > 0.0:
            y = nn.Dropout(cfg.hidden_dropout_prob,
                           name="expert_dropout")(
                y, deterministic=deterministic)
        y = with_logical_constraint(
            y, ("act_expert", "act_expert_batch", None, "act_mlp"))
        if gmm is not None:
            # padding rows here are gelu(b1), not zero — safe because
            # the kernel's skipped-group outputs are never combined
            # (zero gate weight) so their cotangents arrive as zeros
            y = gmm(y.reshape(E * bb, C, m), w2.astype(dtype),
                    g_counts).reshape(E, bb, C, h)
        else:
            y = jnp.einsum("ebcm,emh->ebch", y, w2.astype(dtype))
        y = y + b2.astype(dtype)[:, None, None, :]
        y = checkpoint_name(y, "mlp2")
        return y
