"""Mixture-of-Experts FFN with expert parallelism (beyond-reference).

The reference has no MoE (SURVEY §2.2: "EP … not present"); this adds
it the TPU-native way — the GShard/Switch design expressed as einsums
that GSPMD partitions:

  - a fp32 router picks top-k experts per token;
  - tokens are packed into per-expert capacity slots through a
    one-hot *dispatch* tensor and unpacked through a gate-weighted
    *combine* tensor (all static shapes — no ragged scatter, so the
    MXU sees dense batched matmuls);
  - expert weights are stacked on a leading ``expert`` logical axis.
    Expert parallelism = sharding that axis over the dataflow mesh
    axes (``Distributed.ep_degree`` → dp/fsdp; a *dedicated* mesh
    axis would replicate the attention compute ep-fold, which is why
    EP classically rides the data-parallel groups). XLA inserts the
    token all-to-alls at the dispatch/combine einsum boundaries.
    The ``expert_mlp`` inner dim still shards over mp, composing
    EP x TP.

Load balancing follows Switch/GShard: an auxiliary loss
``E * sum_e f_e * P_e`` (f = fraction of tokens whose top-1 choice is
expert e, P = mean router probability) plus an optional router z-loss
``mean(logsumexp(logits)^2)``. The layer returns the already-weighted
auxiliary total; the model sows it into the ``losses`` collection and
the training loss adds it.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...parallel.sharding import with_logical_constraint
from .config import GPTConfig


def _dense_init(cfg: GPTConfig):
    # single source of truth lives in model.py (which imports this
    # module lazily, so the import is cycle-safe)
    from .model import _dense_init as impl
    return impl(cfg)


def expert_capacity(cfg: GPTConfig, seq_len: int) -> int:
    """Per-expert capacity slots for one routing group (= one batch
    row): ``ceil(top_k * seq * capacity_factor / num_experts)``."""
    return max(1, int(math.ceil(
        cfg.moe_top_k * seq_len * cfg.moe_capacity_factor
        / cfg.moe_num_experts)))


def router_dispatch(probs: jax.Array, top_k: int, capacity: int):
    """Token-choice routing with per-expert capacity.

    Args:
      probs: fp32 router probabilities ``[b, s, E]``.
      top_k: experts per token.
      capacity: slots per expert per batch row.

    Returns ``(dispatch, combine, aux_frac)``:
      dispatch: 0/1 ``[b, s, E, C]`` — token (b,s) occupies slot c of
        expert e. Tokens overflowing an expert's capacity are dropped
        (their dispatch row is zero → they pass through the residual
        only, the standard Switch overflow behavior).
      combine: fp32 ``[b, s, E, C]`` — dispatch weighted by the
        (renormalized, for k>1) gate probabilities.
      aux_frac: fp32 ``[E]`` — fraction of tokens whose *first* choice
        is each expert (the f_e of the Switch load-balance loss,
        computed before capacity drops, as in GShard).
    """
    b, s, E = probs.shape
    gate, idx = jax.lax.top_k(probs, top_k)            # [b, s, k]
    if top_k > 1:
        gate = gate / jnp.maximum(
            gate.sum(axis=-1, keepdims=True), 1e-9)
    onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)   # [b, s, k, E]

    # Position of each (token, choice) in its expert's slot queue:
    # lexicographic (s, k) priority — all of a token's choices are
    # adjacent, earlier tokens win slots, matching the reference-free
    # GShard formulation.
    flat = onehot.reshape(b, s * top_k, E)
    pos = jnp.sum((jnp.cumsum(flat, axis=1) - flat) * flat,
                  axis=-1)                             # [b, s*k]
    kept = (pos < capacity)[..., None] * flat          # [b, s*k, E]
    slot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)
    dispatch = jnp.einsum("bte,btc->btec", kept.astype(jnp.float32),
                          slot)
    dispatch = dispatch.reshape(b, s, top_k, E, capacity)
    combine = jnp.einsum("bskec,bsk->bsec", dispatch, gate)
    dispatch = dispatch.sum(axis=2)                    # [b, s, E, C]

    aux_frac = onehot[:, :, 0, :].astype(jnp.float32).mean(axis=(0, 1))
    return dispatch, combine, aux_frac


class MoEMLP(nn.Module):
    """Drop-in replacement for the decoder block's dense FFN.

    Returns ``(y, aux)`` where ``aux`` is the weighted auxiliary loss
    (load balance + router z-loss) as an fp32 scalar.
    """
    config: GPTConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        E, k = cfg.moe_num_experts, cfg.moe_top_k
        b, s, h = x.shape
        m = cfg.ffn_hidden_size
        dtype = jnp.dtype(cfg.dtype)
        pdtype = jnp.dtype(cfg.param_dtype)

        # router runs in fp32 (bf16 logits make top-k ties and the
        # z-loss noisy); its params are tiny and stay replicated
        wr = self.param(
            "router_kernel",
            nn.with_logical_partitioning(_dense_init(cfg),
                                         ("embed", None)),
            (h, E), pdtype)
        logits = jnp.einsum("bsh,he->bse", x.astype(jnp.float32),
                            wr.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)

        C = expert_capacity(cfg, s)
        dispatch, combine, aux_frac = router_dispatch(probs, k, C)

        # pack tokens into expert slots: [b,s,h] -> [E,b,C,h]; the E
        # axis is ep-sharded, so this einsum IS the all-to-all
        xe = jnp.einsum("bsec,bsh->ebch", dispatch.astype(dtype), x)
        xe = with_logical_constraint(
            xe, ("act_expert", "act_expert_batch", None, None))

        w1 = self.param(
            "wi", nn.with_logical_partitioning(
                _dense_init(cfg), ("expert", "expert_embed",
                                   "expert_mlp")),
            (E, h, m), pdtype)
        b1 = self.param(
            "wi_bias", nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("expert", "expert_mlp")),
            (E, m), pdtype)
        w2 = self.param(
            "wo", nn.with_logical_partitioning(
                _dense_init(cfg), ("expert", "expert_mlp",
                                   "expert_embed")),
            (E, m, h), pdtype)
        b2 = self.param(
            "wo_bias", nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("expert",
                                               "expert_embed")),
            (E, h), pdtype)

        from jax.ad_checkpoint import checkpoint_name
        y = jnp.einsum("ebch,ehm->ebcm", xe, w1.astype(dtype)) \
            + b1.astype(dtype)[:, None, None, :]
        y = checkpoint_name(y, "mlp1")
        y = nn.gelu(y, approximate=True)
        y = with_logical_constraint(
            y, ("act_expert", "act_expert_batch", None, "act_mlp"))
        y = jnp.einsum("ebcm,emh->ebch", y, w2.astype(dtype)) \
            + b2.astype(dtype)[:, None, None, :]
        y = checkpoint_name(y, "mlp2")

        # unpack + gate-weight: the return all-to-all
        out = jnp.einsum("ebch,bsec->bsh", y, combine.astype(dtype))
        out = with_logical_constraint(out, ("batch", None, "act_embed"))

        aux = jnp.asarray(0.0, jnp.float32)
        if cfg.moe_aux_loss_weight:
            load_balance = E * jnp.sum(aux_frac * probs.mean(axis=(0, 1)))
            aux = aux + cfg.moe_aux_loss_weight * load_balance
        if cfg.moe_z_loss_weight:
            z = jnp.mean(
                jax.scipy.special.logsumexp(logits, axis=-1) ** 2)
            aux = aux + cfg.moe_z_loss_weight * z
        return out, aux
