"""GPT task modules implementing the BasicModule contract.

Parity: reference ``language_module.py:112-177`` (``GPTModule``: model
selection by topology, PP batch reshaping, loss wiring). Under GSPMD
there is no per-topology model class — one ``GPTForPretraining`` with
logical axes serves single-card, hybrid, and auto; ``GPTModuleAuto``
is an alias for config compatibility.
"""

from __future__ import annotations

from typing import Any, Dict

import jax

from .. import register_module
from ...core.module import LanguageModule
from .config import GPTConfig
from .model import GPTForPretraining, cross_entropy_loss


@register_module("GPTModule")
class GPTModule(LanguageModule):
    def __init__(self, configs):
        from ..language_utils import process_configs
        process_configs(configs)
        super().__init__(configs)

    def get_model(self):
        self.model_config = GPTConfig.from_config(self.configs)
        return GPTForPretraining(self.model_config)

    def loss_fn(self, params, batch, rng, train: bool = True):
        tokens, position_ids, labels, loss_mask = batch
        deterministic = not train or (
            self.model_config.hidden_dropout_prob == 0.0
            and self.model_config.attention_probs_dropout_prob == 0.0)
        rngs = None if deterministic else {"dropout": rng}
        logits = self.model.apply(
            {"params": params}, tokens, position_ids=position_ids,
            deterministic=deterministic, rngs=rngs)
        return cross_entropy_loss(logits, labels, loss_mask)

    def input_spec(self):
        seq = self.configs.Data.Train.dataset.max_seq_len
        micro = self.configs.Global.micro_batch_size
        return [((micro, seq), "int32"), ((micro, seq), "int32")]

    def training_step_end(self, log_dict: Dict[str, Any]) -> None:
        log_dict.setdefault(
            "max_seq_len", self.configs.Data.Train.dataset.max_seq_len)
        super().training_step_end(log_dict)


@register_module("GPTModuleAuto")
class GPTModuleAuto(GPTModule):
    """The reference's auto-parallel module is the same model here —
    GSPMD is the auto engine (SURVEY.md §7 design stance)."""
