"""GPT task modules implementing the BasicModule contract.

Parity: reference ``language_module.py:112-177`` (``GPTModule``: model
selection by topology, PP batch reshaping, loss wiring). Under GSPMD
there is no per-topology model class — one ``GPTForPretraining`` with
logical axes serves single-card, hybrid, and auto; ``GPTModuleAuto``
is an alias for config compatibility.
"""

from __future__ import annotations

from typing import Any, Dict

import jax

from .. import register_module
from ...core.module import LanguageModule
from .config import GPTConfig
from .model import GPTForPretraining, cross_entropy_loss


@register_module("GPTModule")
class GPTModule(LanguageModule):
    """GPT causal-LM training module: loss, generation and the
    flash-dropout admission gate."""

    #: loss_fn microbatches internally when pp>1 (engine then skips its
    #: own accumulation scan)
    supports_pipeline = True

    def __init__(self, configs):
        from ..language_utils import process_configs
        from ...ops.quantization import QuantizationConfig
        process_configs(configs)
        self.qat_cfg = QuantizationConfig.from_config(configs)
        super().__init__(configs)

    #: ring attention handles the cp-sharded sequence axis
    supports_context_parallel = True

    def get_model(self):
        self.model_config = GPTConfig.from_config(self.configs)
        cp = (self.configs.get("Distributed") or {}).get("cp_degree", 1)
        if (cp or 1) > 1 and not self.model_config.context_parallel:
            import dataclasses
            self.model_config = dataclasses.replace(
                self.model_config, context_parallel=True)
        return GPTForPretraining(self.model_config)

    def _pp_setup(self, tokens, train: bool):
        """(pp, microbatches, deterministic) plus the pp-composition
        guards shared by loss_fn and loss_and_grad."""
        deterministic = not train or (
            self.model_config.hidden_dropout_prob == 0.0
            and self.model_config.attention_probs_dropout_prob == 0.0)
        mc = self.model_config
        if train and mc.attention_probs_dropout_prob > 0.0:
            # TRAINING with active attention dropout cannot take the
            # flash/ring kernels (no in-kernel dropout) — the silent
            # dense fallback is a documented, benign operating point
            # at short sequence, but an unexplained [b, h, s, s] OOM
            # trap at long sequence (VERDICT r3 #5). Refuse where it
            # traps; eval/generation (deterministic) are unaffected
            # and still use the kernels.
            if mc.context_parallel and \
                    mc.context_parallel_algo == "ring":
                raise ValueError(
                    "training with context_parallel algo='ring' "
                    "requires attention_probs_dropout_prob = 0 (ring "
                    "attention implements no prob dropout; the dense "
                    "fallback materializes the full [b, h, s, s] "
                    "scores ring attention exists to avoid). Use "
                    "context_parallel_algo: ulysses to keep dropout.")
            # keyed on the ACTUAL training sequence length, not the
            # position-table size: fine-tuning a long-context
            # checkpoint at s=1024 is the benign short-seq case even
            # when max_position_embeddings is 8192. With in-kernel
            # dropout enabled (self-certifying gate: chip-cert
            # artifact or PFX_FLASH_DROPOUT override — see
            # _kernel_dropout_enabled, ops/attention.py) AND the
            # kernel actually able to take this shape on this
            # backend, the kernel handles the dropout itself — no
            # dense fallback, nothing to refuse. The gate alone is
            # NOT enough: a shape the kernel rejects at dispatch
            # (head_dim, block alignment, non-TPU backend) would
            # silently fall back to dense and re-open the OOM trap.
            kernel_dropout_ok = False
            from ...ops.attention import _kernel_dropout_enabled
            if _kernel_dropout_enabled():
                try:
                    import jax

                    from ...ops.pallas.flash_attention import (
                        check_shapes,
                    )
                    check_shapes(
                        tokens.shape[1], tokens.shape[1],
                        mc.hidden_size // mc.num_attention_heads)
                    kernel_dropout_ok = \
                        jax.default_backend() == "tpu"
                except (ImportError, NotImplementedError):
                    kernel_dropout_ok = False
            if mc.use_flash_attention and \
                    tokens.shape[1] >= 4096 and \
                    not mc.context_parallel and \
                    not kernel_dropout_ok:
                raise ValueError(
                    "training with use_flash_attention=True and "
                    "attention_probs_dropout_prob="
                    f"{mc.attention_probs_dropout_prob} at sequence "
                    f"length {tokens.shape[1]}: the flash kernel "
                    "implements no prob dropout, so training would "
                    "silently fall back to dense XLA attention whose "
                    "[b, h, s, s] scores do not fit at this length. "
                    "Set attention_probs_dropout_prob: 0.0 "
                    "(GPT-3-style pretraining uses none) or "
                    "use_flash_attention: False to accept dense "
                    "attention explicitly.")
        pp = (self.configs.get("Distributed") or {}).get("pp_degree", 1) \
            or 1
        # pp > 1 never reaches here with loss_chunks > 1:
        # process_model_configs subsumes the knob (the pipeline already
        # computes per-microbatch logits) and resets it to 1
        if self.model_config.loss_chunks > 1 and self.qat_cfg.enable:
            # a silent dense fallback would defeat the knob's
            # O(s/chunks) logits-memory purpose (same policy as the
            # cp guard above)
            raise ValueError(
                "loss_chunks > 1 is not supported with QAT")
        if pp > 1 and self.qat_cfg.enable:
            raise ValueError("QAT is not supported with pipeline "
                             "parallelism (reference QAT recipe is "
                             "mp-only, pretrain_gpt_345M_mp8_qat)")
        if self.model_config.moe_num_experts and self.qat_cfg.enable:
            raise ValueError("QAT is not supported with MoE (the QAT "
                             "wrapper fake-quantizes dense Linear "
                             "kernels only)")
        # microbatch count = accumulate_steps (reference
        # ``utils/config.py:117``); eval batches that don't divide
        # fall back to a single microbatch
        acc = self.configs.Engine.get("accumulate_steps", 1) or 1
        m = acc if tokens.shape[0] % acc == 0 else 1
        return pp, m, deterministic

    def _resolve_pp_schedule(self, sched, params, tokens, *, pp,
                             num_microbatches):
        """Budget-aware ``(schedule, h2_depth)`` for the pipelined
        train step.

        ``1F1B``/``zb`` pass through. ``zb_h2``/``zb_auto`` consult
        the analytic per-stage byte model (parallel/pp_memory.py) with
        the LIVE param count and microbatch shape: an explicitly
        requested depth that exceeds the device budget raises here —
        a config error at step-build time, not an OOM mid-trace —
        while ``zb_auto`` (and ``zb_h2_depth: -1``) pick the deepest
        feasible depth and log the decision.
        """
        if sched in ("1F1B", "zb"):
            return sched, 0
        from ...observability import metrics
        from ...parallel import pp_memory
        from ...utils.log import logger
        mc = self.model_config
        param_count = sum(int(x.size) for x in jax.tree.leaves(params))
        mb = max(tokens.shape[0] // num_microbatches, 1)
        pick = pp_memory.resolve_pipeline_schedule(
            sched, pp=pp, vpp=mc.virtual_pp_degree,
            requested_depth=mc.zb_h2_depth,
            budget_bytes=pp_memory.hbm_budget_bytes(),
            mem_kwargs=dict(
                microbatch_tokens=mb * tokens.shape[1],
                hidden_size=mc.hidden_size, param_count=param_count,
                compute_dtype=mc.dtype, param_dtype=mc.param_dtype))
        if sched == "zb_auto":
            metrics.inc("pipeline/auto_schedule_picks")
        logger.info(
            "[pipeline] schedule %s -> %s (h2_depth=%d): %s "
            "(predicted %s bytes/stage, budget %s)", sched,
            pick["schedule"], pick["h2_depth"], pick["reason"],
            pick["predicted_stage_bytes"], pick["budget_bytes"])
        return pick["schedule"], pick["h2_depth"]

    def loss_and_grad(self, params, batch, rng):
        """One-pass (loss, grads) for the engine's train step.

        With pp>1 under ``pipeline_schedule: 1F1B`` (default), ``zb``,
        ``zb_h2`` or ``zb_auto`` this drives the explicit schedule in
        ``pipeline_value_and_grad`` (bounded activation memory; the zb
        family additionally drains deferred weight-grads into the
        bubble — zb_h2 after memory-model depth resolution, see
        ``_resolve_pp_schedule``); otherwise it is plain
        ``jax.value_and_grad`` of ``loss_fn``.
        """
        pp, m, deterministic = self._pp_setup(batch[0], train=True)
        sched = self.model_config.pipeline_schedule
        if pp > 1 and sched in ("1F1B", "zb", "zb_h2", "zb_auto"):
            from .model import pipelined_lm_loss_and_grad
            tokens, position_ids, labels, loss_mask = batch
            sched, h2_depth = self._resolve_pp_schedule(
                sched, params, tokens, pp=pp, num_microbatches=m)
            return pipelined_lm_loss_and_grad(
                self.model_config, params, tokens, labels, loss_mask,
                pp=pp, num_microbatches=m,
                vpp=self.model_config.virtual_pp_degree, rng=rng,
                position_ids=position_ids, deterministic=deterministic,
                schedule=sched, h2_depth=h2_depth)
        if pp > 1 and self.model_config.moe_num_experts:
            # GPipe trains via autodiff through pipeline_forward, which
            # discards the router aux — refuse rather than silently
            # train without the load-balance term
            raise ValueError(
                "MoE with pipeline parallelism requires "
                "pipeline_schedule '1F1B', 'zb', 'zb_h2' or 'zb_auto' "
                "(GPipe's autodiff path drops the router aux loss)")
        return jax.value_and_grad(
            lambda p: self.loss_fn(p, batch, rng, train=True))(params)

    def loss_fn(self, params, batch, rng, train: bool = True):
        """Masked-mean LM loss; routes through the pipelined loss
        when pp > 1."""
        tokens, position_ids, labels, loss_mask = batch
        pp, m, deterministic = self._pp_setup(tokens, train)
        if pp > 1:
            from .model import pipelined_lm_loss
            return pipelined_lm_loss(
                self.model_config, params, tokens, labels, loss_mask,
                pp=pp, num_microbatches=m,
                vpp=self.model_config.virtual_pp_degree, rng=rng,
                position_ids=position_ids, deterministic=deterministic)
        rngs = None if deterministic else {"dropout": rng}
        if self.model_config.loss_chunks > 1:
            from .model import chunked_lm_loss
            return chunked_lm_loss(
                self.model, params, tokens, labels, loss_mask,
                chunks=self.model_config.loss_chunks,
                position_ids=position_ids, deterministic=deterministic,
                rngs=rngs, include_moe_aux=train)
        if self.qat_cfg.enable:
            from ...ops.quantization import qat_apply
            logits = qat_apply(
                self.model, self.qat_cfg, params, tokens,
                stacked_module="decoder"
                if self.model_config.scan_layers else None,
                position_ids=position_ids, deterministic=deterministic,
                rngs=rngs)
        else:
            if self.model_config.moe_num_experts:
                # the router's load-balance/z losses are sown into the
                # "losses" collection (models/gpt/moe.py); the TRAIN
                # loss adds them to the LM cross-entropy (eval reports
                # pure CE so perplexities stay comparable)
                logits, mods = self.model.apply(
                    {"params": params}, tokens,
                    position_ids=position_ids,
                    deterministic=deterministic, rngs=rngs,
                    mutable=["losses"])
                ce = cross_entropy_loss(logits, labels, loss_mask)
                if train:
                    ce = ce + sum(jax.tree.leaves(mods["losses"]))
                return ce
            logits = self.model.apply(
                {"params": params}, tokens, position_ids=position_ids,
                deterministic=deterministic, rngs=rngs)
        return cross_entropy_loss(logits, labels, loss_mask)

    def input_spec(self):
        section = self._data_section()
        seq = section.dataset.max_seq_len if section \
            else self.model_config.max_position_embeddings
        micro = self.configs.Global.micro_batch_size
        return [((micro, seq), "int32"), ((micro, seq), "int32")]

    def training_step_end(self, log_dict: Dict[str, Any]) -> None:
        log_dict.setdefault(
            "max_seq_len", self.configs.Data.Train.dataset.max_seq_len)
        super().training_step_end(log_dict)


@register_module("GPTModuleAuto")
class GPTModuleAuto(GPTModule):
    """The reference's auto-parallel module is the same model here —
    GSPMD is the auto engine (SURVEY.md §7 design stance)."""


@register_module("GPTGenerationModule")
class GPTGenerationModule(GPTModule):
    """Text in -> sampled text out (reference
    ``language_module.py:179-275``: tokenize, left-pad, sample,
    decode)."""

    def __init__(self, configs):
        super().__init__(configs)
        from ...data.tokenizers.gpt_tokenizer import GPTTokenizer
        from .generation import GenerationConfig
        self.tokenizer = GPTTokenizer.from_pretrained(
            configs.get("Generation", {}).get("vocab_dir", "gpt2"))
        gen_section = dict(configs.get("Generation", {}))
        gen_section.setdefault("eos_token_id", self.tokenizer.eos_token_id)
        gen_section.setdefault("pad_token_id", self.tokenizer.pad_token_id)
        self.generation_cfg = GenerationConfig.from_config(gen_section)

    def export_fn(self):
        """Export the full sampling loop (the reference exports
        ``GPTForGeneration`` through dy2static for ``paddle.inference``;
        here the jitted ``generate`` itself is the artifact).

        Exported signature: ``(params, input_ids[b, prompt], mask[b,
        prompt]) -> ids[b * num_return_sequences, max_dec_len]``
        (prompt-major rows; the metadata carries
        ``num_return_sequences`` so consumers can de-tile); prompt
        capacity is ``max_position_embeddings - max_dec_len``.
        Sampling randomness is derived from the config seed and the
        prompt so the artifact stays a pure function of its inputs.
        """
        import jax
        import jax.numpy as jnp
        from .generation import generate
        model, gen_cfg = self.model, self.generation_cfg
        seed = self.configs.Global.get("seed", 1024)
        batch = self.configs.Global.micro_batch_size or 1
        prompt_cap = (self.model_config.max_position_embeddings
                      - gen_cfg.max_dec_len)

        def fn(params, input_ids, attention_mask):
            rng = jax.random.fold_in(
                jax.random.key(seed),
                jnp.sum(input_ids, dtype=jnp.uint32))
            return generate(model, params, input_ids, attention_mask,
                            rng, gen_cfg)

        spec = [((batch, prompt_cap), "int32"),
                ((batch, prompt_cap), "int32")]
        metadata = {"pad_values": [gen_cfg.pad_token_id, 0],
                    # generate() requires LEFT-padded prompts (the
                    # prefill reads logits from the last slot)
                    "pad_sides": ["left", "left"],
                    "max_dec_len": gen_cfg.max_dec_len,
                    "eos_token_id": gen_cfg.eos_token_id,
                    # output rows = batch * num_return_sequences,
                    # prompt-major — consumers must de-tile with this
                    "num_return_sequences":
                        gen_cfg.num_return_sequences}
        return fn, spec, metadata

    def generate(self, params, texts, rng=None):
        """Tokenize ``texts``, left-pad to a batch, decode with the
        configured generation strategy and return the strings."""
        import jax
        import numpy as np
        from .generation import generate, left_pad_batch
        if isinstance(texts, str):
            texts = [texts]
        encoded = [self.tokenizer.encode(t) for t in texts]
        ids, mask = left_pad_batch(encoded, self.tokenizer.pad_token_id)
        rng = rng if rng is not None else jax.random.key(
            self.configs.Global.get("seed", 1024))
        out = np.asarray(generate(self.model, params, ids, mask, rng,
                                  self.generation_cfg))
        results = []
        for row in out:
            row = row.tolist()
            if self.generation_cfg.eos_token_id in row:
                row = row[: row.index(self.generation_cfg.eos_token_id)]
            results.append(self.tokenizer.decode(row))
        return results


@register_module("GPTEvalModule")
class GPTEvalModule(GPTModule):
    """Offline WikiText-PPL / LAMBADA-accuracy evaluation (reference
    ``language_module.py:277-389``)."""

    def __init__(self, configs):
        self.eval_cfgs = configs.Offline_Eval
        self.cloze_eval = bool(self.eval_cfgs.get("cloze_eval", False))
        self._post_process_configs(configs)
        super().__init__(configs)
        self.total_score = 0.0
        self.first_step = True
        self.num_original_tokens = None
        self.num_tokenized_tokens = None
        self.num_examples = None

    def _post_process_configs(self, configs):
        data_eval = configs.Data.Eval
        data_eval.dataset["input_dir"] = self.eval_cfgs.eval_path
        data_eval.dataset["max_seq_len"] = self.eval_cfgs.get(
            "max_seq_len", data_eval.dataset.get("max_seq_len", 1024))
        if self.cloze_eval:
            data_eval.dataset["name"] = "Lambada_Eval_Dataset"
        else:
            data_eval.dataset["name"] = "LM_Eval_Dataset"
            data_eval.dataset["overlapping_eval"] = self.eval_cfgs.get(
                "overlapping_eval", 32)
        data_eval["loader"] = data_eval.get("loader") or {}
        data_eval.loader["collate_fn"] = "gpt_eval_collate_fn"
        data_eval["sampler"] = {
            "name": "GPTBatchSampler",
            "batch_size": self.eval_cfgs.get("batch_size", 8),
            "shuffle": False, "drop_last": False}

    def loss_fn(self, params, batch, rng, train: bool = False):
        """Eval score for one batch: summed NLL (LM) or number of
        exactly-correct cloze completions (LAMBADA)."""
        import jax.numpy as jnp
        from .model import masked_nll_sums
        tokens, loss_mask, _attn, position_ids, labels, _info = batch
        logits = self.model.apply(
            {"params": params}, tokens, position_ids=position_ids,
            deterministic=True)
        if not self.cloze_eval:
            return masked_nll_sums(logits, labels, loss_mask)[0]
        logits = logits.astype(jnp.float32)
        preds = jnp.argmax(logits, axis=-1)
        correct = jnp.where(loss_mask > 0, preds == labels, True)
        return jnp.sum(jnp.prod(correct.astype(jnp.float32), axis=-1))

    def pretreating_batch(self, batch):
        if self.first_step:
            info = batch[-1]
            if self.cloze_eval:
                self.num_examples = int(info[0][0])
            else:
                self.num_original_tokens = int(info[0][0])
                self.num_tokenized_tokens = int(info[0][1])
            self.first_step = False
        return batch

    def validation_step_end(self, log_dict):
        """Accumulate the eval score (loss or cloze correct count)."""
        from ...utils.log import logger
        if not self.cloze_eval:
            self.total_score += log_dict["loss"] / (
                self.num_tokenized_tokens - 1)
            name = "loss"
        else:
            self.total_score += log_dict["loss"]
            name = "number correct"
        logger.eval("[eval] epoch: %d, batch: %d, %s: %.9f",
                    log_dict["epoch"], log_dict["batch"], name,
                    self.total_score)

    def validation_epoch_end(self, log_dict):
        """Report final perplexity (LM eval) or accuracy (cloze)."""
        import math
        from ...utils.log import logger
        if not self.cloze_eval:
            total_loss = float(self.total_score)
            ppl = math.exp(min(20, total_loss))
            token_ratio = (self.num_tokenized_tokens - 1) / (
                self.num_original_tokens - 1)
            adjusted_ppl = math.exp(min(20, total_loss * token_ratio))
            logger.info(
                "validation results | avg loss: %.4E | ppl: %.4E | "
                "adjusted ppl: %.4E | token ratio: %s", total_loss, ppl,
                adjusted_ppl, token_ratio)
            self.metrics = {"loss": total_loss, "ppl": ppl,
                            "adjusted_ppl": adjusted_ppl}
        else:
            correct = float(self.total_score)
            acc = correct / self.num_examples
            logger.info(
                "validation results | number correct: %.4E | total "
                "examples: %.4E | avg accuracy: %.4E", correct,
                self.num_examples, acc)
            self.metrics = {"acc": acc, "correct": correct}
