"""Gpt subpackage."""
from .config import GPTConfig  # noqa: F401
from .model import (  # noqa: F401
    GPTEmbeddings, GPTForPretraining, GPTModel, MultiHeadAttention,
    TransformerDecoderLayer, cross_entropy_loss,
)
