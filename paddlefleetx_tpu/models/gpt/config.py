"""GPT model hyper-parameter container.

Field names and defaults follow the reference's ``Model`` YAML section
(reference ``single_model.py:475-510`` constructor signature and
``models/language_model/utils.py:39-110`` derivations: ffn defaults to
4*hidden, recompute granularity defaults to "full").
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    """Frozen GPT hyper-parameters (the YAML ``Model`` section)."""

    vocab_size: int = 51200
    hidden_size: int = 768
    num_layers: int = 12
    num_attention_heads: int = 12
    ffn_hidden_size: Optional[int] = None
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 16
    initializer_range: float = 0.02
    use_recompute: bool = False
    # full | full_attn | core_attn | save_dots (TPU-only: keep matmul
    # outputs, recompute elementwise — see _remat_policy)
    recompute_granularity: str = "full"
    fused_linear: bool = False            # no-op on TPU: XLA fuses bias
    fuse_attn_qkv: bool = True
    sequence_parallel: bool = False
    #: mp>1: replace the GSPMD all-gather+matmul / matmul+reduce-scatter
    #: lowering of the column/row-parallel linears with the decomposed
    #: bidirectional-ring kernels (ops/collective_matmul.py) so the mp
    #: collectives overlap the per-shard matmul chunks. Requires
    #: sequence_parallel (the rings stream seq shards); falls back to
    #: the plain with_logical_constraint path per-site when shapes are
    #: ring-indivisible, mp == 1, or there is no mesh — the dispatch
    #: matrix is docs/tensor_parallel.md.
    use_collective_matmul: bool = False
    virtual_pp_degree: int = 1
    #: pipeline schedule when pp_degree > 1. "1F1B" (reference default,
    #: bounded activation memory via the explicit fwd/bwd-interleaved
    #: schedule), "zb" (zero-bubble: dX stays on the 1F1B critical
    #: path, dW is deferred into a bounded per-stage queue and drained
    #: during former bubble ticks — grads identical to 1F1B; see
    #: docs/pipeline.md), "zb_h2" (zero-bubble H2: extra warm-up
    #: forwards spend HBM headroom to also fill the fill-phase bubble;
    #: depth from ``zb_h2_depth``, validated against the device budget
    #: by parallel/pp_memory.py), "zb_auto" (pick the deepest feasible
    #: 1F1B -> zb -> zb_h2@depth rung for the memory budget and log
    #: the decision), or "GPipe" (all-forwards-then-autodiff).
    #: Case-insensitive, '-' and '_' interchangeable; canonicalized in
    #: __post_init__.
    pipeline_schedule: str = "1F1B"
    #: zb_h2 warm-up depth d: stage k may run up to
    #: min(2(pp*vpp-k)-1, (pp*vpp-k)+d) forwards ahead of its backward
    #: wave (bubble (K-1-d)(K-d)/2, zero at d = K-1). -1 = deepest
    #: depth the HBM budget admits (full depth when no budget is
    #: known). Ignored by the other schedules.
    zb_h2_depth: int = -1
    # TPU-specific knobs (absent in reference):
    scan_layers: bool = True              # lax.scan over layers
    use_flash_attention: bool = False     # Pallas kernel on TPU
    context_parallel: bool = False        # sequence sharded over the cp
    #                                       mesh axis (long context)
    #: cp algorithm: "ring" (exact ring attention, O((s/cp)^2) memory,
    #: ops/ring_attention.py) or "ulysses" (all-to-all: seq gathers
    #: while heads shard over cp x mp for the attention itself — two
    #: sharding constraints, XLA emits the all-to-alls; supports
    #: attention dropout, needs heads % (cp*mp) == 0)
    context_parallel_algo: str = "ring"
    #: >1: compute the LM loss over this many sequence chunks inside a
    #: rematerialized scan — the [b, s, V] logits tensor (the largest
    #: single activation: bs8 x s1024 x 50304 is 1.6 GB fp32) never
    #: materializes beyond one chunk. Trades one extra head matmul
    #: per chunk in backward for O(s/chunks) logits memory.
    loss_chunks: int = 1
    #: Mixture-of-Experts (beyond-reference; the reference has no MoE,
    #: SURVEY §2.2 EP row). 0 = dense FFN. >0: every decoder block's
    #: FFN becomes ``moe_num_experts`` routed experts (models/gpt/moe.py),
    #: expert-parallel over ``Distributed.ep_degree`` dataflow devices.
    moe_num_experts: int = 0
    moe_top_k: int = 2                    # experts per token
    moe_capacity_factor: float = 1.25     # slots = ceil(k*s*cf/E)
    moe_aux_loss_weight: float = 0.01     # Switch load-balance loss
    moe_z_loss_weight: float = 0.0        # router z-loss (off by default)
    #: How routed tokens reach their experts (docs/moe.md):
    #: "einsum" — dense one-hot [b, s, E, C] dispatch/combine einsums
    #:   (the parity/fallback reference; O(b·s·E·C·h) pack/unpack);
    #: "sort" — counting-sort gather into the contiguous per-expert
    #:   [E, b, C, h] buffer and gate-weighted scatter-combine back
    #:   (O(b·s·k·h) data movement, identical dropped-token set);
    #: "sort_pallas" — "sort" dispatch + the Pallas grouped expert
    #:   GEMM (ops/pallas/grouped_matmul.py) that skips empty expert
    #:   groups from the routing counts (falls back to the XLA expert
    #:   einsums per-site when the kernel rejects the shape).
    moe_dispatch: str = "einsum"
    #: Paged KV serving (core/paging.py + core/serving.py): fixed page
    #: size in TOKENS. 0 = contiguous per-slot cache (the default; the
    #: training path never pages). When > 0 it must be a multiple of
    #: 128 — the same lane-width rounding ``cache_capacity`` applies —
    #: and divide ``cache_capacity``, so a slot's logical capacity is
    #: exactly ``max_kv_pages`` pages and every page tiles the
    #: flash-decode kernel.
    kv_page_size: int = 0
    #: Physical pages in the global KV pool (the per-layer cache leaf
    #: becomes ``[kv_pool_pages, heads, head_dim, kv_page_size]``).
    #: Page 0 is the reserved null page, so the pool must hold at
    #: least ``max_kv_pages + 1`` pages — one maximum-length request
    #: plus the null page — or a single request could deadlock the
    #: server. Required (> 0) whenever ``kv_page_size`` is set.
    kv_pool_pages: int = 0
    #: Decode KV-cache storage dtype (docs/quantization.md). "bf16"
    #: stores the cache in the compute dtype (the historical layout —
    #: the name covers fp32 compute too); "int8" stores K/V as int8
    #: plus one fp32 scale per (row, head, position), halving the
    #: cache bytes per token so the same pool HBM admits ~2x the
    #: paged slots. Both decode kernels (ragged and paged, verify
    #: windows included) dequantize in-kernel; the dense fallback
    #: widens up front (``attention/*_int8`` counters).
    kv_cache_dtype: str = "bf16"
    #: Dense-matmul execution (docs/quantization.md). "off" runs the
    #: fp kernels as ever; "weight_only_int8" expects the param tree
    #: a PTQ pass emitted (scripts/quantize_checkpoint.py: int8
    #: ``kernel`` + fp32 per-output-channel ``kernel_scale``) and
    #: routes qkv/out-proj/fc1/fc2 — the `_CollectiveDense` mp path
    #: included — through the weight-only int8 Pallas GEMM
    #: (ops/pallas/quantized_matmul.py; ``quant/*`` counters, per-site
    #: XLA dequantize-then-dot fallback).
    quant_execution: str = "off"
    #: Multi-tenant LoRA (docs/lora.md). 0 = off — the param tree is
    #: byte-identical to the base model (the ``_CollectiveDense``
    #: knob-off convention). > 0: every qkv/out-proj/fc1/fc2 site
    #: grows a stacked adapter pair ``lora_a [A, K, r]`` /
    #: ``lora_b [A, r, N]`` (A = ``lora_num_adapters`` resident bank
    #: rows) and the forward adds ``(alpha/r)·B[id](A[id](x))`` per
    #: batch row keyed by the traced ``adapter_ids`` argument —
    #: grouped Pallas GEMMs when the kernel admits the shape, XLA
    #: gather-einsum otherwise (``lora/{grouped,fallback}`` counters).
    lora_rank: int = 0
    #: Adapter bank rows (the stacked leading dim of every
    #: ``lora_a``/``lora_b``). Row 0 is the RESERVED zero adapter:
    #: adapter id 0 means "base model" and its delta is masked out
    #: structurally, so the parity pin never depends on bank contents.
    #: Must be >= 2 when ``lora_rank`` > 0 (at least one real adapter
    #: beside the reserved row).
    lora_num_adapters: int = 0
    #: LoRA scale numerator: the delta is ``(lora_alpha / lora_rank) *
    #: B(A(x))``. 0.0 (default) means alpha = rank, i.e. scale 1.0.
    lora_alpha: float = 0.0
    dtype: str = "float32"                # compute dtype (bf16 for AMP-O2)
    param_dtype: str = "float32"

    def __post_init__(self):
        if self.ffn_hidden_size is None:
            object.__setattr__(self, "ffn_hidden_size", 4 * self.hidden_size)
        if self.hidden_size % self.num_attention_heads != 0:
            raise ValueError(
                f"num_attention_heads ({self.num_attention_heads}) must "
                f"divide hidden_size ({self.hidden_size})")
        if self.recompute_granularity not in ("full", "full_attn",
                                              "core_attn", "save_dots"):
            raise ValueError(
                f"unknown recompute_granularity "
                f"{self.recompute_granularity!r}")
        canon = {"1f1b": "1F1B", "gpipe": "GPipe", "zb": "zb",
                 "zb_h2": "zb_h2", "zb_auto": "zb_auto"}.get(
            str(self.pipeline_schedule).lower().replace("-", "_"))
        if canon is None:
            raise ValueError(
                f"unknown pipeline_schedule {self.pipeline_schedule!r} "
                f"(expected '1F1B', 'zb', 'zb_h2', 'zb_auto' or "
                f"'GPipe')")
        object.__setattr__(self, "pipeline_schedule", canon)
        if self.zb_h2_depth < -1:
            raise ValueError(
                f"zb_h2_depth must be >= -1 (-1 = deepest feasible), "
                f"got {self.zb_h2_depth}")
        if self.context_parallel_algo not in ("ring", "ulysses"):
            raise ValueError(
                f"unknown context_parallel_algo "
                f"{self.context_parallel_algo!r} (expected 'ring' or "
                f"'ulysses')")
        # No silent degradation (VERDICT r3 #5): neither the flash
        # kernel nor the ring-cp path implements attention-prob
        # dropout, so a TRAINING config combining them with
        # attention_probs_dropout_prob > 0 falls back to dense XLA
        # attention — materializing the [b, h, s, s] scores those
        # paths exist to avoid. Construction only WARNS (dropout is
        # inert under deterministic=True, so eval/generation use the
        # kernel regardless — a raise here would block legitimate
        # inference-only use of checkpoints whose config carries the
        # common 0.1 default); the TRAINING entry point refuses the
        # long-sequence OOM traps loudly (GPTModule._pp_setup).
        # Ulysses-cp gets no warning: its attention is dense per
        # head-shard BY DESIGN (O(s^2/cp) memory is its documented
        # trade against the ring), so dropout there is supported.
        if self.attention_probs_dropout_prob > 0.0 and not (
                self.context_parallel
                and self.context_parallel_algo == "ulysses"):
            if self.context_parallel and \
                    self.context_parallel_algo == "ring":
                from ...utils.log import logger
                logger.warning(
                    "context_parallel algo='ring' with "
                    "attention_probs_dropout_prob=%s: TRAINING would "
                    "fall back to dense attention, materializing the "
                    "full [b, h, s, s] scores ring attention exists "
                    "to avoid (the training module refuses this). "
                    "Set the prob to 0.0 or context_parallel_algo="
                    "'ulysses' (dense per head-shard by design; "
                    "supports dropout).",
                    self.attention_probs_dropout_prob)
            elif self.use_flash_attention:
                # with in-kernel dropout configured the kernel path
                # holds under training dropout — nothing to warn
                # about. The CONFIGURED check (env var + artifact
                # presence only) is deliberate: config construction
                # must not probe jax.devices() and initialize the
                # PJRT backend as a side effect; the device-kind
                # match happens at kernel-dispatch time
                from ...ops.attention import _kernel_dropout_configured
                if not _kernel_dropout_configured():
                    from ...utils.log import logger
                    logger.warning(
                        "use_flash_attention=True with "
                        "attention_probs_dropout_prob=%s: TRAINING "
                        "attention takes the dense XLA path — "
                        "in-kernel dropout is enabled by the "
                        "chip-certification artifact "
                        "(ops/pallas/dropout_cert.json, written by "
                        "scripts/validate_flash_dropout.py on a "
                        "passing live-chip run), which is absent or "
                        "overridden here; eval/generation still use "
                        "the kernel. Set the prob to 0.0 to train "
                        "through the flash kernel.%s",
                        self.attention_probs_dropout_prob,
                        " At max_position_embeddings >= 4096 the dense "
                        "[b, h, s, s] scores will not fit and the "
                        "training module refuses to start."
                        if self.max_position_embeddings >= 4096 else "")
        # Same no-silent-degradation stance for the overlapped mp
        # rings: they stream sequence shards, so without Megatron-SP
        # there is nothing sharded to stream and every site falls back
        # to the plain GSPMD path. Warn instead of raising — the knob
        # is a pure perf optimization and the fallback is numerically
        # identical.
        if self.use_collective_matmul and not self.sequence_parallel:
            from ...utils.log import logger
            logger.warning(
                "use_collective_matmul=True without sequence_parallel: "
                "the decomposed collective-matmul rings stream sequence "
                "shards over mp and are inert without Megatron-SP — "
                "every linear falls back to the plain GSPMD constraint "
                "path. Set sequence_parallel: True to enable the "
                "overlap (docs/tensor_parallel.md).")
        if self.moe_num_experts:
            if not 1 <= self.moe_top_k <= self.moe_num_experts:
                raise ValueError(
                    f"moe_top_k ({self.moe_top_k}) must be in "
                    f"[1, moe_num_experts={self.moe_num_experts}]")
            if self.moe_capacity_factor <= 0:
                raise ValueError("moe_capacity_factor must be > 0")
            if self.moe_dispatch not in ("einsum", "sort",
                                         "sort_pallas"):
                raise ValueError(
                    f"unknown moe_dispatch {self.moe_dispatch!r} "
                    f"(expected 'einsum', 'sort' or 'sort_pallas')")
        # Paged-KV composition: the three sizes must agree BEFORE any
        # device allocation happens — a page that does not tile the
        # capacity (or the lane width) would knock decode off the
        # flash_decode_paged kernel or leave unreachable pool columns,
        # and an undersized pool deadlocks the first max-length request.
        if self.kv_page_size or self.kv_pool_pages:
            if self.kv_page_size <= 0:
                raise ValueError(
                    f"kv_pool_pages ({self.kv_pool_pages}) is set but "
                    f"kv_page_size is {self.kv_page_size}; paged KV "
                    f"needs both (set kv_page_size to a multiple of "
                    f"128 that divides cache_capacity "
                    f"{self.cache_capacity})")
            if self.kv_page_size % 128:
                raise ValueError(
                    f"kv_page_size ({self.kv_page_size}) must be a "
                    f"multiple of 128 — the same TPU-lane rounding "
                    f"cache_capacity uses, so every page tiles the "
                    f"flash-decode kernel's 128-aligned KV blocks")
            if self.cache_capacity % self.kv_page_size:
                raise ValueError(
                    f"cache_capacity ({self.cache_capacity}, "
                    f"max_position_embeddings "
                    f"{self.max_position_embeddings} rounded up to "
                    f"128) must be divisible by kv_page_size "
                    f"({self.kv_page_size}) so a slot's page table "
                    f"covers it exactly (max_kv_pages = "
                    f"capacity / page)")
            if self.kv_pool_pages < self.max_kv_pages + 1:
                raise ValueError(
                    f"kv_pool_pages ({self.kv_pool_pages}) must be at "
                    f"least max_kv_pages + 1 = {self.max_kv_pages + 1} "
                    f"(one maximum-length request's "
                    f"{self.max_kv_pages} pages plus the reserved "
                    f"null page 0), or a single request can deadlock "
                    f"the page pool")
        # Quantized execution knobs fail construction loudly: a typo'd
        # value silently running fp would defeat the whole A/B (the
        # YAML-side typo path is caught earlier by the config-warning
        # pass — utils/config.py)
        if self.kv_cache_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"unknown kv_cache_dtype {self.kv_cache_dtype!r} "
                f"(expected 'bf16' or 'int8' — "
                f"docs/quantization.md)")
        if self.quant_execution not in ("off", "weight_only_int8"):
            raise ValueError(
                f"unknown quant_execution {self.quant_execution!r} "
                f"(expected 'off' or 'weight_only_int8' — "
                f"docs/quantization.md)")
        # LoRA knobs fail construction loudly for the same reason: a
        # typo'd rank silently serving the base model would defeat the
        # multi-tenant A/B entirely.
        if self.lora_rank < 0:
            raise ValueError(
                f"lora_rank must be >= 0, got {self.lora_rank}")
        if self.lora_alpha < 0:
            raise ValueError(
                f"lora_alpha must be >= 0, got {self.lora_alpha}")
        if self.lora_num_adapters and not self.lora_rank:
            raise ValueError(
                f"lora_num_adapters ({self.lora_num_adapters}) is set "
                f"but lora_rank is 0; multi-tenant LoRA needs both "
                f"(docs/lora.md)")
        if self.lora_rank:
            if self.lora_num_adapters < 2:
                raise ValueError(
                    f"lora_num_adapters ({self.lora_num_adapters}) "
                    f"must be >= 2 with lora_rank > 0 — row 0 is the "
                    f"reserved zero adapter (base model), so at least "
                    f"one real adapter row must exist (docs/lora.md)")
            if not self.fuse_attn_qkv:
                raise ValueError(
                    "lora_rank > 0 requires fuse_attn_qkv=True: the "
                    "adapter sites are exactly qkv/out-proj/fc1/fc2 "
                    "(docs/lora.md); the non-fused q/k/v projections "
                    "carry no adapter pair and would silently serve "
                    "partial adapters")
            if self.moe_num_experts:
                raise ValueError(
                    "lora_rank > 0 is incompatible with "
                    "moe_num_experts > 0: the MoE block replaces the "
                    "fc1/fc2 sites the adapter pair rides on "
                    "(docs/lora.md)")
        if self.quant_execution != "off" and self.use_collective_matmul:
            from ...utils.log import logger
            logger.warning(
                "quant_execution=%r with use_collective_matmul=True: "
                "the overlapped mp rings stream fp weight chunks and "
                "cannot consume the frozen int8 kernels, so quantized "
                "sites take the int8 GEMM (or its XLA dequant "
                "fallback) under the plain GSPMD constraint path — "
                "quantization wins over the rings at shared sites "
                "(docs/quantization.md).", self.quant_execution)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def cache_capacity(self) -> int:
        """Decode KV-cache slots per row: ``max_position_embeddings``
        rounded UP to a multiple of 128 (the TPU lane width and the
        flash-decode block alignment), so the cache minor dim always
        tiles and an unaligned ``max_position_embeddings`` can never
        knock decode off the kernel path via the ``skv % block_kv``
        rejection in ``ops/pallas/flash_attention.py::flash_decode``.
        The extra slots are dead weight only: positions are still
        bounded by ``max_position_embeddings`` (the embedding table
        size) and causal/validity masking never reads them."""
        return -(-self.max_position_embeddings // 128) * 128

    @property
    def lora_scale(self) -> float:
        """Effective LoRA delta scale ``alpha / rank`` (1.0 when
        ``lora_alpha`` is 0.0 — the alpha = rank convention)."""
        if not self.lora_rank:
            return 0.0
        if not self.lora_alpha:
            return 1.0
        return self.lora_alpha / self.lora_rank

    @property
    def max_kv_pages(self) -> int:
        """Width of a slot's page table under paged KV serving:
        ``cache_capacity / kv_page_size`` logical pages cover one
        slot's full capacity. 0 when paging is off."""
        if not self.kv_page_size:
            return 0
        return self.cache_capacity // self.kv_page_size

    @classmethod
    def from_config(cls, config) -> "GPTConfig":
        """Build from a parsed YAML tree (Model + Engine sections)."""
        from ...utils.config import bf16_enabled
        model = dict(config.get("Model", {}))
        fields = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in model.items()
                  if k in fields and v is not None}
        if model.get("use_recompute") and \
                not model.get("recompute_granularity"):
            kwargs["recompute_granularity"] = "full"
        # AMP-O2 / use_pure_fp16 maps to bf16 compute on TPU
        if bf16_enabled(config):
            kwargs.setdefault("dtype", "bfloat16")
        return cls(**kwargs)
