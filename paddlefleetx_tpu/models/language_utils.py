"""Language-model config derivations.

Parity: reference ``ppfleetx/models/language_model/utils.py:39-150``:
  - ``process_data_configs`` (:117-141): per-mode ``num_samples``
    (train = gbs * max_steps; eval = gbs * (max_steps/eval_freq + 1) *
    eval_iters; test = gbs * test_iters), seed and batch-size plumbing.
  - ``process_model_configs`` (:56-110): ffn defaults to 4*hidden,
    recompute granularity default, virtual-pp divisibility checks.
"""

from __future__ import annotations


def process_model_configs(config) -> None:
    """Derive/validate model-section defaults in place — ffn=4h,
    recompute granularity, virtual-pp divisibility (reference
    ``models/language_model/utils.py:39-110``)."""
    model = config.Model
    if model.get("ffn_hidden_size") is None:
        model["ffn_hidden_size"] = 4 * model["hidden_size"]
    if model.get("use_recompute"):
        if not model.get("recompute_granularity"):
            model["recompute_granularity"] = "full"
    vpp = model.get("virtual_pp_degree") or 1
    pp = config.Distributed.pp_degree
    if pp > 1:
        if model["num_layers"] % pp != 0:
            raise ValueError(
                f"num_layers {model['num_layers']} must be divisible by "
                f"pp_degree {pp}")
        if model.get("scan_layers") is False:
            # same policy as loss_chunks below: the single-chip recipe
            # sets scan_layers False for throughput, and a -o
            # pp_degree override on top of it must not be fatal —
            # pipeline stages need the stacked decoder params, so the
            # knob flips back with a log line
            from ..utils.log import logger
            logger.info("pp_degree > 1 needs scan-stacked decoder "
                        "params; overriding scan_layers False -> True")
            model["scan_layers"] = True
        if (model.get("loss_chunks") or 1) > 1:
            # the pipeline computes the loss per microbatch, which IS
            # the logits-memory property loss_chunks exists for — the
            # knob is subsumed, not silently dropped (a base config
            # default must not make every pp override fatal)
            from ..utils.log import logger
            logger.info("pp_degree > 1 computes per-microbatch logits; "
                        "loss_chunks=%s is subsumed and reset to 1",
                        model["loss_chunks"])
            model["loss_chunks"] = 1
    if vpp > 1:
        local_batch_size = config.Global.local_batch_size
        micro_batch_size = config.Global.micro_batch_size
        if local_batch_size // micro_batch_size % pp != 0:
            raise ValueError(
                "micro-batch count must divide pp_degree with virtual "
                "pipeline stages")
        if model["num_layers"] % (vpp * pp) != 0:
            raise ValueError(
                f"num_layers {model['num_layers']} must be divisible by "
                f"virtual_pp_degree*pp_degree {vpp * pp}")
    if model.get("sequence_parallel") and \
            config.Distributed.mp_degree <= 1:
        # reference forces SP off when mp<=1 (hybrid_model.py:649-652)
        model["sequence_parallel"] = False
    cp = config.Distributed.get("cp_degree") or 1
    if cp > 1 and model.get("context_parallel_algo") == "ulysses":
        mp = config.Distributed.mp_degree or 1
        heads = model["num_attention_heads"]
        if heads % (cp * mp):
            raise ValueError(
                f"Ulysses context parallelism shards attention heads "
                f"over cp*mp: num_attention_heads ({heads}) must be "
                f"divisible by cp_degree*mp_degree ({cp * mp})")
    n_experts = model.get("moe_num_experts") or 0
    if n_experts:
        if pp > 1 and str(
                model.get("pipeline_schedule", "1F1B")).lower() == \
                "gpipe":
            raise ValueError(
                "MoE with pipeline parallelism requires "
                "pipeline_schedule '1F1B', 'zb', 'zb_h2' or 'zb_auto' "
                "(GPipe trains via autodiff through the forward-only "
                "schedule, which drops the per-layer router aux loss)")
        ep = config.Distributed.get("ep_degree") or 1
        if n_experts % ep != 0:
            raise ValueError(
                f"moe_num_experts ({n_experts}) must be divisible by "
                f"ep_degree ({ep})")


def process_data_configs(config) -> None:
    """Derive per-mode ``num_samples`` from the step/eval cadence
    (reference ``models/language_model/utils.py:113-150``)."""
    g = config.Global
    engine = config.Engine
    max_steps = engine.get("max_steps", 500000)
    eval_freq = engine.get("eval_freq") or max(max_steps, 1)
    eval_iters = engine.get("eval_iters", 10)
    test_iters = engine.get("test_iters", eval_iters * 10)
    mode_to_num_samples = {
        "Train": g.global_batch_size * max_steps,
        "Eval": g.global_batch_size *
        (max_steps // eval_freq + 1) * eval_iters,
        "Test": g.global_batch_size * test_iters,
    }
    for mode, num in mode_to_num_samples.items():
        if mode in config.get("Data", {}):
            dataset = config.Data[mode]["dataset"]
            dataset.setdefault("num_samples", num)
            dataset.setdefault("mode", mode)
            dataset.setdefault("seed", g.get("seed", 1024))
            sampler = config.Data[mode].setdefault("sampler", {})
            sampler.setdefault("batch_size", g.local_batch_size)


def process_configs(config):
    process_model_configs(config)
    process_data_configs(config)
    return config
