"""Model zoo + the name-driven module factory.

Reference: ``ppfleetx/models/__init__.py:28-32`` resolves
``Model.module`` by name. Same contract here, without ``eval``.
"""

from __future__ import annotations

_REGISTRY = {}


def register_module(name: str):
    def deco(cls):
        _REGISTRY[name] = cls
        return cls
    return deco


def build_module(config):
    """Instantiate the module named by ``config.Model.module``."""
    # populate the registry lazily to avoid heavy imports at package load
    import importlib
    for mod in ("gpt.modules", "ernie.modules", "vit.modules",
                "imagen.modules"):
        try:
            importlib.import_module(f".{mod}", __package__)
        except ModuleNotFoundError as e:
            # tolerate only the module itself being absent (not yet
            # built); propagate broken imports inside an existing module
            if e.name != f"{__package__}.{mod}":
                raise
    name = config.Model.module
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown module {name!r}; available: {sorted(_REGISTRY)}")
    return _REGISTRY[name](config)
