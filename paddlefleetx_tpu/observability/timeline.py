"""Per-thread activity timeline: who was doing what, when.

Spans (``observability/spans.py``) answer *what happened to a
request*; this module answers *where wall-clock time went across
threads*. Every long-lived thread registers a named **track** and
appends ``(state, t0, t1, trace)`` intervals to it — ``tick`` /
``harvest_wait`` / ``park`` / ``spill_device_get`` / ``handoff_d2d``
and friends (the full vocabulary is tabled in
``docs/observability.md``). From the intervals the pure functions
below derive per-thread utilization and the fleet ``overlap_ratio``
that makes the async-vs-lockstep claim falsifiable: under a lockstep
router at most one worker is ever mid-``tick`` (ratio ~1/N), under
the async router ticks overlap (ratio approaching 1).

Cost discipline (same contract as ``metrics.MetricsRegistry``): the
recorder is DISABLED by default and a disabled ``begin``/``add`` is
an attribute load plus one boolean test — the bench-harness tests pin
that overhead below 1% of a step budget. Enabled appends are
lock-free: each track's ring is a ``collections.deque(maxlen=...)``
whose ``append`` is a single GIL-atomic C call, so the hot path never
takes a lock and memory stays bounded at ``PFX_TIMELINE_RING``
intervals per track (oldest intervals fall off). The module lock
guards only track registration and ``snapshot()``.

Thread model: a track is normally written by exactly one thread (the
pfxlint PFX304 rule holds every thread entrypoint to registering
one); tracks shared by construction (the per-request ``pfx-metrics``
handler threads) tolerate interleaved appends because the deque
append is atomic and intervals are self-contained tuples. The
``enabled`` flag is a ``threading.Event`` — flips publish safely
without a lock on the read side.

Knobs: ``PFX_TIMELINE=1`` enables recording at import;
``PFX_TIMELINE_RING`` sizes the per-track ring (default 4096).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

#: one recorded interval: (state, t0, t1, trace-id-or-None); times are
#: wall-clock ``time.time()`` seconds so tracks align with span ``ts``
#: in the merged Perfetto view
Interval = Tuple[str, float, float, Any]

#: states that count as *not busy* for utilization / overlap math —
#: threads parked on queues, events or poll sleeps
WAIT_STATES = frozenset(
    {"idle", "wait", "park", "poll", "harvest_wait"})


class Track:
    """One thread's interval ring.

    ``begin()``/``add()`` are the whole hot-path API: ``begin``
    stamps a start time (0.0 when the recorder is off), ``add``
    appends the closed interval (a no-op when the recorder is off or
    the matching ``begin`` happened while it was off — a mid-interval
    enable never fabricates a since-epoch-long interval)."""

    def __init__(self, name: str, on: threading.Event, cap: int):
        self.name = name
        self._on = on
        self._buf: Deque[Interval] = deque(maxlen=cap)

    def begin(self) -> float:
        """Start-of-interval timestamp, or 0.0 while disabled."""
        if self._on.is_set():
            return time.time()
        return 0.0

    def add(self, state: str, t0: float,
            t1: Optional[float] = None, trace: Any = None) -> None:
        """Record ``[t0, t1]`` (``t1`` defaults to now) under
        ``state``; drops the oldest interval once the ring is full."""
        if not self._on.is_set() or not t0:
            return
        self._buf.append(
            (state, t0, time.time() if t1 is None else t1, trace))

    def intervals(self) -> List[Interval]:
        """Copy of the ring, oldest first (one atomic C call)."""
        return list(self._buf)


class ThreadTimeline:
    """Registry of named tracks plus the shared enabled flag.

    One process-global instance (``get_timeline``) backs the module
    helpers; tests construct private instances freely."""

    def __init__(self, enabled: bool = False, cap: int = 4096):
        self._on = threading.Event()
        if enabled:
            self._on.set()
        self._cap = max(1, int(cap))
        self._lock = threading.Lock()
        self._tracks: Dict[str, Track] = {}

    @property
    def enabled(self) -> bool:
        return self._on.is_set()

    def set_enabled(self, flag: bool) -> None:
        """Flip recording; existing intervals are kept either way."""
        if flag:
            self._on.set()
        else:
            self._on.clear()

    def track(self, name: str) -> Track:
        """The track registered under ``name`` (created on first
        use). Idempotent — a restarted thread reattaches to the same
        ring rather than forking a duplicate Perfetto row."""
        with self._lock:
            tr = self._tracks.get(name)
            if tr is None:
                tr = self._tracks[name] = Track(
                    name, self._on, self._cap)
            return tr

    def snapshot(self, since: float = 0.0
                 ) -> Dict[str, List[Interval]]:
        """Point-in-time ``{track name: [intervals]}`` copy, keeping
        intervals that end after ``since`` (pass a router/bench start
        stamp to scope a long-lived process's rings to one run).
        Empty tracks are kept — an instrumented-but-idle thread still
        earns its Perfetto row. The one safe cross-thread read."""
        with self._lock:
            tracks = list(self._tracks.values())
        return {tr.name: [iv for iv in tr.intervals()
                          if iv[2] > since]
                for tr in tracks}


def utilization(snapshot: Dict[str, List[Interval]]
                ) -> Dict[str, Dict[str, float]]:
    """Per-track time attribution over a ``snapshot()``.

    Returns ``{track: {"busy_s", "wait_s", "util", "window_s"}}``:
    busy = summed duration of non-``WAIT_STATES`` intervals, wait =
    the complement, util = busy / (busy + wait) (0.0 for an empty
    track). Intervals are summed as recorded — the recorder never
    nests states on one track, so no de-overlap pass is needed."""
    out: Dict[str, Dict[str, float]] = {}
    for name, ivs in snapshot.items():
        busy = wait = 0.0
        for state, t0, t1, _ in ivs:
            d = max(0.0, t1 - t0)
            if state in WAIT_STATES:
                wait += d
            else:
                busy += d
        total = busy + wait
        out[name] = {
            "busy_s": busy, "wait_s": wait,
            "util": busy / total if total > 0 else 0.0,
            "window_s": total,
        }
    return out


def overlap_ratio(snapshot: Dict[str, List[Interval]],
                  prefix: str = "fleet-worker-",
                  state: str = "tick") -> Optional[float]:
    """Mean ``state`` concurrency across ``prefix`` tracks, normalized
    by track count — how much of the fleet is mid-tick at once.

    Sweep-line over the matching intervals: with ``depth(t)`` = how
    many tracks are ticking at instant ``t``, the ratio is
    ``mean(depth over the time depth >= 1) / N`` where ``N`` is the
    number of distinct contributing tracks. A lockstep router that
    ticks its N replicas back-to-back scores exactly 1/N (depth never
    exceeds 1); the async router's overlapping ticks push the ratio
    toward 1 (all N busy simultaneously). Returns None when no
    matching intervals exist (recorder off or no fleet)."""
    edges: List[Tuple[float, int]] = []
    tracks = set()
    for name, ivs in snapshot.items():
        if not name.startswith(prefix):
            continue
        for st, t0, t1, _ in ivs:
            if st == state and t1 > t0:
                tracks.add(name)
                edges.append((t0, 1))
                edges.append((t1, -1))
    if not edges:
        return None
    edges.sort()
    depth = 0
    busy_any = depth_time = 0.0
    prev = edges[0][0]
    for t, d in edges:
        span = t - prev
        if depth >= 1:
            busy_any += span
            depth_time += depth * span
        depth += d
        prev = t
    if busy_any <= 0.0:
        return None
    return depth_time / busy_any / len(tracks)


#: process-global timeline; off unless PFX_TIMELINE=1 (or a caller
#: flips it on — bench --mode fleet and the fleet A/B tests do)
_global = ThreadTimeline(
    enabled=os.environ.get("PFX_TIMELINE", "") == "1",
    cap=int(os.environ.get("PFX_TIMELINE_RING", "4096") or "4096"))


def get_timeline() -> ThreadTimeline:
    """The process-global recorder."""
    return _global


def track(name: str) -> Track:
    """Register (or reattach to) the global track ``name`` — the call
    every thread entrypoint must make (pfxlint PFX304)."""
    return _global.track(name)


def set_enabled(flag: bool) -> None:
    """Flip the global recorder."""
    _global.set_enabled(flag)


def enabled() -> bool:
    """Whether the global recorder is recording."""
    return _global.enabled
