"""Model-FLOPs and peak-FLOPs accounting — the single source of truth.

The Megatron fwd+bwd formula and the per-chip bf16 peaks used to live
in ``bench.py`` with a forward-only copy in ``scripts/profile_mfu.py``;
both now import from here and the engine's in-band MFU
(``core/engine.py::_print_summary``) uses the same numbers, so the
banked headline metric and the summary's figure can never drift.
"""

from __future__ import annotations

from typing import Optional

# bf16 dense peak by device kind (jax Device.device_kind) — platform
# alone can't distinguish TPU generations and would silently mis-scale
# MFU on anything but the calibrated chip.
PEAK_FLOPS_BY_KIND = {
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,        # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
    "TPU v6e": 918e12,
}


def model_flops_per_token(num_layers: int, hidden_size: int,
                          vocab_size: int, seq: int) -> float:
    """Megatron fwd+bwd model FLOPs per token for a GPT geometry:
    ``72*L*h^2*(1 + s/6h + V/12Lh)`` (assumes ffn = 4h; counts the
    model's own fwd+bwd only — remat recompute burns hardware FLOPs
    but does not count as model FLOPs)."""
    L, h, V = num_layers, hidden_size, vocab_size
    return 72.0 * L * h * h * (1 + seq / (6.0 * h) + V / (12.0 * L * h))


def causal_attn_flops(b: int, h: int, s: int, d: int) -> float:
    """Model FLOPs of one causal-attention forward at [b, h, s, d]:
    QK^T + PV matmuls (2 each per element), half the square live.
    Shared by the tuning/profiling scripts so the roofline accounting
    cannot drift between them."""
    return 4.0 * b * h * s * s * d * 0.5


def peak_flops(device=None) -> Optional[float]:
    """Per-chip bf16 peak for ``device`` (default: the first attached
    device), or None off-TPU / for an uncalibrated device_kind — MFU
    is then reported as n/a rather than against a guessed peak."""
    if device is None:
        import jax
        try:
            device = jax.devices()[0]
        except Exception:
            return None
    if device.platform != "tpu":
        return None
    peak = PEAK_FLOPS_BY_KIND.get(device.device_kind)
    if peak is None:
        from ..utils.log import logger
        logger.warning(
            "unknown TPU device_kind %r; MFU not reported (add it to "
            "PEAK_FLOPS_BY_KIND)", device.device_kind)
    return peak


def mfu(tokens_per_sec: float, flops_per_token: float,
        peak_per_chip: Optional[float],
        n_chips: int = 1) -> Optional[float]:
    """Achieved model FLOPs over the aggregate peak, or None when the
    peak is unknown (non-TPU platforms)."""
    if not peak_per_chip or not tokens_per_sec:
        return None
    return tokens_per_sec * flops_per_token / (peak_per_chip * n_chips)
