"""Device-memory telemetry: HBM watermarks from ``memory_stats()``.

TPU PJRT devices report allocator stats (bytes_in_use /
peak_bytes_in_use / bytes_limit); the CPU test platform returns None.
Sampling happens at window edges and after compile — a host call per
logging window, never per step — so a RESOURCE_EXHAUSTED run leaves
its watermark trail in the step lines, the summary and the flight
recorder instead of dying unattributed.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

#: the memory_stats keys worth carrying; anything else the backend
#: reports is allocator-internal noise for this purpose
_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
         "largest_alloc_size")


def device_memory_stats(device=None) -> Optional[Dict[str, int]]:
    """``device.memory_stats()`` distilled to the HBM-watermark keys,
    or None when the backend keeps no stats (CPU) or is unreachable.
    Never raises — telemetry must not kill the run it observes."""
    try:
        if device is None:
            import jax
            device = jax.devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    out = {k: int(stats[k]) for k in _KEYS if k in stats}
    return out or None


def format_bytes(n: Any) -> str:
    """Human HBM figure (``"3.42G"``); '?' for missing values."""
    if not isinstance(n, (int, float)):
        return "?"
    return f"{n / 2**30:.2f}G"
