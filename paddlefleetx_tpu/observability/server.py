"""Live telemetry endpoints: a stdlib HTTP server for /metrics & co.

A serving deployment is scraped, not ssh'd into: this module runs a
daemon-threaded ``ThreadingHTTPServer`` next to the run so Prometheus
(or ``curl``) can read the process live. Endpoints:

- ``/metrics`` — Prometheus text exposition of every attached
  registry (``observability/export.py``);
- ``/vars`` — the merged registry snapshot as JSON (histograms as
  summary dicts), for humans and tests;
- ``/healthz`` — liveness + drain state: HTTP 200 with
  ``{"status": "ok", ...}`` normally, HTTP 503 with
  ``{"status": "draining", ...}`` once the generation server enters
  drain (docs/robustness.md) — the signal a load balancer needs to
  stop routing to a preempted worker while in-flight requests finish;
- ``/trace`` — the span records of the attached events.jsonl plus
  the live thread-timeline tracks, merged into one Perfetto/Chrome
  trace-event JSON (spans under the ``requests`` process, thread
  activity under ``threads``);
- ``/timeline`` — the raw thread-timeline snapshot as JSON
  (``tracks`` + derived ``utilization`` and ``overlap_ratio``), for
  tooling that wants the intervals without the Chrome envelope.

Wiring: ``PFX_METRICS_PORT`` names the port (``0`` = ephemeral, read
it back from ``get_server().port``); when unset nothing starts and
nothing costs. One process-wide singleton serves every component —
the Engine and a GenerationServer in the same process attach their
registries to the same server via :func:`start_from_env`.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from . import export
from . import metrics as metrics_mod
from . import timeline as timeline_mod
from .recorder import read_events


class MetricsServer:
    """One live telemetry HTTP server over attached registries.

    Starts serving on construction (daemon thread — never blocks
    process exit); ``close()`` shuts it down. The process-global
    registry is always attached; components add their own via
    :meth:`add_registry`.
    """

    def __init__(self, port: int = 0, host: str = "0.0.0.0",
                 registries: Optional[List[Any]] = None,
                 health: Optional[Callable[[], Dict[str, Any]]] = None,
                 events_path: Optional[str] = None):
        # the wiring tables are written by the main thread (attach
        # calls after construction) and read by per-request threads,
        # so both sides go through _cb_lock; handlers snapshot under
        # it and do their (blocking) socket IO outside it
        self._cb_lock = threading.Lock()
        self._registries: List[Any] = [metrics_mod.get_registry()]
        for reg in registries or []:
            self.add_registry(reg)
        self._health = health
        self._events_path = events_path
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            """Request handler bound to the owning server."""

            def do_GET(self):          # noqa: N802 (stdlib API name)
                outer._handle(self)

            def log_message(self, fmt, *args):
                pass   # scrapes must not spam the serving log

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="pfx-metrics",
            daemon=True)
        self._thread.start()

    # -- wiring --------------------------------------------------------
    @property
    def port(self) -> int:
        """The bound port (useful when constructed with port=0)."""
        return self._httpd.server_address[1]

    def url(self, path: str = "/metrics") -> str:
        """A loopback URL for ``path`` — the curl-equivalent tests
        and the CI smoke scrape use."""
        return f"http://127.0.0.1:{self.port}{path}"

    def add_registry(self, reg: Any) -> None:
        """Attach another live registry to /metrics and /vars."""
        with self._cb_lock:
            if reg is not None and reg not in self._registries:
                self._registries.append(reg)

    def set_health(self, fn: Callable[[], Dict[str, Any]]) -> None:
        """Install the /healthz payload provider (dict with a
        ``status`` key; anything but ``"ok"`` answers 503)."""
        with self._cb_lock:
            self._health = fn

    def set_events_path(self, path: str) -> None:
        """Point /trace at an events.jsonl stream."""
        with self._cb_lock:
            self._events_path = path

    def close(self) -> None:
        """Stop serving and release the port. Idempotent."""
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass

    # -- request handling ----------------------------------------------
    def _respond(self, handler, code: int, body: str,
                 content_type: str) -> None:
        data = body.encode("utf-8")
        handler.send_response(code)
        handler.send_header("Content-Type", content_type)
        handler.send_header("Content-Length", str(len(data)))
        handler.end_headers()
        handler.wfile.write(data)

    def _handle(self, handler) -> None:
        # per-request handler threads share one timeline track (the
        # deque append is atomic, so interleaved scrapes are safe)
        tl = timeline_mod.track("pfx-metrics")
        tl_t0 = tl.begin()
        path = handler.path.split("?", 1)[0]
        # snapshot the wiring under the lock, then render and answer
        # outside it — _respond blocks on the client socket and must
        # never do so while holding _cb_lock
        with self._cb_lock:
            registries = list(self._registries)
            health = self._health
            events_path = self._events_path
        try:
            if path == "/metrics":
                self._respond(
                    handler, 200,
                    export.prometheus_text(registries),
                    "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/vars":
                snap = export.merge_snapshots(
                    r.snapshot() for r in registries)
                self._respond(handler, 200,
                              json.dumps(snap, default=str),
                              "application/json")
            elif path == "/healthz":
                payload = health() if health is not None \
                    else {"status": "ok"}
                code = 200 if payload.get("status") == "ok" else 503
                self._respond(handler, code, json.dumps(payload),
                              "application/json")
            elif path == "/trace":
                if not events_path:
                    self._respond(handler, 404,
                                  '{"error": "no events stream"}',
                                  "application/json")
                    return
                trace = export.chrome_trace(
                    read_events(events_path),
                    timeline=timeline_mod.get_timeline().snapshot())
                self._respond(handler, 200,
                              json.dumps(trace, default=str),
                              "application/json")
            elif path == "/timeline":
                snap = timeline_mod.get_timeline().snapshot()
                ratio = timeline_mod.overlap_ratio(snap)
                self._respond(
                    handler, 200,
                    json.dumps({
                        "enabled": timeline_mod.enabled(),
                        "tracks": snap,
                        "utilization":
                            timeline_mod.utilization(snap),
                        "overlap_ratio": ratio,
                    }, default=str),
                    "application/json")
            else:
                self._respond(handler, 404, '{"error": "not found"}',
                              "application/json")
        except Exception as exc:   # noqa: BLE001 — a scrape racing a
            # mutating registry must answer 500, never kill the server
            try:
                self._respond(handler, 500,
                              json.dumps({"error": str(exc)}),
                              "application/json")
            except OSError:
                pass   # client hung up mid-answer
        finally:
            tl.add("serve", tl_t0)


#: the process-wide server (every component shares one port)
_server: Optional[MetricsServer] = None
_lock = threading.Lock()


def get_server() -> Optional[MetricsServer]:
    """The live singleton, or None when nothing started one."""
    return _server


def start_from_env(registry: Any = None,
                   health: Optional[Callable[[], Dict[str, Any]]] = None,
                   events_path: Optional[str] = None
                   ) -> Optional[MetricsServer]:
    """Start (or attach to) the singleton when ``PFX_METRICS_PORT``
    is set; None (and zero cost) when it is not.

    Args:
        registry: a component registry to attach (the global one is
            always included).
        health: /healthz payload provider (last caller wins — in
            practice the GenerationServer, whose drain state is the
            payload that matters).
        events_path: events.jsonl to serve on /trace (last caller
            wins).

    Returns:
        The singleton server, or None (knob unset, bad port, or the
        port is taken — telemetry never kills the run it observes).
    """
    raw = os.environ.get("PFX_METRICS_PORT", "").strip()
    if not raw:
        return None
    try:
        port = int(raw)
    except ValueError:
        return None
    global _server
    with _lock:
        if _server is None:
            try:
                _server = MetricsServer(port=port)
            except OSError:
                return None
        if registry is not None:
            _server.add_registry(registry)
        if health is not None:
            _server.set_health(health)
        if events_path is not None:
            _server.set_events_path(events_path)
        return _server


def stop() -> None:
    """Shut the singleton down (tests; long-lived runs just exit —
    the serving thread is a daemon). The singleton swap happens under
    ``_lock`` but the actual shutdown — which BLOCKS until the serve
    loop exits — runs outside it, so a request thread that needs the
    module lock can finish and the loop can drain."""
    global _server
    with _lock:
        doomed, _server = _server, None
    if doomed is not None:
        doomed.close()
