"""Trace/span primitives emitted through the flight recorder.

The device profiler shows what XLA ran; it cannot show the HOST-side
schedule — admission order, prefill chunking, preemption, checkpoint
stalls — which under the single-program GSPMD model is exactly where
serving latency is decided. Spans make that schedule durable: every
begin/end/point is one fsynced ``events.jsonl`` line, so one grep of
the stream reconstructs any request's full timeline even after a
crash, and ``observability/export.py`` renders the same records as a
Perfetto/Chrome trace to view next to the ``jax.profiler`` device
timeline.

Id grammar: ``trace_id`` is 16 lowercase hex chars (one per request /
per fit), ``span_id`` 8 hex chars; children carry ``parent`` so the
tree re-nests. Record kinds (each also carries the recorder's ``ts``
wall-clock seconds):

- ``span_begin`` — ``name, trace, span[, parent]`` + open attrs;
- ``span_end`` — ``name, trace, span, dur_ms`` + close attrs;
- ``span`` — a retroactively-reported complete span (``dur_ms``
  measured by the caller; starts at ``ts - dur_ms``);
- ``span_point`` — an instant event on a parent span.

A :class:`Tracer` over ``recorder=None`` hands out the shared
:data:`NULL_SPAN`, whose methods are no-ops returning itself — call
sites never branch on whether tracing is on, and the disabled cost is
one attribute call per lifecycle transition.
"""

from __future__ import annotations

import os
import time
from typing import Any, Optional


def _new_id(nbytes: int) -> str:
    """A fresh random id as ``2 * nbytes`` lowercase hex chars."""
    return os.urandom(nbytes).hex()


class Span:
    """One open span; ``end()`` (idempotent) emits its duration.
    Usable as a context manager — ``__exit__`` ends it."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id",
                 "_tracer", "_t0", "_ended")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: Optional[str] = None, **attrs: Any):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id(4)
        self.parent_id = parent_id
        self._tracer = tracer
        self._t0 = time.perf_counter()
        self._ended = False
        fields = {"name": name, "trace": trace_id,
                  "span": self.span_id}
        if parent_id is not None:
            fields["parent"] = parent_id
        tracer._emit("span_begin", **fields, **attrs)

    # -- tree ----------------------------------------------------------
    def start_span(self, name: str, **attrs: Any) -> "Span":
        """Open a child span under this one (same trace)."""
        return Span(self._tracer, name, self.trace_id,
                    parent_id=self.span_id, **attrs)

    def span_point(self, name: str, **attrs: Any) -> None:
        """Emit an instant event attached to this span."""
        self._tracer._emit("span_point", name=name,
                           trace=self.trace_id, parent=self.span_id,
                           **attrs)

    def complete_span(self, name: str, dur_s: float,
                      **attrs: Any) -> None:
        """Report an already-measured child span in one record (used
        for phases timed by existing code, e.g. compile/h2d/save)."""
        self._tracer._emit("span", name=name, trace=self.trace_id,
                           span=_new_id(4), parent=self.span_id,
                           dur_ms=round(dur_s * 1000.0, 3), **attrs)

    # -- lifecycle -----------------------------------------------------
    def end(self, **attrs: Any) -> None:
        """Close the span, emitting ``span_end`` with ``dur_ms``.
        Idempotent — a second call is a no-op, so defensive cleanup
        paths can end unconditionally."""
        if self._ended:
            return
        self._ended = True
        self._tracer._emit(
            "span_end", name=self.name, trace=self.trace_id,
            span=self.span_id,
            dur_ms=round((time.perf_counter() - self._t0) * 1000.0, 3),
            **attrs)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.end()


class _NullSpan:
    """The do-nothing span a disabled tracer hands out; every method
    is a no-op and child-creation returns the same singleton, so call
    sites stay branch-free."""

    __slots__ = ()
    name = ""
    trace_id = None
    span_id = None
    parent_id = None

    def start_span(self, name: str, **attrs: Any) -> "_NullSpan":
        return self

    def span_point(self, name: str, **attrs: Any) -> None:
        pass

    def complete_span(self, name: str, dur_s: float,
                      **attrs: Any) -> None:
        pass

    def end(self, **attrs: Any) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


#: the shared no-op span — a safe initial value for "current span"
#: attributes, and what a disabled tracer returns
NULL_SPAN = _NullSpan()


class Tracer:
    """Span factory over one flight recorder (or None = disabled)."""

    __slots__ = ("_recorder",)

    def __init__(self, recorder=None):
        self._recorder = recorder

    @property
    def enabled(self) -> bool:
        """Whether spans will actually reach a recorder."""
        return self._recorder is not None

    def _emit(self, event: str, **fields: Any) -> None:
        if self._recorder is not None:
            self._recorder.emit(event, **fields)

    def start_trace(self, name: str, trace_id: Optional[str] = None,
                    **attrs: Any):
        """Open a ROOT span under a fresh trace id (or ``trace_id``,
        which is how a resumed request links back to its original
        trace). Returns :data:`NULL_SPAN` when disabled."""
        if self._recorder is None:
            return NULL_SPAN
        return Span(self, name, trace_id or _new_id(8), **attrs)
