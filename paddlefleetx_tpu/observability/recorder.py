"""Crash-surviving flight recorder: an append-only events.jsonl stream.

Every ``emit`` writes one JSON line and flushes + fsyncs it before
returning, so an OOM-killed (SIGKILL, no handler runs) or wedged run
still leaves its last known state on disk — the r5 failure mode this
exists for: an rc=137 MoE bench and three null BENCH rounds whose only
evidence was "probe hung". SIGTERM needs no special file handling for
the same reason; handlers (engine preemption, bench reporter) just
``emit`` one more event and it is durable.

Schema: ``{"ts": <unix seconds>, "event": <name>, ...fields}``; the
event vocabulary is pinned in ``docs/observability.md``. ``tail``
re-reads the file so a DIFFERENT process (the bench embedding its
recorder tail into a failure record) sees everything flushed so far.

Rotation: a long serving run (or the chaos loop) must not grow the
stream unboundedly, so when the file would exceed
``PFX_RECORDER_MAX_BYTES`` (default 64 MiB) it rolls once to
``<path>.1`` — the new file opens with a ``recorder_rotated`` event,
and ``read_tail``/``read_events`` transparently read the rotated file
first, so crash diagnostics still see across the roll.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

#: rotation threshold when PFX_RECORDER_MAX_BYTES is unset: ~64 MiB
_DEFAULT_MAX_BYTES = 64 * 1024 * 1024


def _max_bytes_from_env() -> int:
    """The rotation threshold, from ``PFX_RECORDER_MAX_BYTES`` (bytes;
    unset/unparseable/non-positive falls back to the 64 MiB default)."""
    raw = os.environ.get("PFX_RECORDER_MAX_BYTES", "").strip()
    try:
        n = int(raw)
    except ValueError:
        return _DEFAULT_MAX_BYTES
    return n if n > 0 else _DEFAULT_MAX_BYTES


class FlightRecorder:
    """Append-only JSONL event log that survives crashes: every
    ``emit`` is flushed and fsynced, so the last record is on disk
    even if the process is SIGKILLed right after. Size-capped: the
    stream rolls once to ``<path>.1`` at ``max_bytes``.

    Thread-safe: the watchdog thread emits stall events into the same
    recorder the main loop writes, so ``emit``/``close`` serialize on
    ``self._lock`` — without it a rotation racing an emit can write
    through a closed handle (``_write``/``_rotate`` run only inside
    that region and need no lock of their own)."""

    def __init__(self, path: str, max_bytes: Optional[int] = None):
        self.path = path
        self.max_bytes = int(max_bytes) if max_bytes else \
            _max_bytes_from_env()
        self._lock = threading.Lock()
        self._f = None
        self._size = 0
        try:
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
            self._f = open(path, "a")
            self._size = os.fstat(self._f.fileno()).st_size
        except OSError:
            pass   # telemetry must never kill the run it observes

    def _write(self, record: Dict[str, Any]) -> None:
        """Serialize + append one record durably, tracking file size."""
        try:
            line = json.dumps(record, default=str) + "\n"
            self._f.write(line)
            self._f.flush()
            os.fsync(self._f.fileno())
            self._size += len(line)
        except (OSError, ValueError):
            pass

    def _rotate(self) -> None:
        """Roll the stream to ``<path>.1`` (replacing any previous
        roll) and restart the live file with a ``recorder_rotated``
        event, so the roll itself is on the record."""
        old_size = self._size
        try:
            self._f.close()
            os.replace(self.path, self.path + ".1")
            self._f = open(self.path, "a")
            self._size = 0
        except OSError:
            # re-open best-effort; a failed roll keeps appending to
            # whatever file handle survives
            try:
                self._f = open(self.path, "a")
                self._size = os.fstat(self._f.fileno()).st_size
            except OSError:
                self._f = None
                return
        self._write({"ts": round(time.time(), 3),
                     "event": "recorder_rotated",
                     "rotated_bytes": old_size,
                     "rotated_to": self.path + ".1"})

    def emit(self, event: str, **fields: Any) -> None:
        """Append one event line, durably (flush + fsync), rotating
        first when the file would exceed ``max_bytes``."""
        with self._lock:
            if self._f is None:
                return
            if self._size >= self.max_bytes and self._size > 0:
                self._rotate()
                if self._f is None:
                    return
            # stamped AFTER any rotation: the roll writes its own
            # recorder_rotated event, and a pre-roll stamp would order
            # this record before it whenever the roll's fsync crosses
            # a millisecond boundary
            record = {"ts": round(time.time(), 3), "event": event}
            record.update(fields)
            self._write(record)

    def tail(self, n: int = 10) -> List[Dict[str, Any]]:
        return read_tail(self.path, n)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None


def _read_lines(path: Optional[str]) -> List[str]:
    if not path:
        return []
    try:
        with open(path) as f:
            return f.readlines()
    except OSError:
        return []


def _parse(lines: List[str]) -> List[Dict[str, Any]]:
    out = []
    for line in lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def read_tail(path: Optional[str], n: int = 10) -> List[Dict[str, Any]]:
    """Last ``n`` parseable event records of ``path`` (missing or
    malformed files yield ``[]`` — the tail decorates diagnostics, it
    must never raise over them). When the live file holds fewer than
    ``n`` lines and a rotated ``<path>.1`` exists, the tail continues
    across the roll."""
    if not path:
        return []
    lines = _read_lines(path)
    if len(lines) < n:
        lines = _read_lines(path + ".1")[-(n - len(lines)):] + lines
    return _parse(lines[-n:])


def read_events(path: Optional[str]) -> List[Dict[str, Any]]:
    """EVERY parseable record of the stream, rotated file first — the
    full-timeline reader the trace exporter and tests use."""
    if not path:
        return []
    return _parse(_read_lines(path + ".1") + _read_lines(path))
