"""Crash-surviving flight recorder: an append-only events.jsonl stream.

Every ``emit`` writes one JSON line and flushes + fsyncs it before
returning, so an OOM-killed (SIGKILL, no handler runs) or wedged run
still leaves its last known state on disk — the r5 failure mode this
exists for: an rc=137 MoE bench and three null BENCH rounds whose only
evidence was "probe hung". SIGTERM needs no special file handling for
the same reason; handlers (engine preemption, bench reporter) just
``emit`` one more event and it is durable.

Schema: ``{"ts": <unix seconds>, "event": <name>, ...fields}``; the
event vocabulary is pinned in ``docs/observability.md``. ``tail``
re-reads the file so a DIFFERENT process (the bench embedding its
recorder tail into a failure record) sees everything flushed so far.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional


class FlightRecorder:
    """Append-only JSONL event log that survives crashes: every
    ``emit`` is flushed and fsynced, so the last record is on disk
    even if the process is SIGKILLed right after."""

    def __init__(self, path: str):
        self.path = path
        self._f = None
        try:
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
            self._f = open(path, "a")
        except OSError:
            pass   # telemetry must never kill the run it observes

    def emit(self, event: str, **fields: Any) -> None:
        """Append one event line, durably (flush + fsync)."""
        if self._f is None:
            return
        record = {"ts": round(time.time(), 3), "event": event}
        record.update(fields)
        try:
            self._f.write(json.dumps(record, default=str) + "\n")
            self._f.flush()
            os.fsync(self._f.fileno())
        except (OSError, ValueError):
            pass

    def tail(self, n: int = 10) -> List[Dict[str, Any]]:
        return read_tail(self.path, n)

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None


def read_tail(path: Optional[str], n: int = 10) -> List[Dict[str, Any]]:
    """Last ``n`` parseable event records of ``path`` (missing or
    malformed files yield ``[]`` — the tail decorates diagnostics, it
    must never raise over them)."""
    if not path:
        return []
    try:
        with open(path) as f:
            lines = f.readlines()[-n:]
    except OSError:
        return []
    out = []
    for line in lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out
