"""Lightweight metrics registry: counters / gauges / timers / series /
histograms.

Two registries exist:

- per-``Engine`` instances absorb the loop's sample series (the
  former ad-hoc ``_step_costs`` / ``_h2d_waits`` lists) plus gauges
  and wall-time buckets;
- ONE process-global registry (``get_registry``) collects
  dispatch-decision counters from code that has no engine handle —
  ``ops/attention.py`` (which attention path a trace chose and why a
  fallback happened) and ``models/gpt/model.py::_CollectiveDense``
  (mp-linear lowering). It is DISABLED by default; the engine enables
  it when ``Telemetry.enable`` is on.

Cost discipline: the module-level ``inc`` and ``observe`` are the
only calls that can sit on a hot path, and when the global registry
is disabled each is a single attribute load + boolean test (the
bench-harness test pins the disabled overhead below 1% of a host
step). Dispatch counters additionally fire only at TRACE time — once
per compilation, never per executed step.

Histograms (``observe``) are fixed-memory log-bucketed estimators
(``observability/histogram.py``) — the latency-percentile series
(serving TTFT/queue-wait/tick, engine step time) ride them instead of
unbounded sample lists; names are pinned to the docs matrices by the
same PFX201/PFX202 contract as the counters.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from .histogram import LogHistogram


class MetricsRegistry:
    """Counters / gauges / timers / series / histograms in plain
    dicts, guarded by one lock.

    Thread model: the watchdog thread (``core/resilience.py``) and the
    metrics HTTP server's per-request threads
    (``observability/server.py``) read and increment registries the
    main loop mutates, so every table access goes through
    ``self._lock``. The ``enabled`` fast path stays OUTSIDE the lock —
    it is a GIL-atomic boolean read and the only thing the hot path
    pays when telemetry is off (the bench-harness test pins that
    overhead below 1%)."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, Any] = {}
        self._timers: Dict[str, float] = {}
        self._series: Dict[str, List[float]] = {}
        self._hists: Dict[str, LogHistogram] = {}

    # -- counters ------------------------------------------------------
    def inc(self, name: str, n: float = 1) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0)

    # -- gauges --------------------------------------------------------
    def set_gauge(self, name: str, value: Any) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._gauges[name] = value

    def gauge(self, name: str, default: Any = None) -> Any:
        with self._lock:
            return self._gauges.get(name, default)

    # -- timers --------------------------------------------------------
    def add_time(self, name: str, seconds: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._timers[name] = self._timers.get(name, 0.0) + seconds

    @contextmanager
    def timer(self, name: str):
        """Accumulate the block's wall time under ``name`` (and count
        entries under ``name + "/calls"``)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add_time(name, time.perf_counter() - t0)
            self.inc(name + "/calls")

    def timed(self, name: str) -> float:
        with self._lock:
            return self._timers.get(name, 0.0)

    # -- series --------------------------------------------------------
    def series(self, name: str) -> List[float]:
        """The mutable sample list registered under ``name`` (created
        on first use). Callers append/clear the returned list directly
        — an alias, not a copy — so absorbing an existing ad-hoc list
        costs nothing on the appending path. The alias is main-thread
        state: cross-thread readers must use ``snapshot()``, which
        copies under the registry lock."""
        with self._lock:
            return self._series.setdefault(name, [])

    # -- histograms ----------------------------------------------------
    def observe(self, name: str, value: float) -> None:
        """Record one sample into the log-bucketed histogram under
        ``name`` (created on first use). O(1), O(buckets) memory —
        the percentile-series counterpart of ``inc``."""
        if not self.enabled:
            return
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = LogHistogram()
            h.observe(value)

    def histogram(self, name: str) -> Optional[LogHistogram]:
        """The live histogram registered under ``name``, or None.
        Like ``series()``, the returned object is main-thread state —
        exporters on other threads read ``snapshot()`` instead."""
        with self._lock:
            return self._hists.get(name)

    def histograms(self) -> Dict[str, LogHistogram]:
        """Shallow copy of the name -> histogram table. The histogram
        objects are live — cross-thread consumers (the Prometheus
        exporter) must use ``snapshot()["histograms"]``."""
        with self._lock:
            return dict(self._hists)

    # -- lifecycle -----------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Point-in-time copy: ``{"counters", "gauges", "timers",
        "series", "histograms"}`` (series copied shallowly, histograms
        as summary dicts). The one safe cross-thread read."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timers": dict(self._timers),
                "series": {k: list(v)
                           for k, v in self._series.items()},
                "histograms": {k: h.snapshot()
                               for k, h in self._hists.items()},
            }

    def reset(self) -> None:
        """Zero everything; registered series are cleared IN PLACE so
        aliases handed out by ``series()`` stay live (histograms
        likewise reset in place, not dropped)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()
            for v in self._series.values():
                del v[:]
            for h in self._hists.values():
                h.reset()


#: process-global dispatch-counter registry; disabled until the engine
#: (or a test) turns telemetry on
_global = MetricsRegistry(enabled=False)


def get_registry() -> MetricsRegistry:
    return _global


def set_enabled(flag: bool) -> None:
    # benign race by design: `enabled` is a GIL-atomic boolean the hot
    # path reads WITHOUT the registry lock (that unlocked read is the
    # entire disabled-cost budget); a racing reader sees the old value
    # for at most one sample, which telemetry tolerates
    _global.enabled = bool(flag)   # pfxlint: disable=PFX301


def inc(name: str, n: float = 1) -> None:
    """Hot-path global counter increment; a no-op boolean test when
    telemetry is disabled."""
    if not _global.enabled:
        return
    _global.inc(name, n)


def observe(name: str, value: float) -> None:
    """Hot-path global histogram sample; a no-op boolean test when
    telemetry is disabled (same cost discipline as ``inc``)."""
    if not _global.enabled:
        return
    _global.observe(name, value)
