"""Lightweight metrics registry: counters / gauges / timers / series.

Two registries exist:

- per-``Engine`` instances absorb the loop's sample series (the
  former ad-hoc ``_step_costs`` / ``_h2d_waits`` lists) plus gauges
  and wall-time buckets;
- ONE process-global registry (``get_registry``) collects
  dispatch-decision counters from code that has no engine handle —
  ``ops/attention.py`` (which attention path a trace chose and why a
  fallback happened) and ``models/gpt/model.py::_CollectiveDense``
  (mp-linear lowering). It is DISABLED by default; the engine enables
  it when ``Telemetry.enable`` is on.

Cost discipline: the module-level ``inc`` is the only call that can
sit on a hot path, and when the global registry is disabled it is a
single attribute load + boolean test (the bench-harness test pins
the disabled overhead below 1% of a host step). Dispatch counters
additionally fire only at TRACE time — once per compilation, never
per executed step.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Any, Dict, List


class MetricsRegistry:
    """Counters / gauges / timers / sample series in plain dicts."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, Any] = {}
        self._timers: Dict[str, float] = {}
        self._series: Dict[str, List[float]] = {}

    # -- counters ------------------------------------------------------
    def inc(self, name: str, n: float = 1) -> None:
        if not self.enabled:
            return
        self._counters[name] = self._counters.get(name, 0) + n

    def counter(self, name: str) -> float:
        return self._counters.get(name, 0)

    # -- gauges --------------------------------------------------------
    def set_gauge(self, name: str, value: Any) -> None:
        if not self.enabled:
            return
        self._gauges[name] = value

    def gauge(self, name: str, default: Any = None) -> Any:
        return self._gauges.get(name, default)

    # -- timers --------------------------------------------------------
    def add_time(self, name: str, seconds: float) -> None:
        if not self.enabled:
            return
        self._timers[name] = self._timers.get(name, 0.0) + seconds

    @contextmanager
    def timer(self, name: str):
        """Accumulate the block's wall time under ``name`` (and count
        entries under ``name + "/calls"``)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add_time(name, time.perf_counter() - t0)
            self.inc(name + "/calls")

    def timed(self, name: str) -> float:
        return self._timers.get(name, 0.0)

    # -- series --------------------------------------------------------
    def series(self, name: str) -> List[float]:
        """The mutable sample list registered under ``name`` (created
        on first use). Callers append/clear the returned list directly
        — an alias, not a copy — so absorbing an existing ad-hoc list
        costs nothing on the appending path."""
        return self._series.setdefault(name, [])

    # -- lifecycle -----------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Point-in-time copy: ``{"counters", "gauges", "timers",
        "series"}`` (series copied shallowly)."""
        return {
            "counters": dict(self._counters),
            "gauges": dict(self._gauges),
            "timers": dict(self._timers),
            "series": {k: list(v) for k, v in self._series.items()},
        }

    def reset(self) -> None:
        """Zero everything; registered series are cleared IN PLACE so
        aliases handed out by ``series()`` stay live."""
        self._counters.clear()
        self._gauges.clear()
        self._timers.clear()
        for v in self._series.values():
            del v[:]


#: process-global dispatch-counter registry; disabled until the engine
#: (or a test) turns telemetry on
_global = MetricsRegistry(enabled=False)


def get_registry() -> MetricsRegistry:
    return _global


def set_enabled(flag: bool) -> None:
    _global.enabled = bool(flag)


def inc(name: str, n: float = 1) -> None:
    """Hot-path global counter increment; a no-op boolean test when
    telemetry is disabled."""
    if not _global.enabled:
        return
    _global._counters[name] = _global._counters.get(name, 0) + n
