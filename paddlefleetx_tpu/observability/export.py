"""Metric and trace exporters: Prometheus text + Perfetto/Chrome JSON.

Two render paths over the in-process telemetry, both stdlib-only:

- :func:`prometheus_text` walks one or more live
  :class:`~paddlefleetx_tpu.observability.metrics.MetricsRegistry`
  objects into the Prometheus text exposition format (version 0.0.4):
  counters as ``pfx_<name>_total``, numeric gauges as ``pfx_<name>``,
  timers as ``pfx_<name>_seconds_total``, histograms as cumulative
  ``_bucket{le=...}`` series + ``_sum``/``_count``. Series names have
  ``/`` mapped to ``_`` (``serving/ttft_ms`` ->
  ``pfx_serving_ttft_ms``); the grammar is pinned by
  ``tests/test_tracing.py``.
- :func:`chrome_trace` converts flight-recorder span records
  (``observability/spans.py``) into the Chrome trace-event JSON that
  Perfetto / ``chrome://tracing`` loads directly — each trace id gets
  its own track under a ``requests`` process, and a thread-timeline
  snapshot (``observability/timeline.py``) adds one track per
  instrumented thread under a ``threads`` process, so a request's
  submit→evict life reads next to the worker/writer threads that
  served it (pid/tid assignment is stable across exports).

Both are served live by ``observability/server.py`` (``/metrics`` and
``/trace``).
"""

from __future__ import annotations

import re
from typing import Any, Dict, Iterable, List

_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_]")

#: flight-recorder event kinds the trace exporter understands
_SPAN_EVENTS = ("span_begin", "span_end", "span", "span_point")


def _metric_name(name: str, suffix: str = "") -> str:
    """``serving/ttft_ms`` -> ``pfx_serving_ttft_ms<suffix>``."""
    return "pfx_" + _SANITIZE_RE.sub("_", name) + suffix


def _fmt(value: Any) -> str:
    """A Prometheus-grammar sample value (floats in repr precision)."""
    return repr(float(value))


def prometheus_text(registries: Iterable[Any]) -> str:
    """Text exposition of the given registries, merged.

    Args:
        registries: live ``MetricsRegistry`` objects. Each is read
            exactly once through ``snapshot()`` — the one
            lock-protected cross-thread read — so the HTTP serving
            thread never touches live tables or bucket arrays the
            main loop is mutating. Counter/timer values merge by
            summation, gauges last-wins, histograms first-wins.

    Returns:
        The exposition body, one ``# TYPE`` comment + samples per
        metric, trailing newline included.
    """
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    timers: Dict[str, float] = {}
    hists: Dict[str, Dict[str, Any]] = {}
    for reg in registries:
        snap = reg.snapshot()
        for k, v in snap["counters"].items():
            counters[k] = counters.get(k, 0) + v
        for k, v in snap["gauges"].items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                gauges[k] = float(v)
        for k, v in snap["timers"].items():
            timers[k] = timers.get(k, 0.0) + v
        for k, h in snap["histograms"].items():
            hists.setdefault(k, h)
    lines: List[str] = []
    for name, val in sorted(counters.items()):
        m = _metric_name(name, "_total")
        lines += [f"# TYPE {m} counter", f"{m} {_fmt(val)}"]
    for name, val in sorted(gauges.items()):
        m = _metric_name(name)
        lines += [f"# TYPE {m} gauge", f"{m} {_fmt(val)}"]
    for name, val in sorted(timers.items()):
        m = _metric_name(name, "_seconds_total")
        lines += [f"# TYPE {m} counter", f"{m} {_fmt(val)}"]
    for name, h in sorted(hists.items()):
        m = _metric_name(name)
        count = h.get("count", 0)
        lines.append(f"# TYPE {m} histogram")
        for upper, cum in h.get("buckets", []):
            lines.append(f'{m}_bucket{{le="{_fmt(upper)}"}} {cum}')
        lines.append(f'{m}_bucket{{le="+Inf"}} {count}')
        lines.append(f"{m}_sum {_fmt(h.get('sum', 0.0))}")
        lines.append(f"{m}_count {count}")
    return "\n".join(lines) + "\n"


def merge_snapshots(snapshots: Iterable[Dict[str, Any]]
                    ) -> Dict[str, Any]:
    """Merge registry ``snapshot()`` dicts for the ``/vars`` endpoint
    (counters/timers sum, gauges/series/histograms last-wins)."""
    out: Dict[str, Any] = {"counters": {}, "gauges": {}, "timers": {},
                           "series": {}, "histograms": {}}
    for snap in snapshots:
        for k, v in snap.get("counters", {}).items():
            out["counters"][k] = out["counters"].get(k, 0) + v
        for k, v in snap.get("timers", {}).items():
            out["timers"][k] = out["timers"].get(k, 0.0) + v
        out["gauges"].update(snap.get("gauges", {}))
        out["series"].update(snap.get("series", {}))
        out["histograms"].update(snap.get("histograms", {}))
    return out


def _span_args(rec: Dict[str, Any]) -> Dict[str, Any]:
    """Everything a span record carries beyond the envelope fields."""
    return {k: v for k, v in rec.items()
            if k not in ("ts", "event", "name", "trace", "span",
                         "parent", "dur_ms")}


#: stable Perfetto process ids: request/span tracks vs thread tracks
_PID_REQUESTS = 1
_PID_THREADS = 2


def chrome_trace(records: Iterable[Dict[str, Any]],
                 timeline: Any = None) -> Dict[str, Any]:
    """Chrome trace-event JSON from flight-recorder records, plus
    (optionally) per-thread activity tracks.

    Args:
        records: parsed events.jsonl records (non-span events are
            skipped); ``observability.recorder.read_events`` provides
            them rotation-aware.
        timeline: optional ``{track name: [(state, t0, t1, trace)]}``
            snapshot from ``observability.timeline`` — each track
            renders as a thread row under a second ``threads``
            process, interval states as ``X`` slices (trace-tagged
            slices carry the request's trace id in ``args`` so a
            handoff lines up against its span row).

    Returns:
        ``{"traceEvents": [...], "displayTimeUnit": "ms"}`` — loadable
        by Perfetto and ``chrome://tracing``. pid/tid assignment is
        STABLE: span tracks live in pid 1 (``requests``) with tids
        assigned 1..N over the sorted trace ids, timeline tracks in
        pid 2 (``threads``) with tids 1..M over the sorted track
        names — two exports of the same data group identically.
        ``span``/``span_begin``/``span_end`` map to phases ``X``/
        ``B``/``E``, points to ``i``.
    """
    recs = [r for r in records if r.get("event") in _SPAN_EVENTS]
    tids = {key: i + 1 for i, key in enumerate(
        sorted({str(r.get("trace")) for r in recs}))}
    events: List[Dict[str, Any]] = []
    if recs:
        events.append({"ph": "M", "name": "process_name",
                       "pid": _PID_REQUESTS, "tid": 0,
                       "args": {"name": "requests"}})
        for key, tid in sorted(tids.items(), key=lambda kv: kv[1]):
            events.append({"ph": "M", "name": "thread_name",
                           "pid": _PID_REQUESTS, "tid": tid,
                           "args": {"name": f"trace {key}"}})
    for rec in recs:
        kind = rec.get("event")
        ts_us = float(rec.get("ts", 0.0)) * 1e6
        base = {"name": rec.get("name", "?"), "pid": _PID_REQUESTS,
                "tid": tids[str(rec.get("trace"))],
                "args": _span_args(rec)}
        if kind == "span_begin":
            events.append({**base, "ph": "B", "ts": ts_us})
        elif kind == "span_end":
            events.append({**base, "ph": "E", "ts": ts_us})
        elif kind == "span":
            dur_us = float(rec.get("dur_ms", 0.0)) * 1e3
            events.append({**base, "ph": "X",
                           "ts": ts_us - dur_us, "dur": dur_us})
        else:   # span_point
            events.append({**base, "ph": "i", "ts": ts_us, "s": "t"})
    if timeline:
        events.append({"ph": "M", "name": "process_name",
                       "pid": _PID_THREADS, "tid": 0,
                       "args": {"name": "threads"}})
        for tid, name in enumerate(sorted(timeline), start=1):
            events.append({"ph": "M", "name": "thread_name",
                           "pid": _PID_THREADS, "tid": tid,
                           "args": {"name": name}})
            for state, t0, t1, trace in timeline[name]:
                events.append({
                    "ph": "X", "name": state, "pid": _PID_THREADS,
                    "tid": tid, "ts": t0 * 1e6,
                    "dur": max(0.0, t1 - t0) * 1e6,
                    "args": ({"trace": trace}
                             if trace is not None else {})})
    return {"traceEvents": events, "displayTimeUnit": "ms"}
