"""Fixed-memory log-bucketed latency histogram with quantile estimation.

The serving path used to keep every TTFT sample in an unbounded Python
list and run ``np.percentile`` over it at summary time — fine for a
test run, unacceptable for a server meant to stay up under millions of
requests. :class:`LogHistogram` replaces that with a fixed array of
log-spaced buckets: ``observe`` is O(1) (one ``math.log`` + an int
increment), memory is O(buckets) forever, and any quantile is
recovered by a cumulative walk with bounded RELATIVE error — the
bucket width ratio, ~8% at the default 30 buckets/decade — which is
exactly the regime latency percentiles live in (nobody needs p99 TTFT
to the microsecond, everybody needs it to survive a week-long run).

Values at or below ``lo`` land in the underflow bucket (reported as
``lo/2``); values above ``hi`` clamp to the top bucket. Quantiles
interpolate geometrically inside the winning bucket and clamp to the
exact observed ``[min, max]``, so ``quantile(0)``/``quantile(1)`` are
exact. ``tests/test_histogram.py`` pins the estimates against
``np.percentile`` on seeded samples within the bucket tolerance.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Dict, Iterator, Tuple

#: default range, tuned for millisecond-denominated latencies:
#: 1 microsecond .. ~2.8 hours, 10 decades
_DEFAULT_LO = 1e-3
_DEFAULT_HI = 1e7
_DEFAULT_BPD = 30


class LogHistogram:
    """Log-bucketed histogram: O(1) observe, O(buckets) memory,
    quantiles within one bucket's relative width."""

    __slots__ = ("lo", "hi", "ratio", "_log_ratio", "_n", "_counts",
                 "_count", "_sum", "_min", "_max", "_lock")

    def __init__(self, lo: float = _DEFAULT_LO,
                 hi: float = _DEFAULT_HI,
                 buckets_per_decade: int = _DEFAULT_BPD):
        if not (0 < lo < hi):
            raise ValueError(f"need 0 < lo < hi, got lo={lo} hi={hi}")
        if buckets_per_decade < 1:
            raise ValueError(
                f"buckets_per_decade must be >= 1, got "
                f"{buckets_per_decade}")
        self.lo = float(lo)
        self.hi = float(hi)
        self.ratio = 10.0 ** (1.0 / buckets_per_decade)
        self._log_ratio = math.log(self.ratio)
        # bucket 0 is the underflow bucket [0, lo]; bucket i >= 1 spans
        # (lo * ratio^(i-1), lo * ratio^i]; the top bucket absorbs
        # everything past hi
        self._n = 1 + int(math.ceil(
            math.log(self.hi / self.lo) / self._log_ratio))
        # the histogram synchronizes itself: the registry observes
        # under its own lock, but always-on local registries (the
        # fleet router's) are read from other threads too — reentrant
        # because snapshot() walks quantile()/cumulative() inline
        self._lock = threading.RLock()
        self._counts = [0] * (self._n + 1)
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    # -- recording -----------------------------------------------------
    def observe(self, value: float) -> None:
        """Record one sample; non-finite values are dropped (telemetry
        must never raise over a NaN latency)."""
        v = float(value)
        if not math.isfinite(v):
            return
        if v <= self.lo:
            idx = 0
        else:
            idx = 1 + int(math.log(v / self.lo) / self._log_ratio)
            if idx > self._n:
                idx = self._n
        with self._lock:
            self._counts[idx] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    def reset(self) -> None:
        """Zero every bucket and the running stats, in place."""
        with self._lock:
            self._counts = [0] * (self._n + 1)
            self._count = 0
            self._sum = 0.0
            self._min = math.inf
            self._max = -math.inf

    # -- reading -------------------------------------------------------
    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def min(self) -> float:
        """Smallest observed sample (``inf`` when empty)."""
        with self._lock:
            return self._min

    @property
    def max(self) -> float:
        """Largest observed sample (``-inf`` when empty)."""
        with self._lock:
            return self._max

    def bounds(self, idx: int) -> Tuple[float, float]:
        """``(lower, upper)`` value bounds of bucket ``idx``."""
        if idx <= 0:
            return (0.0, self.lo)
        return (self.lo * self.ratio ** (idx - 1),
                self.lo * self.ratio ** idx)

    def quantile(self, q: float) -> float:
        """Estimated value at quantile ``q`` in [0, 1]; 0.0 when the
        histogram is empty. Monotonic in ``q``; exact at 0 and 1
        (clamped to the observed min/max)."""
        with self._lock:
            if self._count == 0:
                return 0.0
            q = min(max(float(q), 0.0), 1.0)
            # rank of the target sample among count samples (midpoint
            # convention keeps single-sample histograms exact)
            target = q * (self._count - 1)
            cum = 0
            for idx, c in enumerate(self._counts):
                if c == 0:
                    continue
                if cum + c > target:
                    lower, upper = self.bounds(idx)
                    if idx == 0:
                        est = self.lo / 2.0
                    else:
                        # geometric interpolation inside the bucket:
                        # the error bound is the bucket's relative
                        # width
                        frac = (target - cum + 0.5) / c
                        est = lower * (upper / lower) ** min(frac, 1.0)
                    return min(max(est, self._min), self._max)
                cum += c
            return self._max

    def percentile(self, p: float) -> float:
        """``quantile(p / 100)`` — the ``np.percentile`` spelling."""
        return self.quantile(p / 100.0)

    def cumulative(self) -> Iterator[Tuple[float, int]]:
        """``(upper_bound, cumulative_count)`` over non-empty buckets,
        ascending — the Prometheus ``le`` bucket series."""
        with self._lock:
            cum = 0
            for idx, c in enumerate(self._counts):
                if c == 0:
                    continue
                cum += c
                yield self.bounds(idx)[1], cum

    def snapshot(self) -> Dict[str, Any]:
        """Point-in-time summary dict (count/sum/min/max + p50/p90/p99
        + cumulative ``buckets``), the shape the registry snapshot,
        ``/vars``, and the Prometheus exporter consume — exporters on
        other threads read this copy, never the live bucket arrays."""
        with self._lock:
            if self._count == 0:
                return {"count": 0, "sum": 0.0, "buckets": []}
            return {
                "count": self._count,
                "sum": round(self._sum, 6),
                "min": round(self._min, 6),
                "max": round(self._max, 6),
                "p50": round(self.quantile(0.50), 6),
                "p90": round(self.quantile(0.90), 6),
                "p99": round(self.quantile(0.99), 6),
                "buckets": [[upper, cum]
                            for upper, cum in self.cumulative()],
            }
