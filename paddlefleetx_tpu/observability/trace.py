"""Labeled trace phases: the XProf trace and the summary share names.

``annotate(name)`` wraps a host-side phase in
``jax.profiler.TraceAnnotation`` so the trace viewer shows the same
buckets the goodput accounting reports (``h2d``, ``train_step``,
``eval``, ``save``, ``mp_collective_probe``). Degrades to a no-op
context when the profiler machinery is unavailable — annotation must
never be the thing that kills a run.
"""

from __future__ import annotations

from contextlib import nullcontext


def annotate(name: str):
    """Context manager labeling the enclosed host block ``name`` in
    the profiler timeline (microseconds of overhead; safe per step)."""
    try:
        import jax
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return nullcontext()
