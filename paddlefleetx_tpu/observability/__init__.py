"""Structured telemetry: metrics registry, crash-surviving flight
recorder, FLOPs/MFU accounting, device-memory sampling and labeled
trace annotations.

The training loop's numbers (step time, h2d wait, HBM watermark, MFU,
goodput) and its dispatch decisions (attention path, mp-linear
lowering) are first-class, machine-readable outputs here — not
grep-able log lines plus out-of-band scripts. See
``docs/observability.md`` for the events.jsonl schema and counter
names.
"""

from . import metrics
from .flops import (
    PEAK_FLOPS_BY_KIND, causal_attn_flops, model_flops_per_token,
    peak_flops,
)
from .memory import device_memory_stats, format_bytes
from .metrics import MetricsRegistry, get_registry
from .recorder import FlightRecorder
from .trace import annotate

__all__ = [
    "FlightRecorder", "MetricsRegistry", "PEAK_FLOPS_BY_KIND",
    "annotate", "causal_attn_flops", "device_memory_stats",
    "format_bytes", "get_registry", "metrics", "model_flops_per_token",
    "peak_flops",
]
