"""Structured telemetry: metrics registry, crash-surviving flight
recorder, FLOPs/MFU accounting, device-memory sampling and labeled
trace annotations.

The training loop's numbers (step time, h2d wait, HBM watermark, MFU,
goodput) and its dispatch decisions (attention path, mp-linear
lowering) are first-class, machine-readable outputs here — not
grep-able log lines plus out-of-band scripts. See
``docs/observability.md`` for the events.jsonl schema and counter
names.
"""

from . import export, metrics, server, timeline
from .flops import (
    PEAK_FLOPS_BY_KIND, causal_attn_flops, model_flops_per_token,
    peak_flops,
)
from .histogram import LogHistogram
from .memory import device_memory_stats, format_bytes
from .metrics import MetricsRegistry, get_registry
from .recorder import FlightRecorder, read_events, read_tail
from .server import MetricsServer
from .spans import NULL_SPAN, Span, Tracer
from .timeline import ThreadTimeline, get_timeline
from .trace import annotate

__all__ = [
    "FlightRecorder", "LogHistogram", "MetricsRegistry",
    "MetricsServer", "NULL_SPAN", "PEAK_FLOPS_BY_KIND", "Span",
    "ThreadTimeline", "Tracer", "annotate", "causal_attn_flops",
    "device_memory_stats", "export", "format_bytes", "get_registry",
    "get_timeline", "metrics", "model_flops_per_token", "peak_flops",
    "read_events", "read_tail", "server", "timeline",
]
