"""Quantization-aware training (QAT) by simulated int quantization.

Parity: the reference wraps ``Linear/ColumnParallelLinear/
RowParallelLinear`` with paddleslim QAT (reference
``language_module.py:97-100,142-144``; config section
``configs/nlp/gpt/pretrain_gpt_345M_mp8_qat.yaml:35-43`` — abs_max
weight quant, moving-average abs_max activation quant, 8 bits each).

TPU-native design: no layer surgery. Weights are fake-quantized by a
differentiable tree transform over the parameter pytree (straight-
through estimator), and activations are fake-quantized at every
Dense/DenseGeneral/Conv input through flax's method interception —
the same model definition, two extra pure functions under jit. The
activation scale is the current-batch abs-max (the moving-average
variant needs mutable state; per-batch abs-max is its fixed point and
keeps the step a pure function).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class QuantizationConfig:
    """QAT settings mirroring the reference's quantization surface."""

    enable: bool = False
    weight_quantize_type: str = "abs_max"
    activation_quantize_type: str = "moving_average_abs_max"
    weight_bits: int = 8
    activation_bits: int = 8
    quantizable_layer_type: Sequence[str] = (
        "Conv2D", "Linear", "Conv2DTranspose", "ColumnParallelLinear",
        "RowParallelLinear")

    @classmethod
    def from_config(cls, config) -> "QuantizationConfig":
        """Build from a YAML ``Quantization`` section, warning on (and
        dropping) keys that no field matches."""
        section = dict(config.get("Quantization", {}) or {})
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(section) - fields)
        if unknown:
            # a typo here silently trains WITHOUT quantization (the
            # reference's paddleslim would have raised) — warn loudly
            from ..utils.log import logger
            logger.warning(
                "Quantization config keys %s are not recognized and "
                "will be ignored (known keys: %s)", unknown,
                sorted(fields))
        return cls(**{k: v for k, v in section.items() if k in fields})


def fake_quant(x: jax.Array, bits: int = 8,
               layer_axis: int | None = None) -> jax.Array:
    """Symmetric abs-max fake quantization with a straight-through
    gradient. Per-tensor scale by default; with ``layer_axis`` the
    scale is computed independently along that axis (one scale per
    scan-stacked layer)."""
    qmax = 2.0 ** (bits - 1) - 1
    if layer_axis is None:
        scale = jnp.max(jnp.abs(x))
    else:
        red = tuple(i for i in range(x.ndim) if i != layer_axis)
        scale = jnp.max(jnp.abs(x), axis=red, keepdims=True)
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.round(x / scale * qmax)
    q = jnp.clip(q, -qmax, qmax) * (scale / qmax)
    # STE: forward sees q, backward sees identity
    return x + jax.lax.stop_gradient(q - x)


def quantize_params(params, bits: int = 8,
                    stacked_module: str | None = None):
    """Fake-quantize every dense/conv kernel leaf (path ends in
    'kernel'); biases, norms, and embeddings stay full precision —
    mirroring the reference's quantizable_layer_type list (Linear and
    its parallel variants).

    ``stacked_module`` names the scan-over-layers module ("decoder" /
    "encoder"): its kernels carry a leading ``[num_layers, ...]`` axis
    and get one scale per layer, matching the reference where
    paddleslim quantizes each Linear independently — a single
    per-tensor scale across 24 stacked layers would starve
    small-magnitude layers of resolution."""
    def maybe_q(path, leaf):
        names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        if names and names[-1] == "kernel":
            axis = 0 if stacked_module is not None \
                and stacked_module in names else None
            return fake_quant(leaf, bits, layer_axis=axis)
        return leaf

    return jax.tree_util.tree_map_with_path(maybe_q, params)


def activation_quant_interceptor(bits: int = 8):
    """Flax interceptor quantizing the input of every Dense/Conv."""
    targets = (nn.Dense, nn.DenseGeneral, nn.Conv)

    def interceptor(next_fn, args, kwargs, context):
        if isinstance(context.module, targets) and \
                context.method_name == "__call__" and args:
            args = (fake_quant(args[0], bits),) + args[1:]
        return next_fn(*args, **kwargs)

    return interceptor


def qat_apply(model: nn.Module, cfg: QuantizationConfig, params,
              *args, stacked_module: str | None = None,
              **kwargs) -> Any:
    """``model.apply`` with QAT: weight kernels fake-quantized, dense
    inputs fake-quantized."""
    qparams = quantize_params(params, cfg.weight_bits,
                              stacked_module=stacked_module)
    with nn.intercept_methods(
            activation_quant_interceptor(cfg.activation_bits)):
        return model.apply({"params": qparams}, *args, **kwargs)
