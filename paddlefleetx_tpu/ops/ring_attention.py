"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context scaling beyond the reference (SURVEY §5.7: the reference
has no ring/blockwise/context parallelism — its only sequence scaling
is Megatron-SP, which still materializes full attention per rank and
tops out at ~1k tokens). Here each device holds a sequence block; KV
blocks rotate around the ``cp`` mesh axis with ``jax.lax.ppermute``
(one ICI-neighbor hop per step — compute on the current block overlaps
the transfer of the next) while a streaming log-sum-exp accumulator
(the flash-attention recurrence) combines per-block partial outputs
into the *exact* softmax result. Peak memory per device is
O(s/N * s/N) score blocks instead of O(s * s).

Layout: ``[b, s/N, h, d]`` per device, batch over dp x fsdp, heads
over mp, sequence over cp — composes with every other axis.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..observability import metrics

try:                                    # jax >= 0.5 re-exports it
    _shard_map = jax.shard_map
except AttributeError:                  # 0.4.x spelling
    from jax.experimental.shard_map import shard_map as _shard_map


def _axis_size(axis_name):
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:
        # 0.4.x: psum of a static 1 is evaluated eagerly to a Python
        # int — the classic pre-axis_size spelling
        return jax.lax.psum(1, axis_name)


NEG_INF = -1e30


def _block_attn(q, k, v, scale, causal, q_start, k_start):
    """Scores + masked row-max/row-sum for one (q-block, kv-block)
    pair; returns (out_block, row_max, row_sum) in fp32."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_start + jnp.arange(sq)[:, None]
        k_pos = k_start + jnp.arange(sk)[None, :]
        scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
    m = jnp.max(scores, axis=-1)                          # [b,h,q]
    # rows with no visible key (fully masked) must not produce
    # exp(NEG_INF - NEG_INF) = 1 garbage
    safe_m = jnp.where(m <= NEG_INF / 2, 0.0, m)
    p = jnp.exp(scores - safe_m[..., None])
    p = jnp.where(scores <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)                               # noqa: E741
    out = jnp.einsum("bhqk,bkhd->bqhd", p,
                     v.astype(jnp.float32))
    return out, m, l


def _ring_flash(q, k, v, axis_name, causal, scale):
    """Ring with the Pallas flash kernel on each local block.

    Ring blocks are aligned and equal-sized, so every (q-block,
    kv-block) pair is exactly one of: the diagonal (``src == idx`` —
    plain causal flash), fully visible (``src < idx`` — non-causal
    flash), or fully masked (dead). No masked-offset arithmetic ever
    reaches the kernel. Per-block results merge through the logsumexp
    the kernel already returns — the same streaming combination the
    kernel itself performs across its internal KV blocks, lifted one
    level up the memory hierarchy (VMEM blocks -> ring neighbors).
    """
    from .pallas.flash_attention import flash_attention_with_lse

    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    # admission is hoisted: ring_attention only selects this path after
    # _flash_block_ok proved the block shape admissible, and the merge
    # needs the kernel's raw lse — no per-block try/fallback possible
    def blk_diag(kv):
        return flash_attention_with_lse(  # pfxlint: disable=PFX205
            q, kv[0], kv[1], causal=True, sm_scale=scale)

    def blk_full(kv):
        return flash_attention_with_lse(  # pfxlint: disable=PFX205
            q, kv[0], kv[1], causal=False, sm_scale=scale)

    def blk_dead(kv):
        # constants must carry q's device-varying type or the cond
        # branches disagree under shard_map's vma checker
        zq = jnp.sum(q.astype(jnp.float32)) * 0.0
        return (jnp.zeros((b, sq, h, d), q.dtype) + zq.astype(q.dtype),
                jnp.full((b, h, sq), NEG_INF, jnp.float32) + zq)

    def step(carry, i):
        """One ring hop: flash the resident KV block (diag/full/dead
        by ring position), merge via logsumexp, rotate KV."""
        k_blk, v_blk, out, lse = carry
        src = (idx - i) % n
        if causal:
            blk_out, blk_lse = jax.lax.cond(
                src == idx, blk_diag,
                lambda kv: jax.lax.cond(src < idx, blk_full, blk_dead,
                                        kv),
                (k_blk, v_blk))
        else:
            blk_out, blk_lse = blk_full((k_blk, v_blk))
        new_lse = jnp.logaddexp(lse, blk_lse)
        dead = new_lse <= NEG_INF / 2
        alpha = jnp.where(dead, 0.0, jnp.exp(lse - new_lse))
        beta = jnp.where(dead, 0.0, jnp.exp(blk_lse - new_lse))
        out = out * alpha[..., None].swapaxes(1, 2) + \
            blk_out.astype(jnp.float32) * beta[..., None].swapaxes(1, 2)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, out, new_lse), None

    zero_q = jnp.sum(q.astype(jnp.float32)) * 0.0
    out0 = jnp.zeros((b, sq, h, d), jnp.float32) + zero_q
    lse0 = jnp.full((b, h, sq), NEG_INF, jnp.float32) + zero_q
    (_, _, out, _), _ = jax.lax.scan(step, (k, v, out0, lse0),
                                     jnp.arange(n))
    return out.astype(q.dtype)


def _flash_block_ok(sq, d) -> bool:
    from .pallas.flash_attention import check_shapes
    try:
        check_shapes(sq, sq, d)
        return True
    except NotImplementedError:
        return False


@partial(jax.jit,
         static_argnames=("axis_name", "causal", "scale", "use_flash"))
def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   *, axis_name: str, causal: bool = True,
                   scale: Optional[float] = None,
                   use_flash: Optional[bool] = None) -> jax.Array:
    """Exact attention with KV blocks rotating over ``axis_name``.

    Call under ``shard_map`` (or use :func:`ring_attention_sharded`):
    arguments are the per-device blocks ``[b, s_local, h, d]``.

    ``use_flash=None`` auto-selects the Pallas per-block kernel on real
    TPU backends when the block shapes allow (never materializing the
    ``[b, h, s/N, s/N]`` score blocks the dense path builds); pass
    ``True``/``False`` to force either path.
    """
    b, sq, h, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    if use_flash is None:
        use_flash = (jax.default_backend() == "tpu"
                     and _flash_block_ok(sq, d))
    metrics.inc("attention/ring/flash" if use_flash
                else "attention/ring/dense")
    if use_flash:
        return _ring_flash(q, k, v, axis_name, causal, scale)

    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    q_start = idx * sq

    perm = [(i, (i + 1) % n) for i in range(n)]  # send KV to the right

    def step(carry, i):
        """One ring hop of the dense path: streaming-softmax merge of
        the resident KV block, then rotate KV."""
        k_blk, v_blk, out, m, l = carry  # noqa: E741
        # after i rotations, this device holds the KV block that
        # originated at ring position idx - i
        src = (idx - i) % n
        blk_out, blk_m, blk_l = _block_attn(
            q, k_blk, v_blk, scale, causal, q_start, src * sq)
        new_m = jnp.maximum(m, blk_m)
        # renormalize both accumulators onto the new running max
        safe = lambda x: jnp.where(  # noqa: E731
            new_m <= NEG_INF / 2, 0.0, x)
        alpha = jnp.exp(safe(m - new_m))
        beta = jnp.exp(safe(blk_m - new_m))
        out = out * alpha[..., None].swapaxes(1, 2) + \
            blk_out * beta[..., None].swapaxes(1, 2)
        l = l * alpha + blk_l * beta  # noqa: E741
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, out, new_m, l), None

    # fresh accumulators must carry the same device-varying type as
    # the loop outputs under shard_map; deriving them from q (a
    # varying input) gives them that type on any jax version
    zero_q = jnp.sum(q.astype(jnp.float32)) * 0.0
    out0 = jnp.zeros((b, sq, h, d), jnp.float32) + zero_q
    m0 = jnp.full((b, h, sq), NEG_INF, jnp.float32) + zero_q
    l0 = jnp.zeros((b, h, sq), jnp.float32) + zero_q
    (_, _, out, _, l), _ = jax.lax.scan(
        step, (k, v, out0, m0, l0), jnp.arange(n))
    out = out / jnp.maximum(l, 1e-30)[..., None].swapaxes(1, 2)
    return out.astype(q.dtype)


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                           mesh, *, axis_name: str = None,
                           batch_axes=None, heads_axis: str = None,
                           causal: bool = True,
                           use_flash: Optional[bool] = None) -> jax.Array:
    """A ``shard_map`` wrapper: global ``[b, s, h, d]`` -> global attention
    output, with s sharded over ``axis_name`` and the ring running
    inside. Axis defaults come from the mesh convention
    (``parallel/mesh.py``), not re-spelled strings."""
    from ..parallel.mesh import CP_AXIS, DATA_AXES, MP_AXIS
    axis_name = axis_name or CP_AXIS
    batch_axes = batch_axes or DATA_AXES
    heads_axis = heads_axis or MP_AXIS
    if use_flash is None:
        s_local = q.shape[1] // mesh.shape[axis_name]
        use_flash = (jax.default_backend() == "tpu"
                     and _flash_block_ok(s_local, q.shape[-1]))
    spec = P(batch_axes, axis_name, heads_axis, None)
    fn = partial(ring_attention, axis_name=axis_name, causal=causal,
                 use_flash=use_flash)
    return _shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)(q, k, v)
