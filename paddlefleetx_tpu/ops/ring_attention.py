"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context scaling beyond the reference (SURVEY §5.7: the reference
has no ring/blockwise/context parallelism — its only sequence scaling
is Megatron-SP, which still materializes full attention per rank and
tops out at ~1k tokens). Here each device holds a sequence block; KV
blocks rotate around the ``cp`` mesh axis with ``jax.lax.ppermute``
(one ICI-neighbor hop per step — compute on the current block overlaps
the transfer of the next) while a streaming log-sum-exp accumulator
(the flash-attention recurrence) combines per-block partial outputs
into the *exact* softmax result. Peak memory per device is
O(s/N * s/N) score blocks instead of O(s * s).

Layout: ``[b, s/N, h, d]`` per device, batch over dp x fsdp, heads
over mp, sequence over cp — composes with every other axis.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _block_attn(q, k, v, scale, causal, q_start, k_start):
    """Scores + masked row-max/row-sum for one (q-block, kv-block)
    pair; returns (out_block, row_max, row_sum) in fp32."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_start + jnp.arange(sq)[:, None]
        k_pos = k_start + jnp.arange(sk)[None, :]
        scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
    m = jnp.max(scores, axis=-1)                          # [b,h,q]
    # rows with no visible key (fully masked) must not produce
    # exp(NEG_INF - NEG_INF) = 1 garbage
    safe_m = jnp.where(m <= NEG_INF / 2, 0.0, m)
    p = jnp.exp(scores - safe_m[..., None])
    p = jnp.where(scores <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)                               # noqa: E741
    out = jnp.einsum("bhqk,bkhd->bqhd", p,
                     v.astype(jnp.float32))
    return out, m, l


@partial(jax.jit, static_argnames=("axis_name", "causal", "scale"))
def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   *, axis_name: str, causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Exact attention with KV blocks rotating over ``axis_name``.

    Call under ``shard_map`` (or use :func:`ring_attention_sharded`):
    arguments are the per-device blocks ``[b, s_local, h, d]``.
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    q_start = idx * sq

    perm = [(i, (i + 1) % n) for i in range(n)]  # send KV to the right

    def step(carry, i):
        k_blk, v_blk, out, m, l = carry  # noqa: E741
        # after i rotations, this device holds the KV block that
        # originated at ring position idx - i
        src = (idx - i) % n
        blk_out, blk_m, blk_l = _block_attn(
            q, k_blk, v_blk, scale, causal, q_start, src * sq)
        new_m = jnp.maximum(m, blk_m)
        # renormalize both accumulators onto the new running max
        safe = lambda x: jnp.where(  # noqa: E731
            new_m <= NEG_INF / 2, 0.0, x)
        alpha = jnp.exp(safe(m - new_m))
        beta = jnp.exp(safe(blk_m - new_m))
        out = out * alpha[..., None].swapaxes(1, 2) + \
            blk_out * beta[..., None].swapaxes(1, 2)
        l = l * alpha + blk_l * beta  # noqa: E741
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, out, new_m, l), None

    # fresh accumulators must carry the same device-varying type as
    # the loop outputs under shard_map; deriving them from q (a
    # varying input) gives them that type on any jax version
    zero_q = jnp.sum(q.astype(jnp.float32)) * 0.0
    out0 = jnp.zeros((b, sq, h, d), jnp.float32) + zero_q
    m0 = jnp.full((b, h, sq), NEG_INF, jnp.float32) + zero_q
    l0 = jnp.zeros((b, h, sq), jnp.float32) + zero_q
    (_, _, out, _, l), _ = jax.lax.scan(
        step, (k, v, out0, m0, l0), jnp.arange(n))
    out = out / jnp.maximum(l, 1e-30)[..., None].swapaxes(1, 2)
    return out.astype(q.dtype)


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                           mesh, *, axis_name: str = None,
                           batch_axes=None, heads_axis: str = None,
                           causal: bool = True) -> jax.Array:
    """shard_map wrapper: global ``[b, s, h, d]`` -> global attention
    output, with s sharded over ``axis_name`` and the ring running
    inside. Axis defaults come from the mesh convention
    (``parallel/mesh.py``), not re-spelled strings."""
    from ..parallel.mesh import CP_AXIS, DATA_AXES, MP_AXIS
    axis_name = axis_name or CP_AXIS
    batch_axes = batch_axes or DATA_AXES
    heads_axis = heads_axis or MP_AXIS
    spec = P(batch_axes, axis_name, heads_axis, None)
    fn = partial(ring_attention, axis_name=axis_name, causal=causal)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)
