"""Grouped multi-adapter LoRA matmul for mixed-adapter decode batches.

Multi-tenant LoRA serving (docs/lora.md) puts requests for *different*
adapters in one decode batch: every batch row carries an adapter id and
the per-site delta is ``(alpha/r) * (x @ A[id]) @ B[id]`` against that
row's adapter pair. XLA expresses this as a per-row gather of the
``[A, K, r]`` / ``[A, r, N]`` adapter banks followed by batched
einsums — materializing ``[M, K, r]`` gathered weights per site. This
module instead reuses the grouped-GEMM machinery built for sort-based
MoE dispatch (``ops/pallas/grouped_matmul.py``), with adapters playing
the role of experts:

1. sort the ``M`` rows by adapter id (counting-sort layout, same as
   the MoE sort dispatch);
2. scatter them into a ``[A, C, K]`` capacity-padded group buffer
   (``C`` = M rounded to the fp32 sublane tile — decode batches are
   slot-sized, so the padding is cheap);
3. run TWO grouped GEMMs — ``x @ A`` then ``(xA) @ B`` — whose
   scalar-prefetched group boundaries skip adapters no live row uses;
4. gather the deltas back to the original row order.

Admission mirrors the other Pallas families: the grouped path raises
``NotImplementedError`` off-TPU (unless ``PFX_PALLAS_INTERPRET=1``) or
on kernel-indigestible shapes, and the caller
(``models/gpt/model.py::_LoRADelta``) falls back per site to the XLA
gather-einsum form — counted as ``lora/grouped`` vs ``lora/fallback``
so a "grouped configured but silently gathering" run is visible.

Row semantics: adapter id 0 is the reserved zero adapter (base model).
Callers zero id-0 rows before dispatch and mask the delta after it, so
whatever bank row 0 holds never reaches the output — the adapter-id-0
parity pin in tests/test_lora.py is structural, not numerical.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .pallas.flash_attention import _interpret
from .pallas.grouped_matmul import grouped_matmul


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def grouped_lora_delta(x2: jax.Array, ids: jax.Array,
                       lora_a: jax.Array,
                       lora_b: jax.Array) -> jax.Array:
    """Per-row adapter delta ``out[m] = (x2[m] @ A[ids[m]]) @ B[ids[m]]``
    through the grouped-GEMM pair.

    Args:
      x2: ``[M, K]`` flattened site input rows (id-0 rows pre-zeroed
        by the caller).
      ids: int32 ``[M]`` adapter id per row, in ``[0, A)``.
      lora_a: ``[A, K, r]`` stacked down-projection bank.
      lora_b: ``[A, r, N]`` stacked up-projection bank.

    Returns ``[M, N]`` in ``x2.dtype`` (unscaled — the caller applies
    ``alpha/r`` and the id-0 mask). Raises ``NotImplementedError``
    when the kernel cannot take the backend/shape; the caller falls
    back to the XLA gather-einsum form.
    """
    if jax.default_backend() != "tpu" and not _interpret():
        raise NotImplementedError(
            "grouped LoRA needs a TPU backend (or "
            "PFX_PALLAS_INTERPRET=1)")
    if x2.ndim != 2 or lora_a.ndim != 3 or lora_b.ndim != 3:
        raise NotImplementedError(
            f"grouped_lora_delta wants x[M,K] a[A,K,r] b[A,r,N], got "
            f"{x2.shape} / {lora_a.shape} / {lora_b.shape}")
    m, k = x2.shape
    num_adapters, k_a, r = lora_a.shape
    if k_a != k or lora_b.shape[:2] != (num_adapters, r):
        raise NotImplementedError(
            f"grouped_lora_delta bank mismatch: x {x2.shape}, a "
            f"{lora_a.shape}, b {lora_b.shape}")
    n = lora_b.shape[2]

    ids = jnp.asarray(ids, jnp.int32)
    # counting-sort layout: group g holds its rows contiguously at
    # positions 0..counts[g]-1 of its capacity block. Worst case every
    # row lands on one adapter, so capacity is M rounded to the fp32
    # sublane tile (grouped blocks are (1, C, bk)).
    capacity = _round_up(max(m, 1), 8)
    order = jnp.argsort(ids)
    sids = ids[order]
    counts = jnp.bincount(ids, length=num_adapters)
    starts = jnp.cumsum(counts) - counts
    pos = jnp.arange(m, dtype=jnp.int32) - starts[sids]
    xg = jnp.zeros((num_adapters, capacity, k), x2.dtype)
    xg = xg.at[sids, pos].set(x2[order])

    # Dispatch contract (counter + try/except fallback) lives in the
    # one caller, models/gpt/model.py::_LoRADelta — counting per GEMM
    # here would double-count the single lora site dispatch.
    h = grouped_matmul(xg, lora_a.astype(x2.dtype), counts,  # pfxlint: disable=PFX205
                       block_n=128, block_k=512)
    d = grouped_matmul(h.astype(x2.dtype), lora_b.astype(x2.dtype),  # pfxlint: disable=PFX205
                       counts, block_n=128, block_k=512)

    out_sorted = d[sids, pos]
    return jnp.zeros((m, n), x2.dtype).at[order].set(out_sorted)


def fallback_lora_delta(x2: jax.Array, ids: jax.Array,
                        lora_a: jax.Array,
                        lora_b: jax.Array) -> jax.Array:
    """XLA gather-einsum oracle of :func:`grouped_lora_delta`: per-row
    bank gathers plus two batched contractions. Always available; the
    grouped kernel is parity-pinned against this form
    (tests/test_lora.py)."""
    ids = jnp.asarray(ids, jnp.int32)
    a = lora_a.astype(x2.dtype)[ids]          # [M, K, r]
    b = lora_b.astype(x2.dtype)[ids]          # [M, r, N]
    h = jnp.einsum("mk,mkr->mr", x2, a)
    return jnp.einsum("mr,mrn->mn", h, b)
