"""Scaled dot-product attention with fused-causal-softmax semantics.

The reference fuses mask+softmax through a CUDA kernel
(``incubate.softmax_mask_fuse_upper_triangle``, reference
``single_model.py:198``) and otherwise materializes the full
``[b, heads, s, s]`` score matrix. On TPU the XLA path below already
fuses mask+softmax into the matmul epilogue; the Pallas flash-attention
kernel (``ops/pallas/flash_attention.py``) replaces it on real TPU
devices for long sequences, never materializing the score matrix.

Layout: ``q [b, sq, h, d]``, ``k/v [b, skv, h, d]`` (batch-major,
head-split), output ``[b, sq, h, d]``. With ``kv_cache_layout`` the
keys/values arrive as ``[b, h, d, skv]`` — the decode cache's native
TPU tiling (see ``models/gpt/model.py`` cache comment) — and no
relayout of the (large) cache happens on this path.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ..observability import metrics

NEG_INF = -1e9


#: certification artifact written by scripts/validate_flash_dropout.py
#: on a PASSING live-chip run (rate-0 bit-equivalence, determinism,
#: dropped-mass fraction, finite-difference fwd/bwd mask identity) and
#: committed as evidence — its presence flips the gate default on
DROPOUT_CERT_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "pallas",
    "dropout_cert.json")


def _dropout_env_force():
    """The ``PFX_FLASH_DROPOUT`` tri-state: True/False when forced,
    None to fall through to the certification artifact."""
    env = os.environ.get("PFX_FLASH_DROPOUT")
    if env is not None:
        v = env.strip().lower()
        if v in ("1", "true", "yes", "on"):
            return True
        if v in ("0", "false", "no", "off"):
            return False
        # unrecognized (including empty) must not silently veto a
        # valid certification — fall through to the artifact
    return None


#: mtime-keyed cache of the certification artifact read, so the gate
#: decision does not re-read the file on every dispatch trace; tests
#: that rewrite the artifact invalidate it naturally via mtime
_cert_cache: dict = {}


def _dropout_cert_kind():
    """``device_kind`` recorded in the certification artifact, or None
    when absent/unreadable. Pure file I/O — never touches the jax
    backend."""
    try:
        mtime = os.path.getmtime(DROPOUT_CERT_PATH)
    except OSError:
        return None
    hit = _cert_cache.get(DROPOUT_CERT_PATH)
    if hit is not None and hit[0] == mtime:
        return hit[1]
    try:
        import json
        with open(DROPOUT_CERT_PATH) as f:
            kind = json.load(f).get("device_kind") or None
    except (OSError, ValueError):
        kind = None
    _cert_cache[DROPOUT_CERT_PATH] = (mtime, kind)
    return kind


def _kernel_dropout_configured() -> bool:
    """Whether in-kernel dropout is CONFIGURED on: the env force, else
    the certification artifact's presence. Checks only the env var and
    artifact — no ``jax.devices()`` probe — so config-construction
    warning paths (``models/gpt/config.py``) can call it without
    initializing the PJRT backend as a side effect. The device-kind
    match is deferred to ``_kernel_dropout_enabled`` at
    kernel-dispatch time, where the backend is up anyway."""
    forced = _dropout_env_force()
    if forced is not None:
        return forced
    return _dropout_cert_kind() is not None


def _kernel_dropout_enabled() -> bool:
    """Gate for IN-KERNEL flash attention dropout. Self-certifying:

    - ``PFX_FLASH_DROPOUT=1`` / ``=0`` force it on / off;
    - otherwise it is on iff the chip-certification artifact
      (``DROPOUT_CERT_PATH``) exists AND its recorded ``device_kind``
      matches the attached TPU. Certification is per TPU generation —
      Mosaic PRNG semantics differ across libtpu/device kinds (the r5
      session hit a v5e-specific two-operand ``prng_seed`` limit), so
      a v5e cert must not flip the default on a v3/v4 fleet; mismatch
      falls back to dense with the documented warning. Only called at
      kernel-dispatch time — config-construction paths use
      ``_kernel_dropout_configured`` and never probe the backend."""
    forced = _dropout_env_force()
    if forced is not None:
        return forced
    kind = _dropout_cert_kind()
    if not kind:
        return False
    try:
        d = jax.devices()[0]
    except Exception:  # backend unavailable — claim nothing
        return False
    return d.platform == "tpu" and d.device_kind == kind

# Non-causal dispatch crossover: below this KV length the dense XLA
# batched matmul beats the flash kernel (measured on a v5e at ERNIE
# shapes h=768/s=512/d=64: 10.9 vs 16.7 ms fwd — no causal-mask work
# to save and too few blocks to amortize program overhead). The
# break-even moves with TPU generation and head dim; retune here.
DENSE_NONCAUSAL_MAX_SKV = 2048

# Widest multi-token window the VERIFY decode kernels take
# (speculative k-token verification, k+1 <= this). Chunked paged
# prefill also arrives as per-row-offset multi-token attention but in
# page-sized chunks (>= 128 tokens), far past any sane draft length —
# this bound keeps it on the gather + dense path the kernels were
# never shaped for (the verify kernel unrolls its window statically,
# so a huge window would also explode the program).
MAX_VERIFY_WINDOW = 32


def _gather_kv_pages(pool, page_table):
    """Resolve a paged KV pool back to per-row contiguous layout: the
    XLA-side mirror of the ``flash_decode_paged`` index-map
    indirection. ``pool [num_pages, h, d, page]`` gathered by
    ``page_table [b, max_pages]`` becomes ``[b, h, d,
    max_pages * page]`` with each row's logical positions back in
    order — after which the ordinary per-row-offset causal masking of
    :func:`_xla_attention` applies unchanged (positions past a row's
    offset are masked whatever garbage its unwritten/null pages hold).
    Materializes every row at full capacity, so it is the parity
    oracle and fallback, not the fast path."""
    g = jnp.take(pool, page_table, axis=0)     # [b, m, h, d, page]
    b, m, h, d, p = g.shape
    return g.transpose(0, 2, 3, 1, 4).reshape(b, h, d, m * p)


def _xla_attention(q, k, v, bias, causal, query_offset, dropout_rate,
                   dropout_rng, deterministic, softmax_in_fp32,
                   kv_cache_layout=False):
    head_dim = q.shape[-1]
    scale = head_dim ** -0.5
    k_eq = "bhdk" if kv_cache_layout else "bkhd"
    scores = jnp.einsum(f"bqhd,{k_eq}->bhqk", q * scale, k)
    if softmax_in_fp32:
        scores = scores.astype(jnp.float32)
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        # query i attends to keys <= i + query_offset (offset > 0 during
        # cached decode where keys include the past). A [b] offset
        # vector masks PER ROW — the XLA oracle/fallback for the
        # ragged slot decode (flash_decode_ragged): row i's mask
        # broadcasts as [b, 1, sq, sk] against the [b, h, sq, sk]
        # scores, so each slot sees exactly its own cache prefix.
        off = jnp.asarray(query_offset)
        if off.ndim == 1:
            q_pos = (jnp.arange(sq)[:, None]
                     + off[:, None, None, None])   # [b, 1, sq, 1]
        else:
            q_pos = jnp.arange(sq)[:, None] + off  # [sq, 1]
        k_pos = jnp.arange(sk)[None, :]
        scores = jnp.where(k_pos <= q_pos, scores, NEG_INF)
    if bias is not None:
        scores = scores + bias.astype(scores.dtype)
    weights = jax.nn.softmax(scores, axis=-1)
    weights = checkpoint_name(weights, "core_attn")
    if dropout_rate > 0.0 and not deterministic:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    weights.shape)
        weights = weights * keep / (1.0 - dropout_rate)
    weights = weights.astype(v.dtype)
    v_eq = "bhdk" if kv_cache_layout else "bkhd"
    out = jnp.einsum(f"bhqk,{v_eq}->bqhd", weights, v)
    return checkpoint_name(out, "core_attn")


def dot_product_attention(
        q: jax.Array, k: jax.Array, v: jax.Array,
        bias: Optional[jax.Array] = None,
        causal: bool = True,
        query_offset=0,
        dropout_rate: float = 0.0,
        dropout_rng: Optional[jax.Array] = None,
        deterministic: bool = True,
        softmax_in_fp32: bool = True,
        use_flash: bool = False,
        kv_cache_layout: bool = False,
        page_table: Optional[jax.Array] = None,
        k_scale: Optional[jax.Array] = None,
        v_scale: Optional[jax.Array] = None) -> jax.Array:
    """Causal attention; dispatches to the Pallas flash kernel on TPU.

    ``bias`` is an additive mask broadcastable to ``[b, h, sq, sk]``
    (the reference's ``attn_mask`` convention, additive -1e4 style).

    ``page_table`` (requires ``kv_cache_layout``): ``k``/``v`` are the
    PAGED pool ``[num_pages, h, d, page]`` and each row's logical
    cache is ``page_table[row]``'s pages in order (``core/paging.py``).
    Single-token ragged decode takes ``flash_decode_paged``
    (``attention/flash_decode_paged`` counter); a short multi-token
    window (``1 < sq <= MAX_VERIFY_WINDOW``, per-row offsets, no
    bias) is the speculative k-token VERIFY and takes the same kernel
    with the within-window causal mask
    (``attention/flash_decode_paged_verify`` /
    ``attention/flash_decode_ragged_verify``); everything else —
    chunked prefill, kernel rejection, ``use_flash=False`` — gathers
    the rows contiguous (:func:`_gather_kv_pages`) and rides the
    per-row-offset dense path (dispatch matrix: docs/inference.md).

    ``k_scale``/``v_scale`` (require ``kv_cache_layout``): the cache
    is int8 (``GPTConfig.kv_cache_dtype="int8"``) and these are its
    per-(row, head, position) fp32 dequant scales, shaped like the
    cache minus its d axis (``[b, h, 1, S]``, or the page-parallel
    pool ``[P, h, 1, page]``). Every kernel branch takes its
    dequant-in-kernel variant (``attention/*_int8`` counters); the
    dense fallback dequantizes the gathered rows up front and is the
    parity oracle (dispatch matrix: docs/quantization.md).
    """
    if (k_scale is None) is not (v_scale is None):
        raise ValueError("k_scale and v_scale come together")
    if k_scale is not None and not kv_cache_layout:
        raise ValueError("KV scales require kv_cache_layout (the "
                         "int8 cache is decode-only)")
    skv = k.shape[3] if kv_cache_layout else k.shape[1]
    if page_table is not None:
        if not kv_cache_layout:
            raise ValueError("page_table requires kv_cache_layout")
        skv = page_table.shape[1] * k.shape[3]
    # training dropout on the kernel path: in-kernel philox masks
    # (reference fused softmax-with-dropout, hybrid_model.py:277-285).
    # Bias (ERNIE padding masks, GPT attn_mask) rides into the kernel
    # as a tiled operand, causal or not; no DENSE_NONCAUSAL crossover
    # here — the dense path pays the [b, h, sq, sk] dropout-mask
    # traffic on top of the score materialization, so the kernel wins
    # at every training shape
    # dispatch counters fire at trace time (once per compiled shape,
    # not per step) into the process-global registry — free when
    # telemetry is off, and they let the flight recorder / summary
    # attest which lowering each run actually took
    if (use_flash and dropout_rate > 0.0 and not deterministic
            and dropout_rng is not None
            and not kv_cache_layout):
        if _kernel_dropout_enabled():
            try:
                from .pallas import flash_attention as fa
                out = fa.flash_attention(q, k, v, causal=causal,
                                         query_offset=query_offset,
                                         dropout_rate=dropout_rate,
                                         dropout_rng=dropout_rng,
                                         bias=bias)
                metrics.inc("attention/flash_dropout")
                return out
            except (ImportError, NotImplementedError):
                metrics.inc("attention/fallback/kernel_rejected")
        else:
            metrics.inc("attention/fallback/dropout_gate_off")
    # deterministic makes a configured dropout_rate inert, so eval and
    # generation may take the kernel even when training cannot
    if use_flash and (deterministic or dropout_rate == 0.0):
        # the decode kernel takes a per-key additive bias (generation's
        # left-pad mask: [b, 1, 1, skv]); the training kernel takes
        # any bias broadcastable to [b, h, sq, skv]
        decode_bias_ok = causal and q.shape[1] == 1 and (
            bias is None or
            (bias.ndim == 4 and bias.shape[1] == bias.shape[2] == 1
             and bias.shape[0] == q.shape[0]
             and bias.shape[-1] == skv))
        try:
            from .pallas import flash_attention as fa
            if kv_cache_layout and page_table is not None:
                if causal and q.shape[1] == 1 and bias is None and \
                        getattr(query_offset, "ndim", 0) == 1:
                    # paged ragged decode: the kernel's scalar
                    # prefetch walks the slot->page indirection table
                    # (flash_decode_paged) — each row streams only its
                    # own pages
                    out = fa.flash_decode_paged(q, k, v, query_offset,
                                                page_table,
                                                k_scale=k_scale,
                                                v_scale=v_scale)
                    if k_scale is not None:
                        metrics.inc("attention/flash_decode_paged_int8")
                    else:
                        metrics.inc("attention/flash_decode_paged")
                    return out
                if causal and 1 < q.shape[1] <= MAX_VERIFY_WINDOW \
                        and bias is None \
                        and getattr(query_offset, "ndim", 0) == 1:
                    # speculative k-token verify over the paged pool:
                    # same table walk, within-window causal mask
                    # (docs/inference.md, speculative decoding)
                    out = fa.flash_decode_paged(q, k, v, query_offset,
                                                page_table,
                                                k_scale=k_scale,
                                                v_scale=v_scale)
                    if k_scale is not None:
                        metrics.inc(
                            "attention/flash_decode_paged_verify_int8")
                    else:
                        metrics.inc(
                            "attention/flash_decode_paged_verify")
                    return out
                # chunked prefill (page-sized sq) and other paged
                # shapes fall through to the shared kv_cache_layout
                # fallback counter and the gather + dense path below
            elif decode_bias_ok and kv_cache_layout:
                if getattr(query_offset, "ndim", 0) == 1:
                    # ragged slot decode: a [b] offset vector (the
                    # continuous-batching server's per-slot lengths) —
                    # each row masks and block-skips against its OWN
                    # last valid position
                    out = fa.flash_decode_ragged(q, k, v, query_offset,
                                                 bias=bias,
                                                 k_scale=k_scale,
                                                 v_scale=v_scale)
                    if k_scale is not None:
                        metrics.inc(
                            "attention/flash_decode_ragged_int8")
                    else:
                        metrics.inc("attention/flash_decode_ragged")
                    return out
                # cached decode: single query token, dynamic cache
                # index — the kernel skips blocks past the index
                out = fa.flash_decode(q, k, v, query_offset,
                                      bias=bias, k_scale=k_scale,
                                      v_scale=v_scale)
                if k_scale is not None:
                    metrics.inc("attention/flash_decode_int8")
                else:
                    metrics.inc("attention/flash_decode")
                return out
            elif kv_cache_layout and causal and bias is None \
                    and 1 < q.shape[1] <= MAX_VERIFY_WINDOW \
                    and getattr(query_offset, "ndim", 0) == 1:
                # speculative k-token verify over the contiguous slot
                # cache: window query j of row i masks keys
                # <= query_offset[i] + j (within-window causal mask)
                out = fa.flash_decode_ragged(q, k, v, query_offset,
                                             k_scale=k_scale,
                                             v_scale=v_scale)
                if k_scale is not None:
                    metrics.inc(
                        "attention/flash_decode_ragged_verify_int8")
                else:
                    metrics.inc("attention/flash_decode_ragged_verify")
                return out
            # non-causal at short seq: the dense XLA batched matmul
            # beats the kernel (measured on ERNIE h=768/s=512/d=64:
            # 10.9 vs 16.7 ms fwd — no causal-mask work to save and
            # too few blocks to amortize program overhead); the kernel
            # wins causally (mask never materializes) and at long
            # sequences in either mode
            flash_worthwhile = causal or skv >= DENSE_NONCAUSAL_MAX_SKV
            if not kv_cache_layout and flash_worthwhile:
                out = fa.flash_attention(q, k, v, causal=causal,
                                         query_offset=query_offset,
                                         bias=bias)
                metrics.inc("attention/flash")
                return out
            metrics.inc("attention/fallback/kv_cache_layout"
                        if kv_cache_layout
                        else "attention/fallback/short_noncausal")
        except (ImportError, NotImplementedError):
            metrics.inc("attention/fallback/kernel_rejected")
    elif not use_flash:
        metrics.inc("attention/fallback/flash_disabled")
    metrics.inc("attention/dense")
    if page_table is not None:
        # matching indirection for the dense path: gather each row's
        # pages back into contiguous [b, h, d, capacity] order, after
        # which the per-row offset masking below needs no page
        # awareness at all
        k = _gather_kv_pages(k, page_table)
        v = _gather_kv_pages(v, page_table)
        if k_scale is not None:
            k_scale = _gather_kv_pages(k_scale, page_table)
            v_scale = _gather_kv_pages(v_scale, page_table)
    if k_scale is not None:
        # dense oracle for the int8 cache: widen up front with the
        # same per-(row, head, position) scales the kernels apply
        # in-VMEM, then attend exactly as bf16 would
        k = (k.astype(jnp.float32) * k_scale).astype(q.dtype)
        v = (v.astype(jnp.float32) * v_scale).astype(q.dtype)
    return _xla_attention(q, k, v, bias, causal, query_offset, dropout_rate,
                          dropout_rng, deterministic, softmax_in_fp32,
                          kv_cache_layout=kv_cache_layout)
