"""Grouped (per-expert) matmul for sort-based MoE dispatch, in Pallas.

The sort-based MoE dispatch (``models/gpt/moe.py``,
``moe_dispatch="sort*"``) gathers routed tokens into a contiguous
``[E·b, C, h]`` buffer of per-(expert, batch-row) groups, each padded
to the static capacity ``C``. The expert FFN is then G independent
matmuls against per-expert weights — a *grouped* GEMM. XLA expresses
it as one dense batched matmul over all ``G·C`` slots; this kernel
instead iterates the expert group boundaries carried by the routing
counts and **skips groups no token routed to** (their padded rows are
zero, so the skipped matmul is exactly the zero block the dense form
would have produced — bit-identical outputs, less MXU work; at the
shipped ep8 config's load imbalance a third of (expert, row) groups
are routinely empty).

Layout: ``x [G, C, K]`` groups, ``w [Gw, K, N]`` per-expert weights
with ``G == Gw * rep`` (``rep`` batch rows share one expert's weight),
``counts [G]`` int32 live rows per group delivered by scalar prefetch
(``PrefetchScalarGridSpec`` — the counts land in SMEM before the grid
body runs, so the skip predicate costs no HBM traffic). The grid is
``(G, N/bn, K/bk)`` with the K axis innermost-sequential, accumulating
in fp32 VMEM scratch exactly like ``flash_attention.py``; the backward
is wired through ``jax.custom_vjp``: dx reuses the forward kernel with
``w`` transposed, dw is a second kernel accumulating ``xᵀ·dy`` over
each expert's ``rep`` groups. Interpret mode
(``PFX_PALLAS_INTERPRET=1``) lets the CPU test suite validate kernel
semantics (tests/test_grouped_matmul.py) without a TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _dot, _interpret, _sds


def _block(dim: int, target: int) -> int:
    """Largest power-of-two-shrunk block <= target dividing ``dim``
    (1 always divides, so the shrink terminates)."""
    b = max(1, min(target, dim))
    while dim % b:
        b //= 2
    return b


def _gmm_kernel(counts_ref, x_ref, w_ref, o_ref, acc_scr, *, num_k):
    """out[g] = x[g] @ w[g // rep], skipping empty groups.

    Scratch accumulates fp32 across the sequential ki axis; a group
    with zero live rows never touches the MXU — its scratch stays the
    zeros ``_init`` wrote, which IS the product of its all-zero padded
    rows, so skipping preserves bitwise output parity with the dense
    batched matmul."""
    g = pl.program_id(0)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(counts_ref[g] > 0)
    def _accumulate():
        acc_scr[:] += _dot(x_ref[0], w_ref[0])

    @pl.when(ki == num_k - 1)
    def _finish():
        o_ref[0] = acc_scr[:].astype(o_ref.dtype)


def _gmm_dw_kernel(counts_ref, x_ref, dy_ref, dw_ref, acc_scr, *,
                   rep):
    """dw[e] = sum over e's ``rep`` groups of x[g]ᵀ @ dy[g].

    The group axis is innermost-sequential so the [K, bn] scratch
    accumulates one expert's contributions before moving on; empty
    groups are skipped (their x rows are zero — no contribution)."""
    e = pl.program_id(0)
    gi = pl.program_id(2)

    @pl.when(gi == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(counts_ref[e * rep + gi] > 0)
    def _accumulate():
        acc_scr[:] += _dot(x_ref[0], dy_ref[0], trans_a=True)

    @pl.when(gi == rep - 1)
    def _finish():
        dw_ref[0] = acc_scr[:].astype(dw_ref.dtype)


def _gmm_forward(x, w, counts, block_n, block_k):
    """One grouped-GEMM pallas_call: ``[G, C, K] @ [Gw, K, N] ->
    [G, C, N]`` with per-group skip from ``counts``."""
    g_groups, c_rows, k_dim = x.shape
    w_groups, _, n_dim = w.shape
    rep = g_groups // w_groups
    bn = _block(n_dim, block_n)
    bk = _block(k_dim, block_k)
    num_n, num_k = n_dim // bn, k_dim // bk
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(g_groups, num_n, num_k),
        in_specs=[
            pl.BlockSpec((1, c_rows, bk),
                         lambda g, ni, ki, c_ref: (g, 0, ki)),
            pl.BlockSpec((1, bk, bn),
                         lambda g, ni, ki, c_ref, _r=rep:
                         (g // _r, ki, ni)),
        ],
        out_specs=pl.BlockSpec((1, c_rows, bn),
                               lambda g, ni, ki, c_ref: (g, 0, ni)),
        scratch_shapes=[pltpu.VMEM((c_rows, bn), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(_gmm_kernel, num_k=num_k),
        grid_spec=grid_spec,
        out_shape=_sds((g_groups, c_rows, n_dim), x.dtype, x),
        interpret=_interpret(),
    )(counts, x, w)


def _gmm_dw(x, dy, counts, w_groups, block_n):
    """dw pallas_call: fp32 ``[Gw, K, N]`` cotangent of the weights."""
    g_groups, c_rows, k_dim = x.shape
    n_dim = dy.shape[-1]
    rep = g_groups // w_groups
    bn = _block(n_dim, block_n)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(w_groups, n_dim // bn, rep),
        in_specs=[
            pl.BlockSpec((1, c_rows, k_dim),
                         lambda e, ni, gi, c_ref, _r=rep:
                         (e * _r + gi, 0, 0)),
            pl.BlockSpec((1, c_rows, bn),
                         lambda e, ni, gi, c_ref, _r=rep:
                         (e * _r + gi, 0, ni)),
        ],
        out_specs=pl.BlockSpec((1, k_dim, bn),
                               lambda e, ni, gi, c_ref: (e, 0, ni)),
        scratch_shapes=[pltpu.VMEM((k_dim, bn), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(_gmm_dw_kernel, rep=rep),
        grid_spec=grid_spec,
        out_shape=_sds((w_groups, k_dim, n_dim), jnp.float32, x),
        interpret=_interpret(),
    )(counts, x, dy)


def _check_shapes(x, w, counts):
    """Kernel admission: a ``NotImplementedError`` here sends the MoE
    layer to its XLA expert-einsum fallback (counted as
    ``moe/fallback/pallas_rejected`` — docs/moe.md)."""
    if x.ndim != 3 or w.ndim != 3 or counts.ndim != 1:
        raise NotImplementedError(
            f"grouped_matmul wants x[G,C,K] w[Gw,K,N] counts[G], got "
            f"{x.shape} / {w.shape} / {counts.shape}")
    if x.shape[0] != counts.shape[0] or \
            x.shape[0] % w.shape[0] or x.shape[2] != w.shape[1]:
        raise NotImplementedError(
            f"grouped_matmul shape mismatch: x {x.shape}, w {w.shape},"
            f" counts {counts.shape}")
    if not jnp.issubdtype(counts.dtype, jnp.integer):
        raise NotImplementedError("counts must be integer")


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _grouped_matmul(x, w, counts, block_n, block_k):
    return _gmm_forward(x, w, counts, block_n, block_k)


def _grouped_matmul_fwd(x, w, counts, block_n, block_k):
    return (_gmm_forward(x, w, counts, block_n, block_k),
            (x, w, counts))


def _grouped_matmul_bwd(block_n, block_k, res, g):
    x, w, counts = res
    # dx[g] = dy[g] @ w[g // rep]ᵀ — the forward kernel with w
    # transposed; empty groups skip in BOTH directions, so dx is zero
    # exactly where the dense form's zero dy rows would have made it
    dx = _gmm_forward(g, jnp.swapaxes(w, 1, 2), counts, block_k,
                      block_n)
    dw = _gmm_dw(x, g, counts, w.shape[0], block_n)
    return (dx.astype(x.dtype), dw.astype(w.dtype),
            np.zeros(counts.shape, jax.dtypes.float0))


_grouped_matmul.defvjp(_grouped_matmul_fwd, _grouped_matmul_bwd)


def grouped_matmul(x: jax.Array, w: jax.Array, counts: jax.Array,
                   block_n: int = 128, block_k: int = 512) -> jax.Array:
    """Per-group matmul ``out[g] = x[g] @ w[g // (G//Gw)]`` that skips
    groups with ``counts[g] == 0``.

    Args:
      x: ``[G, C, K]`` — G groups of C capacity-padded rows (rows past
        ``counts[g]`` MUST be zero; the sort dispatch guarantees it).
      w: ``[Gw, K, N]`` — per-expert weights, ``Gw`` divides ``G``;
        consecutive blocks of ``G // Gw`` groups share one weight.
      counts: int32 ``[G]`` live rows per group (a trace-time array —
        fresh routing per step must not retrace; delivered to the
        kernels by scalar prefetch).
      block_n / block_k: N/K tile targets (shrunk to divisors).

    Returns ``[G, C, N]`` in ``x.dtype``, accumulated in fp32. The
    custom VJP computes dx with the same kernel (w transposed) and dw
    with a per-expert accumulation kernel — both honor the same
    empty-group skip. The skip is gradient-exact under the MoE
    contract: dw loses nothing (a skipped group's x rows are zero)
    and dx loses nothing because an empty group's outputs are pure
    capacity padding that the combine step zero-weights, so its
    cotangent rows arrive as zeros.
    """
    _check_shapes(x, w, counts)
    return _grouped_matmul(x, w, counts.astype(jnp.int32), block_n,
                           block_k)
