"""pallas subpackage."""
