"""Pallas subpackage."""
