"""Pallas subpackage.

Kernels are imported lazily by their dispatch sites (ops/attention.py,
models/gpt/moe.py) so an environment where the Pallas import itself
fails still runs every XLA fallback path; importing THIS package stays
side-effect free for the same reason.
"""
