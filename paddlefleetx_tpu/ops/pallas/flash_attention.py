"""Causal flash attention for TPU, written in Pallas.

Replaces the reference's fused CUDA causal softmax
(``incubate.softmax_mask_fuse_upper_triangle``, reference
``single_model.py:198`` / ``hybrid_model.py:277``) — and goes further:
the reference still materializes the full ``[b, h, s, s]`` score
matrix (SURVEY.md §5.7); this kernel never does. FlashAttention-2
style: online softmax over KV blocks with running max / sum / output
accumulator held in VMEM scratch, fp32 accumulation, bf16 block
matmuls on the MXU. Forward saves the per-row logsumexp; backward is
two more Pallas kernels (dKV over the KV-block grid, dQ over the
Q-block grid) wired through ``jax.custom_vjp``.

Layout: ``[b, s, h, d]`` at the API, ``[b*h, s, d]`` internally; the
TPU grid is ``(bh, outer_block, inner_block)`` — the innermost axis
runs sequentially on-core, so VMEM scratch persists across the inner
loop.

Measured forward throughput on one v5e (b=2, h=16, d=64, causal, r2):
``s=2048`` 6 TF/s (1.3x the dense XLA path), ``s=4096`` 16 TF/s
(4.1x dense), ``s=8192`` 23 TF/s (dense materializes [b,h,s,s] and
stops being viable). Utilization grows with s because the fraction of
fully-live interior blocks (which skip mask arithmetic) grows and the
per-program overhead amortizes; at short s the kernel is bound by the
online-softmax exp passes, not the MXU (see
projects/gpt/docs/single_card.md for the step-level analysis).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret() -> bool:
    """Interpreter mode lets CPU tests validate kernel semantics
    (``PFX_PALLAS_INTERPRET=1``)."""
    return os.environ.get("PFX_PALLAS_INTERPRET") == "1"


def _bf16_exp() -> bool:
    """Opt-in bf16 exp in the online softmax (perf playbook lever #2):
    halves the VPU transcendental work that bounds the kernel at
    d=64/short-s. Numerics: the exp argument ``s - m_new`` is in
    [-inf, 0] where bf16's 8-bit mantissa costs ~2^-8 relative — the
    fp32 accumulation of l/acc is unchanged. Only enable with
    TPU-validated tolerances (tests/test_flash_attention.py on chip);
    interpret mode cannot certify TPU VPU numerics."""
    return os.environ.get("PFX_FLASH_BF16_EXP") == "1"
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_KV = 1024


def _dropout_threshold(rate: float):
    """uint32 comparison threshold: keep a lane iff its random bits
    fall below ``(1-rate) * 2^32`` (clamped — a tiny nonzero rate must
    keep nearly everything, not wrap to zero)."""
    return jnp.uint32(min(4294967295,
                          int(round((1.0 - rate) * 4294967296.0))))


def _interpret_random_bits(seed, fold, block_q, block_kv):
    """Counter-based uint32 bits for INTERPRET mode only: pltpu's
    per-core PRNG has no CPU lowering, so off-TPU the keep mask comes
    from a stateless murmur3-style finalizer over (seed, block fold,
    lane coordinates). Same regenerability contract as the TPU path —
    a pure function of the same inputs, so forward and backward
    rebuild identical masks — but a DIFFERENT bit pattern: interpret
    runs validate dropout semantics and plumbing, never TPU numerics
    (those are certified on-chip by scripts/validate_flash_dropout.py).
    Module-level so tests can rebuild the exact mask for a dense
    oracle."""
    r = jax.lax.broadcasted_iota(jnp.uint32, (block_q, block_kv), 0)
    c = jax.lax.broadcasted_iota(jnp.uint32, (block_q, block_kv), 1)
    x = (jnp.asarray(seed, jnp.int32).astype(jnp.uint32)
         * jnp.uint32(0x9E3779B1)
         + jnp.asarray(fold, jnp.int32).astype(jnp.uint32)
         * jnp.uint32(0x85EBCA77)
         + r * jnp.uint32(0xC2B2AE3D) + c * jnp.uint32(0x27D4EB2F))
    x = (x ^ (x >> 15)) * jnp.uint32(0x2C1B3C6D)
    x = (x ^ (x >> 12)) * jnp.uint32(0x297A2D39)
    return x ^ (x >> 15)


def _block_keep_mask(seed_ref, b, qi, ki, n_q, n_kv, rate, block_q,
                     block_kv):
    """Regenerable [block_q, block_kv] keep mask for score block
    (b, qi, ki): the per-core PRNG is reseeded from (run seed, block
    coordinates) so forward and every backward kernel reproduce the
    SAME mask for the same block regardless of their grid iteration
    order (the backward grids iterate (ki, qi)).

    The coordinates are folded mixed-radix into ONE value — Mosaic's
    ``prng_set_seed_32`` rejects more than two seed operands on v5e
    libtpu ("Setting seed with more than 2 values is not supported",
    r5 chip cert) — using the STATIC block counts (n_q, n_kv) shared
    by the forward and backward pallas_calls, so the fold is injective
    and kernel-order independent. Callers guard the fold against i32
    overflow. Interpret mode substitutes the stateless hash above for
    the (TPU-only) hardware PRNG."""
    fold = (b * n_q + qi) * n_kv + ki
    if _interpret():
        bits = _interpret_random_bits(seed_ref[0], fold, block_q,
                                      block_kv)
    else:
        pltpu.prng_seed(seed_ref[0], fold)
        bits = pltpu.bitcast(pltpu.prng_random_bits((block_q, block_kv)),
                             jnp.uint32)
    return bits < _dropout_threshold(rate)


def _auto_block(s: int, target: int, align: int) -> int:
    """Largest power-of-two-shrunk block <= target that divides s.
    1024 blocks measure fastest on v5e at training shapes (b=8/h=16/
    s=1024/d=64: fwd+bwd 1.89 ms vs 2.42 ms with 512 blocks — fewer
    program launches and mask-free interior work amortize better);
    halving keeps odd lengths (1536, 2560, ...) on the kernel instead
    of falling back to the dense path."""
    b = min(target, s)
    while b > align and (s % b or b % align):
        b //= 2
    return b


def _causal_mask(qi, ki, block_q, block_kv, offset):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 0) + offset
    k_pos = ki * block_kv + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 1)
    return k_pos <= q_pos


def _dot(a, b, trans_a=False, trans_b=False):
    dims = ((0,) if trans_a else (1,), (1,) if trans_b else (0,))
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


# -- forward -----------------------------------------------------------


def _online_update(s, v, m_scr, l_scr, acc_scr, drop_fn=None):
    """One online-softmax accumulator step over a masked score block
    (the training forward's MXU formulation; the decode kernel
    vectorizes the same recurrence over heads with VPU reduces —
    semantic parity between the two is pinned by
    ``tests/test_flash_attention.py`` decode-vs-XLA cases).

    ``drop_fn`` (in-kernel attention dropout): the normalizer ``l``
    accumulates the FULL ``p`` — dropout multiplies the normalized
    probabilities, and the row division by ``l`` is uniform, so
    ``dropout(softmax(s)) @ v == (sum keep*p/keep_prob @ v) / l`` —
    while only the value-matmul operand is masked+rescaled."""
    m_prev = m_scr[:]                              # [bq, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    if _bf16_exp():
        # bf16 transcendental, fp32 accumulate (lever #2; opt-in)
        p = jnp.exp((s - m_new).astype(jnp.bfloat16))
        l_scr[:] = l_scr[:] * alpha + jnp.sum(
            p.astype(jnp.float32), axis=1, keepdims=True)
    else:
        p = jnp.exp(s - m_new)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
    pv = p if drop_fn is None else drop_fn(p)
    acc_scr[:] = acc_scr[:] * alpha + _dot(pv.astype(v.dtype), v)
    m_scr[:] = m_new


def _live_interior(qi, ki, block_q, block_kv, causal, query_offset):
    """(live, interior): whether the (qi, ki) score block has any
    unmasked entry, and whether it is FULLY unmasked (strictly below
    the causal diagonal). Interior blocks skip the iota/compare/where
    mask arithmetic entirely. At s=1024/512-blocks only a third of
    live blocks are interior, so the gain is within measurement noise
    there (the kernel is exp-pass-bound); the fraction — and the
    payoff — grows with sequence length (78% interior at s=4096)."""
    if not causal:
        return ki >= 0, True
    live = qi * block_q + block_q - 1 + query_offset >= ki * block_kv
    interior = ki * block_kv + block_kv - 1 <= qi * block_q + query_offset
    return live, interior


def _masked_dispatch(block_fn, qi, ki, block_q, block_kv, causal,
                     query_offset):
    """Run ``block_fn(masked)`` under ``pl.when``: the masked variant
    on diagonal-crossing blocks, the mask-free variant on fully-live
    interior blocks, nothing on dead blocks. Single definition so the
    three kernels cannot diverge."""
    live, interior = _live_interior(qi, ki, block_q, block_kv, causal,
                                    query_offset)
    if causal:
        pl.when(live & jnp.logical_not(interior))(
            lambda: block_fn(True))
        pl.when(interior)(lambda: block_fn(False))
    else:
        pl.when(live)(lambda: block_fn(False))


def _fwd_kernel(q_ref, k_ref, v_ref, *refs, sm_scale, causal, block_q,
                block_kv, num_kv, query_offset, dropout_rate=0.0,
                seed_ref=None, num_q=None, has_bias=False):
    if has_bias:
        bias_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    else:
        bias_ref = None
        o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    # hoisted OUTSIDE the pl.when blocks: 0.4.x interpret mode cannot
    # substitute program_id inside a cond closure
    bhi = pl.program_id(0)
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _block(masked: bool):
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        # sm_scale rides on q ([bq, d]) instead of on the [bq, bkv]
        # score block — 1/8th the multiplies at d=64/bkv=512
        q = (q.astype(jnp.float32) * sm_scale).astype(q.dtype)
        s = _dot(q, k, trans_b=True)                   # [bq, bkv] f32
        if masked:
            s = jnp.where(
                _causal_mask(qi, ki, block_q, block_kv, query_offset),
                s, NEG_INF)
        if has_bias:
            # additive bias tile ([bq|1, bkv] broadcasts over rows for
            # the [b,1,1,sk] padding-mask form), AFTER the causal mask
            # like the XLA path — -1e9-style mask values on top of the
            # -1e30 causal fill stay very negative
            s = s + bias_ref[0, 0].astype(jnp.float32)
        drop_fn = None
        if dropout_rate > 0.0:
            def drop_fn(p):
                keep = _block_keep_mask(
                    seed_ref, bhi, qi, ki, num_q, num_kv,
                    dropout_rate, block_q, block_kv)
                return jnp.where(keep, p / (1.0 - dropout_rate),
                                 jnp.zeros_like(p))
        _online_update(s, v, m_scr, l_scr, acc_scr, drop_fn)

    _masked_dispatch(_block, qi, ki, block_q, block_kv, causal,
                     query_offset)

    @pl.when(ki == num_kv - 1)
    def _finish():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = (m_scr[:] + jnp.log(l))


def _vma(x):
    """Varying-across-mesh axes of a traced value — pallas out_shapes
    must carry them for shard_map's vma checker to accept the call
    (outputs vary exactly where q does). jax 0.4.x has neither
    ``jax.typeof`` nor the vma concept; there the checker doesn't
    exist either, so None is correct."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return None
    return getattr(typeof(x), "vma", None)


def _sds(shape, dtype, ref):
    """ShapeDtypeStruct carrying ``ref``'s vma when this jax supports
    the kwarg (0.4.x ShapeDtypeStruct rejects it)."""
    vma = _vma(ref)
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _bias_spec(bias, num_heads, block_q, block_kv, qk_of_ids):
    """BlockSpec for a canonical ``[b0, h0, q0, skv]`` additive bias
    (each leading dim 1 or full — ``_canon_bias``) on a bh-flattened
    grid: broadcast dims pin their block index to 0 so the SAME tile
    is re-referenced (Pallas elides the redundant copies), full dims
    follow the program's (batch, head, q-block, kv-block) coordinates.
    ``qk_of_ids`` maps the grid ids to (qi, ki) — the three backward
    grids iterate in different orders."""
    b0, h0, q0, _ = bias.shape
    bq_b = block_q if q0 > 1 else 1

    def idx(*ids):
        qi, ki = qk_of_ids(*ids)
        return ((ids[0] // num_heads) if b0 > 1 else 0,
                (ids[0] % num_heads) if h0 > 1 else 0,
                qi if q0 > 1 else 0,
                ki)

    return pl.BlockSpec((1, 1, bq_b, block_kv), idx)


def _flash_forward(q, k, v, sm_scale, causal, query_offset, block_q,
                   block_kv, dropout_rate=0.0, seed=None, bias=None,
                   num_heads=None):
    bh, sq, d = q.shape
    skv = k.shape[1]
    num_q, num_kv = sq // block_q, skv // block_kv
    out_shape = [
        _sds((bh, sq, d), q.dtype, q),
        _sds((bh, sq, 1), jnp.float32, q),
    ]
    scratch = [
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, d), jnp.float32),
    ]
    # ONE spec set for both paths (the dropout path lifts the index
    # maps for the prefetched scalar, _lift_spec)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
        pl.BlockSpec((1, block_kv, d), lambda b, qi, ki: (b, ki, 0)),
        pl.BlockSpec((1, block_kv, d), lambda b, qi, ki: (b, ki, 0)),
    ]
    out_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, qi, ki: (b, qi, 0)),
    ]
    operands = [q, k, v]
    if bias is not None:
        in_specs.append(_bias_spec(bias, num_heads, block_q, block_kv,
                                   lambda b, qi, ki: (qi, ki)))
        operands.append(bias)
    if dropout_rate > 0.0:
        # the mixed-radix (b, qi, ki) seed fold must stay within i32
        if bh * num_q * num_kv >= 2 ** 31:
            raise NotImplementedError(
                "dropout seed fold overflows i32 for this grid")
        kernel = functools.partial(
            _seeded(_fwd_kernel), sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_kv=block_kv, num_kv=num_kv,
            query_offset=query_offset, dropout_rate=dropout_rate,
            num_q=num_q, has_bias=bias is not None)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, num_q, num_kv),
            in_specs=[_lift_spec(s) for s in in_specs],
            out_specs=[_lift_spec(s) for s in out_specs],
            scratch_shapes=scratch,
        )
        return pl.pallas_call(
            kernel, grid_spec=grid_spec, out_shape=out_shape,
            interpret=_interpret(),
        )(seed, *operands)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_kv=block_kv, num_kv=num_kv, query_offset=query_offset,
        has_bias=bias is not None)
    return pl.pallas_call(
        kernel,
        grid=(bh, num_q, num_kv),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=_interpret(),
    )(*operands)


# -- backward ----------------------------------------------------------


def _bwd_block_math(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    masked, qi, ki, sm_scale, block_q, block_kv,
                    query_offset, dropout_rate=0.0, seed_ref=None,
                    num_q=None, num_kv=None, bias_ref=None, bhi=None):
    """Score-block recomputation shared by all backward kernels:
    ``(q_s, p_dv, ds)`` with q pre-scaled (so dk = ds^T @ q_s absorbs
    one sm_scale factor and the OTHER stays pending on dq — the caller
    applies it once on [bq, d]). Single definition so the backward
    kernels cannot diverge (same contract as ``_masked_dispatch``).

    With dropout the SAME per-block keep mask as the forward is
    regenerated from (seed, b, qi, ki). Writing the dropped
    probabilities p~ = keep*p/keep_prob, the chain rule gives
    ``dv = p~^T @ do`` and ``ds = p * (keep*dp/keep_prob - delta)``
    with ``delta = rowsum(do*o) = rowsum(p~ * dp)`` — the caller's
    delta needs no change."""
    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    lse, delta = lse_ref[0], delta_ref[0]               # [bq, 1]
    q_s = (q.astype(jnp.float32) * sm_scale).astype(q.dtype)
    s = _dot(q_s, k, trans_b=True)                      # [bq, bkv]
    if masked:
        s = jnp.where(
            _causal_mask(qi, ki, block_q, block_kv, query_offset),
            s, NEG_INF)
    if bias_ref is not None:
        # same post-mask position as the forward: lse was computed on
        # the biased scores, so p = exp(s + bias - lse) reconstructs
        # the forward's probabilities exactly
        s = s + bias_ref[0, 0].astype(jnp.float32)
    p = jnp.exp(s - lse)                                # [bq, bkv]
    dp = _dot(do, v, trans_b=True)                      # [bq, bkv]
    p_dv = p
    if dropout_rate > 0.0:
        keep = _block_keep_mask(seed_ref, bhi, qi, ki,
                                num_q, num_kv, dropout_rate, block_q,
                                block_kv)
        inv = 1.0 / (1.0 - dropout_rate)
        p_dv = jnp.where(keep, p * inv, jnp.zeros_like(p))
        dp = jnp.where(keep, dp * inv, jnp.zeros_like(dp))
    ds = p * (dp - delta)
    return q_s, p_dv, ds


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    *refs, sm_scale, causal, block_q, block_kv, num_q,
                    query_offset, dropout_rate=0.0, seed_ref=None,
                    num_kv=None, has_bias=False):
    if has_bias:
        bias_ref, dk_ref, dv_ref, dk_scr, dv_scr = refs
    else:
        bias_ref = None
        dk_ref, dv_ref, dk_scr, dv_scr = refs
    bhi = pl.program_id(0)
    ki, qi = pl.program_id(1), pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _block(masked: bool):
        q_s, p_dv, ds = _bwd_block_math(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, masked,
            qi, ki, sm_scale, block_q, block_kv, query_offset,
            dropout_rate, seed_ref, num_q, num_kv, bias_ref, bhi)
        dv_scr[:] += _dot(p_dv.astype(do_ref.dtype), do_ref[0],
                          trans_a=True)
        dk_scr[:] += _dot(ds.astype(q_s.dtype), q_s, trans_a=True)

    _masked_dispatch(_block, qi, ki, block_q, block_kv, causal,
                     query_offset)

    @pl.when(qi == num_q - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   *refs, sm_scale, causal, block_q, block_kv, num_kv,
                   query_offset, dropout_rate=0.0, seed_ref=None,
                   num_q=None, has_bias=False):
    if has_bias:
        bias_ref, dq_ref, dq_scr = refs
    else:
        bias_ref = None
        dq_ref, dq_scr = refs
    bhi = pl.program_id(0)
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _block(masked: bool):
        _, _, ds = _bwd_block_math(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, masked,
            qi, ki, sm_scale, block_q, block_kv, query_offset,
            dropout_rate, seed_ref, num_q, num_kv, bias_ref, bhi)
        dq_scr[:] += _dot(ds.astype(k_ref.dtype), k_ref[0])

    _masked_dispatch(_block, qi, ki, block_q, block_kv, causal,
                     query_offset)

    @pl.when(ki == num_kv - 1)
    def _finish():
        dq_ref[0] = (dq_scr[:] * sm_scale).astype(dq_ref.dtype)


def _bwd_combined_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                         delta_ref, *refs, sm_scale, causal, block_q,
                         block_kv, num_kv, query_offset,
                         dropout_rate=0.0, seed_ref=None,
                         has_bias=False):
    """Combined backward for the ``num_q == 1`` regime (the training
    hot path: s <= block_q, and every ring-attention shard): ONE pass
    over the ki blocks produces dq, dk, AND dv — the split kernel
    pair recomputes each score block and its exp twice (the pair
    measured 33.7 ms of the 345M microbatch backward; combined 24).
    With a single q block, dq accumulates in VMEM scratch exactly
    like the split dq kernel, while each ki's dk/dv block is visited
    once and written directly."""
    if has_bias:
        bias_ref, dq_ref, dk_ref, dv_ref, dq_scr = refs
    else:
        bias_ref = None
        dq_ref, dk_ref, dv_ref, dq_scr = refs
    bhi = pl.program_id(0)
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _block(masked: bool):
        q_s, p_dv, ds = _bwd_block_math(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, masked,
            0, ki, sm_scale, block_q, block_kv, query_offset,
            dropout_rate, seed_ref, 1, num_kv, bias_ref, bhi)
        dv_ref[0] = _dot(p_dv.astype(do_ref.dtype), do_ref[0],
                         trans_a=True).astype(dv_ref.dtype)
        dk_ref[0] = _dot(ds.astype(q_s.dtype), q_s,
                         trans_a=True).astype(dk_ref.dtype)
        dq_scr[:] += _dot(ds.astype(k_ref.dtype), k_ref[0])

    _masked_dispatch(_block, 0, ki, block_q, block_kv, causal,
                     query_offset)

    # a dead kv block (possible only with query_offset < block math
    # bounds; defensive — with sq == skv and one q block every kv
    # block is live) must still define its dk/dv output
    live, _ = _live_interior(0, ki, block_q, block_kv, causal,
                             query_offset)

    @pl.when(jnp.logical_not(live))
    def _dead():
        dk_ref[0] = jnp.zeros_like(dk_ref[0])
        dv_ref[0] = jnp.zeros_like(dv_ref[0])

    @pl.when(ki == num_kv - 1)
    def _finish():
        dq_ref[0] = (dq_scr[:] * sm_scale).astype(dq_ref.dtype)


#: fused multi-q-block backward: bytes of VMEM the resident tensors
#: (q, do at input dtype; dq f32; lse, delta f32) may claim. 6 MB
#: leaves ~10 MB of the ~16 MB/core for the streamed k/v blocks and
#: the [bq, bkv] score/exp temporaries. At bf16/d=64 this admits
#: sq <= 11776, covering the s=8192 long-context bench point; at
#: bf16/d=128, sq <= 5632, covering the 6.7B s=2048 geometry.
FUSED_BWD_RESIDENT_BUDGET = 6 * 1024 * 1024
#: internal block sizes of the fused backward's qi loop / ki grid —
#: inside one kernel there are no per-block launch overheads, so
#: small blocks only shrink the [bq, bkv] score temporaries that
#: compete with the resident tensors for VMEM
FUSED_BWD_BLOCK_Q = 512
FUSED_BWD_BLOCK_KV = 512


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, *, sm_scale, causal,
                      block_q, block_kv, num_q, query_offset):
    """One-pass backward for the multi-q-block regime with q RESIDENT:
    grid (bh, ki); q/do/lse/delta/dq map to the same block for every
    ki, so they are fetched once per bh and stay in VMEM, dq (fp32)
    accumulating in place; k/v stream per ki; an inner fori_loop over
    qi computes each score block exactly once and emits its dk/dv and
    dq contributions together. The split kernel pair computes every
    score block twice — this path removes that recomputation for
    1024 < sq <= the VMEM budget (``FUSED_BWD_RESIDENT_BUDGET``),
    which is exactly the long-context operating point."""
    ki = pl.program_id(1)
    k, v = k_ref[0], v_ref[0]

    @pl.when(ki == 0)
    def _init():
        dq_ref[0] = jnp.zeros_like(dq_ref[0])

    def _compute(qi, dk_acc, dv_acc, masked):
        sl = pl.ds(qi * block_q, block_q)
        q = q_ref[0, sl, :]
        do = do_ref[0, sl, :]
        lse = lse_ref[0, sl, :]
        delta = delta_ref[0, sl, :]
        q_s = (q.astype(jnp.float32) * sm_scale).astype(q.dtype)
        s = _dot(q_s, k, trans_b=True)
        if masked:
            s = jnp.where(
                _causal_mask(qi, ki, block_q, block_kv, query_offset),
                s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = _dot(do, v, trans_b=True)
        ds = p * (dp - delta)
        return (dk_acc + _dot(ds.astype(q_s.dtype), q_s, trans_a=True),
                dv_acc + _dot(p.astype(do.dtype), do, trans_a=True),
                _dot(ds.astype(k.dtype), k))

    def _body(qi, carry):
        dk_acc, dv_acc = carry
        if causal:
            live, interior = _live_interior(qi, ki, block_q, block_kv,
                                            causal, query_offset)
            dk_acc, dv_acc, dq_blk = jax.lax.cond(
                interior,
                lambda: _compute(qi, dk_acc, dv_acc, False),
                # diagonal-crossing: masked math; dead (possible only
                # off the fori_loop start estimate): the mask zeroes p
                # and ds, so contributions are exactly zero anyway
                lambda: _compute(qi, dk_acc, dv_acc, True))
        else:
            dk_acc, dv_acc, dq_blk = _compute(qi, dk_acc, dv_acc, False)
        cur = dq_ref[0, pl.ds(qi * block_q, block_q), :]
        dq_ref[0, pl.ds(qi * block_q, block_q), :] = cur + dq_blk
        return dk_acc, dv_acc

    zeros = jnp.zeros((k.shape[0], k.shape[1]), jnp.float32)
    # first possibly-live qi block: its end must reach the kv block
    qi_start = ((ki * block_kv - query_offset) // block_q) if causal \
        else 0
    qi_start = jnp.maximum(qi_start, 0) if causal else 0
    dk_acc, dv_acc = jax.lax.fori_loop(qi_start, num_q, _body,
                                       (zeros, zeros))
    dk_ref[0] = dk_acc.astype(dk_ref.dtype)
    dv_ref[0] = dv_acc.astype(dv_ref.dtype)


def _flash_backward_fused(q, k, v, g, lse, delta, sm_scale, causal,
                          query_offset):
    """Dispatch wrapper for ``_bwd_fused_kernel``; returns None when
    the shape doesn't fit the resident-VMEM budget (caller falls back
    to the split kernel pair)."""
    bh, sq, d = q.shape
    skv = k.shape[1]
    bq, bkv = FUSED_BWD_BLOCK_Q, FUSED_BWD_BLOCK_KV
    if sq % bq or skv % bkv:
        return None
    # q and do (the out-cotangent) are resident at the input dtype,
    # dq at fp32, lse+delta at fp32 — fp32 inputs must not sneak past
    # a bf16-sized estimate into a Mosaic allocation failure
    itemsize = jnp.dtype(q.dtype).itemsize
    if sq * (d * (2 * itemsize + 4) + 8) > FUSED_BWD_RESIDENT_BUDGET:
        return None
    # the resident tensors' block index never changes within one bh —
    # single-buffer them so the pipeline does not allocate a useless
    # second copy of the largest VMEM tenants (jax 0.4.x has no
    # pipeline_mode; there the pipeline still elides the copies, it
    # just double-allocates the buffers)
    buffered = getattr(pl, "Buffered", None)
    mode_kw = {} if buffered is None else {
        "pipeline_mode": buffered(buffer_count=1)}
    res_spec = pl.BlockSpec((1, sq, d), lambda b, i: (b, 0, 0),
                            **mode_kw)
    row_spec = pl.BlockSpec((1, sq, 1), lambda b, i: (b, 0, 0),
                            **mode_kw)
    kv_spec = pl.BlockSpec((1, bkv, d), lambda b, i: (b, i, 0))
    dq32, dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_fused_kernel, sm_scale=sm_scale, causal=causal,
            block_q=bq, block_kv=bkv, num_q=sq // bq,
            query_offset=query_offset),
        grid=(bh, skv // bkv),
        in_specs=[res_spec, kv_spec, kv_spec, res_spec, row_spec,
                  row_spec],
        out_specs=[res_spec, kv_spec, kv_spec],
        out_shape=[_sds((bh, sq, d), jnp.float32, q),
                   _sds((bh, skv, d), k.dtype, q),
                   _sds((bh, skv, d), v.dtype, q)],
        interpret=_interpret(),
    )(q, k, v, g, lse, delta)
    return (dq32 * sm_scale).astype(q.dtype), dk, dv


def _seeded(kernel):
    """Scalar-prefetch adapter: reorder the leading seed ref into the
    kernel's ``seed_ref`` kwarg."""
    def wrapped(seed_ref, *refs, **kw):
        kernel(*refs, seed_ref=seed_ref, **kw)
    return wrapped


def _lift_spec(spec):
    """BlockSpec adapter for PrefetchScalarGridSpec: the index map
    gains a trailing scalar-ref arg it ignores. Shared by the forward
    and backward dropout paths so specs cannot diverge from their
    non-dropout twins."""
    f = spec.index_map
    return pl.BlockSpec(spec.block_shape,
                        lambda *idx, _f=f: _f(*idx[:-1]))


def _flash_backward(res, g, sm_scale, causal, query_offset, block_q,
                    block_kv, g_lse=None, dropout_rate=0.0, seed=None,
                    bias=None, num_heads=None):
    q, k, v, out, lse = res
    bh, sq, d = q.shape
    skv = k.shape[1]
    num_q, num_kv = sq // block_q, skv // block_kv
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)             # [bh, sq, 1]
    if g_lse is not None:
        # cotangent of the returned logsumexp: d lse_i / d s_ij = p_ij,
        # so it folds into the kernels' existing ds = p * (dp - delta)
        # as delta' = delta - g_lse — no kernel change needed
        delta = delta - g_lse.astype(jnp.float32)
    dropout = dropout_rate > 0.0
    if dropout and bh * num_q * num_kv >= 2 ** 31:
        # the mixed-radix (b, qi, ki) seed fold must stay within i32
        raise NotImplementedError(
            "dropout seed fold overflows i32 for this grid")
    bias_ops = () if bias is None else (bias,)

    def _call(kernel_fn, grid, in_specs, out_specs, out_shape,
              scratch_shapes, qk_of_ids, **kernel_kw):
        """One backward pallas_call; the bias (if any) rides as a
        trailing operand with a per-grid index map; with dropout the
        seed rides as a prefetched scalar and every index map gains
        the trailing scalar-ref arg."""
        if bias is not None:
            in_specs = in_specs + [_bias_spec(
                bias, num_heads, block_q, block_kv, qk_of_ids)]
            kernel_kw["has_bias"] = True
        if dropout:
            kernel = functools.partial(
                _seeded(kernel_fn), dropout_rate=dropout_rate,
                **kernel_kw)
            grid_spec = pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1, grid=grid,
                in_specs=[_lift_spec(s) for s in in_specs],
                out_specs=([_lift_spec(s) for s in out_specs]
                           if isinstance(out_specs, list)
                           else _lift_spec(out_specs)),
                scratch_shapes=scratch_shapes)
            return pl.pallas_call(
                kernel, grid_spec=grid_spec, out_shape=out_shape,
                interpret=_interpret(),
            )(seed, q, k, v, g, lse, delta, *bias_ops)
        kernel = functools.partial(kernel_fn, **kernel_kw)
        return pl.pallas_call(
            kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
            out_shape=out_shape, scratch_shapes=scratch_shapes,
            interpret=_interpret(),
        )(q, k, v, g, lse, delta, *bias_ops)

    if num_q == 1:
        q_spec = pl.BlockSpec((1, block_q, d), lambda b, i: (b, 0, 0))
        r_spec = pl.BlockSpec((1, block_q, 1), lambda b, i: (b, 0, 0))
        kv_spec = pl.BlockSpec((1, block_kv, d),
                               lambda b, i: (b, i, 0))
        dq, dk, dv = _call(
            _bwd_combined_kernel,
            grid=(bh, num_kv),
            in_specs=[q_spec, kv_spec, kv_spec, q_spec, r_spec,
                      r_spec],
            out_specs=[q_spec, kv_spec, kv_spec],
            qk_of_ids=lambda b, i: (0, i),
            out_shape=[_sds((bh, sq, d), q.dtype, q),
                       _sds((bh, skv, d), k.dtype, q),
                       _sds((bh, skv, d), v.dtype, q)],
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            sm_scale=sm_scale, causal=causal, block_q=block_q,
            block_kv=block_kv, num_kv=num_kv,
            query_offset=query_offset)
        return dq, dk, dv

    if not dropout and bias is None:
        # the fused kernel tiles at its own internal block sizes, so
        # its regenerated dropout masks could not match the forward's
        # (and it has no bias plumbing) — those cases use the split
        # pair below instead
        fused = _flash_backward_fused(q, k, v, g, lse, delta, sm_scale,
                                      causal, query_offset)
        if fused is not None:
            return fused

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0))
    r_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0))
    kv_spec = pl.BlockSpec((1, block_kv, d), lambda b, i, j: (b, i, 0))
    dk, dv = _call(
        _bwd_dkv_kernel,
        grid=(bh, num_kv, num_q),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, r_spec, r_spec],
        out_specs=[kv_spec, kv_spec],
        qk_of_ids=lambda b, i, j: (j, i),
        out_shape=[_sds((bh, skv, d), k.dtype, q),
                   _sds((bh, skv, d), v.dtype, q)],
        scratch_shapes=[pltpu.VMEM((block_kv, d), jnp.float32),
                        pltpu.VMEM((block_kv, d), jnp.float32)],
        sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_kv=block_kv, num_q=num_q, num_kv=num_kv,
        query_offset=query_offset)

    q_spec2 = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    r_spec2 = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
    kv_spec2 = pl.BlockSpec((1, block_kv, d), lambda b, i, j: (b, j, 0))
    dq = _call(
        _bwd_dq_kernel,
        grid=(bh, num_q, num_kv),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, r_spec2,
                  r_spec2],
        out_specs=q_spec2,
        qk_of_ids=lambda b, i, j: (i, j),
        out_shape=_sds((bh, sq, d), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_kv=block_kv, num_kv=num_kv, num_q=num_q,
        query_offset=query_offset)
    return dq, dk, dv


# -- public API --------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_lse(q, k, v, sm_scale, causal, block_q, block_kv):
    return _flash_forward(q, k, v, sm_scale, causal, 0, block_q,
                          block_kv)


def _flash_lse_fwd(q, k, v, sm_scale, causal, block_q, block_kv):
    out, lse = _flash_forward(q, k, v, sm_scale, causal, 0, block_q,
                              block_kv)
    # Tag the residuals that only this kernel can produce with the same
    # checkpoint name the model puts on q/k/v ("attn"): under a remat
    # policy that saves "attn" (save_dots, core_attn) the backward can
    # then reconstruct ALL residuals without re-running the forward
    # kernel — without the tag the untagged lse forces a full forward
    # re-run just to regenerate it (measured 19 ms of the 224 ms 345M
    # microbatch, ~8%). lse is [bh, sq, 1] fp32 = 0.5 MB per 345M
    # layer. Policies that exclude "attn" (full_attn) recompute
    # exactly as before.
    out = checkpoint_name(out, "attn")
    lse = checkpoint_name(lse, "attn")
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_bwd(sm_scale, causal, block_q, block_kv, res, g):
    g_out, g_lse = g
    return _flash_backward(res, g_out, sm_scale, causal, 0, block_q,
                           block_kv, g_lse=g_lse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_lse_dropout(q, k, v, seed, sm_scale, causal, block_q,
                       block_kv, dropout_rate):
    """Dropout twin of ``_flash_lse``: the [1] int32 ``seed`` is a
    TRACED operand (a fresh dropout pattern per step must not
    retrace), delivered to the kernels by scalar prefetch; the keep
    mask is regenerated per score block from (seed, b, qi, ki) in
    both directions, so nothing beyond the standard residuals is
    saved."""
    return _flash_forward(q, k, v, sm_scale, causal, 0, block_q,
                          block_kv, dropout_rate, seed)


def _flash_lse_dropout_fwd(q, k, v, seed, sm_scale, causal, block_q,
                           block_kv, dropout_rate):
    out, lse = _flash_forward(q, k, v, sm_scale, causal, 0, block_q,
                              block_kv, dropout_rate, seed)
    out = checkpoint_name(out, "attn")
    lse = checkpoint_name(lse, "attn")
    return (out, lse), (q, k, v, out, lse, seed)


def _flash_lse_dropout_bwd(sm_scale, causal, block_q, block_kv,
                           dropout_rate, res, g):
    q, k, v, out, lse, seed = res
    g_out, g_lse = g
    dq, dk, dv = _flash_backward(
        (q, k, v, out, lse), g_out, sm_scale, causal, 0, block_q,
        block_kv, g_lse=g_lse, dropout_rate=dropout_rate, seed=seed)
    import numpy as np
    return dq, dk, dv, np.zeros(seed.shape, jax.dtypes.float0)


_flash_lse_dropout.defvjp(_flash_lse_dropout_fwd,
                          _flash_lse_dropout_bwd)


def _canon_bias(bias, b, h, sq, skv):
    """Validate an additive attention bias for the kernel: 4D
    ``[b0, h0, q0, skv]`` with every leading dim either 1 or full (the
    padding-mask ``[b, 1, 1, skv]`` and dense ``[b, h, sq, skv]``
    forms both qualify) and the LAST dim full — a size-1 key dim would
    add the same value to every score in a row, which softmax's shift
    invariance makes a no-op, so refusing it costs nothing.
    NotImplementedError sends the caller to the XLA fallback."""
    if bias.ndim != 4:
        raise NotImplementedError(
            f"bias must be 4D broadcastable, got shape {bias.shape}")
    b0, h0, q0, k0 = bias.shape
    if k0 != skv:
        raise NotImplementedError(
            f"bias key dim {k0} must equal kv length {skv}")
    if b0 not in (1, b) or h0 not in (1, h) or q0 not in (1, sq):
        raise NotImplementedError(
            f"bias shape {bias.shape} not broadcastable to "
            f"[{b}, {h}, {sq}, {skv}]")
    return bias


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_lse_biased(q, k, v, bias, seed, sm_scale, causal, block_q,
                      block_kv, dropout_rate, num_heads):
    """Biased twin of ``_flash_lse`` / ``_flash_lse_dropout``: an
    additive ``[b0, h0, q0, skv]`` bias rides into every kernel as a
    tiled operand (``_bias_spec``). The bias is treated as an
    attention MASK, not a trained tensor — its cotangent is defined
    as ZERO (learned ALiBi-style biases must use the XLA path; see
    docs/attention_dispatch.md). ``seed`` is ignored when
    ``dropout_rate == 0`` (callers pass a dummy)."""
    return _flash_forward(q, k, v, sm_scale, causal, 0, block_q,
                          block_kv, dropout_rate, seed, bias=bias,
                          num_heads=num_heads)


def _flash_lse_biased_fwd(q, k, v, bias, seed, sm_scale, causal,
                          block_q, block_kv, dropout_rate, num_heads):
    out, lse = _flash_forward(q, k, v, sm_scale, causal, 0, block_q,
                              block_kv, dropout_rate, seed, bias=bias,
                              num_heads=num_heads)
    out = checkpoint_name(out, "attn")
    lse = checkpoint_name(lse, "attn")
    return (out, lse), (q, k, v, out, lse, bias, seed)


def _flash_lse_biased_bwd(sm_scale, causal, block_q, block_kv,
                          dropout_rate, num_heads, res, g):
    q, k, v, out, lse, bias, seed = res
    g_out, g_lse = g
    dq, dk, dv = _flash_backward(
        (q, k, v, out, lse), g_out, sm_scale, causal, 0, block_q,
        block_kv, g_lse=g_lse, dropout_rate=dropout_rate, seed=seed,
        bias=bias, num_heads=num_heads)
    import numpy as np
    return (dq, dk, dv, jnp.zeros_like(bias),
            np.zeros(seed.shape, jax.dtypes.float0))


_flash_lse_biased.defvjp(_flash_lse_biased_fwd, _flash_lse_biased_bwd)


def check_shapes(sq, skv, d, block_q: int = None,
                 block_kv: int = None):
    """(block_q, block_kv) after clamping, or NotImplementedError —
    shared by the public wrappers and by callers (ring attention) that
    must decide statically whether the kernel can take their shapes.
    ``None`` blocks auto-pick the largest aligned divisor <= 1024."""
    block_q = _auto_block(sq, DEFAULT_BLOCK_Q, 8) if block_q is None \
        else min(block_q, sq)
    block_kv = _auto_block(skv, DEFAULT_BLOCK_KV, 128) \
        if block_kv is None else min(block_kv, skv)
    if sq % block_q or skv % block_kv:
        raise NotImplementedError(
            f"sequence ({sq}, {skv}) not divisible by blocks "
            f"({block_q}, {block_kv})")
    if block_q % 8 or block_kv % 128:
        # clamped blocks (short sequences) must still be TPU
        # tile-aligned — sublane 8 for q rows, lane 128 for kv columns;
        # Mosaic would reject unaligned blocks with a compile error
        # that the NotImplementedError fallback can't catch
        raise NotImplementedError(
            f"blocks ({block_q}, {block_kv}) not tile-aligned")
    if d % 128 and d not in (64,):
        raise NotImplementedError(f"head_dim {d} unsupported")
    return block_q, block_kv


def _to_bh(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def flash_attention(q, k, v, causal: bool = True, query_offset=0,
                    block_q: int = None, block_kv: int = None,
                    dropout_rate: float = 0.0, dropout_rng=None,
                    bias=None):
    """``[b, s, h, d]`` attention; raises NotImplementedError when the
    shape/backend can't take the kernel (caller falls back to the XLA
    path in ``ops.attention``).

    ``bias`` is an additive score bias broadcastable to
    ``[b, h, sq, skv]`` (each leading dim 1 or full — ERNIE padding
    masks ``[b, 1, 1, skv]``, GPT attn_mask) tiled into every kernel;
    it is treated as a non-differentiable MASK (zero cotangent).

    ``dropout_rate > 0`` runs IN-KERNEL attention-probs dropout (the
    reference's fused softmax-with-dropout training path,
    ``hybrid_model.py:277-285``): the per-core PRNG generates the keep
    mask inside each score block from (seed, block coords) — no
    [b, h, s, s] mask tensor ever exists, in either direction. In
    interpret mode a stateless hash substitutes for the (TPU-only)
    hardware PRNG so CPU tests can validate the plumbing."""
    if jax.default_backend() != "tpu" and not _interpret():
        raise NotImplementedError("flash kernel targets TPU")
    if not isinstance(query_offset, int) or query_offset != 0:
        raise NotImplementedError("cached decode uses the XLA path")
    b, sq, h, d = q.shape
    block_q, block_kv = check_shapes(sq, k.shape[1], d, block_q,
                                     block_kv)
    if dropout_rate > 0.0 and dropout_rng is None:
        raise NotImplementedError(
            "flash dropout needs a dropout_rng")
    if bias is not None:
        bias = _canon_bias(bias, b, h, sq, k.shape[1])
        # the kernels add the bias in f32 and its (zero) cotangent
        # must be float-typed; one cast here covers bool/int masks
        if bias.dtype != jnp.float32:
            bias = bias.astype(jnp.float32)
        if dropout_rate > 0.0:
            seed = jax.random.randint(dropout_rng, (1,), 0,
                                      2 ** 31 - 1, dtype=jnp.int32)
        else:
            seed = jnp.zeros((1,), jnp.int32)   # ignored
        out, _ = _flash_lse_biased(
            _to_bh(q), _to_bh(k), _to_bh(v), bias, seed, d ** -0.5,
            causal, block_q, block_kv, float(dropout_rate), h)
        return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    if dropout_rate > 0.0:
        seed = jax.random.randint(dropout_rng, (1,), 0, 2 ** 31 - 1,
                                  dtype=jnp.int32)
        out, _ = _flash_lse_dropout(
            _to_bh(q), _to_bh(k), _to_bh(v), seed, d ** -0.5, causal,
            block_q, block_kv, float(dropout_rate))
        return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    # lse discarded: its cotangent is then symbolically zero and the
    # backward's delta adjustment is a no-op — one custom_vjp serves
    # both the plain and the with-lse surface
    out, _ = _flash_lse(_to_bh(q), _to_bh(k), _to_bh(v), d ** -0.5,
                        causal, block_q, block_kv)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def flash_attention_with_lse(q, k, v, causal: bool = True,
                             sm_scale=None,
                             block_q: int = None, block_kv: int = None):
    """Like :func:`flash_attention` but also returns the per-row
    logsumexp of the (scaled) scores, ``[b, h, sq]`` fp32 — the
    streaming-combination state ring attention needs to merge exact
    softmax results across KV blocks held on other devices. Fully
    differentiable: the lse cotangent folds into the backward kernels'
    delta term."""
    if jax.default_backend() != "tpu" and not _interpret():
        raise NotImplementedError("flash kernel targets TPU")
    b, sq, h, d = q.shape
    block_q, block_kv = check_shapes(sq, k.shape[1], d, block_q,
                                     block_kv)
    sm_scale = d ** -0.5 if sm_scale is None else sm_scale
    out, lse = _flash_lse(_to_bh(q), _to_bh(k), _to_bh(v), sm_scale,
                          causal, block_q, block_kv)
    return (out.reshape(b, h, sq, d).transpose(0, 2, 1, 3),
            lse.reshape(b, h, sq))


# -- cached decode -----------------------------------------------------


def _decode_kernel(off_ref, q_ref, k_ref, v_ref, *refs, sm_scale,
                   block_kv, num_kv, has_bias, ragged=False,
                   quantized=False):
    """Single-token decode over the fixed-capacity KV cache.

    Decode attention is a matvec, not a matmul — per (head, key-block)
    the scores are ``sum_d q[d] * k[d, S]`` and the output is
    ``sum_S p[S] * v[d, S]``, both VPU broadcast-multiply-reduces over
    the cache's native ``[d, S]`` tiles. An MXU formulation pays
    fixed issue latency per tiny matmul (measured 512 matmuls/call =
    ~370us); this kernel folds ALL heads into one program per
    (batch, key-block) so the grid is ``b * num_kv`` programs of pure
    VPU streaming.

    The live length is DYNAMIC (the decode loop's cache index), so it
    arrives as a prefetched scalar: blocks wholly past the last valid
    position are skipped — short prefixes only pay for the cache they
    have actually filled — and the straddling block is masked. With
    ``has_bias`` a per-key additive bias tile rides along (the
    generation loop's left-pad mask).

    ``ragged``: the prefetched offsets are PER ROW (``[b]``, the
    continuous-batching slot lengths) instead of one shared scalar —
    each batch row masks and block-skips against its OWN last valid
    position, so a short slot never pays a long slot's cache walk.

    ``quantized``: the cache tiles are int8 and two extra operands
    carry the per-(row, head, position) fp32 scales (``[h, 1, bkv]``
    blocks riding the same index maps as K/V minus the d axis);
    dequant happens HERE, on the VMEM-resident block — the widened
    f32 copy never exists in HBM, so the streamed bytes stay int8.
    """
    refs = list(refs)
    if quantized:
        ks_ref, vs_ref = refs[0], refs[1]
        refs = refs[2:]
    if has_bias:
        bias_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        bias_ref = None
        o_ref, m_scr, l_scr, acc_scr = refs
    ki = pl.program_id(1)
    # last valid key position: shared (lockstep decode) or this row's
    offset = off_ref[pl.program_id(0)] if ragged else off_ref[0]

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(ki * block_kv <= offset)
    def _block():
        k_pos = ki * block_kv + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_kv), 1)
        live = k_pos <= offset                     # [1, bkv]
        q = q_ref[0].astype(jnp.float32)           # [h, d, 1]
        k = k_ref[0].astype(jnp.float32)           # [h, d, bkv]
        v = v_ref[0].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0]                      # [h, 1, bkv] bcast
            v = v * vs_ref[0]
        # every head in one vectorized pass — a per-head loop would
        # issue ~6x num_heads small VPU ops and dominate the call
        s = jnp.sum(q * k, axis=1) * sm_scale      # [h, bkv] f32
        if has_bias:
            s = s + bias_ref[0]                    # [1, bkv] broadcasts
        s = jnp.where(live, s, NEG_INF)
        m_prev = m_scr[:]                          # [h, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                     # [h, bkv]
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        # output: broadcast p over d, reduce over the key lanes
        acc_scr[:] = acc_scr[:] * alpha + jnp.sum(p[:, None, :] * v,
                                                  axis=2)
        m_scr[:] = m_new

    @pl.when(ki == num_kv - 1)
    def _finish():
        o_ref[0] = (acc_scr[:] /
                    jnp.maximum(l_scr[:], 1e-30))[..., None].astype(
            o_ref.dtype)


def _verify_kernel(off_ref, q_ref, k_ref, v_ref, *refs, sm_scale,
                   block_kv, num_kv, window, ragged=True,
                   quantized=False):
    """Speculative k-token VERIFY over the KV cache: ``window`` query
    tokens per row, where query ``j`` sits at cache position
    ``offset + j`` and attends keys ``<= offset + j`` — the
    within-window causal mask speculative decoding needs to score a
    drafted token run in ONE pass (docs/inference.md).

    Per query the math is exactly :func:`_decode_kernel`'s matvec +
    online softmax (a static Python loop over ``j`` unrolls into
    ``window`` independent VPU passes sharing each resident KV block),
    so greedy verification is bit-compatible with sequential
    single-token decode: a block wholly past query ``j``'s last live
    position contributes masked-out scores only (``alpha == 1``,
    ``p == 0`` — block 0 is always live, so ``m`` is finite before any
    dead block arrives) and the running ``m/l/acc`` state passes
    through unchanged. Scratch carries one ``[h, 1]`` / ``[h, d]``
    state row per window position. No bias operand (serving decode
    carries none — per-slot validity lives in the offsets). With
    ``quantized`` the int8 cache block dequantizes ONCE per resident
    block (``[h, 1, bkv]`` fp32 scale operands, same contract as
    :func:`_decode_kernel`) and all ``window`` queries share the
    widened copy."""
    refs = list(refs)
    if quantized:
        ks_ref, vs_ref = refs[0], refs[1]
        refs = refs[2:]
    o_ref, m_scr, l_scr, acc_scr = refs
    ki = pl.program_id(1)
    offset = off_ref[pl.program_id(0)] if ragged else off_ref[0]

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # a block participates when ANY window query can see it; per-query
    # liveness is the mask below
    @pl.when(ki * block_kv <= offset + (window - 1))
    def _block():
        k_pos = ki * block_kv + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_kv), 1)
        k = k_ref[0].astype(jnp.float32)           # [h, d, bkv]
        v = v_ref[0].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0]                      # [h, 1, bkv] bcast
            v = v * vs_ref[0]
        for j in range(window):
            live = k_pos <= offset + j             # [1, bkv]
            qj = q_ref[0, :, :, j].astype(jnp.float32)   # [h, d]
            s = jnp.sum(qj[:, :, None] * k, axis=1) * sm_scale
            s = jnp.where(live, s, NEG_INF)        # [h, bkv]
            m_prev = m_scr[j]                      # [h, 1]
            m_new = jnp.maximum(m_prev,
                                jnp.max(s, axis=1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            l_scr[j] = l_scr[j] * alpha + jnp.sum(p, axis=1,
                                                  keepdims=True)
            acc_scr[j] = acc_scr[j] * alpha + jnp.sum(p[:, None, :] * v,
                                                      axis=2)
            m_scr[j] = m_new

    @pl.when(ki == num_kv - 1)
    def _finish():
        o = acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)   # [W, h, d]
        o_ref[0] = o.transpose(1, 2, 0).astype(o_ref.dtype)


def _check_kv_scales(k, v, k_scale, v_scale, h, skv):
    """Admission for the int8-KV operand pair: scales come BOTH or
    NEITHER, the cache must actually be int8, and each scale is the
    cache minus its d axis (``[..., h, 1, S]`` fp32 — one scale per
    (row, head, position), written by the cache-update path in
    ``models/gpt/model.py``)."""
    if (k_scale is None) != (v_scale is None):
        raise NotImplementedError(
            "int8 KV wants both k_scale and v_scale (or neither)")
    if k_scale is None:
        return False
    if k.dtype != jnp.int8 or v.dtype != jnp.int8:
        raise NotImplementedError(
            f"KV scales given but cache is {k.dtype}/{v.dtype}, "
            "not int8")
    want = k.shape[:1] + (h, 1, skv)
    if k_scale.shape != want or v_scale.shape != want:
        raise NotImplementedError(
            f"KV scales must be {want}, got {k_scale.shape} / "
            f"{v_scale.shape}")
    return True


def _flash_decode_call(q, k, v, off, bias, block_kv: int, ragged: bool,
                       k_scale=None, v_scale=None):
    """Shared shape-check + ``pallas_call`` builder behind
    :func:`flash_decode` (``off [1]``, one shared cache index) and
    :func:`flash_decode_ragged` (``off [b]``, per-slot lengths). With
    ``sq > 1`` the queries are a speculative VERIFY window — query
    ``j`` of row ``i`` sits at position ``off[i] + j`` and the
    windowed kernel (:func:`_verify_kernel`) applies the within-window
    causal mask; bias is single-token only. ``k_scale``/``v_scale``
    (``[b, h, 1, S]`` fp32) switch the kernels to the int8-KV
    dequant-in-kernel variants. Raises NotImplementedError where the
    caller must fall back to XLA."""
    if jax.default_backend() != "tpu" and not _interpret():
        raise NotImplementedError("flash kernel targets TPU")
    b, sq, h, d = q.shape
    window = sq
    if window < 1:
        raise NotImplementedError("empty decode window")
    if window > 1 and bias is not None:
        raise NotImplementedError(
            "verify window (sq > 1) takes no bias (per-slot validity "
            "is the offsets')")
    skv = k.shape[3]
    quantized = _check_kv_scales(k, v, k_scale, v_scale, h, skv)
    # largest 128-aligned divisor <= block_kv: capacities that are
    # 128-multiples but not block_kv-multiples (e.g. 1280) stay on the
    # kernel instead of tripping the skv % block_kv rejection below
    block_kv = _auto_block(skv, block_kv, 128)
    # all heads ride in one block, so k/v blocks are h-times larger
    # than a per-head grid's: shrink block_kv until double-buffered
    # k+v blocks fit comfortably in the ~16M VMEM (a Mosaic
    # allocation failure would crash instead of falling back)
    budget = 8 * 1024 * 1024
    while block_kv > 128 and \
            4 * h * d * block_kv * k.dtype.itemsize > budget:
        block_kv //= 2
    if skv % block_kv or block_kv % 128 or \
            4 * h * d * block_kv * k.dtype.itemsize > budget:
        raise NotImplementedError(
            f"cache length {skv} not tileable by {block_kv} "
            f"within VMEM budget (h={h}, d={d})")
    if d % 8:
        raise NotImplementedError(f"head_dim {d} unsupported")
    num_kv = skv // block_kv

    # [b, W, h, d] -> [b, h, d, W]: the query token(s) as lane
    # column(s) per head, matching the cache's d-major tiles
    qp = q.transpose(0, 2, 3, 1)

    # clamp the kv block index once past the live length: skipped
    # iterations re-reference the already-resident block, so the
    # HBM->VMEM copy is elided and a short prefix pays only for the
    # cache it has actually filled (the compute skip alone would
    # still stream the full capacity). Ragged, each ROW clamps
    # against its own length — the per-slot cost model of the
    # continuous-batching server. A verify window's LAST query
    # (position off + window - 1) sets the walk bound; earlier
    # queries just mask the tail blocks out.
    def kv_block(bi, ki, off):
        row = (off[bi] if ragged else off[0]) + (window - 1)
        return jnp.minimum(ki, row // block_kv)

    in_specs = [
        pl.BlockSpec((1, h, d, window),
                     lambda bi, ki, off: (bi, 0, 0, 0)),
        pl.BlockSpec((1, h, d, block_kv),
                     lambda bi, ki, off: (bi, 0, 0,
                                          kv_block(bi, ki, off))),
        pl.BlockSpec((1, h, d, block_kv),
                     lambda bi, ki, off: (bi, 0, 0,
                                          kv_block(bi, ki, off))),
    ]
    operands = [qp, k, v]
    if quantized:
        # fp32 scale blocks ride the SAME clamped index maps as their
        # K/V tiles (the d axis collapsed to 1), so a skipped block's
        # scale copy is elided right along with it
        for _ in range(2):
            in_specs.append(pl.BlockSpec(
                (1, h, 1, block_kv),
                lambda bi, ki, off: (bi, 0, 0, kv_block(bi, ki, off))))
        operands += [k_scale, v_scale]
    if bias is not None:
        # per-key additive bias (the generation loop's left-pad mask),
        # [b, skv] or broadcastable [b, 1, 1, skv]; a [1, bkv] row
        # broadcasts against each head's [1, bkv] scores
        operands.append(jnp.reshape(bias.astype(jnp.float32),
                                    (b, 1, skv)))
        in_specs.append(pl.BlockSpec(
            (1, 1, block_kv),
            lambda bi, ki, off: (bi, 0, kv_block(bi, ki, off))))

    if window == 1:
        kernel = functools.partial(_decode_kernel, sm_scale=d ** -0.5,
                                   block_kv=block_kv, num_kv=num_kv,
                                   has_bias=bias is not None,
                                   ragged=ragged, quantized=quantized)
        scratch = [
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, d), jnp.float32),
        ]
    else:
        kernel = functools.partial(_verify_kernel, sm_scale=d ** -0.5,
                                   block_kv=block_kv, num_kv=num_kv,
                                   window=window, ragged=ragged,
                                   quantized=quantized)
        scratch = [
            pltpu.VMEM((window, h, 1), jnp.float32),
            pltpu.VMEM((window, h, 1), jnp.float32),
            pltpu.VMEM((window, h, d), jnp.float32),
        ]
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, num_kv),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, h, d, window), lambda bi, ki, off: (bi, 0, 0, 0)),
            scratch_shapes=scratch,
        ),
        out_shape=_sds((b, h, d, window), q.dtype, q),
        interpret=_interpret(),
    )(off, *operands)
    # [b, h, d, W] -> [b, W, h, d]
    return out.transpose(0, 3, 1, 2)


def flash_decode(q, k, v, query_offset, bias=None,
                 block_kv: int = DEFAULT_BLOCK_KV,
                 k_scale=None, v_scale=None):
    """One decode step through the cache: ``q [b, 1, h, d]`` attends to
    ``k/v [b, h, d, S]`` positions ``<= query_offset`` (a traced
    scalar — the fixed-capacity cache index of ``models/gpt/model.py``).

    Inference-only (no VJP). Raises NotImplementedError when the
    shape/backend can't take the kernel; the caller falls back to the
    XLA path. The cache arrives in its NATIVE ``[b, h, d, S]`` layout
    — minor tile dims (d, S) fill TPU (8,128) tiles exactly (zero
    padding; any d=64-minor layout wastes 2x HBM). One program per
    (batch, key-block) streams every head's ``[d, bkv]`` tiles and
    runs the matvec attention on the VPU (see ``_decode_kernel``).
    """
    off = jnp.reshape(jnp.asarray(query_offset, jnp.int32), (1,))
    return _flash_decode_call(q, k, v, off, bias, block_kv,
                              ragged=False, k_scale=k_scale,
                              v_scale=v_scale)


def flash_decode_ragged(q, k, v, query_offsets, bias=None,
                        block_kv: int = DEFAULT_BLOCK_KV,
                        k_scale=None, v_scale=None):
    """Per-row decode through the cache: row ``i`` of ``q [b, 1, h, d]``
    attends to ``k/v [b, h, d, S]`` positions ``<= query_offsets[i]``
    (a traced ``[b]`` int vector — the continuous-batching server's
    per-slot cache lengths minus one, i.e. each slot's just-written
    position).

    Same kernel body and layout contract as :func:`flash_decode`; the
    offsets prefetch as a ``[b]`` scalar operand so both the in-kernel
    masking and the block-skip index maps read the PER-ROW length —
    a freshly admitted slot walks only its own short cache while a
    long-running neighbour streams its full one.

    ``sq > 1`` is the speculative VERIFY window (no bias): query ``j``
    of row ``i`` sits at position ``query_offsets[i] + j`` and masks
    keys ``<= query_offsets[i] + j`` (:func:`_verify_kernel`) — one
    pass scores a whole drafted token run. Inference-only; raises
    NotImplementedError where the caller must fall back to the XLA
    per-row-offset path (``ops/attention.py::_xla_attention``).

    ``k_scale``/``v_scale`` (``[b, h, 1, S]`` fp32, one scale per
    (slot, head, position)) switch both the single-token and the
    verify-window kernel to their int8-KV dequant-in-kernel variants
    — the cache streams as int8 and widens on the VMEM-resident
    block (docs/quantization.md).
    """
    b = q.shape[0]
    offs = jnp.asarray(query_offsets, jnp.int32)
    if offs.ndim != 1 or offs.shape[0] != b:
        raise NotImplementedError(
            f"ragged offsets must be [b={b}], got {offs.shape}")
    return _flash_decode_call(q, k, v, offs, bias, block_kv,
                              ragged=True, k_scale=k_scale,
                              v_scale=v_scale)


def _paged_decode_kernel(off_ref, pt_ref, *refs, **kw):
    """:func:`_decode_kernel` behind TWO prefetched scalars: the
    per-row offsets AND the page table. The table is consumed entirely
    by the BlockSpec index maps (physical-page redirection happens in
    the grid, before the kernel body runs); the body itself masks and
    block-skips against LOGICAL positions exactly as the ragged kernel
    does, so it needs only the offsets."""
    del pt_ref
    _decode_kernel(off_ref, *refs, **kw)


def _paged_verify_kernel(off_ref, pt_ref, *refs, **kw):
    """:func:`_verify_kernel` behind the paged kernel's two prefetched
    scalars — same delegation as :func:`_paged_decode_kernel`: the
    page table lives entirely in the index maps."""
    del pt_ref
    _verify_kernel(off_ref, *refs, **kw)


def flash_decode_paged(q, k, v, query_offsets, page_table, bias=None,
                       block_kv: int = DEFAULT_BLOCK_KV,
                       k_scale=None, v_scale=None):
    """Per-row decode through a PAGED KV pool: row ``i`` of
    ``q [b, 1, h, d]`` attends to positions ``<= query_offsets[i]`` of
    its logical cache, whose physical storage is scattered across the
    global pool ``k/v [num_pages, h, d, page_size]`` according to
    ``page_table [b, max_pages]`` (int32 physical page ids;
    ``core/paging.py``).

    Same kernel body, grid walk, and per-row block clamping as
    :func:`flash_decode_ragged` — the ONLY difference is the KV
    BlockSpec index map, which redirects logical block ``kb`` to block
    ``kb % blocks_per_page`` of physical page
    ``page_table[i, kb // blocks_per_page]``. Both scalars prefetch
    (``PrefetchScalarGridSpec(num_scalar_prefetch=2)``) so the
    redirection is resolved before each block's HBM->VMEM copy issues,
    and the clamp keeps a short row from streaming pages it never
    wrote. Block size is the largest 128-aligned divisor of the page
    size that fits the VMEM budget, so a block never straddles two
    (physically unrelated) pages.

    ``sq > 1`` is the speculative VERIFY window: the within-window
    causal mask of :func:`flash_decode_ragged` over the paged pool
    (:func:`_paged_verify_kernel`).

    Inference-only; no bias operand (serving decode carries none —
    per-slot validity lives in the offsets). Raises
    NotImplementedError where the caller must fall back to the XLA
    gather path (``ops/attention.py::_gather_kv_pages``).

    ``k_scale``/``v_scale`` (``[num_pages, h, 1, page_size]`` fp32
    scale POOLS, page-parallel with the int8 K/V pools) switch both
    the single-token and the verify-window kernel to their int8-KV
    dequant-in-kernel variants; the scale blocks redirect through the
    same page-table index map as their K/V tiles.
    """
    if jax.default_backend() != "tpu" and not _interpret():
        raise NotImplementedError("flash kernel targets TPU")
    if bias is not None:
        raise NotImplementedError(
            "flash_decode_paged takes no bias (per-slot validity is "
            "the offsets')")
    b, sq, h, d = q.shape
    window = sq
    if window < 1:
        raise NotImplementedError("empty decode window")
    if d % 8:
        raise NotImplementedError(f"head_dim {d} unsupported")
    if k.ndim != 4 or k.shape[1] != h or k.shape[2] != d:
        raise NotImplementedError(
            f"paged pool must be [P, {h}, {d}, page], got {k.shape}")
    page = k.shape[3]
    quantized = _check_kv_scales(k, v, k_scale, v_scale, h, page)
    offs = jnp.asarray(query_offsets, jnp.int32)
    if offs.ndim != 1 or offs.shape[0] != b:
        raise NotImplementedError(
            f"ragged offsets must be [b={b}], got {offs.shape}")
    pt = jnp.asarray(page_table, jnp.int32)
    if pt.ndim != 2 or pt.shape[0] != b:
        raise NotImplementedError(
            f"page_table must be [b={b}, max_pages], got {pt.shape}")
    max_pages = pt.shape[1]
    # block the PAGE, not the logical capacity: a kv block must stay
    # inside one physical page for the redirection to be a pure index
    # remap
    block_kv = _auto_block(page, block_kv, 128)
    budget = 8 * 1024 * 1024
    while block_kv > 128 and page % (block_kv // 2) == 0 and \
            4 * h * d * block_kv * k.dtype.itemsize > budget:
        block_kv //= 2
    if page % block_kv or block_kv % 128 or \
            4 * h * d * block_kv * k.dtype.itemsize > budget:
        raise NotImplementedError(
            f"page size {page} not tileable by {block_kv} within "
            f"VMEM budget (h={h}, d={d})")
    bpp = page // block_kv                     # blocks per page
    num_kv = max_pages * bpp                   # logical capacity walk

    qp = q.transpose(0, 2, 3, 1)               # [b, h, d, W]

    def kv_block(bi, ki, off, pt):
        # clamp to the row's live block (same dead-block elision as
        # the ragged kernel; a verify window's last query sets the
        # bound), then redirect through the page table
        kb = jnp.minimum(ki, (off[bi] + (window - 1)) // block_kv)
        return (pt[bi, kb // bpp], 0, 0, kb % bpp)

    in_specs = [
        pl.BlockSpec((1, h, d, window),
                     lambda bi, ki, off, pt: (bi, 0, 0, 0)),
        pl.BlockSpec((1, h, d, block_kv), kv_block),
        pl.BlockSpec((1, h, d, block_kv), kv_block),
    ]
    operands = [qp, k, v]
    if quantized:
        # scale pools redirect through the SAME page-table index map
        # as their K/V tiles (d axis collapsed to 1)
        for _ in range(2):
            in_specs.append(pl.BlockSpec((1, h, 1, block_kv),
                                         kv_block))
        operands += [k_scale, v_scale]
    if window == 1:
        kernel = functools.partial(_paged_decode_kernel,
                                   sm_scale=d ** -0.5,
                                   block_kv=block_kv, num_kv=num_kv,
                                   has_bias=False, ragged=True,
                                   quantized=quantized)
        scratch = [
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, d), jnp.float32),
        ]
    else:
        kernel = functools.partial(_paged_verify_kernel,
                                   sm_scale=d ** -0.5,
                                   block_kv=block_kv, num_kv=num_kv,
                                   window=window, ragged=True,
                                   quantized=quantized)
        scratch = [
            pltpu.VMEM((window, h, 1), jnp.float32),
            pltpu.VMEM((window, h, 1), jnp.float32),
            pltpu.VMEM((window, h, d), jnp.float32),
        ]
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, num_kv),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, h, d, window),
                lambda bi, ki, off, pt: (bi, 0, 0, 0)),
            scratch_shapes=scratch,
        ),
        out_shape=_sds((b, h, d, window), q.dtype, q),
        interpret=_interpret(),
    )(offs, pt, *operands)
    return out.transpose(0, 3, 1, 2)
