"""Weight-only int8 matmul with per-output-channel scales, in Pallas.

The serving decode path is weight-bandwidth-bound: every tick streams
the full dense stack (qkv/out-proj/fc1/fc2) from HBM for a handful of
query rows. Storing those kernels as int8 plus one fp32 scale per
output channel halves the streamed bytes; this kernel keeps the
matmul exact-to-rounding by dequantizing **inside the accumulation
loop** — each int8 weight tile is widened to the activation dtype in
VMEM right before the MXU dot, partial products accumulate in fp32
scratch, and the per-channel scale (a ``[1, N]`` row held in VMEM for
the whole grid) is applied once at the write-out, which is exact
because a per-output-channel factor commutes with the K-sum.

Layout: ``x [M, K]`` activations (bf16/f32), ``w [K, N]`` frozen int8
weights, ``scale [N]`` fp32. Grid ``(M/bm, N/bn, K/bk)`` with the K
axis innermost-sequential, fp32 VMEM accumulator per ``(bm, bn)``
tile — the same structure as ``grouped_matmul.py``. The backward is
wired through ``jax.custom_vjp``: dx reuses the forward kernel with
the scale folded into the cotangent and the int8 weight transposed
(``dx = (g · s) @ wqᵀ``); the weights are *frozen-quantized* (a PTQ
artifact, not a trainable leaf), so dw is a symbolic zero — int8
operands take ``float0`` cotangents, mirroring the ``counts`` leaf in
``grouped_matmul``. Interpret mode (``PFX_PALLAS_INTERPRET=1``) lets
the CPU suite validate kernel semantics (tests/test_quantized_matmul
.py) without a TPU; shape admission raises ``NotImplementedError`` so
dispatch sites fall back to the XLA dequantize-then-dot path
(``quant/fallback/kernel_rejected`` — docs/quantization.md).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _dot, _interpret, _sds
from .grouped_matmul import _block


def _qmm_kernel(x_ref, w_ref, s_ref, o_ref, acc_scr, *, num_k):
    """out = x @ dequant(w), scale applied at the final-ki write-out.

    The int8 tile widens to the activation dtype in VMEM (the fused
    dequant — no f32 weight copy ever exists in HBM); fp32 scratch
    accumulates across the sequential ki axis."""
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    acc_scr[:] += _dot(x_ref[:], w_ref[:].astype(x_ref.dtype))

    @pl.when(ki == num_k - 1)
    def _finish():
        o_ref[:] = (acc_scr[:] * s_ref[:]).astype(o_ref.dtype)


def _qmm_call(x, w, scale, block_m, block_n, block_k):
    """One pallas_call: ``[M, K] @ int8 [K, N] * scale [N] ->
    [M, N]`` in ``x.dtype``, accumulated in fp32."""
    m_dim, k_dim = x.shape
    n_dim = w.shape[1]
    bm = _block(m_dim, block_m)
    bn = _block(n_dim, block_n)
    bk = _block(k_dim, block_k)
    num_m, num_n, num_k = m_dim // bm, n_dim // bn, k_dim // bk
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(num_m, num_n, num_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda mi, ni, ki: (mi, ki)),
            pl.BlockSpec((bk, bn), lambda mi, ni, ki: (ki, ni)),
            pl.BlockSpec((1, bn), lambda mi, ni, ki: (0, ni)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda mi, ni, ki: (mi, ni)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(_qmm_kernel, num_k=num_k),
        grid_spec=grid_spec,
        out_shape=_sds((m_dim, n_dim), x.dtype, x),
        interpret=_interpret(),
    )(x, w, scale.astype(jnp.float32).reshape(1, n_dim))


def _check_shapes(x, w, scale):
    """Kernel admission: a ``NotImplementedError`` here sends the
    dense site to its XLA dequantize-then-dot fallback (counted as
    ``quant/fallback/kernel_rejected`` — docs/quantization.md)."""
    if jax.default_backend() != "tpu" and not _interpret():
        raise NotImplementedError("quantized_matmul needs TPU")
    if x.ndim != 2 or w.ndim != 2 or scale.ndim != 1:
        raise NotImplementedError(
            f"quantized_matmul wants x[M,K] w[K,N] scale[N], got "
            f"{x.shape} / {w.shape} / {scale.shape}")
    if x.shape[1] != w.shape[0] or w.shape[1] != scale.shape[0]:
        raise NotImplementedError(
            f"quantized_matmul shape mismatch: x {x.shape}, "
            f"w {w.shape}, scale {scale.shape}")
    if w.dtype != jnp.int8:
        raise NotImplementedError(
            f"quantized_matmul wants int8 weights, got {w.dtype}")
    m_dim, k_dim = x.shape
    n_dim = w.shape[1]
    # tiling floor: int8 wants (32, 128) tiles, activations (8, 128);
    # _block() shrinks toward 1 but sub-tile blocks lower badly, so
    # reject shapes the MXU can't tile instead of limping through
    if m_dim % 8 or k_dim % 128 or n_dim % 128:
        raise NotImplementedError(
            f"quantized_matmul wants M%8==0, K%128==0, N%128==0; got "
            f"M={m_dim} K={k_dim} N={n_dim}")


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _quantized_matmul(x, w, scale, block_m, block_n, block_k):
    return _qmm_call(x, w, scale, block_m, block_n, block_k)


def _quantized_matmul_fwd(x, w, scale, block_m, block_n, block_k):
    return (_qmm_call(x, w, scale, block_m, block_n, block_k),
            (w, scale))


def _quantized_matmul_bwd(block_m, block_n, block_k, res, g):
    w, scale = res
    # dx = (g * s) @ wqᵀ — the forward kernel with the per-channel
    # scale folded into the cotangent (exact: s is per-N, the
    # contraction axis of this product) and unit scales on the
    # transposed int8 weight
    gs = (g.astype(jnp.float32) * scale[None, :]).astype(g.dtype)
    dx = _qmm_call(gs, jnp.swapaxes(w, 0, 1),
                   jnp.ones((w.shape[0],), jnp.float32),
                   block_m, block_k, block_n)
    # frozen-quantized weights: int8 leaves take float0 cotangents and
    # the scale is a calibration constant, not a trainable parameter
    return (dx, np.zeros(w.shape, jax.dtypes.float0),
            jnp.zeros_like(scale))


_quantized_matmul.defvjp(_quantized_matmul_fwd, _quantized_matmul_bwd)


def quantized_matmul(x: jax.Array, w: jax.Array, scale: jax.Array,
                     block_m: int = 256, block_n: int = 256,
                     block_k: int = 512) -> jax.Array:
    """Weight-only int8 matmul ``out = x @ (w.astype(f32) * scale)``.

    Args:
      x: ``[M, K]`` activations (bf16/f32); M is the flattened
        batch·sequence token count at a dense site.
      w: ``[K, N]`` frozen int8 weights (a PTQ artifact —
        ``core/quantize.py`` emits them on the QAT abs-max grid).
      scale: ``[N]`` fp32 per-output-channel dequant scales, held in
        VMEM for the whole grid.
      block_m / block_n / block_k: tile targets (shrunk to divisors).

    Returns ``[M, N]`` in ``x.dtype``, accumulated in fp32 with the
    int8→activation-dtype widening fused into the K loop and the
    scale applied once at write-out (exact — per-output-channel
    factors commute with the K-sum). The custom VJP computes dx
    through the same kernel; dw/dscale are symbolic zeros (weights
    are frozen-quantized).
    """
    _check_shapes(x, w, scale)
    return _quantized_matmul(x, w, scale, block_m, block_n, block_k)
