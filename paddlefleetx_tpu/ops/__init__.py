"""Ops subpackage."""
from .attention import dot_product_attention  # noqa: F401
from .collective_matmul import (  # noqa: F401
    all_gather_matmul, matmul_reduce_scatter, mp_ring_viable,
)
