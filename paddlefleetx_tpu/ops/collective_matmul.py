"""Overlapped tensor-parallel matmuls: decomposed collective rings.

GSPMD lowers the Megatron column/row-parallel linears to a blocking
collective glued to a matmul: under sequence parallelism the column
projection waits for a full seq all-gather over ``mp`` before the MXU
starts, and the row projection's reduce-scatter waits on the full
product. Following "On Optimizing the Communication of Model
Parallelism" (arxiv 2211.05322) and the GSPMD paper's decomposed
collectives (arxiv 2105.04663 §3.4), each collective is decomposed
here into a **bidirectional ppermute ring** whose per-hop transfers
overlap the per-shard matmul chunks:

- :func:`all_gather_matmul` (column-parallel, qkv / fc1): the local
  seq shard of ``x`` circulates both ways around the ``mp`` ring; at
  every hop the chunk that just arrived multiplies the resident weight
  shard, so after ``ceil((mp-1)/2)`` hops every device holds its
  ``[b, s, n/mp]`` output column without ever materializing a blocking
  all-gather.
- :func:`matmul_reduce_scatter` (row-parallel, out-proj / fc2): the
  dual — partial products accumulate into two counter-rotating
  accumulators that arrive fully reduced at their destination shard.

Both carry a custom VJP so the backward pass overlaps too: the
transpose of an all-gather-matmul is a matmul-reduce-scatter and vice
versa, and the weight gradient streams through the same ring
(:func:`_ring_visit`). The ring/ppermute idiom and jax-version shims
follow ``ops/ring_attention.py``.

Dispatch lives in the model (`models/gpt/model.py::_CollectiveDense`
behind ``use_collective_matmul``); :func:`mp_ring_viable` is the
single shape gate, pinned by ``tests/test_collective_matmul.py``. The
matrix is documented in ``docs/tensor_parallel.md``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .ring_attention import _axis_size, _shard_map


def _smap(fn, mesh, in_specs, out_specs):
    """shard_map with replication/vma checking off: the 0.4.x checker
    has no rewrite rule for ``custom_vjp_call`` in transposed rings,
    and the specs below are exact by construction."""
    try:
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)
    except TypeError:       # newer jax renamed the knob
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)


def _ring_visit(shard, axis_name, fold, init):
    """Bidirectionally circulate ``shard`` over the ring; call
    ``fold(acc, shard_from_src, src)`` exactly once per ring position
    — the local shard first, then one hop each way per step, so both
    ICI directions carry traffic while the previous chunks compute.
    """
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    acc = fold(init, shard, idx)
    perm_fwd = [(i, (i + 1) % n) for i in range(n)]
    perm_bwd = [(i, (i - 1) % n) for i in range(n)]
    hops_fwd, hops_bwd = n // 2, (n - 1) // 2
    fwd = bwd = shard
    for i in range(1, hops_fwd + 1):
        fwd = jax.lax.ppermute(fwd, axis_name, perm_fwd)
        acc = fold(acc, fwd, (idx - i) % n)
        if i <= hops_bwd:
            bwd = jax.lax.ppermute(bwd, axis_name, perm_bwd)
            acc = fold(acc, bwd, (idx + i) % n)
    return acc


def _zero_like_varying(shape, dtype, ref):
    """A zeros array carrying ``ref``'s device-varying type (the
    ring_attention accumulator trick — required if a future jax build
    re-enables vma tracking for these rings)."""
    z = jnp.sum(ref.astype(jnp.float32)) * 0.0
    return jnp.zeros(shape, dtype) + z.astype(dtype)


# -- per-shard kernels (call under shard_map) ---------------------------

def _ag_matmul_ring(x, w, axis_name):
    """Per-shard all-gather-matmul: ``x [b, s/n, k]`` (one seq shard),
    ``w [k, n_l]`` (one output-column shard) -> ``y [b, s, n_l]``."""
    n = _axis_size(axis_name)
    b, s_l, _ = x.shape
    n_l = w.shape[-1]

    def fold(buf, blk, src):
        chunk = jnp.einsum("bsk,kn->bsn", blk, w)
        return jax.lax.dynamic_update_slice(buf, chunk,
                                            (0, src * s_l, 0))

    return _ring_visit(
        x, axis_name, fold,
        _zero_like_varying((b, n * s_l, n_l), x.dtype, x))


def _matmul_rs_ring(x, w, axis_name):
    """Per-shard matmul-reduce-scatter: ``x [b, s, k_l]`` (full seq,
    one contraction shard), ``w [k_l, n]`` -> ``y [b, s/n, n]`` fully
    reduced for this device's seq shard.

    Two counter-rotating fp32 accumulators: the forward one starts
    ``n//2`` ring positions before its destination and collects a
    partial product at every hop; the backward one covers the
    remaining ``(n-1)//2`` positions from the other side. Each arrives
    at its destination having visited a disjoint device set, so their
    sum is the exact psum — in half the hops of a one-way ring.
    """
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s, k_l = x.shape
    s_l = s // n
    n_out = w.shape[-1]
    perm_fwd = [(i, (i + 1) % n) for i in range(n)]
    perm_bwd = [(i, (i - 1) % n) for i in range(n)]
    hops_fwd, hops_bwd = n // 2, (n - 1) // 2

    def partial_for(dst):
        xc = jax.lax.dynamic_slice(x, (0, dst * s_l, 0), (b, s_l, k_l))
        return jnp.einsum("bsk,kn->bsn", xc, w,
                          preferred_element_type=jnp.float32)

    acc_f = _zero_like_varying((b, s_l, n_out), jnp.float32, x)
    acc_b = _zero_like_varying((b, s_l, n_out), jnp.float32, x)
    for t in range(hops_fwd + 1):
        acc_f = acc_f + partial_for((idx + hops_fwd - t) % n)
        if t < hops_fwd:
            acc_f = jax.lax.ppermute(acc_f, axis_name, perm_fwd)
        if t < hops_bwd:
            acc_b = acc_b + partial_for((idx - hops_bwd + t) % n)
            acc_b = jax.lax.ppermute(acc_b, axis_name, perm_bwd)
    return (acc_f + acc_b).astype(x.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _ag_matmul(x, w, axis_name):
    return _ag_matmul_ring(x, w, axis_name)


def _ag_matmul_fwd(x, w, axis_name):
    return _ag_matmul_ring(x, w, axis_name), (x, w)


def _ag_matmul_bwd(axis_name, res, dy):
    # dy [b, s, n_l]: the cotangent of the seq-gathered, column-sharded
    # output. dx contracts the mp-sharded n_l dim -> partial sums whose
    # seq-sharded reduction is exactly the matmul-reduce-scatter ring
    # (the transpose duality the module docstring states).
    x, w = res
    dx = _matmul_rs_ring(dy, w.T, axis_name).astype(x.dtype)

    # dw [k, n_l] = AG(x)^T @ dy: stream the x shards through the same
    # bidirectional ring, contracting each against its dy rows
    b, s_l, k = x.shape
    n_l = dy.shape[-1]

    def fold(acc, x_blk, src):
        dyc = jax.lax.dynamic_slice(dy, (0, src * s_l, 0),
                                    (b, s_l, n_l))
        return acc + jnp.einsum("bsk,bsn->kn", x_blk, dyc,
                                preferred_element_type=jnp.float32)

    dw = _ring_visit(
        x, axis_name, fold,
        _zero_like_varying((k, n_l), jnp.float32, x))
    return dx, dw.astype(w.dtype)


_ag_matmul.defvjp(_ag_matmul_fwd, _ag_matmul_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _matmul_rs(x, w, axis_name):
    return _matmul_rs_ring(x, w, axis_name)


def _matmul_rs_fwd(x, w, axis_name):
    return _matmul_rs_ring(x, w, axis_name), (x, w)


def _matmul_rs_bwd(axis_name, res, dy):
    # dy [b, s/n, n]: seq-sharded cotangent. dx needs the full seq of
    # dy against w^T -> the all-gather-matmul ring (dual of fwd).
    x, w = res
    n = _axis_size(axis_name)
    dx = _ag_matmul_ring(dy, w.T, axis_name).astype(x.dtype)

    # dw [k_l, n] = x^T @ AG(dy): circulate the dy shards, contract
    # each against the matching seq rows of the resident x
    b, s, k_l = x.shape
    s_l = s // n
    n_out = dy.shape[-1]

    def fold(acc, dy_blk, src):
        xc = jax.lax.dynamic_slice(x, (0, src * s_l, 0), (b, s_l, k_l))
        return acc + jnp.einsum("bsk,bsn->kn", xc, dy_blk,
                                preferred_element_type=jnp.float32)

    dw = _ring_visit(
        dy, axis_name, fold,
        _zero_like_varying((k_l, n_out), jnp.float32, dy))
    return dx, dw.astype(w.dtype)


_matmul_rs.defvjp(_matmul_rs_fwd, _matmul_rs_bwd)


# -- global-view wrappers ----------------------------------------------

def mp_ring_viable(mesh, batch: int, seq: int,
                   sharded_dims: Sequence[int] = (),
                   axis_name: Optional[str] = None,
                   batch_axes=None) -> bool:
    """True iff the decomposed rings can run these global shapes: a
    live mesh with mp >= 2, batch divisible over the dataflow axes,
    seq divisible by mp (equal ring chunks), and every mp-sharded
    weight dim divisible by mp. Exactly the fallback gate of the model
    wiring — pinned by the dispatch probes in
    ``tests/test_collective_matmul.py``."""
    from ..parallel.mesh import DATA_AXES, MP_AXIS
    axis_name = axis_name or MP_AXIS
    batch_axes = batch_axes or DATA_AXES
    if mesh is None:
        return False
    mp = mesh.shape.get(axis_name, 1)
    if mp < 2:
        return False
    bsz = int(np.prod([mesh.shape[a] for a in batch_axes]))
    if batch % bsz or seq % mp:
        return False
    return all(d % mp == 0 for d in sharded_dims)


def all_gather_matmul(x: jax.Array, w: jax.Array, mesh, *,
                      w_shard_dim: int = 0,
                      axis_name: Optional[str] = None,
                      batch_axes=None) -> jax.Array:
    """Column-parallel ``x @ w`` with the seq all-gather decomposed
    into the overlapped ring.

    ``x``: global ``[b, s, k]`` with s sharded over ``axis_name``
    (the Megatron-SP layout); ``w``: global ``[k, *feat]`` with
    ``feat[w_shard_dim]`` sharded over ``axis_name``. Returns global
    ``[b, s, *feat]`` — seq gathered, ``feat[w_shard_dim]`` sharded —
    the exact sharding the plain GSPMD path produces. Weight dims
    sharded over *other* axes (ZeRO-3's fsdp on k) are gathered by
    GSPMD outside the shard_map, as in the plain lowering.
    """
    from ..parallel.mesh import DATA_AXES, MP_AXIS
    axis_name = axis_name or MP_AXIS
    batch_axes = batch_axes or DATA_AXES
    feat = w.shape[1:]
    feat_spec = [axis_name if i == w_shard_dim else None
                 for i in range(len(feat))]

    def body(xl, wl):
        y = _ag_matmul(xl, wl.reshape(wl.shape[0], -1), axis_name)
        return y.reshape(y.shape[:2] + wl.shape[1:])

    return _smap(
        body, mesh,
        in_specs=(P(batch_axes, axis_name, None), P(None, *feat_spec)),
        out_specs=P(batch_axes, None, *feat_spec))(x, w)


def matmul_reduce_scatter(x: jax.Array, w: jax.Array, mesh, *,
                          contract_ndim: int = 1,
                          axis_name: Optional[str] = None,
                          batch_axes=None) -> jax.Array:
    """Row-parallel ``x @ w`` with the output reduce-scatter
    decomposed into the overlapped ring.

    ``x``: global ``[b, s, *c]`` where ``c = w.shape[:contract_ndim]``
    and ``c[0]`` is sharded over ``axis_name`` (the row-parallel input
    layout: attention heads for out-proj, the ffn dim for fc2);
    ``w``: global ``[*c, n]`` with ``c[0]`` sharded. Returns global
    ``[b, s, n]`` with s sharded over ``axis_name`` — the
    sequence-parallel layout the plain GSPMD reduce-scatter produces.
    """
    from ..parallel.mesh import DATA_AXES, MP_AXIS
    axis_name = axis_name or MP_AXIS
    batch_axes = batch_axes or DATA_AXES
    rest = [None] * (contract_ndim - 1)

    def body(xl, wl):
        xl2 = xl.reshape(xl.shape[0], xl.shape[1], -1)
        return _matmul_rs(xl2, wl.reshape(-1, wl.shape[-1]), axis_name)

    return _smap(
        body, mesh,
        in_specs=(P(batch_axes, None, axis_name, *rest),
                  P(axis_name, *rest, None)),
        out_specs=P(batch_axes, axis_name, None))(x, w)
