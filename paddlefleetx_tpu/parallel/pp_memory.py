"""Analytic per-stage HBM model for the pipeline schedule family.

ZB-H2 (``parallel/pipeline.py``) spends memory to kill the fill-phase
bubble: each extra warm-up forward is one more stashed microbatch
activation, and the deferred-dW FIFO grows a cotangent ring row per
depth step. This module prices that spend — dtype-aware byte
accounting per *physical* pipeline stage (the unit one device along
the ``pp`` mesh axis holds), in the same spirit as the byte math in
paging/quantization — validates a requested depth against a device
memory budget BEFORE anything is traced (a clean ``ValueError``
instead of an OOM deep inside XLA), and powers the ``zb_auto``
schedule chooser: pick the deepest feasible point on the
``1F1B -> zb -> zb_h2@depth`` ladder and say why.

The model counts the schedule-dependent residents of one stage:

  - parameters: ``param_count / pp`` in ``param_dtype`` (the stacked
    decoder dominates; embeddings/head are compute-replicated),
  - gradients: the same count in fp32 (the schedules accumulate
    microbatch grads in fp32),
  - activation ring: ``vpp * 2K`` microbatch activations in the
    compute dtype (depth 2K for every schedule in the family — the
    just-in-time dW pops keep it so, ``zb_dw_schedule``),
  - cotangent ring (zb family only): ``vpp * (K + depth + 1)``
    microbatch cotangents in the compute dtype — the term that grows
    with ZB-H2 depth,
  - wave buffers: the forward state plus two fp32 backward-wave
    buffers.

Optimizer state is deliberately out of scope (it is schedule-
independent; the planner of ROADMAP item 5 owns that axis). The
budget defaults to the device's ``bytes_limit`` from
``observability.memory.device_memory_stats`` and can be pinned with
``PFX_PP_HBM_BUDGET_BYTES`` (docs/observability.md) — useful both for
tests and for reserving headroom below the physical limit.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

__all__ = [
    "dtype_bytes",
    "stage_memory_bytes",
    "hbm_budget_bytes",
    "max_feasible_h2_depth",
    "resolve_pipeline_schedule",
]

_DTYPE_BYTES = {
    "float64": 8, "fp64": 8,
    "float32": 4, "fp32": 4,
    "bfloat16": 2, "bf16": 2,
    "float16": 2, "fp16": 2,
    "int8": 1, "uint8": 1, "fp8": 1,
}


def dtype_bytes(dtype) -> int:
    """Bytes per element for a dtype name or numpy-like dtype."""
    name = getattr(dtype, "name", None) or str(dtype)
    try:
        return _DTYPE_BYTES[name.lower()]
    except KeyError:
        raise ValueError(f"unknown dtype {dtype!r} for byte "
                         f"accounting") from None


def stage_memory_bytes(*, schedule: str, pp: int, vpp: int = 1,
                       microbatch_tokens: int, hidden_size: int,
                       param_count: int, h2_depth: int = 0,
                       compute_dtype: str = "float32",
                       param_dtype: str = "float32") -> dict:
    """Analytic HBM residents of ONE physical pipeline stage.

    ``microbatch_tokens`` is ``batch / M * seq_len`` — the activation
    unit every ring row holds. Returns a per-component breakdown plus
    ``total_bytes``; see the module docstring for what is (and is
    deliberately not) counted.
    """
    sched = str(schedule).lower().replace("-", "_")
    K = pp * vpp
    d = max(int(h2_depth), 0) if sched == "zb_h2" else 0
    cdb = dtype_bytes(compute_dtype)
    mb_act = microbatch_tokens * hidden_size * cdb
    mb_f32 = microbatch_tokens * hidden_size * 4
    params_b = param_count // pp * dtype_bytes(param_dtype)
    grads_b = param_count // pp * 4
    act_ring_b = vpp * 2 * K * mb_act
    gstash_b = vpp * (K + d + 1) * mb_act \
        if sched in ("zb", "zb_h2") else 0
    wave_b = vpp * (mb_act + 2 * mb_f32)
    return {
        "schedule": sched,
        "h2_depth": d,
        "microbatch_act_bytes": mb_act,
        "params_bytes": params_b,
        "grads_bytes": grads_b,
        "act_ring_bytes": act_ring_b,
        "gstash_bytes": gstash_b,
        "wave_bytes": wave_b,
        "total_bytes": (params_b + grads_b + act_ring_b + gstash_b
                        + wave_b),
    }


def hbm_budget_bytes(device=None) -> Optional[int]:
    """Per-device HBM budget for depth validation, or ``None`` when
    unknown (CPU/interpret runs). ``PFX_PP_HBM_BUDGET_BYTES`` pins it
    explicitly (<= 0 disables budget checking); otherwise the
    device's allocator ``bytes_limit`` is used."""
    env = os.environ.get("PFX_PP_HBM_BUDGET_BYTES")
    if env is not None:
        try:
            val = int(env)
        except ValueError:
            raise ValueError(
                f"PFX_PP_HBM_BUDGET_BYTES={env!r} is not an integer")
        return val if val > 0 else None
    from ..observability.memory import device_memory_stats
    stats = device_memory_stats(device)
    if stats and stats.get("bytes_limit"):
        return int(stats["bytes_limit"])
    return None


def max_feasible_h2_depth(budget_bytes: int, K: int,
                          bytes_at: Callable[[int], int]) -> int:
    """Deepest ``d`` in ``[0, K - 1]`` with ``bytes_at(d) <=
    budget_bytes``, or ``-1`` when even depth 0 (plain zb) does not
    fit. ``bytes_at`` is monotone in ``d`` so the scan walks down."""
    for d in range(K - 1, -1, -1):
        if bytes_at(d) <= budget_bytes:
            return d
    return -1


def resolve_pipeline_schedule(schedule: str, *, pp: int, vpp: int = 1,
                              requested_depth: int = -1,
                              budget_bytes: Optional[int] = None,
                              mem_kwargs: Optional[dict] = None) -> dict:
    """Resolve a configured ``pipeline_schedule`` into the concrete
    ``(schedule, h2_depth)`` the scan should run, with a reason.

    ``mem_kwargs`` carries the ``stage_memory_bytes`` inputs other
    than ``schedule``/``pp``/``vpp``/``h2_depth``; with both it and
    ``budget_bytes`` present the choice is budget-aware, otherwise it
    is optimistic (full depth) and the reason says so.

    - ``1F1B`` / ``GPipe`` / ``zb`` pass through unchanged.
    - ``zb_h2`` with an explicit ``requested_depth`` that does NOT fit
      the budget raises ``ValueError`` — the configured schedule is
      rejected up front instead of OOMing at trace time. A negative
      ``requested_depth`` asks for the deepest feasible depth.
    - ``zb_auto`` picks the deepest feasible point on the
      ``1F1B -> zb -> zb_h2@d`` ladder.

    Returns ``{"schedule", "h2_depth", "reason",
    "predicted_stage_bytes", "budget_bytes"}`` with ``schedule`` in
    the canonical config spelling (``"1F1B"``, ``"GPipe"``, ``"zb"``,
    ``"zb_h2"``).
    """
    sched = str(schedule).lower().replace("-", "_")
    K = pp * vpp
    full = max(K - 1, 0)

    def bytes_for(s, d):
        if mem_kwargs is None:
            return None
        return stage_memory_bytes(schedule=s, pp=pp, vpp=vpp,
                                  h2_depth=d, **mem_kwargs)["total_bytes"]

    def out(s, d, reason):
        canon = {"1f1b": "1F1B", "gpipe": "GPipe", "zb": "zb",
                 "zb_h2": "zb_h2"}[s]
        return {"schedule": canon, "h2_depth": d, "reason": reason,
                "predicted_stage_bytes": bytes_for(s, d),
                "budget_bytes": budget_bytes}

    if sched in ("1f1b", "gpipe", "zb"):
        return out(sched, 0, "configured explicitly")
    if sched not in ("zb_h2", "zb_auto"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")

    blind = budget_bytes is None or mem_kwargs is None
    if sched == "zb_h2":
        want = full if requested_depth < 0 else min(int(requested_depth),
                                                   full)
        if blind:
            return out("zb_h2", want,
                       "no HBM budget information; assuming depth fits")
        need = bytes_for("zb_h2", want)
        if need <= budget_bytes:
            return out("zb_h2", want,
                       f"depth {want} fits: {need} <= {budget_bytes} "
                       f"bytes per stage")
        if requested_depth >= 0:
            raise ValueError(
                f"pipeline_schedule zb_h2 at depth {want} needs {need} "
                f"bytes per stage but the HBM budget is {budget_bytes} "
                f"(use zb_auto, lower zb_h2_depth, or raise "
                f"PFX_PP_HBM_BUDGET_BYTES)")
        feas = max_feasible_h2_depth(budget_bytes, K,
                                     lambda d: bytes_for("zb_h2", d))
        if feas < 0:
            raise ValueError(
                f"pipeline_schedule zb_h2 does not fit at any depth: "
                f"even depth 0 needs {bytes_for('zb_h2', 0)} bytes per "
                f"stage against a budget of {budget_bytes}")
        return out("zb_h2", feas,
                   f"deepest feasible depth under {budget_bytes} "
                   f"bytes per stage")

    # zb_auto: deepest feasible rung of 1F1B -> zb -> zb_h2@d
    if blind:
        return out("zb_h2", full,
                   "zb_auto without HBM budget information; assuming "
                   "full depth fits")
    feas = max_feasible_h2_depth(budget_bytes, K,
                                 lambda d: bytes_for("zb_h2", d))
    if feas >= 1:
        return out("zb_h2", feas,
                   f"zb_auto: deepest feasible depth under "
                   f"{budget_bytes} bytes per stage")
    if feas == 0 or bytes_for("zb", 0) <= budget_bytes:
        return out("zb", 0,
                   f"zb_auto: zb_h2 depth >= 1 exceeds {budget_bytes} "
                   f"bytes per stage; zb fits")
    return out("1f1b", 0,
               f"zb_auto: the zb cotangent ring exceeds "
               f"{budget_bytes} bytes per stage; falling back to 1F1B")
