"""Device-mesh topology: the TPU-native HybridCommunicateGroup.

The reference builds a 4D NCCL HybridCommunicateGroup from
``strategy.hybrid_configs{dp,mp,pp,sharding}`` (reference
``ppfleetx/utils/env.py:49-69``) and queries per-axis ranks throughout.
On TPU the HCG *is* a ``jax.sharding.Mesh`` with named axes — XLA/GSPMD
emits the collectives that Fleet issued by hand, and they ride the ICI
torus because the mesh is laid out with ``mesh_utils`` so neighboring
mesh coordinates are ICI neighbors.

Axis convention (outermost to innermost):
  ``pp``   pipeline stages          (slowest-varying; DCN-friendly)
  ``dp``   pure data parallel
  ``fsdp`` sharding/ZeRO axis       (reference ``sharding_degree``)
  ``mp``   tensor parallel          (innermost; highest-bandwidth ICI)

The dataflow axis of the reference — ``dp_degree * sharding_degree``
(``env.py:76-96``), used for batch sharding, seeds, and checkpoint
dedup — is ``("dp", "fsdp")`` here.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PP_AXIS = "pp"
DP_AXIS = "dp"
CP_AXIS = "cp"
FSDP_AXIS = "fsdp"
MP_AXIS = "mp"
MESH_AXES = (PP_AXIS, DP_AXIS, CP_AXIS, FSDP_AXIS, MP_AXIS)
#: the reference's dp x sharding composite dataflow axis (env.py:76-96)
DATA_AXES = (DP_AXIS, FSDP_AXIS)


@dataclasses.dataclass(frozen=True)
class TopologyConfig:
    """Parsed ``Distributed`` section; mirrors reference degree names."""
    dp_degree: int = 1
    mp_degree: int = 1
    pp_degree: int = 1
    cp_degree: int = 1          # context parallel (ring attention) —
    #                             beyond-reference (SURVEY §5.7)
    ep_degree: int = 1          # expert parallel (MoE) — beyond-
    #                             reference. Rides the dataflow axes
    #                             (dp x fsdp): a dedicated mesh axis
    #                             would replicate non-MoE compute
    #                             ep-fold, so ep does NOT multiply
    #                             world_size; it must equal dp, fsdp,
    #                             or dp*fsdp (parallel/sharding.py)
    sharding_degree: int = 1
    sharding_stage: int = 1
    sharding_offload: bool = False
    sequence_parallel: bool = False

    def __post_init__(self):
        if self.cp_degree > 1 and self.sequence_parallel:
            raise ValueError(
                "cp_degree (ring attention) and sequence_parallel "
                "(Megatron-SP seq-over-mp) both shard the sequence "
                "axis; enable at most one")

    @classmethod
    def from_config(cls, config) -> "TopologyConfig":
        dist = config.get("Distributed", {}) if hasattr(config, "get") else {}
        sharding = dist.get("sharding", {}) or {}
        model = config.get("Model", {}) if hasattr(config, "get") else {}
        return cls(
            dp_degree=dist.get("dp_degree") or 1,
            mp_degree=dist.get("mp_degree") or 1,
            pp_degree=dist.get("pp_degree") or 1,
            cp_degree=dist.get("cp_degree") or 1,
            ep_degree=dist.get("ep_degree") or 1,
            sharding_degree=sharding.get("sharding_degree") or 1,
            sharding_stage=sharding.get("sharding_stage") or 1,
            sharding_offload=bool(sharding.get("sharding_offload", False)),
            sequence_parallel=bool(model.get("sequence_parallel", False)),
        )

    @property
    def world_size(self) -> int:
        return (self.dp_degree * self.mp_degree * self.pp_degree
                * self.cp_degree * self.sharding_degree)

    @property
    def data_world_size(self) -> int:
        return self.dp_degree * self.sharding_degree


def build_mesh(topo: TopologyConfig,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the 4-axis mesh ``(pp, dp, fsdp, mp)``.

    On real TPU slices ``mesh_utils.create_device_mesh`` maps mesh
    coordinates onto the physical ICI torus; elsewhere (CPU test
    meshes) a plain reshape is used.
    """
    shape = (topo.pp_degree, topo.dp_degree, topo.cp_degree,
             topo.sharding_degree, topo.mp_degree)
    n = int(np.prod(shape))
    if devices is None:
        if n != jax.device_count():
            raise ValueError(
                f"topology {dict(zip(MESH_AXES, shape))} covers {n} devices "
                f"but {jax.device_count()} are available; set Distributed "
                f"degrees to use every device (reference asserts the same, "
                f"utils/config.py:54)")
        if jax.devices()[0].platform == "tpu":
            from jax.experimental import mesh_utils
            dev_array = mesh_utils.create_device_mesh(shape)
        else:
            dev_array = np.asarray(jax.devices()).reshape(shape)
    else:
        if len(devices) != n:
            raise ValueError(
                f"topology {shape} needs exactly {n} devices, "
                f"got {len(devices)}")
        # caller-supplied order is authoritative (tests, sub-meshes)
        dev_array = np.asarray(list(devices)).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


_ACTIVE_MESH: Optional[Mesh] = None


def set_mesh(mesh: Optional[Mesh]) -> None:
    global _ACTIVE_MESH
    _ACTIVE_MESH = mesh


def get_mesh() -> Optional[Mesh]:
    return _ACTIVE_MESH


def batch_spec(extra_dims: int = 0) -> P:
    """PartitionSpec for a batch-leading array, sharded over dp x fsdp."""
    return P(DATA_AXES, *([None] * extra_dims))


def batch_sharding(mesh: Mesh, extra_dims: int = 0) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(extra_dims))


def data_world_size(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    if mesh is None:
        return 1
    return mesh.shape[DP_AXIS] * mesh.shape[FSDP_AXIS]


def _process_data_groups(mesh: Mesh):
    """Group processes by the set of dataflow coordinates they own.

    Processes whose devices cover the same dataflow (dp x fsdp) slice
    (e.g. two hosts split along mp or pp) are *replicas* of the same
    data stream and must load identical batches; distinct coordinate
    sets are distinct loader ranks. Returns (groups, my_group_index)
    with groups ordered by their first dataflow coordinate.
    """
    dp_axis = mesh.axis_names.index(DP_AXIS)
    fsdp_axis = mesh.axis_names.index(FSDP_AXIS)
    coords = {}
    for idx, dev in np.ndenumerate(mesh.devices):
        pos = int(idx[dp_axis] * mesh.shape[FSDP_AXIS]
                  + idx[fsdp_axis])
        coords.setdefault(dev.process_index, set()).add(pos)
    groups = {}
    for proc, pos_set in coords.items():
        groups.setdefault(frozenset(pos_set), []).append(proc)
    ordered = sorted(groups, key=min)
    me = jax.process_index()
    mine = next(i for i, g in enumerate(ordered) if me in groups[g])
    return ordered, mine


def process_data_rank(mesh: Optional[Mesh] = None) -> int:
    """This process's data-loader rank: the index of its dataflow
    coordinate group. Processes that are mp/pp replicas of the same
    batch slice share a rank (and must load identical data)."""
    mesh = mesh or get_mesh()
    if mesh is None or jax.process_count() == 1:
        return 0
    return _process_data_groups(mesh)[1]


def process_data_loader_count(mesh: Optional[Mesh] = None) -> int:
    """Number of distinct data-loader ranks (== distinct dataflow
    coordinate groups across processes)."""
    mesh = mesh or get_mesh()
    if mesh is None or jax.process_count() == 1:
        return 1
    return len(_process_data_groups(mesh)[0])


def cpu_mesh_env(n: int = 8) -> None:
    """Force an ``n``-device CPU platform for mesh tests/dry-runs.

    Works whether or not jax is already imported (site customization
    may import jax at interpreter start): sets the env vars for a
    fresh process *and* updates jax.config for the current one. Must
    run before the first backend initialization.
    """
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n}")
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", n)
