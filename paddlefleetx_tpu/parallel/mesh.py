"""Device-mesh topology: the TPU-native HybridCommunicateGroup.

The reference builds a 4D NCCL HybridCommunicateGroup from
``strategy.hybrid_configs{dp,mp,pp,sharding}`` (reference
``ppfleetx/utils/env.py:49-69``) and queries per-axis ranks throughout.
On TPU the HCG *is* a ``jax.sharding.Mesh`` with named axes — XLA/GSPMD
emits the collectives that Fleet issued by hand, and they ride the ICI
torus because the mesh is laid out with ``mesh_utils`` so neighboring
mesh coordinates are ICI neighbors.

Axis convention (outermost to innermost):
  ``pp``   pipeline stages          (slowest-varying; DCN-friendly)
  ``dp``   pure data parallel
  ``fsdp`` sharding/ZeRO axis       (reference ``sharding_degree``)
  ``mp``   tensor parallel          (innermost; highest-bandwidth ICI)

The dataflow axis of the reference — ``dp_degree * sharding_degree``
(``env.py:76-96``), used for batch sharding, seeds, and checkpoint
dedup — is ``("dp", "fsdp")`` here.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PP_AXIS = "pp"
DP_AXIS = "dp"
CP_AXIS = "cp"
FSDP_AXIS = "fsdp"
MP_AXIS = "mp"
MESH_AXES = (PP_AXIS, DP_AXIS, CP_AXIS, FSDP_AXIS, MP_AXIS)
#: the reference's dp x sharding composite dataflow axis (env.py:76-96)
DATA_AXES = (DP_AXIS, FSDP_AXIS)


@dataclasses.dataclass(frozen=True)
class TopologyConfig:
    """Parsed ``Distributed`` section; mirrors reference degree names."""
    dp_degree: int = 1
    mp_degree: int = 1
    pp_degree: int = 1
    cp_degree: int = 1          # context parallel (ring attention) —
    #                             beyond-reference (SURVEY §5.7)
    ep_degree: int = 1          # expert parallel (MoE) — beyond-
    #                             reference. Rides the dataflow axes
    #                             (dp x fsdp): a dedicated mesh axis
    #                             would replicate non-MoE compute
    #                             ep-fold, so ep does NOT multiply
    #                             world_size; it must equal dp, fsdp,
    #                             or dp*fsdp (parallel/sharding.py)
    sharding_degree: int = 1
    sharding_stage: int = 1
    sharding_offload: bool = False
    sequence_parallel: bool = False

    def __post_init__(self):
        if self.cp_degree > 1 and self.sequence_parallel:
            raise ValueError(
                "cp_degree (ring attention) and sequence_parallel "
                "(Megatron-SP seq-over-mp) both shard the sequence "
                "axis; enable at most one")

    @classmethod
    def from_config(cls, config) -> "TopologyConfig":
        """Build the topology from a parsed YAML config's
        ``Distributed``/``Model`` sections (degree semantics of
        reference ``utils/config.py:30-65``)."""
        dist = config.get("Distributed", {}) if hasattr(config, "get") else {}
        sharding = dist.get("sharding", {}) or {}
        model = config.get("Model", {}) if hasattr(config, "get") else {}
        return cls(
            dp_degree=dist.get("dp_degree") or 1,
            mp_degree=dist.get("mp_degree") or 1,
            pp_degree=dist.get("pp_degree") or 1,
            cp_degree=dist.get("cp_degree") or 1,
            ep_degree=dist.get("ep_degree") or 1,
            sharding_degree=sharding.get("sharding_degree") or 1,
            sharding_stage=sharding.get("sharding_stage") or 1,
            sharding_offload=bool(sharding.get("sharding_offload", False)),
            sequence_parallel=bool(model.get("sequence_parallel", False)),
        )

    @property
    def world_size(self) -> int:
        return (self.dp_degree * self.mp_degree * self.pp_degree
                * self.cp_degree * self.sharding_degree)

    @property
    def data_world_size(self) -> int:
        return self.dp_degree * self.sharding_degree


#: Axes allowed to span the DCN (inter-slice) network, in preference
#: order. dp first — its gradient allreduce happens once per step and
#: pipelines over DCN well; pp next — stage boundaries transfer one
#: activation per microbatch; fsdp last — its per-layer param
#: all-gathers tolerate DCN only with generous compute to hide them.
#: cp/mp issue per-layer (or per-block) latency-bound collectives and
#: must stay inside a slice's ICI torus.
DCN_AXIS_PREFERENCE = (DP_AXIS, PP_AXIS, FSDP_AXIS)


def dcn_factorization(num_slices: int, shape: Sequence[int]) -> tuple:
    """Split ``num_slices`` multiplicatively across the DCN-tolerant
    axes of ``shape`` (ordered as ``MESH_AXES``), greedily in
    ``DCN_AXIS_PREFERENCE`` order. Returns the per-axis DCN degrees
    (the ``Mesh`` axis degree = dcn_degree * per-slice ICI degree).

    Raises if the topology cannot be laid out with mp/cp intact inside
    a slice — e.g. 4 slices but dp*pp*fsdp only has a factor of 2
    across DCN-tolerant axes.
    """
    import math
    dcn = {a: 1 for a in MESH_AXES}
    remaining = num_slices
    for axis in DCN_AXIS_PREFERENCE:
        f = math.gcd(remaining, shape[MESH_AXES.index(axis)])
        dcn[axis] = f
        remaining //= f
    if remaining != 1:
        raise ValueError(
            f"cannot lay topology {dict(zip(MESH_AXES, shape))} across "
            f"{num_slices} slices: dp/pp/fsdp degrees leave a factor "
            f"of {remaining} that would force mp/cp collectives onto "
            f"DCN; make dp (or pp) divisible by the slice count")
    return tuple(dcn[a] for a in MESH_AXES)


def _compose_slices(slice_arrays, dcn_shape) -> np.ndarray:
    """Tile per-slice device arrays (all of the same ICI shape) into
    the full mesh array so each slice occupies one contiguous block:
    full-mesh index along axis k = dcn_coord * ici_degree + ici_coord.
    Walking any axis therefore stays on ICI until a slice-block
    boundary, and only dcn_degree-1 of the hops cross DCN.

    Deliberately hand-rolled rather than delegating to
    ``mesh_utils.create_hybrid_device_mesh``: the library helper
    detects granules from real device attrs (slice_index /
    process_index), which virtual CPU test devices don't carry, so it
    cannot be exercised by the 8-device CPU suite. One small composed
    path that every test runs beats a library path the tests can't
    reach (the per-slice ICI layout still comes from
    ``create_device_mesh`` on real TPU)."""
    ici_shape = slice_arrays[0].shape
    full = np.empty(
        tuple(d * i for d, i in zip(dcn_shape, ici_shape)), object)
    for k, arr in enumerate(slice_arrays):
        coords = np.unravel_index(k, dcn_shape)
        full[tuple(slice(c * i, (c + 1) * i)
                   for c, i in zip(coords, ici_shape))] = arr
    return full


def build_mesh(topo: TopologyConfig,
               devices: Optional[Sequence[jax.Device]] = None,
               slice_id_fn=None) -> Mesh:
    """Build the 5-axis mesh ``(pp, dp, cp, fsdp, mp)``.

    On a single real TPU slice ``mesh_utils.create_device_mesh`` maps
    mesh coordinates onto the physical ICI torus. On a multi-slice
    (Multislice/multi-pod) platform — detected via the devices'
    ``slice_index`` — each slice gets its own ICI-optimised sub-array
    and slices are tiled along the DCN-tolerant axes only (dp, then
    pp, then fsdp; never mp/cp), so per-layer collectives ride ICI and
    only the once-per-step dataflow traffic crosses DCN
    (``dcn_factorization``). Elsewhere (CPU test meshes) a plain
    reshape is used. ``slice_id_fn`` overrides slice detection (tests
    inject a fake slice id over CPU devices).
    """
    shape = (topo.pp_degree, topo.dp_degree, topo.cp_degree,
             topo.sharding_degree, topo.mp_degree)
    n = int(np.prod(shape))
    if devices is None:
        if n != jax.device_count():
            raise ValueError(
                f"topology {dict(zip(MESH_AXES, shape))} covers {n} devices "
                f"but {jax.device_count()} are available; set Distributed "
                f"degrees to use every device (reference asserts the same, "
                f"utils/config.py:54)")
        devices = jax.devices()
        on_tpu = devices[0].platform == "tpu"
    else:
        if len(devices) != n:
            raise ValueError(
                f"topology {shape} needs exactly {n} devices, "
                f"got {len(devices)}")
        if slice_id_fn is None:
            # caller-supplied order is authoritative (tests, sub-meshes)
            return Mesh(np.asarray(list(devices)).reshape(shape),
                        MESH_AXES)
        on_tpu = False
    if slice_id_fn is None:
        slice_id_fn = (lambda d: getattr(d, "slice_index", None)) \
            if on_tpu else (lambda d: None)
    by_slice = {}
    for d in devices:
        by_slice.setdefault(slice_id_fn(d), []).append(d)
    if len(by_slice) > 1:
        dcn_shape = dcn_factorization(len(by_slice), shape)
        ici_shape = tuple(s // d for s, d in zip(shape, dcn_shape))
        per = n // len(by_slice)
        slice_arrays = []
        for sid in sorted(by_slice):
            devs = by_slice[sid]
            if len(devs) != per:
                raise ValueError(
                    f"uneven slices: slice {sid} has {len(devs)} "
                    f"devices, expected {per}")
            if on_tpu:
                from jax.experimental import mesh_utils
                slice_arrays.append(mesh_utils.create_device_mesh(
                    ici_shape, devices=devs))
            else:
                slice_arrays.append(
                    np.asarray(devs).reshape(ici_shape))
        dev_array = _compose_slices(slice_arrays, dcn_shape)
    elif on_tpu:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(shape)
    else:
        dev_array = np.asarray(list(devices)).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


_ACTIVE_MESH: Optional[Mesh] = None


def set_mesh(mesh: Optional[Mesh]) -> None:
    global _ACTIVE_MESH
    _ACTIVE_MESH = mesh


def get_mesh() -> Optional[Mesh]:
    return _ACTIVE_MESH


def batch_spec(extra_dims: int = 0) -> P:
    """PartitionSpec for a batch-leading array, sharded over dp x fsdp."""
    return P(DATA_AXES, *([None] * extra_dims))


def batch_sharding(mesh: Mesh, extra_dims: int = 0) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(extra_dims))


def data_world_size(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    if mesh is None:
        return 1
    return mesh.shape[DP_AXIS] * mesh.shape[FSDP_AXIS]


def _process_data_groups(mesh: Mesh):
    """Group processes by the set of dataflow coordinates they own.

    Processes whose devices cover the same dataflow (dp x fsdp) slice
    (e.g. two hosts split along mp or pp) are *replicas* of the same
    data stream and must load identical batches; distinct coordinate
    sets are distinct loader ranks. Returns (groups, my_group_index)
    with groups ordered by their first dataflow coordinate.
    """
    dp_axis = mesh.axis_names.index(DP_AXIS)
    fsdp_axis = mesh.axis_names.index(FSDP_AXIS)
    coords = {}
    for idx, dev in np.ndenumerate(mesh.devices):
        pos = int(idx[dp_axis] * mesh.shape[FSDP_AXIS]
                  + idx[fsdp_axis])
        coords.setdefault(dev.process_index, set()).add(pos)
    groups = {}
    for proc, pos_set in coords.items():
        groups.setdefault(frozenset(pos_set), []).append(proc)
    ordered = sorted(groups, key=min)
    me = jax.process_index()
    mine = next(i for i, g in enumerate(ordered) if me in groups[g])
    return ordered, mine


def process_data_rank(mesh: Optional[Mesh] = None) -> int:
    """This process's data-loader rank: the index of its dataflow
    coordinate group. Processes that are mp/pp replicas of the same
    batch slice share a rank (and must load identical data)."""
    mesh = mesh or get_mesh()
    if mesh is None or jax.process_count() == 1:
        return 0
    return _process_data_groups(mesh)[1]


def process_data_loader_count(mesh: Optional[Mesh] = None) -> int:
    """Number of distinct data-loader ranks (== distinct dataflow
    coordinate groups across processes)."""
    mesh = mesh or get_mesh()
    if mesh is None or jax.process_count() == 1:
        return 1
    return len(_process_data_groups(mesh)[0])


def cpu_mesh_env(n: int = 8) -> None:
    """Force an ``n``-device CPU platform for mesh tests/dry-runs.

    Works whether or not jax is already imported (site customization
    may import jax at interpreter start): sets the env vars for a
    fresh process *and* updates jax.config for the current one. Must
    run before the first backend initialization.
    """
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n}")
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        # older jax has no jax_num_cpu_devices option; the XLA_FLAGS
        # line above already forces the host device count there
        pass
