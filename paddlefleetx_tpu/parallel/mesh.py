"""Device-mesh topology: the TPU-native HybridCommunicateGroup.

The reference builds a 4D NCCL HybridCommunicateGroup from
``strategy.hybrid_configs{dp,mp,pp,sharding}`` (reference
``ppfleetx/utils/env.py:49-69``) and queries per-axis ranks throughout.
On TPU the HCG *is* a ``jax.sharding.Mesh`` with named axes — XLA/GSPMD
emits the collectives that Fleet issued by hand, and they ride the ICI
torus because the mesh is laid out with ``mesh_utils`` so neighboring
mesh coordinates are ICI neighbors.

Axis convention (outermost to innermost):
  ``pp``   pipeline stages          (slowest-varying; DCN-friendly)
  ``dp``   pure data parallel
  ``fsdp`` sharding/ZeRO axis       (reference ``sharding_degree``)
  ``mp``   tensor parallel          (innermost; highest-bandwidth ICI)

The dataflow axis of the reference — ``dp_degree * sharding_degree``
(``env.py:76-96``), used for batch sharding, seeds, and checkpoint
dedup — is ``("dp", "fsdp")`` here.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PP_AXIS = "pp"
DP_AXIS = "dp"
FSDP_AXIS = "fsdp"
MP_AXIS = "mp"
MESH_AXES = (PP_AXIS, DP_AXIS, FSDP_AXIS, MP_AXIS)
#: the reference's dp x sharding composite dataflow axis (env.py:76-96)
DATA_AXES = (DP_AXIS, FSDP_AXIS)


@dataclasses.dataclass(frozen=True)
class TopologyConfig:
    """Parsed ``Distributed`` section; mirrors reference degree names."""
    dp_degree: int = 1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sharding_stage: int = 1
    sharding_offload: bool = False
    sequence_parallel: bool = False

    @classmethod
    def from_config(cls, config) -> "TopologyConfig":
        dist = config.get("Distributed", {}) if hasattr(config, "get") else {}
        sharding = dist.get("sharding", {}) or {}
        model = config.get("Model", {}) if hasattr(config, "get") else {}
        return cls(
            dp_degree=dist.get("dp_degree") or 1,
            mp_degree=dist.get("mp_degree") or 1,
            pp_degree=dist.get("pp_degree") or 1,
            sharding_degree=sharding.get("sharding_degree") or 1,
            sharding_stage=sharding.get("sharding_stage") or 1,
            sharding_offload=bool(sharding.get("sharding_offload", False)),
            sequence_parallel=bool(model.get("sequence_parallel", False)),
        )

    @property
    def world_size(self) -> int:
        return (self.dp_degree * self.mp_degree * self.pp_degree
                * self.sharding_degree)

    @property
    def data_world_size(self) -> int:
        return self.dp_degree * self.sharding_degree


def build_mesh(topo: TopologyConfig,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the 4-axis mesh ``(pp, dp, fsdp, mp)``.

    On real TPU slices ``mesh_utils.create_device_mesh`` maps mesh
    coordinates onto the physical ICI torus; elsewhere (CPU test
    meshes) a plain reshape is used.
    """
    shape = (topo.pp_degree, topo.dp_degree, topo.sharding_degree,
             topo.mp_degree)
    n = int(np.prod(shape))
    if devices is None:
        if n != jax.device_count():
            raise ValueError(
                f"topology {dict(zip(MESH_AXES, shape))} covers {n} devices "
                f"but {jax.device_count()} are available; set Distributed "
                f"degrees to use every device (reference asserts the same, "
                f"utils/config.py:54)")
        if jax.devices()[0].platform == "tpu":
            from jax.experimental import mesh_utils
            dev_array = mesh_utils.create_device_mesh(shape)
        else:
            dev_array = np.asarray(jax.devices()).reshape(shape)
    else:
        if len(devices) != n:
            raise ValueError(
                f"topology {shape} needs exactly {n} devices, "
                f"got {len(devices)}")
        # caller-supplied order is authoritative (tests, sub-meshes)
        dev_array = np.asarray(list(devices)).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


_ACTIVE_MESH: Optional[Mesh] = None


def set_mesh(mesh: Optional[Mesh]) -> None:
    global _ACTIVE_MESH
    _ACTIVE_MESH = mesh


def get_mesh() -> Optional[Mesh]:
    return _ACTIVE_MESH


def batch_spec(extra_dims: int = 0) -> P:
    """PartitionSpec for a batch-leading array, sharded over dp x fsdp."""
    return P(DATA_AXES, *([None] * extra_dims))


def batch_sharding(mesh: Mesh, extra_dims: int = 0) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(extra_dims))


def data_world_size(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    if mesh is None:
        return 1
    return mesh.shape[DP_AXIS] * mesh.shape[FSDP_AXIS]


def process_data_rank(mesh: Optional[Mesh] = None) -> int:
    """This process's rank among all *processes* ordered along the
    dataflow (dp x fsdp) axis.

    Used for per-host data loading: host h feeds batch shards
    ``[process_data_rank :: jax.process_count()]`` and the engine
    assembles them into a global array. Processes are ordered by the
    first dataflow coordinate their local devices own, so consecutive
    ranks feed consecutive slices of the global batch.
    """
    mesh = mesh or get_mesh()
    if mesh is None or jax.process_count() == 1:
        return 0
    first_coord = {}
    for idx, dev in np.ndenumerate(mesh.devices):
        _, dp_i, fsdp_i, _ = idx
        pos = int(dp_i * mesh.shape[FSDP_AXIS] + fsdp_i)
        p = dev.process_index
        first_coord[p] = min(first_coord.get(p, 1 << 62), pos)
    order = sorted(first_coord, key=lambda p: (first_coord[p], p))
    return order.index(jax.process_index())


def cpu_mesh_env(n: int = 8) -> None:
    """Force an ``n``-device CPU platform for mesh tests/dry-runs.

    Works whether or not jax is already imported (site customization
    may import jax at interpreter start): sets the env vars for a
    fresh process *and* updates jax.config for the current one. Must
    run before the first backend initialization.
    """
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n}")
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", n)
