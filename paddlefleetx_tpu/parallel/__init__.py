"""Parallel subpackage."""
from .mesh import (  # noqa: F401
    DP_AXIS, FSDP_AXIS, MP_AXIS, PP_AXIS, DATA_AXES,
    TopologyConfig, build_mesh, get_mesh, set_mesh, batch_spec,
    data_world_size,
)
from .sharding import (  # noqa: F401
    make_sharding_rules, logical_to_mesh_spec, shard_logical,
    param_shardings, with_logical_constraint,
)
