"""Pipeline parallelism: SPMD microbatch pipelines over the ``pp`` axis.

The reference's PP stack is bespoke machinery inside Paddle —
``PipelineLayer`` flattens the model into ``LayerDesc`` lists
(reference ``hybrid_model.py:895-961``), a 1F1B scheduler drives
``train_batch`` with NCCL P2P send/recv between stage ranks
(``eager_engine.py:406-415``), interleaved stages come from
``virtual_pp_degree`` chunk assignment (``hybrid_model.py:962``,
validation ``models/language_model/utils.py:76-100``), and shared
embeddings are tied across first/last stages via ``SharedLayerDesc``.

TPU-native design: none of that machinery is rank-local here. The
whole pipeline is ONE jitted SPMD program:

  - layer parameters stay in the same stacked ``[L, ...]`` layout the
    scan-over-layers model already uses, sharded over ``pp`` on the
    leading axis, so checkpoints are topology-portable — unlike the
    reference's per-rank ``pdparams`` dirs. With ``virtual_pp_degree
    = vpp > 1`` the reshape to ``[vpp, S, L/(S*vpp), ...]`` (sharded
    over ``pp`` on axis 1) gives physical stage ``s`` the
    non-contiguous layer chunks ``{s, S+s, 2S+s, ...}`` — exactly the
    reference's interleaved assignment;
  - a ``[vpp, S, microbatch, ...]`` slot buffer is sharded over
    ``pp``; each pipeline tick runs every virtual stage's local
    layers in parallel (a ``vmap`` over slots of a ``lax.scan`` over
    the slot's layers) and advances the buffer with a roll along the
    virtual-stage order, which GSPMD lowers to a collective-permute
    between ICI neighbors — the NCCL P2P of the reference;
  - two schedules are provided. ``pipeline_forward`` is the
    forward-only GPipe fill/drain (``M + S*vpp - 1`` ticks); taking
    ``jax.grad`` through it yields a GPipe-memory-profile backward.
    ``pipeline_value_and_grad`` is an explicit 1F1B: each tick runs
    one forward slot-wave and one backward slot-wave (per-slot
    ``jax.vjp`` with recompute, the reference 1F1B's memory story),
    so the activation stash holds at most ``2*S*vpp`` microbatch
    activations per slot-ring instead of all ``M`` — peak activation
    memory is bounded by pipeline depth, not microbatch count;
  - embeddings and the LM head are compute-replicated over ``pp``
    (their FLOPs are negligible next to the decoder stack), which
    makes the reference's ``SharedLayerDesc`` embedding tying
    (``hybrid_model.py:934-945``) trivial: there is only one
    embedding table, visible to both ends of the pipeline.

Schedule timing (K = S*vpp virtual stages): forward of microbatch
``m`` at virtual stage ``k`` happens at tick ``m + k``; its loss (and
output cotangent) at tick ``m + K - 1``; its backward at stage ``k``
at tick ``m + 2K - 1 - k``. An activation stashed at the forward tick
is consumed ``2(K - 1 - k) + 1 < 2K`` ticks later, so a depth-``2K``
ring buffer never collides. The 1F1B bubble is the same ``(K-1)``-tick
fill/drain as GPipe's; the win is memory (the reference's motivation
for defaulting to 1F1B).

Zero-bubble schedule (``schedule="zb"``, after the ZB-H1 family of
arXiv:2412.14374): each stage's backward splits into dX (the input
cotangent, which stays on the critical path — the next stage's
backward needs it one tick later) and dW (the weight gradient, which
nothing downstream consumes until the optimizer). dX runs at the same
tick 1F1B runs the combined backward; the dW job is pushed into a
bounded per-slot FIFO and drained during ticks where that slot's
backward wave is otherwise idle — virtual stage ``k`` has exactly
``k`` such drain-bubble ticks at the end of the schedule, so its
queue capacity is ``min(k, M)`` and every deferred dW lands in a
formerly-empty slot-tick. The drain order is FIFO, so per-slot weight
gradients accumulate in the same microbatch order as 1F1B and the
results match bitwise up to XLA scheduling. Because the whole
schedule is a static function of ``(M, K)``, the pop timetable is
precomputed host-side (``zb_dw_schedule``) and fed to the scan as
per-tick indices; the same host math yields the
``pipeline/{fwd,bwd_dx,bwd_dw,bubble}_ticks`` trace-time counters
that make the occupancy win auditable (docs/pipeline.md).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..observability import metrics
from .mesh import DATA_AXES, PP_AXIS, get_mesh


def _constrain(x, spec: P):
    """Sharding constraint against the active mesh (no-op without one)."""
    mesh = get_mesh()
    if mesh is None or mesh.size == 1:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def _slot_params(stacked_params: Any, S: int, vpp: int) -> Tuple[Any, int]:
    """``[L, ...]`` stacked params -> ``[vpp, S, L/(S*vpp), ...]``
    sharded over ``pp`` on the physical-stage axis. Virtual stage
    ``k = v*S + s`` owns the contiguous layer block ``[k*Lc, (k+1)*Lc)``
    — i.e. physical stage ``s`` owns interleaved chunks."""
    leaves = jax.tree.leaves(stacked_params)
    if not leaves:
        raise ValueError("stacked_params has no leaves")
    L = leaves[0].shape[0]
    K = S * vpp
    if L % K != 0:
        raise ValueError(
            f"num_layers {L} not divisible by pp*vpp {K}")
    Lc = L // K
    slotted = jax.tree.map(
        lambda p: _constrain(p.reshape(vpp, S, Lc, *p.shape[1:]),
                             P(None, PP_AXIS)), stacked_params)
    return slotted, Lc


def _advance(processed: jax.Array, vpp: int) -> jax.Array:
    """Forward roll along the virtual-stage order: slot k's output
    becomes slot k+1's next input. The s-axis roll is the inter-stage
    collective-permute; chunk wrap (s=S-1 -> next chunk's s=0) moves
    within the same device ring."""
    nxt = jnp.roll(processed, 1, axis=1)
    if vpp > 1:
        wrapped = jnp.roll(processed[:, -1], 1, axis=0)
        nxt = nxt.at[:, 0].set(wrapped)
    return nxt


def _retreat(b_out: jax.Array, dy_prev: jax.Array, vpp: int) -> jax.Array:
    """Backward roll: slot k's next cotangent is slot k+1's backward
    output; the last virtual stage ingests the loss cotangent."""
    g = jnp.roll(b_out, -1, axis=1)
    if vpp > 1:
        wrapped = jnp.roll(b_out[:, 0], -1, axis=0)
        g = g.at[:, -1].set(wrapped)
    return g.at[-1, -1].set(dy_prev)


def _slot_keys(base_rng: jax.Array, m_arr: jax.Array,
               K: int) -> jax.Array:
    """Per-slot dropout keys folded by (microbatch, virtual stage) so
    a 1F1B backward recompute reproduces the forward's masks exactly
    (tick-based folding would not: F and B of the same microbatch
    happen at different ticks)."""
    k_arr = jnp.arange(K)

    def key_for(m, k):
        return jax.random.fold_in(jax.random.fold_in(base_rng, m), k)

    return jax.vmap(key_for)(m_arr, k_arr)


def zb_queue_bound(num_microbatches: int, num_virtual_stages: int) -> int:
    """Upper bound on the zb per-slot dW-queue depth: virtual stage
    ``k`` defers at most ``min(k, M)`` weight-grad jobs (it has exactly
    ``k`` drain-bubble ticks to spend them in), so no slot ever queues
    more than ``min(K - 1, M)`` microbatch cotangents."""
    return min(num_virtual_stages - 1, num_microbatches)


def zb_dw_schedule(num_microbatches: int, num_virtual_stages: int):
    """Static dW drain timetable for the zero-bubble schedule.

    Pure host math — the 1F1B tick grid is a fixed function of
    ``(M, K)``, so *when* each deferred weight-grad job runs is decided
    here, not inside the scan. Per virtual stage ``k`` a FIFO of
    capacity ``min(k, M)`` receives one job at each dX tick; a job pops
    (and its dW runs) either when the push would overflow the capacity
    (steady state — the same tick, exactly like 1F1B, for ``k = 0``) or
    at a tick where the slot's backward wave is idle (the former
    drain-bubble ticks, which the deferred jobs now fill).

    Returns ``(dw_m, max_depth)``: ``dw_m`` is an int ``[T, K]`` array
    (``T = M + 2K - 1``) whose entry is the microbatch whose dW runs at
    that (tick, virtual stage), or ``-1``; ``max_depth`` is the deepest
    any FIFO ever got (``<= zb_queue_bound(M, K)``).
    """
    M, K = num_microbatches, num_virtual_stages
    T = M + 2 * K - 1
    dw_m = np.full((T, K), -1, np.int32)
    max_depth = 0
    for k in range(K):
        cap = min(k, M)
        fifo: list = []
        for t in range(T):
            m_b = t - (2 * K - 1 - k)
            if 0 <= m_b < M:
                fifo.append(m_b)
                if len(fifo) > cap:
                    dw_m[t, k] = fifo.pop(0)
            elif fifo:
                dw_m[t, k] = fifo.pop(0)
            max_depth = max(max_depth, len(fifo))
        if fifo:   # every job must drain within the schedule
            raise AssertionError(
                f"zb schedule leaked {len(fifo)} dW jobs at stage {k}")
    return dw_m, max_depth


def pipeline_tick_stats(num_microbatches: int, num_virtual_stages: int,
                        schedule: str = "1f1b") -> dict:
    """Analytic (slot, tick) occupancy of a pipeline schedule.

    The scan runs in SPMD lockstep, so tick counts are trace-time
    constants — this is the single source for the
    ``pipeline/{fwd,bwd_dx,bwd_dw,bubble}_ticks`` counters and the
    engine's ``pipeline_bubble`` goodput bucket. A slot-tick counts as
    ``bubble`` when the slot schedules NO useful work there: no valid
    forward, no valid dX/backward, and (zb) no drained dW job. For
    ``M >= 2K - 1`` the zb drain fills every trailing bubble slot-tick,
    halving ``bubble_ticks`` vs 1f1b — the fill-phase half precedes any
    runnable job and is irreducible in a lockstep schedule.
    """
    M, K = num_microbatches, num_virtual_stages
    sched = str(schedule).lower()
    if sched == "gpipe":
        T = M + K - 1
        fwd = np.zeros((T, K), bool)
        for k in range(K):
            fwd[k:k + M, k] = True
        return {"fwd_ticks": int(fwd.sum()), "bwd_dx_ticks": 0,
                "bwd_dw_ticks": 0,
                "bubble_ticks": int(T * K - fwd.sum()),
                "total_slot_ticks": T * K}
    if sched not in ("1f1b", "zb"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    T = M + 2 * K - 1
    fwd = np.zeros((T, K), bool)
    bwd = np.zeros((T, K), bool)
    for k in range(K):
        fwd[k:k + M, k] = True
        bwd[2 * K - 1 - k:2 * K - 1 - k + M, k] = True
    if sched == "zb":
        dw = zb_dw_schedule(M, K)[0] >= 0
    else:
        dw = bwd   # 1f1b computes dW in the same tick as dX
    busy = fwd | bwd | dw
    return {"fwd_ticks": int(fwd.sum()),
            "bwd_dx_ticks": int(bwd.sum()),
            "bwd_dw_ticks": int(dw.sum()),
            "bubble_ticks": int(T * K - busy.sum()),
            "total_slot_ticks": T * K}


def pipeline_forward(
    layer_apply: Callable[[Any, jax.Array, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    *,
    pp: int,
    num_microbatches: int,
    vpp: int = 1,
    out_fn: Optional[Callable[[Any, jax.Array, Any], Any]] = None,
    out_init: Any = None,
    extras: Any = None,
    rng: Optional[jax.Array] = None,
    layer_has_aux: bool = False,
) -> Any:
    """Run ``x`` through ``L`` stacked layers with a GPipe-scheduled
    ``pp``-stage (optionally ``vpp``-way interleaved) pipeline.

    Args:
      layer_apply: ``(layer_params, h, rng_key) -> h`` — one decoder
        layer as a pure function (wrap with ``jax.checkpoint`` for
        recompute before passing).
      stacked_params: pytree whose leaves have leading dim ``L``
        (``nn.scan`` layout), ``L % (pp * vpp) == 0``.
      x: ``[B, ...]`` input activations, ``B % num_microbatches == 0``.
      pp: number of physical pipeline stages (mesh ``pp`` axis size).
      num_microbatches: M; the reference's ``accumulate_steps``
        (``utils/config.py:117``).
      vpp: interleaved virtual stages per physical stage (the
        reference's ``virtual_pp_degree``).
      out_fn: optional per-microbatch reducer ``(acc, y_mb, extras_mb)
        -> acc`` applied to the last stage's output (e.g. LM head +
        loss). When given, the full ``[B, ...]`` output is never
        materialized — the pipelined analogue of the reference
        computing loss per microbatch inside ``train_batch``.
      out_init: initial reducer carry (required with ``out_fn``).
      extras: pytree of ``[B, ...]`` arrays sliced per-microbatch and
        fed to ``out_fn`` (labels, loss masks).
      rng: base dropout key; folded per (microbatch, virtual stage,
        layer).
      layer_has_aux: ``layer_apply`` returns ``(h, aux_scalar)`` (MoE
        layers: the router aux loss). This forward-only schedule
        DISCARDS the aux — eval reports pure CE (docs/moe.md); the
        training aux flows through ``pipeline_value_and_grad``.

    Returns the reducer carry, or the ``[B, ...]`` outputs when
    ``out_fn`` is None.
    """
    S, M = pp, num_microbatches
    K = S * vpp
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    ts = pipeline_tick_stats(M, K, schedule="gpipe")
    metrics.inc("pipeline/fwd_ticks", ts["fwd_ticks"])
    metrics.inc("pipeline/bubble_ticks", ts["bubble_ticks"])
    slot_params, Lc = _slot_params(stacked_params, S, vpp)

    x_mb = x.reshape(M, B // M, *x.shape[1:])
    x_mb = _constrain(x_mb, P(None, DATA_AXES))
    extras_mb = None
    if extras is not None:
        extras_mb = jax.tree.map(
            lambda e: e.reshape(M, B // M, *e.shape[1:]), extras)

    state0 = _constrain(
        jnp.zeros((vpp, S) + x_mb.shape[1:], x.dtype),
        P(None, PP_AXIS, DATA_AXES))
    collect = out_fn is None
    acc0 = jnp.zeros_like(x_mb) if collect else out_init
    base_rng = rng if rng is not None else jax.random.key(0)

    def stage_fn(sp, h, key):
        def body(h, xs):
            lp, k = xs
            out = layer_apply(lp, h, k)
            return (out[0] if layer_has_aux else out), None
        h, _ = jax.lax.scan(body, h, (sp, jax.random.split(key, Lc)))
        return h

    slot_stage = jax.vmap(jax.vmap(stage_fn))

    def tick(carry, t):
        """One pipeline clock: every virtual stage computes, then
        activations rotate one hop."""
        state, acc = carry
        # virtual stage 0 ingests microbatch t (clamped past the fill
        # phase — drain ticks feed it a stale microbatch whose output
        # is never collected)
        inp = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.minimum(t, M - 1), 0, keepdims=False)
        state = _constrain(state.at[0, 0].set(inp),
                           P(None, PP_AXIS, DATA_AXES))

        m_arr = jnp.clip(t - jnp.arange(K), 0, M - 1)
        keys = _slot_keys(base_rng, m_arr, K).reshape(vpp, S)
        processed = slot_stage(slot_params, state, keys)
        processed = _constrain(processed, P(None, PP_AXIS, DATA_AXES))

        # collect the last virtual stage's output for microbatch
        # t-(K-1); ticks before the pipeline is full carry warmup
        # garbage — the cond skips the collection (and the reducer's
        # head/loss FLOPs) entirely on those ticks
        y = processed[-1, -1]
        idx = jnp.clip(t - (K - 1), 0, M - 1)
        valid = t >= K - 1
        if collect:
            acc = jax.lax.cond(
                valid,
                lambda a: jax.lax.dynamic_update_index_in_dim(
                    a, y, idx, 0),
                lambda a: a, acc)
        else:
            def reduce(a):
                ex = None
                if extras_mb is not None:
                    ex = jax.tree.map(
                        lambda e: jax.lax.dynamic_index_in_dim(
                            e, idx, 0, keepdims=False), extras_mb)
                return out_fn(a, y, ex)
            acc = jax.lax.cond(valid, reduce, lambda a: a, acc)

        state = _advance(processed, vpp)
        return (state, acc), None

    (_, acc), _ = jax.lax.scan(tick, (state0, acc0),
                               jnp.arange(M + K - 1))
    if collect:
        return acc.reshape(B, *x.shape[1:])
    return acc


def pipeline_value_and_grad(
    layer_apply: Callable[[Any, jax.Array, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    *,
    pp: int,
    num_microbatches: int,
    vpp: int = 1,
    loss_and_grad: Callable[[jax.Array, Any],
                            Tuple[jax.Array, jax.Array, Any]],
    extras: Any = None,
    rng: Optional[jax.Array] = None,
    schedule: str = "1f1b",
    layer_has_aux: bool = False,
) -> Tuple[jax.Array, Any, Any, jax.Array]:
    """Explicit 1F1B (or zero-bubble) schedule: loss AND gradients in
    one pass.

    Unlike ``jax.grad(pipeline_forward)`` — which structurally runs
    all forwards before any backward and therefore stashes every
    microbatch's activations (the GPipe memory profile) — each tick
    here runs one forward slot-wave and one backward slot-wave. A
    microbatch's backward starts ``1`` tick after its loss, so the
    activation ring holds at most ``2K`` entries per slot regardless
    of ``M`` (the 1F1B property; reference default schedule,
    ``hybrid_model.py:962`` area). The per-slot backward is
    ``jax.vjp`` of the slot forward — recompute-from-stashed-input,
    i.e. full recompute granularity, matching how the reference runs
    PP with recompute enabled.

    Args:
      layer_apply / stacked_params / x / pp / vpp / extras / rng: as
        in ``pipeline_forward``.
      num_microbatches: M (gradient accumulation happens inside the
        schedule).
      loss_and_grad: ``(y_mb, extras_mb) -> (loss_mb, dy_mb,
        dhead_mb)`` — per-microbatch loss, its cotangent wrt ``y_mb``,
        and the gradient pytree for any head/criterion parameters
        closed over by the caller (summed over microbatches here).
      schedule: ``"1f1b"`` (the combined backward above) or ``"zb"``
        (zero-bubble: dX-only vjp on the critical path, dW replayed
        from the stashed input at the statically precomputed drain
        tick — see the module docstring). Gradients are identical
        between the two: the dW FIFO drains in microbatch order, so
        even the fp32 accumulation order matches.
      layer_has_aux: ``layer_apply`` returns ``(h, aux_scalar)`` (MoE
        router aux loss). The aux of every valid (microbatch, virtual
        stage) is added to ``loss_sum`` at its forward tick, and a
        unit aux cotangent rides the matching dX/dW pulls so router
        gradients flow through both schedules.

    Returns ``(loss_sum, d_stacked, dhead_sum, dx)`` where
    ``d_stacked`` matches ``stacked_params``' ``[L, ...]`` layout,
    ``dhead_sum`` sums ``dhead_mb`` over microbatches, and ``dx`` is
    the ``[B, ...]`` cotangent wrt ``x`` (feed it to the embedding
    vjp). All sums are over microbatches — divide by M for a mean.
    """
    S, M = pp, num_microbatches
    K = S * vpp
    D = 2 * K  # activation ring depth; see module docstring
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    sched = str(schedule).lower()
    if sched not in ("1f1b", "zb"):
        raise ValueError(
            f"unknown pipeline schedule {schedule!r} (expected '1f1b' "
            f"or 'zb'; GPipe routes through pipeline_forward)")
    # trace-time occupancy counters: the tick grid is a static function
    # of (M, K), so one inc per compilation records the whole schedule
    ts = pipeline_tick_stats(M, K, schedule=sched)
    metrics.inc("pipeline/fwd_ticks", ts["fwd_ticks"])
    metrics.inc("pipeline/bwd_dx_ticks", ts["bwd_dx_ticks"])
    metrics.inc("pipeline/bwd_dw_ticks", ts["bwd_dw_ticks"])
    metrics.inc("pipeline/bubble_ticks", ts["bubble_ticks"])
    slot_params, Lc = _slot_params(stacked_params, S, vpp)

    x_mb = x.reshape(M, B // M, *x.shape[1:])
    x_mb = _constrain(x_mb, P(None, DATA_AXES))
    extras_mb = jax.tree.map(
        lambda e: e.reshape(M, B // M, *e.shape[1:]), extras) \
        if extras is not None else None
    base_rng = rng if rng is not None else jax.random.key(0)
    mb_shape = x_mb.shape[1:]

    def stage_fn(sp, h, key):
        def body(h, xs):
            lp, k = xs
            if layer_has_aux:
                h, aux = layer_apply(lp, h, k)
                return h, aux
            return layer_apply(lp, h, k), None
        h, auxs = jax.lax.scan(body, h, (sp, jax.random.split(key, Lc)))
        if layer_has_aux:
            return h, jnp.sum(auxs)
        return h

    slot_stage = jax.vmap(jax.vmap(stage_fn))

    # The combined pull (1f1b) extracts dW and dX from one backward;
    # the zb pulls split them — dX on the critical path, dW replayed
    # later from the stashed input. With layer_has_aux the aux
    # cotangent (1.0 on valid work, else 0.0) rides along so router
    # aux gradients flow at exactly the ticks the matching dX/dW run.
    def slot_vjp(sp, h, key, g):
        _, pull = jax.vjp(lambda p, hh: stage_fn(p, hh, key), sp, h)
        return pull(g)

    def slot_vjp_aux(sp, h, key, g, a_ct):
        _, pull = jax.vjp(lambda p, hh: stage_fn(p, hh, key), sp, h)
        return pull((g, a_ct))

    def slot_dx(sp, h, key, g):
        _, pull = jax.vjp(lambda hh: stage_fn(sp, hh, key), h)
        return pull(g)[0]

    def slot_dx_aux(sp, h, key, g, a_ct):
        _, pull = jax.vjp(lambda hh: stage_fn(sp, hh, key), h)
        return pull((g, a_ct))[0]

    def slot_dw(sp, h, key, g):
        _, pull = jax.vjp(lambda p: stage_fn(p, h, key), sp)
        return pull(g)[0]

    def slot_dw_aux(sp, h, key, g, a_ct):
        _, pull = jax.vjp(lambda p: stage_fn(p, h, key), sp)
        return pull((g, a_ct))[0]

    slot_backward = jax.vmap(jax.vmap(slot_vjp))
    slot_backward_aux = jax.vmap(jax.vmap(slot_vjp_aux))
    slot_backward_dx = jax.vmap(jax.vmap(slot_dx))
    slot_backward_dx_aux = jax.vmap(jax.vmap(slot_dx_aux))
    slot_backward_dw = jax.vmap(jax.vmap(slot_dw))
    slot_backward_dw_aux = jax.vmap(jax.vmap(slot_dw_aux))

    # zero templates for the loss head's outputs
    y_abs = jax.ShapeDtypeStruct(mb_shape, x.dtype)
    ex_abs = jax.tree.map(
        lambda e: jax.ShapeDtypeStruct(e.shape[1:], e.dtype), extras_mb) \
        if extras_mb is not None else None
    _, dy_abs, dhead_abs = jax.eval_shape(loss_and_grad, y_abs, ex_abs)
    zeros_of = lambda ab: jax.tree.map(  # noqa: E731
        lambda a: jnp.zeros(a.shape, a.dtype), ab)

    fstate0 = _constrain(jnp.zeros((vpp, S) + mb_shape, x.dtype),
                         P(None, PP_AXIS, DATA_AXES))
    # cotangents ride in fp32 regardless of the compute dtype (the
    # backward wave accumulates them into fp32 param grads)
    bstate0 = _constrain(jnp.zeros((vpp, S) + mb_shape, jnp.float32),
                         P(None, PP_AXIS, DATA_AXES))
    stash0 = _constrain(jnp.zeros((vpp, S, D) + mb_shape, x.dtype),
                        P(None, PP_AXIS, None, DATA_AXES))
    dparams0 = jax.tree.map(
        lambda p: _constrain(jnp.zeros(p.shape, jnp.float32),
                             P(None, PP_AXIS)), slot_params)
    dhead0 = zeros_of(dhead_abs)
    dy0 = zeros_of(dy_abs)
    dx0 = _constrain(jnp.zeros((M,) + mb_shape, jnp.float32),
                     P(None, DATA_AXES))
    loss0 = jnp.zeros((), jnp.float32)

    k_arr = jnp.arange(K)

    def _gather_ring(ring, depths):
        """Per-slot dynamic read of a ``[vpp, S, depth, ...]`` ring."""
        return jax.vmap(jax.vmap(
            lambda st, d: jax.lax.dynamic_index_in_dim(
                st, d, 0, keepdims=False)))(ring,
                                            depths.reshape(vpp, S))

    def _accumulate(dparams, dp, mask):
        return jax.tree.map(
            lambda acc, g: acc + jnp.where(
                mask.reshape(mask.shape + (1,) * (g.ndim - 2)),
                g.astype(jnp.float32), 0.0),
            dparams, dp)

    def _forward_wave(fstate, stash, loss_sum, t):
        inp = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, M - 1), 0, keepdims=False)
        # .at[0, 0].set is a two-dim-index scatter; with dim 1 sharded
        # over pp the SPMD partitioner mis-broadcasts the index
        # concatenation (hlo-verifier RET_CHECK on 0.4.x). A
        # dynamic_update_slice at a constant origin partitions cleanly
        # and is the same write.
        fstate = jax.lax.dynamic_update_slice(
            fstate, inp[None, None], (0,) * fstate.ndim)
        fstate = _constrain(fstate, P(None, PP_AXIS, DATA_AXES))
        stash = _constrain(stash.at[:, :, t % D].set(fstate),
                           P(None, PP_AXIS, None, DATA_AXES))
        m_f = jnp.clip(t - k_arr, 0, M - 1)
        f_keys = _slot_keys(base_rng, m_f, K).reshape(vpp, S)
        if layer_has_aux:
            processed, aux_f = slot_stage(slot_params, fstate, f_keys)
            valid_f = jnp.logical_and(t - k_arr >= 0, t - k_arr < M)
            loss_sum = loss_sum + jnp.sum(
                jnp.where(valid_f.reshape(vpp, S), aux_f, 0.0))
        else:
            processed = slot_stage(slot_params, fstate, f_keys)
        processed = _constrain(processed, P(None, PP_AXIS, DATA_AXES))
        return processed, stash, loss_sum

    def _loss_head(processed, t, loss_sum, dhead):
        m_l = t - (K - 1)
        y_last = processed[-1, -1]
        ex = jax.tree.map(
            lambda e: jax.lax.dynamic_index_in_dim(
                e, jnp.clip(m_l, 0, M - 1), 0, keepdims=False),
            extras_mb) if extras_mb is not None else None

        def do_loss(_):
            return loss_and_grad(y_last, ex)

        def no_loss(_):
            return loss0, dy0, zeros_of(dhead_abs)

        valid_l = jnp.logical_and(m_l >= 0, m_l < M)
        loss_mb, dy_new, dhead_mb = jax.lax.cond(valid_l, do_loss,
                                                 no_loss, None)
        loss_sum = loss_sum + loss_mb
        dhead = jax.tree.map(jnp.add, dhead, dhead_mb)
        return loss_sum, dy_new, dhead

    def _dx_capture(dx, dh, t):
        # cotangent wrt the pipeline input, for the embedding backward
        m_b0 = t - (2 * K - 1)
        return jax.lax.cond(
            jnp.logical_and(m_b0 >= 0, m_b0 < M),
            lambda d: jax.lax.dynamic_update_index_in_dim(
                d, dh[0, 0].astype(jnp.float32),
                jnp.clip(m_b0, 0, M - 1), 0),
            lambda d: d, dx)

    if sched == "1f1b":
        def tick(carry, t):
            """One 1F1B clock: forward wave + combined backward wave
            (dW and dX in a single pull)."""
            fstate, b_out, dy_prev, stash, loss_sum, dparams, dhead, \
                dx = carry
            processed, stash, loss_sum = _forward_wave(
                fstate, stash, loss_sum, t)
            loss_sum, dy_new, dhead = _loss_head(
                processed, t, loss_sum, dhead)

            # ---- backward wave --------------------------------------
            m_b = t - (2 * K - 1 - k_arr)
            valid_b = jnp.logical_and(m_b >= 0, m_b < M)
            g_in = _retreat(b_out, dy_prev, vpp)
            g_in = _constrain(g_in, P(None, PP_AXIS, DATA_AXES))
            depth = (t - (2 * K - 1) + 2 * k_arr) % D  # fwd-tick slot
            x_in = _gather_ring(stash, depth)
            b_keys = _slot_keys(base_rng, jnp.clip(m_b, 0, M - 1),
                                K).reshape(vpp, S)
            g_cast = g_in.astype(x.dtype)
            if layer_has_aux:
                dp, dh = slot_backward_aux(
                    slot_params, x_in, b_keys, g_cast,
                    valid_b.astype(jnp.float32).reshape(vpp, S))
            else:
                dp, dh = slot_backward(slot_params, x_in, b_keys,
                                       g_cast)
            dparams = _accumulate(dparams, dp, valid_b.reshape(vpp, S))
            b_out_new = _constrain(dh.astype(jnp.float32),
                                   P(None, PP_AXIS, DATA_AXES))
            dx = _dx_capture(dx, dh, t)

            fstate = _advance(processed, vpp)
            return (fstate, b_out_new, dy_new, stash, loss_sum,
                    dparams, dhead, dx), None

        carry0 = (fstate0, bstate0, dy0, stash0, loss0, dparams0,
                  dhead0, dx0)
        (_, _, _, _, loss_sum, dparams, dhead, dx), _ = jax.lax.scan(
            tick, carry0, jnp.arange(M + 2 * K - 1))
    else:
        # ---- zero-bubble: dX on the critical path, dW drained at the
        # statically precomputed tick (module docstring) --------------
        dw_np, _ = zb_dw_schedule(M, K)
        dw_rows = jnp.asarray(dw_np.reshape(len(dw_np), vpp, S))
        # cotangent ring: the dW queue holds at most min(k, M) + 1
        # entries per slot (<= K), indexed m % K; row K is scratch so
        # masked writes never clobber a live entry
        gstash0 = _constrain(
            jnp.zeros((vpp, S, K + 1) + mb_shape, x.dtype),
            P(None, PP_AXIS, None, DATA_AXES))

        def tick(carry, xs):
            """One zb clock: forward wave + dX wave + dW drain."""
            t, dw_m = xs
            fstate, b_out, dy_prev, stash, gstash, loss_sum, dparams, \
                dhead, dx = carry
            processed, stash, loss_sum = _forward_wave(
                fstate, stash, loss_sum, t)
            loss_sum, dy_new, dhead = _loss_head(
                processed, t, loss_sum, dhead)

            # ---- dX wave (critical path) ----------------------------
            m_b = t - (2 * K - 1 - k_arr)
            valid_b = jnp.logical_and(m_b >= 0, m_b < M)
            g_in = _retreat(b_out, dy_prev, vpp)
            g_in = _constrain(g_in, P(None, PP_AXIS, DATA_AXES))
            depth = (t - (2 * K - 1) + 2 * k_arr) % D
            x_in = _gather_ring(stash, depth)
            b_keys = _slot_keys(base_rng, jnp.clip(m_b, 0, M - 1),
                                K).reshape(vpp, S)
            g_cast = g_in.astype(x.dtype)
            if layer_has_aux:
                dh = slot_backward_dx_aux(
                    slot_params, x_in, b_keys, g_cast,
                    valid_b.astype(jnp.float32).reshape(vpp, S))
            else:
                dh = slot_backward_dx(slot_params, x_in, b_keys,
                                      g_cast)
            b_out_new = _constrain(dh.astype(jnp.float32),
                                   P(None, PP_AXIS, DATA_AXES))
            dx = _dx_capture(dx, dh, t)

            # enqueue the cotangent for the deferred dW. The write
            # happens before the drain read on purpose: the k=0 slot
            # (capacity 0) pops the entry it pushed this very tick.
            gdepth = jnp.where(valid_b, jnp.clip(m_b, 0, M - 1) % K, K)
            gstash = jax.vmap(jax.vmap(
                lambda gs, d, gg:
                jax.lax.dynamic_update_index_in_dim(gs, gg, d, 0)))(
                gstash, gdepth.reshape(vpp, S), g_cast)
            gstash = _constrain(gstash,
                                P(None, PP_AXIS, None, DATA_AXES))

            # ---- dW drain at the precomputed tick -------------------
            dw_flat = dw_m.reshape(K)
            valid_w = dw_flat >= 0
            w_m = jnp.clip(dw_flat, 0, M - 1)
            # forward of mb m at slot k ran at tick m + k, so its
            # stashed input lives at ring depth (m + k) % D
            x_w = _gather_ring(stash, (w_m + k_arr) % D)
            g_w = _gather_ring(gstash, jnp.where(valid_w, w_m % K, K))
            w_keys = _slot_keys(base_rng, w_m, K).reshape(vpp, S)
            if layer_has_aux:
                dp = slot_backward_dw_aux(
                    slot_params, x_w, w_keys, g_w,
                    valid_w.astype(jnp.float32).reshape(vpp, S))
            else:
                dp = slot_backward_dw(slot_params, x_w, w_keys, g_w)
            dparams = _accumulate(dparams, dp, valid_w.reshape(vpp, S))

            fstate = _advance(processed, vpp)
            return (fstate, b_out_new, dy_new, stash, gstash,
                    loss_sum, dparams, dhead, dx), None

        carry0 = (fstate0, bstate0, dy0, stash0, gstash0, loss0,
                  dparams0, dhead0, dx0)
        (_, _, _, _, _, loss_sum, dparams, dhead, dx), _ = \
            jax.lax.scan(tick, carry0,
                         (jnp.arange(M + 2 * K - 1), dw_rows))

    d_stacked = jax.tree.map(
        lambda g, p: g.reshape(p.shape).astype(p.dtype),
        dparams, stacked_params)
    return loss_sum, d_stacked, dhead, dx.reshape(B, *x.shape[1:])
