"""Pipeline parallelism: SPMD microbatch pipelines over the ``pp`` axis.

The reference's PP stack is bespoke machinery inside Paddle —
``PipelineLayer`` flattens the model into ``LayerDesc`` lists
(reference ``hybrid_model.py:895-961``), a 1F1B scheduler drives
``train_batch`` with NCCL P2P send/recv between stage ranks
(``eager_engine.py:406-415``), interleaved stages come from
``virtual_pp_degree`` chunk assignment (``hybrid_model.py:962``,
validation ``models/language_model/utils.py:76-100``), and shared
embeddings are tied across first/last stages via ``SharedLayerDesc``.

TPU-native design: none of that machinery is rank-local here. The
whole pipeline is ONE jitted SPMD program:

  - layer parameters stay in the same stacked ``[L, ...]`` layout the
    scan-over-layers model already uses, sharded over ``pp`` on the
    leading axis, so checkpoints are topology-portable — unlike the
    reference's per-rank ``pdparams`` dirs. With ``virtual_pp_degree
    = vpp > 1`` the reshape to ``[vpp, S, L/(S*vpp), ...]`` (sharded
    over ``pp`` on axis 1) gives physical stage ``s`` the
    non-contiguous layer chunks ``{s, S+s, 2S+s, ...}`` — exactly the
    reference's interleaved assignment;
  - a ``[vpp, S, microbatch, ...]`` slot buffer is sharded over
    ``pp``; each pipeline tick runs every virtual stage's local
    layers in parallel (a ``vmap`` over slots of a ``lax.scan`` over
    the slot's layers) and advances the buffer with a roll along the
    virtual-stage order, which GSPMD lowers to a collective-permute
    between ICI neighbors — the NCCL P2P of the reference;
  - two schedules are provided. ``pipeline_forward`` is the
    forward-only GPipe fill/drain (``M + S*vpp - 1`` ticks); taking
    ``jax.grad`` through it yields a GPipe-memory-profile backward.
    ``pipeline_value_and_grad`` is an explicit 1F1B: each tick runs
    one forward slot-wave and one backward slot-wave (per-slot
    ``jax.vjp`` with recompute, the reference 1F1B's memory story),
    so the activation stash holds at most ``2*S*vpp`` microbatch
    activations per slot-ring instead of all ``M`` — peak activation
    memory is bounded by pipeline depth, not microbatch count;
  - embeddings and the LM head are compute-replicated over ``pp``
    (their FLOPs are negligible next to the decoder stack), which
    makes the reference's ``SharedLayerDesc`` embedding tying
    (``hybrid_model.py:934-945``) trivial: there is only one
    embedding table, visible to both ends of the pipeline.

Schedule timing (K = S*vpp virtual stages): forward of microbatch
``m`` at virtual stage ``k`` happens at tick ``m + k``; its loss (and
output cotangent) at tick ``m + K - 1``; its backward at stage ``k``
at tick ``m + 2K - 1 - k``. An activation stashed at the forward tick
is consumed ``2(K - 1 - k) + 1 < 2K`` ticks later, so a depth-``2K``
ring buffer never collides. The 1F1B bubble is the same ``(K-1)``-tick
fill/drain as GPipe's; the win is memory (the reference's motivation
for defaulting to 1F1B).

Zero-bubble schedule (``schedule="zb"``, after the ZB-H1 family of
arXiv:2412.14374): each stage's backward splits into dX (the input
cotangent, which stays on the critical path — the next stage's
backward needs it one tick later) and dW (the weight gradient, which
nothing downstream consumes until the optimizer). dX runs at the same
tick 1F1B runs the combined backward; the dW job is pushed into a
bounded per-slot FIFO and drained during ticks where that slot's
backward wave is otherwise idle — virtual stage ``k`` has exactly
``k`` such drain-bubble ticks at the end of the schedule, so its
queue capacity is ``min(k, M)`` and every deferred dW lands in a
formerly-empty slot-tick. The drain order is FIFO, so per-slot weight
gradients accumulate in the same microbatch order as 1F1B and the
results match bitwise up to XLA scheduling. Because the whole
schedule is a static function of ``(M, K)``, the pop timetable is
precomputed host-side (``zb_dw_schedule``) and fed to the scan as
per-tick indices; the same host math yields the
``pipeline/{fwd,bwd_dx,bwd_dw,bubble}_ticks`` trace-time counters
that make the occupancy win auditable (docs/pipeline.md).

ZB-H2 schedule (``schedule="zb_h2"``, same family): spend HBM
headroom to also kill the *fill-phase* bubble. Virtual stage ``k``
runs up to ``h2_depth`` extra warm-up forwards ahead of the 1F1B
pattern — its in-flight forward cap rises from ``K - k`` to
``min(2(K - k) - 1, (K - k) + h2_depth)`` — so the fill-phase ticks
1F1B leaves idle are filled with real forward work, while the dW FIFO
(its capacity raised to ``min(k + h2_depth, M)``) drains into
whatever bubble remains. In the decoupled-stage occupancy model
(``pipeline_tick_stats``) the bubble at depth ``d`` is
``(K-1-d)(K-d)/2`` once ``M >= 2K - 1`` — zero at the full depth
``d = K - 1``. The lockstep SPMD scan cannot literally run ahead
(stage ``k`` has no input before tick ``k``), so the scan's zb_h2
branch replays the *deferred-dW half* of the schedule: the deeper
FIFO timetable (with forced just-in-time pops so nothing leaks past
the last tick) and the deeper cotangent ring (``K + h2_depth + 1``
rows — the HBM the schedule spends) — proving the numerics and the
queue machinery an MPMD runtime (ROADMAP item 4) would execute for
the wall-clock win. Gradients stay bitwise-equal to 1F1B: pops are
FIFO in microbatch order, so the fp32 accumulation order never
changes. The analytic per-stage byte model and the ``zb_auto``
schedule chooser live in ``parallel/pp_memory.py``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..observability import metrics
from .mesh import DATA_AXES, PP_AXIS, get_mesh


def _constrain(x, spec: P):
    """Sharding constraint against the active mesh (no-op without one)."""
    mesh = get_mesh()
    if mesh is None or mesh.size == 1:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def _slot_params(stacked_params: Any, S: int, vpp: int) -> Tuple[Any, int]:
    """``[L, ...]`` stacked params -> ``[vpp, S, L/(S*vpp), ...]``
    sharded over ``pp`` on the physical-stage axis. Virtual stage
    ``k = v*S + s`` owns the contiguous layer block ``[k*Lc, (k+1)*Lc)``
    — i.e. physical stage ``s`` owns interleaved chunks."""
    leaves = jax.tree.leaves(stacked_params)
    if not leaves:
        raise ValueError("stacked_params has no leaves")
    L = leaves[0].shape[0]
    K = S * vpp
    if L % K != 0:
        raise ValueError(
            f"num_layers {L} not divisible by pp*vpp {K}")
    Lc = L // K
    slotted = jax.tree.map(
        lambda p: _constrain(p.reshape(vpp, S, Lc, *p.shape[1:]),
                             P(None, PP_AXIS)), stacked_params)
    return slotted, Lc


def _advance(processed: jax.Array, vpp: int) -> jax.Array:
    """Forward roll along the virtual-stage order: slot k's output
    becomes slot k+1's next input. The s-axis roll is the inter-stage
    collective-permute; chunk wrap (s=S-1 -> next chunk's s=0) moves
    within the same device ring."""
    nxt = jnp.roll(processed, 1, axis=1)
    if vpp > 1:
        wrapped = jnp.roll(processed[:, -1], 1, axis=0)
        nxt = nxt.at[:, 0].set(wrapped)
    return nxt


def _retreat(b_out: jax.Array, dy_prev: jax.Array, vpp: int) -> jax.Array:
    """Backward roll: slot k's next cotangent is slot k+1's backward
    output; the last virtual stage ingests the loss cotangent."""
    g = jnp.roll(b_out, -1, axis=1)
    if vpp > 1:
        wrapped = jnp.roll(b_out[:, 0], -1, axis=0)
        g = g.at[:, -1].set(wrapped)
    return g.at[-1, -1].set(dy_prev)


def _slot_keys(base_rng: jax.Array, m_arr: jax.Array,
               K: int) -> jax.Array:
    """Per-slot dropout keys folded by (microbatch, virtual stage) so
    a 1F1B backward recompute reproduces the forward's masks exactly
    (tick-based folding would not: F and B of the same microbatch
    happen at different ticks)."""
    k_arr = jnp.arange(K)

    def key_for(m, k):
        return jax.random.fold_in(jax.random.fold_in(base_rng, m), k)

    return jax.vmap(key_for)(m_arr, k_arr)


def zb_queue_bound(num_microbatches: int, num_virtual_stages: int,
                   h2_depth: int = 0) -> int:
    """Upper bound on the zb/zb_h2 per-slot dW-queue depth: virtual
    stage ``k`` defers at most ``min(k + h2_depth, M)`` weight-grad
    jobs (``h2_depth = 0`` is plain zb: stage ``k`` has exactly ``k``
    drain-bubble ticks to spend them in), so no slot ever queues more
    than ``min(K - 1 + h2_depth, M)`` microbatch cotangents."""
    return min(num_virtual_stages - 1 + max(int(h2_depth), 0),
               num_microbatches)


def zb_dw_schedule(num_microbatches: int, num_virtual_stages: int,
                   h2_depth: int = 0):
    """Static dW drain timetable for the zero-bubble schedule family.

    Pure host math — the 1F1B tick grid is a fixed function of
    ``(M, K)``, so *when* each deferred weight-grad job runs is decided
    here, not inside the scan. Per virtual stage ``k`` a FIFO of
    capacity ``min(k + h2_depth, M)`` receives one job at each dX
    tick; a job pops (and its dW runs) when the push would overflow
    the capacity (steady state — the same tick, exactly like 1F1B, for
    ``k = 0`` at depth 0), at a tick where the slot's backward wave is
    idle (the former drain-bubble ticks, which the deferred jobs now
    fill), or — with ``h2_depth > 0``, whose deeper FIFOs can outlast
    the ``k`` trailing idle ticks — just in time: whenever the jobs
    still outstanding (queued or yet to be pushed) need every
    remaining tick to drain one-per-tick, a pop runs alongside that
    tick's dX. At depth 0 the JIT rule fires exactly when the
    overflow rule already does, so the zb timetable is bit-identical
    with and without it;
    at any depth it keeps every pop of microbatch ``m`` at or before
    tick ``m + 2K - 1`` (pops are FIFO, one per tick, and all land by
    ``T - 1``), which is what lets the activation ring stay at depth
    ``2K``: the forward entry for ``(m, k)`` is overwritten at tick
    ``m + k + 2K``, strictly later.

    Returns ``(dw_m, max_depth)``: ``dw_m`` is an int ``[T, K]`` array
    (``T = M + 2K - 1``) whose entry is the microbatch whose dW runs at
    that (tick, virtual stage), or ``-1``; ``max_depth`` is the deepest
    any FIFO ever got (``<= zb_queue_bound(M, K, h2_depth)``).
    """
    M, K = num_microbatches, num_virtual_stages
    d = int(h2_depth)
    if d < 0:
        raise ValueError(f"h2_depth must be >= 0, got {h2_depth}")
    T = M + 2 * K - 1
    dw_m = np.full((T, K), -1, np.int32)
    max_depth = 0
    for k in range(K):
        cap = min(k + d, M)
        fifo: list = []
        npop = 0
        for t in range(T):
            m_b = t - (2 * K - 1 - k)
            pushed = 0 <= m_b < M
            if pushed:
                fifo.append(m_b)
            if fifo and (len(fifo) > cap or not pushed
                         or M - npop >= T - t):
                dw_m[t, k] = fifo.pop(0)
                npop += 1
            max_depth = max(max_depth, len(fifo))
        if fifo:   # every job must drain within the schedule
            raise AssertionError(
                f"zb schedule leaked {len(fifo)} dW jobs at stage {k}")
    return dw_m, max_depth


def h2_fwd_caps(num_microbatches: int, num_virtual_stages: int,
                h2_depth: int) -> list:
    """Per-virtual-stage in-flight forward caps (forwards done minus
    dXs done) for the schedule family. 1f1b/zb warm up ``K - k``
    forwards at stage ``k``; zb_h2 at depth ``d`` warms up
    ``min(2(K - k) - 1, (K - k) + d)`` — each extra in-flight forward
    is one more stashed microbatch activation (the HBM the schedule
    spends, priced by ``parallel/pp_memory.py``)."""
    M, K, d = num_microbatches, num_virtual_stages, h2_depth
    return [min(min(2 * (K - k) - 1, (K - k) + d), M) for k in range(K)]


def pipeline_tick_stats(num_microbatches: int, num_virtual_stages: int,
                        schedule: str = "1f1b",
                        h2_depth: Optional[int] = None) -> dict:
    """Analytic per-stage occupancy of a pipeline schedule.

    For the training schedules (1f1b / zb / zb_h2) this simulates the
    *decoupled-stage unit model*: each virtual stage executes at most
    one work unit (forward, dX, or dW — all unit-cost) per tick, dX
    has priority (critical path), forwards run work-conserving up to
    the stage's in-flight cap (``h2_fwd_caps``), and deferred dW jobs
    drain FIFO into ticks the stage would otherwise idle. A stage's
    ``total`` is its active span (first to last unit), its ``bubble``
    the idle ticks inside that span — so
    ``fwd + bwd_dx + bwd_dw + bubble == total_slot_ticks`` holds
    exactly (the conservation identity the property tests pin). This
    models what each schedule buys on a decoupled MPMD runtime
    (ROADMAP item 4); the lockstep scan replays the matching dW
    timetable to prove the numerics. Closed forms at ``M >= K``:
    1f1b bubble ``K(K-1)``, zb ``K(K-1)/2``, and zb_h2 at depth ``d``
    ``(K-1-d)(K-d)/2`` once ``M >= 2K - 1`` — zero at ``d = K - 1``.

    ``schedule="gpipe"`` keeps the lockstep forward-only fill/drain
    grid (that IS what ``pipeline_forward`` executes): ``M*K`` forward
    slot-ticks inside a ``(M + K - 1) * K`` grid, the rest bubble —
    the same conservation identity, different accounting basis.

    ``h2_depth`` (zb_h2 only): extra warm-up forwards per stage;
    ``None`` or negative picks the full depth ``K - 1``.

    This is the single source for the
    ``pipeline/{fwd,bwd_dx,bwd_dw,bubble}_ticks`` counters and the
    engine's ``pipeline_bubble`` goodput bucket.
    """
    M, K = num_microbatches, num_virtual_stages
    sched = str(schedule).lower().replace("-", "_")
    if sched == "gpipe":
        T = M + K - 1
        return {"fwd_ticks": M * K, "bwd_dx_ticks": 0,
                "bwd_dw_ticks": 0,
                "bubble_ticks": T * K - M * K,
                "total_slot_ticks": T * K,
                "makespan_ticks": T,
                "per_stage_bubble_ticks": [K - 1] * K,
                "h2_depth": 0,
                "dw_queue_peak": 0}
    if sched not in ("1f1b", "zb", "zb_h2"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    d = 0
    if sched == "zb_h2":
        d = (K - 1) if (h2_depth is None or h2_depth < 0) \
            else min(int(h2_depth), K - 1)
    if sched == "zb_h2":
        cap = h2_fwd_caps(M, K, d)
    else:
        cap = [min(K - k, M) for k in range(K)]

    t_first = [None] * K
    t_last = [0] * K
    nF = [0] * K            # forwards done per stage
    nD = [0] * K            # dXs done
    nW = [0] * K            # dWs done
    fin_F = [[-1] * M for _ in range(K)]   # completion tick of F(m, k)
    fin_D = [[-1] * M for _ in range(K)]
    pend_W: list = [[] for _ in range(K)]  # FIFO of mbs whose dX ran
    pair_W = [-1] * K       # 1f1b: dW bound to the dX one tick earlier
    q_peak = 0
    done, total_units = 0, 3 * M * K
    t = 0
    limit = 4 * (M + K) + 8 * K + 8
    while done < total_units and t < limit:
        for k in range(K):
            ran = -1
            # 1f1b's combined backward: dW immediately follows its dX
            if sched == "1f1b" and pair_W[k] >= 0:
                pair_W[k] = -1
                nW[k] += 1
                ran = t
            else:
                m = nD[k]
                d_ready = m < M and (
                    fin_D[k + 1][m] >= 0 and fin_D[k + 1][m] < t
                    if k < K - 1
                    else fin_F[k][m] >= 0 and fin_F[k][m] < t)
                m_f = nF[k]
                f_ready = m_f < M and (nF[k] - nD[k]) < cap[k] and (
                    k == 0 or (fin_F[k - 1][m_f] >= 0
                               and fin_F[k - 1][m_f] < t))
                if d_ready:
                    fin_D[k][m] = t
                    nD[k] += 1
                    ran = t
                    if sched == "1f1b":
                        pair_W[k] = m
                    else:
                        pend_W[k].append(m)
                        q_peak = max(q_peak, len(pend_W[k]))
                elif f_ready:
                    fin_F[k][m_f] = t
                    nF[k] += 1
                    ran = t
                elif pend_W[k]:
                    pend_W[k].pop(0)
                    nW[k] += 1
                    ran = t
            if ran >= 0:
                done += 1
                if t_first[k] is None:
                    t_first[k] = t
                t_last[k] = t
        t += 1
    if done != total_units:
        raise AssertionError(
            f"pipeline unit-model deadlock: {done}/{total_units} units "
            f"at (M={M}, K={K}, schedule={sched!r}, depth={d})")
    spans = [t_last[k] - t_first[k] + 1 for k in range(K)]
    per_stage_bubble = [spans[k] - 3 * M for k in range(K)]
    return {"fwd_ticks": M * K,
            "bwd_dx_ticks": M * K,
            "bwd_dw_ticks": M * K,
            "bubble_ticks": sum(per_stage_bubble),
            "total_slot_ticks": sum(spans),
            "makespan_ticks": max(t_last) + 1,
            "per_stage_bubble_ticks": per_stage_bubble,
            "h2_depth": d,
            "dw_queue_peak": q_peak}


def pipeline_forward(
    layer_apply: Callable[[Any, jax.Array, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    *,
    pp: int,
    num_microbatches: int,
    vpp: int = 1,
    out_fn: Optional[Callable[[Any, jax.Array, Any], Any]] = None,
    out_init: Any = None,
    extras: Any = None,
    rng: Optional[jax.Array] = None,
    layer_has_aux: bool = False,
) -> Any:
    """Run ``x`` through ``L`` stacked layers with a GPipe-scheduled
    ``pp``-stage (optionally ``vpp``-way interleaved) pipeline.

    Args:
      layer_apply: ``(layer_params, h, rng_key) -> h`` — one decoder
        layer as a pure function (wrap with ``jax.checkpoint`` for
        recompute before passing).
      stacked_params: pytree whose leaves have leading dim ``L``
        (``nn.scan`` layout), ``L % (pp * vpp) == 0``.
      x: ``[B, ...]`` input activations, ``B % num_microbatches == 0``.
      pp: number of physical pipeline stages (mesh ``pp`` axis size).
      num_microbatches: M; the reference's ``accumulate_steps``
        (``utils/config.py:117``).
      vpp: interleaved virtual stages per physical stage (the
        reference's ``virtual_pp_degree``).
      out_fn: optional per-microbatch reducer ``(acc, y_mb, extras_mb)
        -> acc`` applied to the last stage's output (e.g. LM head +
        loss). When given, the full ``[B, ...]`` output is never
        materialized — the pipelined analogue of the reference
        computing loss per microbatch inside ``train_batch``.
      out_init: initial reducer carry (required with ``out_fn``).
      extras: pytree of ``[B, ...]`` arrays sliced per-microbatch and
        fed to ``out_fn`` (labels, loss masks).
      rng: base dropout key; folded per (microbatch, virtual stage,
        layer).
      layer_has_aux: ``layer_apply`` returns ``(h, aux_scalar)`` (MoE
        layers: the router aux loss). This forward-only schedule
        DISCARDS the aux — eval reports pure CE (docs/moe.md); the
        training aux flows through ``pipeline_value_and_grad``.

    Returns the reducer carry, or the ``[B, ...]`` outputs when
    ``out_fn`` is None.
    """
    S, M = pp, num_microbatches
    K = S * vpp
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    ts = pipeline_tick_stats(M, K, schedule="gpipe")
    metrics.inc("pipeline/fwd_ticks", ts["fwd_ticks"])
    metrics.inc("pipeline/bubble_ticks", ts["bubble_ticks"])
    slot_params, Lc = _slot_params(stacked_params, S, vpp)

    x_mb = x.reshape(M, B // M, *x.shape[1:])
    x_mb = _constrain(x_mb, P(None, DATA_AXES))
    extras_mb = None
    if extras is not None:
        extras_mb = jax.tree.map(
            lambda e: e.reshape(M, B // M, *e.shape[1:]), extras)

    state0 = _constrain(
        jnp.zeros((vpp, S) + x_mb.shape[1:], x.dtype),
        P(None, PP_AXIS, DATA_AXES))
    collect = out_fn is None
    acc0 = jnp.zeros_like(x_mb) if collect else out_init
    base_rng = rng if rng is not None else jax.random.key(0)

    def stage_fn(sp, h, key):
        def body(h, xs):
            lp, k = xs
            out = layer_apply(lp, h, k)
            return (out[0] if layer_has_aux else out), None
        h, _ = jax.lax.scan(body, h, (sp, jax.random.split(key, Lc)))
        return h

    slot_stage = jax.vmap(jax.vmap(stage_fn))

    def tick(carry, t):
        """One pipeline clock: every virtual stage computes, then
        activations rotate one hop."""
        state, acc = carry
        # virtual stage 0 ingests microbatch t (clamped past the fill
        # phase — drain ticks feed it a stale microbatch whose output
        # is never collected)
        inp = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.minimum(t, M - 1), 0, keepdims=False)
        state = _constrain(state.at[0, 0].set(inp),
                           P(None, PP_AXIS, DATA_AXES))

        m_arr = jnp.clip(t - jnp.arange(K), 0, M - 1)
        keys = _slot_keys(base_rng, m_arr, K).reshape(vpp, S)
        processed = slot_stage(slot_params, state, keys)
        processed = _constrain(processed, P(None, PP_AXIS, DATA_AXES))

        # collect the last virtual stage's output for microbatch
        # t-(K-1); ticks before the pipeline is full carry warmup
        # garbage — the cond skips the collection (and the reducer's
        # head/loss FLOPs) entirely on those ticks
        y = processed[-1, -1]
        idx = jnp.clip(t - (K - 1), 0, M - 1)
        valid = t >= K - 1
        if collect:
            acc = jax.lax.cond(
                valid,
                lambda a: jax.lax.dynamic_update_index_in_dim(
                    a, y, idx, 0),
                lambda a: a, acc)
        else:
            def reduce(a):
                ex = None
                if extras_mb is not None:
                    ex = jax.tree.map(
                        lambda e: jax.lax.dynamic_index_in_dim(
                            e, idx, 0, keepdims=False), extras_mb)
                return out_fn(a, y, ex)
            acc = jax.lax.cond(valid, reduce, lambda a: a, acc)

        state = _advance(processed, vpp)
        return (state, acc), None

    (_, acc), _ = jax.lax.scan(tick, (state0, acc0),
                               jnp.arange(M + K - 1))
    if collect:
        return acc.reshape(B, *x.shape[1:])
    return acc


def pipeline_value_and_grad(
    layer_apply: Callable[[Any, jax.Array, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    *,
    pp: int,
    num_microbatches: int,
    vpp: int = 1,
    loss_and_grad: Callable[[jax.Array, Any],
                            Tuple[jax.Array, jax.Array, Any]],
    extras: Any = None,
    rng: Optional[jax.Array] = None,
    schedule: str = "1f1b",
    h2_depth: int = -1,
    layer_has_aux: bool = False,
) -> Tuple[jax.Array, Any, Any, jax.Array]:
    """Explicit 1F1B (or zero-bubble) schedule: loss AND gradients in
    one pass.

    Unlike ``jax.grad(pipeline_forward)`` — which structurally runs
    all forwards before any backward and therefore stashes every
    microbatch's activations (the GPipe memory profile) — each tick
    here runs one forward slot-wave and one backward slot-wave. A
    microbatch's backward starts ``1`` tick after its loss, so the
    activation ring holds at most ``2K`` entries per slot regardless
    of ``M`` (the 1F1B property; reference default schedule,
    ``hybrid_model.py:962`` area). The per-slot backward is
    ``jax.vjp`` of the slot forward — recompute-from-stashed-input,
    i.e. full recompute granularity, matching how the reference runs
    PP with recompute enabled.

    Args:
      layer_apply / stacked_params / x / pp / vpp / extras / rng: as
        in ``pipeline_forward``.
      num_microbatches: M (gradient accumulation happens inside the
        schedule).
      loss_and_grad: ``(y_mb, extras_mb) -> (loss_mb, dy_mb,
        dhead_mb)`` — per-microbatch loss, its cotangent wrt ``y_mb``,
        and the gradient pytree for any head/criterion parameters
        closed over by the caller (summed over microbatches here).
      schedule: ``"1f1b"`` (the combined backward above), ``"zb"``
        (zero-bubble: dX-only vjp on the critical path, dW replayed
        from the stashed input at the statically precomputed drain
        tick — see the module docstring), or ``"zb_h2"`` (the same
        machinery with the dW FIFO deepened by ``h2_depth``: the
        timetable an MPMD runtime running ``h2_depth`` extra warm-up
        forwards would drain, priced by the deeper cotangent ring).
        Gradients are identical across all three: the dW FIFO drains
        in microbatch order, so even the fp32 accumulation order
        matches.
      h2_depth: zb_h2 only — extra warm-up forwards per virtual
        stage, ``0 <= h2_depth <= K - 1`` (``-1`` picks the full
        depth ``K - 1``; depth 0 degenerates to plain zb). Raises the
        per-slot dW FIFO capacity to ``min(k + h2_depth, M)`` and the
        cotangent ring to ``K + h2_depth + 1`` rows — the HBM spend
        ``parallel/pp_memory.py`` prices and validates.
      layer_has_aux: ``layer_apply`` returns ``(h, aux_scalar)`` (MoE
        router aux loss). The aux of every valid (microbatch, virtual
        stage) is added to ``loss_sum`` at its forward tick, and a
        unit aux cotangent rides the matching dX/dW pulls so router
        gradients flow through both schedules.

    Returns ``(loss_sum, d_stacked, dhead_sum, dx)`` where
    ``d_stacked`` matches ``stacked_params``' ``[L, ...]`` layout,
    ``dhead_sum`` sums ``dhead_mb`` over microbatches, and ``dx`` is
    the ``[B, ...]`` cotangent wrt ``x`` (feed it to the embedding
    vjp). All sums are over microbatches — divide by M for a mean.
    """
    S, M = pp, num_microbatches
    K = S * vpp
    D = 2 * K  # activation ring depth; see module docstring
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    sched = str(schedule).lower().replace("-", "_")
    if sched not in ("1f1b", "zb", "zb_h2"):
        raise ValueError(
            f"unknown pipeline schedule {schedule!r} (expected '1f1b', "
            f"'zb' or 'zb_h2'; GPipe routes through pipeline_forward)")
    h2 = 0
    if sched == "zb_h2":
        h2 = (K - 1) if h2_depth < 0 else min(int(h2_depth), K - 1)
    # trace-time occupancy counters: the tick grid is a static function
    # of (M, K), so one inc per compilation records the whole schedule
    ts = pipeline_tick_stats(M, K, schedule=sched, h2_depth=h2)
    metrics.inc("pipeline/fwd_ticks", ts["fwd_ticks"])
    metrics.inc("pipeline/bwd_dx_ticks", ts["bwd_dx_ticks"])
    metrics.inc("pipeline/bwd_dw_ticks", ts["bwd_dw_ticks"])
    metrics.inc("pipeline/bubble_ticks", ts["bubble_ticks"])
    if sched == "zb_h2":
        metrics.inc("pipeline/h2_depth", h2)
    slot_params, Lc = _slot_params(stacked_params, S, vpp)

    x_mb = x.reshape(M, B // M, *x.shape[1:])
    x_mb = _constrain(x_mb, P(None, DATA_AXES))
    extras_mb = jax.tree.map(
        lambda e: e.reshape(M, B // M, *e.shape[1:]), extras) \
        if extras is not None else None
    base_rng = rng if rng is not None else jax.random.key(0)
    mb_shape = x_mb.shape[1:]

    def stage_fn(sp, h, key):
        def body(h, xs):
            lp, k = xs
            if layer_has_aux:
                h, aux = layer_apply(lp, h, k)
                return h, aux
            return layer_apply(lp, h, k), None
        h, auxs = jax.lax.scan(body, h, (sp, jax.random.split(key, Lc)))
        if layer_has_aux:
            return h, jnp.sum(auxs)
        return h

    slot_stage = jax.vmap(jax.vmap(stage_fn))

    # The combined pull (1f1b) extracts dW and dX from one backward;
    # the zb pulls split them — dX on the critical path, dW replayed
    # later from the stashed input. With layer_has_aux the aux
    # cotangent (1.0 on valid work, else 0.0) rides along so router
    # aux gradients flow at exactly the ticks the matching dX/dW run.
    def slot_vjp(sp, h, key, g):
        _, pull = jax.vjp(lambda p, hh: stage_fn(p, hh, key), sp, h)
        return pull(g)

    def slot_vjp_aux(sp, h, key, g, a_ct):
        _, pull = jax.vjp(lambda p, hh: stage_fn(p, hh, key), sp, h)
        return pull((g, a_ct))

    def slot_dx(sp, h, key, g):
        _, pull = jax.vjp(lambda hh: stage_fn(sp, hh, key), h)
        return pull(g)[0]

    def slot_dx_aux(sp, h, key, g, a_ct):
        _, pull = jax.vjp(lambda hh: stage_fn(sp, hh, key), h)
        return pull((g, a_ct))[0]

    def slot_dw(sp, h, key, g):
        _, pull = jax.vjp(lambda p: stage_fn(p, h, key), sp)
        return pull(g)[0]

    def slot_dw_aux(sp, h, key, g, a_ct):
        _, pull = jax.vjp(lambda p: stage_fn(p, h, key), sp)
        return pull((g, a_ct))[0]

    slot_backward = jax.vmap(jax.vmap(slot_vjp))
    slot_backward_aux = jax.vmap(jax.vmap(slot_vjp_aux))
    slot_backward_dx = jax.vmap(jax.vmap(slot_dx))
    slot_backward_dx_aux = jax.vmap(jax.vmap(slot_dx_aux))
    slot_backward_dw = jax.vmap(jax.vmap(slot_dw))
    slot_backward_dw_aux = jax.vmap(jax.vmap(slot_dw_aux))

    # zero templates for the loss head's outputs
    y_abs = jax.ShapeDtypeStruct(mb_shape, x.dtype)
    ex_abs = jax.tree.map(
        lambda e: jax.ShapeDtypeStruct(e.shape[1:], e.dtype), extras_mb) \
        if extras_mb is not None else None
    _, dy_abs, dhead_abs = jax.eval_shape(loss_and_grad, y_abs, ex_abs)
    zeros_of = lambda ab: jax.tree.map(  # noqa: E731
        lambda a: jnp.zeros(a.shape, a.dtype), ab)

    fstate0 = _constrain(jnp.zeros((vpp, S) + mb_shape, x.dtype),
                         P(None, PP_AXIS, DATA_AXES))
    # cotangents ride in fp32 regardless of the compute dtype (the
    # backward wave accumulates them into fp32 param grads)
    bstate0 = _constrain(jnp.zeros((vpp, S) + mb_shape, jnp.float32),
                         P(None, PP_AXIS, DATA_AXES))
    stash0 = _constrain(jnp.zeros((vpp, S, D) + mb_shape, x.dtype),
                        P(None, PP_AXIS, None, DATA_AXES))
    dparams0 = jax.tree.map(
        lambda p: _constrain(jnp.zeros(p.shape, jnp.float32),
                             P(None, PP_AXIS)), slot_params)
    dhead0 = zeros_of(dhead_abs)
    dy0 = zeros_of(dy_abs)
    dx0 = _constrain(jnp.zeros((M,) + mb_shape, jnp.float32),
                     P(None, DATA_AXES))
    loss0 = jnp.zeros((), jnp.float32)

    k_arr = jnp.arange(K)

    def _gather_ring(ring, depths):
        """Per-slot dynamic read of a ``[vpp, S, depth, ...]`` ring."""
        return jax.vmap(jax.vmap(
            lambda st, d: jax.lax.dynamic_index_in_dim(
                st, d, 0, keepdims=False)))(ring,
                                            depths.reshape(vpp, S))

    def _accumulate(dparams, dp, mask):
        return jax.tree.map(
            lambda acc, g: acc + jnp.where(
                mask.reshape(mask.shape + (1,) * (g.ndim - 2)),
                g.astype(jnp.float32), 0.0),
            dparams, dp)

    def _forward_wave(fstate, stash, loss_sum, t):
        inp = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, M - 1), 0, keepdims=False)
        # .at[0, 0].set is a two-dim-index scatter; with dim 1 sharded
        # over pp the SPMD partitioner mis-broadcasts the index
        # concatenation (hlo-verifier RET_CHECK on 0.4.x). A
        # dynamic_update_slice at a constant origin partitions cleanly
        # and is the same write.
        fstate = jax.lax.dynamic_update_slice(
            fstate, inp[None, None], (0,) * fstate.ndim)
        fstate = _constrain(fstate, P(None, PP_AXIS, DATA_AXES))
        stash = _constrain(stash.at[:, :, t % D].set(fstate),
                           P(None, PP_AXIS, None, DATA_AXES))
        m_f = jnp.clip(t - k_arr, 0, M - 1)
        f_keys = _slot_keys(base_rng, m_f, K).reshape(vpp, S)
        if layer_has_aux:
            processed, aux_f = slot_stage(slot_params, fstate, f_keys)
            valid_f = jnp.logical_and(t - k_arr >= 0, t - k_arr < M)
            loss_sum = loss_sum + jnp.sum(
                jnp.where(valid_f.reshape(vpp, S), aux_f, 0.0))
        else:
            processed = slot_stage(slot_params, fstate, f_keys)
        processed = _constrain(processed, P(None, PP_AXIS, DATA_AXES))
        return processed, stash, loss_sum

    def _loss_head(processed, t, loss_sum, dhead):
        m_l = t - (K - 1)
        y_last = processed[-1, -1]
        ex = jax.tree.map(
            lambda e: jax.lax.dynamic_index_in_dim(
                e, jnp.clip(m_l, 0, M - 1), 0, keepdims=False),
            extras_mb) if extras_mb is not None else None

        def do_loss(_):
            return loss_and_grad(y_last, ex)

        def no_loss(_):
            return loss0, dy0, zeros_of(dhead_abs)

        valid_l = jnp.logical_and(m_l >= 0, m_l < M)
        loss_mb, dy_new, dhead_mb = jax.lax.cond(valid_l, do_loss,
                                                 no_loss, None)
        loss_sum = loss_sum + loss_mb
        dhead = jax.tree.map(jnp.add, dhead, dhead_mb)
        return loss_sum, dy_new, dhead

    def _dx_capture(dx, dh, t):
        # cotangent wrt the pipeline input, for the embedding backward
        m_b0 = t - (2 * K - 1)
        return jax.lax.cond(
            jnp.logical_and(m_b0 >= 0, m_b0 < M),
            lambda d: jax.lax.dynamic_update_index_in_dim(
                d, dh[0, 0].astype(jnp.float32),
                jnp.clip(m_b0, 0, M - 1), 0),
            lambda d: d, dx)

    if sched == "1f1b":
        def tick(carry, t):
            """One 1F1B clock: forward wave + combined backward wave
            (dW and dX in a single pull)."""
            fstate, b_out, dy_prev, stash, loss_sum, dparams, dhead, \
                dx = carry
            processed, stash, loss_sum = _forward_wave(
                fstate, stash, loss_sum, t)
            loss_sum, dy_new, dhead = _loss_head(
                processed, t, loss_sum, dhead)

            # ---- backward wave --------------------------------------
            m_b = t - (2 * K - 1 - k_arr)
            valid_b = jnp.logical_and(m_b >= 0, m_b < M)
            g_in = _retreat(b_out, dy_prev, vpp)
            g_in = _constrain(g_in, P(None, PP_AXIS, DATA_AXES))
            depth = (t - (2 * K - 1) + 2 * k_arr) % D  # fwd-tick slot
            x_in = _gather_ring(stash, depth)
            b_keys = _slot_keys(base_rng, jnp.clip(m_b, 0, M - 1),
                                K).reshape(vpp, S)
            g_cast = g_in.astype(x.dtype)
            if layer_has_aux:
                dp, dh = slot_backward_aux(
                    slot_params, x_in, b_keys, g_cast,
                    valid_b.astype(jnp.float32).reshape(vpp, S))
            else:
                dp, dh = slot_backward(slot_params, x_in, b_keys,
                                       g_cast)
            dparams = _accumulate(dparams, dp, valid_b.reshape(vpp, S))
            b_out_new = _constrain(dh.astype(jnp.float32),
                                   P(None, PP_AXIS, DATA_AXES))
            dx = _dx_capture(dx, dh, t)

            fstate = _advance(processed, vpp)
            return (fstate, b_out_new, dy_new, stash, loss_sum,
                    dparams, dhead, dx), None

        carry0 = (fstate0, bstate0, dy0, stash0, loss0, dparams0,
                  dhead0, dx0)
        (_, _, _, _, loss_sum, dparams, dhead, dx), _ = jax.lax.scan(
            tick, carry0, jnp.arange(M + 2 * K - 1))
    else:
        # ---- zero-bubble: dX on the critical path, dW drained at the
        # statically precomputed tick (module docstring). zb_h2 is the
        # same scan with the FIFO deepened by h2 — only the cotangent
        # ring grows; the activation ring stays 2K because the forced
        # just-in-time pops keep every drain of microbatch m at or
        # before tick m + 2K - 1 (zb_dw_schedule docstring) ----------
        dw_np, _ = zb_dw_schedule(M, K, h2_depth=h2)
        dw_rows = jnp.asarray(dw_np.reshape(len(dw_np), vpp, S))
        # cotangent ring: the dW queue holds at most min(k + h2, M)
        # entries per slot (<= K + h2 - 1), indexed m % (K + h2) plus
        # the in-flight push; row K + h2 is scratch so masked writes
        # never clobber a live entry
        Rg = K + h2
        gstash0 = _constrain(
            jnp.zeros((vpp, S, Rg + 1) + mb_shape, x.dtype),
            P(None, PP_AXIS, None, DATA_AXES))

        def tick(carry, xs):
            """One zb clock: forward wave + dX wave + dW drain."""
            t, dw_m = xs
            fstate, b_out, dy_prev, stash, gstash, loss_sum, dparams, \
                dhead, dx = carry
            processed, stash, loss_sum = _forward_wave(
                fstate, stash, loss_sum, t)
            loss_sum, dy_new, dhead = _loss_head(
                processed, t, loss_sum, dhead)

            # ---- dX wave (critical path) ----------------------------
            m_b = t - (2 * K - 1 - k_arr)
            valid_b = jnp.logical_and(m_b >= 0, m_b < M)
            g_in = _retreat(b_out, dy_prev, vpp)
            g_in = _constrain(g_in, P(None, PP_AXIS, DATA_AXES))
            depth = (t - (2 * K - 1) + 2 * k_arr) % D
            x_in = _gather_ring(stash, depth)
            b_keys = _slot_keys(base_rng, jnp.clip(m_b, 0, M - 1),
                                K).reshape(vpp, S)
            g_cast = g_in.astype(x.dtype)
            if layer_has_aux:
                dh = slot_backward_dx_aux(
                    slot_params, x_in, b_keys, g_cast,
                    valid_b.astype(jnp.float32).reshape(vpp, S))
            else:
                dh = slot_backward_dx(slot_params, x_in, b_keys,
                                      g_cast)
            b_out_new = _constrain(dh.astype(jnp.float32),
                                   P(None, PP_AXIS, DATA_AXES))
            dx = _dx_capture(dx, dh, t)

            # enqueue the cotangent for the deferred dW. The write
            # happens before the drain read on purpose: the k=0 slot
            # (capacity 0) pops the entry it pushed this very tick.
            gdepth = jnp.where(valid_b, jnp.clip(m_b, 0, M - 1) % Rg,
                               Rg)
            gstash = jax.vmap(jax.vmap(
                lambda gs, d, gg:
                jax.lax.dynamic_update_index_in_dim(gs, gg, d, 0)))(
                gstash, gdepth.reshape(vpp, S), g_cast)
            gstash = _constrain(gstash,
                                P(None, PP_AXIS, None, DATA_AXES))

            # ---- dW drain at the precomputed tick -------------------
            dw_flat = dw_m.reshape(K)
            valid_w = dw_flat >= 0
            w_m = jnp.clip(dw_flat, 0, M - 1)
            # forward of mb m at slot k ran at tick m + k, so its
            # stashed input lives at ring depth (m + k) % D
            x_w = _gather_ring(stash, (w_m + k_arr) % D)
            g_w = _gather_ring(gstash, jnp.where(valid_w, w_m % Rg, Rg))
            w_keys = _slot_keys(base_rng, w_m, K).reshape(vpp, S)
            if layer_has_aux:
                dp = slot_backward_dw_aux(
                    slot_params, x_w, w_keys, g_w,
                    valid_w.astype(jnp.float32).reshape(vpp, S))
            else:
                dp = slot_backward_dw(slot_params, x_w, w_keys, g_w)
            dparams = _accumulate(dparams, dp, valid_w.reshape(vpp, S))

            fstate = _advance(processed, vpp)
            return (fstate, b_out_new, dy_new, stash, gstash,
                    loss_sum, dparams, dhead, dx), None

        carry0 = (fstate0, bstate0, dy0, stash0, gstash0, loss0,
                  dparams0, dhead0, dx0)
        (_, _, _, _, _, loss_sum, dparams, dhead, dx), _ = \
            jax.lax.scan(tick, carry0,
                         (jnp.arange(M + 2 * K - 1), dw_rows))

    d_stacked = jax.tree.map(
        lambda g, p: g.reshape(p.shape).astype(p.dtype),
        dparams, stacked_params)
    return loss_sum, d_stacked, dhead, dx.reshape(B, *x.shape[1:])
