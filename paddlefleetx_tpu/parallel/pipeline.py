"""Pipeline parallelism: an SPMD microbatch pipeline over the ``pp`` axis.

The reference's PP stack is bespoke machinery inside Paddle —
``PipelineLayer`` flattens the model into ``LayerDesc`` lists
(reference ``hybrid_model.py:895-961``), a 1F1B scheduler drives
``train_batch`` with NCCL P2P send/recv between stage ranks
(``eager_engine.py:406-415``), and shared embeddings are tied across
first/last stages via ``SharedLayerDesc``.

TPU-native design: none of that machinery is rank-local here. The
whole pipeline is ONE jitted SPMD program:

  - layer parameters stay in the same stacked ``[L, ...]`` layout the
    scan-over-layers model already uses, sharded over ``pp`` on the
    leading axis (stage s owns layers ``[s*L/S, (s+1)*L/S)``), so
    checkpoints are topology-portable — unlike the reference's
    per-rank ``pdparams`` dirs;
  - a ``[S, microbatch, ...]`` stage buffer is sharded over ``pp``;
    each pipeline tick runs every stage's local layers in parallel
    (a ``vmap`` over stages of a ``lax.scan`` over the stage's
    layers) and advances the buffer with ``jnp.roll``, which GSPMD
    lowers to a collective-permute between ICI neighbors — the NCCL
    P2P of the reference;
  - the GPipe fill/drain schedule is a ``lax.scan`` over
    ``M + S - 1`` ticks; microbatch gradient accumulation falls out
    of ``jax.grad`` through that scan (the backward pass pipelines in
    reverse automatically, where the reference needed a hand-written
    1F1B backward);
  - embeddings and the LM head are compute-replicated over ``pp``
    (their FLOPs are negligible next to the decoder stack), which
    makes the reference's ``SharedLayerDesc`` embedding tying
    (``hybrid_model.py:934-945``) trivial: there is only one
    embedding table, visible to both ends of the pipeline.

Schedule note: this is GPipe (bubble fraction ``(S-1)/(M+S-1)``).
The reference's default is 1F1B, which has the same bubble but lower
peak activation memory; under XLA the remat policy covers most of
that difference. Interleaved/virtual stages (``virtual_pp_degree``)
map to a circular schedule and are validated but not yet scheduled
differently.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import DATA_AXES, PP_AXIS, get_mesh


def _constrain(x, spec: P):
    """Sharding constraint against the active mesh (no-op without one)."""
    mesh = get_mesh()
    if mesh is None or mesh.size == 1:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def pipeline_forward(
    layer_apply: Callable[[Any, jax.Array, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    *,
    pp: int,
    num_microbatches: int,
    out_fn: Optional[Callable[[Any, jax.Array, Any], Any]] = None,
    out_init: Any = None,
    extras: Any = None,
    rng: Optional[jax.Array] = None,
) -> Any:
    """Run ``x`` through ``L`` stacked layers with a ``pp``-stage
    microbatch pipeline.

    Args:
      layer_apply: ``(layer_params, h, rng_key) -> h`` — one decoder
        layer as a pure function (wrap with ``jax.checkpoint`` for
        recompute before passing).
      stacked_params: pytree whose leaves have leading dim ``L``
        (``nn.scan`` layout), ``L % pp == 0``.
      x: ``[B, ...]`` input activations, ``B % num_microbatches == 0``.
      pp: number of pipeline stages (== mesh ``pp`` axis size).
      num_microbatches: M; the reference's ``accumulate_steps``
        (``utils/config.py:117``).
      out_fn: optional per-microbatch reducer ``(acc, y_mb, extras_mb)
        -> acc`` applied to the last stage's output (e.g. LM head +
        loss). When given, the full ``[B, ...]`` output is never
        materialized — the pipelined analogue of the reference
        computing loss per microbatch inside ``train_batch``.
      out_init: initial reducer carry (required with ``out_fn``).
      extras: pytree of ``[B, ...]`` arrays sliced per-microbatch and
        fed to ``out_fn`` (labels, loss masks).
      rng: base dropout key; folded per (tick, stage, layer).

    Returns the reducer carry, or the ``[B, ...]`` outputs when
    ``out_fn`` is None.
    """
    S, M = pp, num_microbatches
    leaves = jax.tree.leaves(stacked_params)
    if not leaves:
        raise ValueError("stacked_params has no leaves")
    L = leaves[0].shape[0]
    if L % S != 0:
        raise ValueError(f"num_layers {L} not divisible by pp {S}")
    Ls = L // S
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")

    x_mb = x.reshape(M, B // M, *x.shape[1:])
    x_mb = _constrain(x_mb, P(None, DATA_AXES))
    stage_params = jax.tree.map(
        lambda p: _constrain(p.reshape(S, Ls, *p.shape[1:]),
                             P(PP_AXIS)), stacked_params)
    extras_mb = None
    if extras is not None:
        extras_mb = jax.tree.map(
            lambda e: e.reshape(M, B // M, *e.shape[1:]), extras)

    state0 = _constrain(jnp.zeros((S,) + x_mb.shape[1:], x.dtype),
                        P(PP_AXIS, DATA_AXES))
    collect = out_fn is None
    acc0 = jnp.zeros_like(x_mb) if collect else out_init
    base_rng = rng if rng is not None else jax.random.key(0)

    def tick(carry, t):
        state, acc = carry
        # stage 0 ingests microbatch t (clamped past the fill phase —
        # the drain ticks feed it a stale microbatch whose output is
        # never collected)
        inp = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.minimum(t, M - 1), 0, keepdims=False)
        state = _constrain(state.at[0].set(inp), P(PP_AXIS, DATA_AXES))

        tick_rng = jax.random.fold_in(base_rng, t)
        stage_rngs = jax.vmap(
            lambda i: jax.random.fold_in(tick_rng, i))(jnp.arange(S))

        def stage_fn(sp, h, key):
            def body(h, xs):
                lp, k = xs
                return layer_apply(lp, h, k), None
            h, _ = jax.lax.scan(body, h, (sp, jax.random.split(key, Ls)))
            return h

        processed = jax.vmap(stage_fn)(stage_params, state, stage_rngs)
        processed = _constrain(processed, P(PP_AXIS, DATA_AXES))

        # collect the last stage's output for microbatch t-(S-1); ticks
        # before the pipeline is full carry warmup garbage — the cond
        # skips the collection (and the reducer's head/loss FLOPs)
        # entirely on those ticks
        y = processed[-1]
        idx = jnp.clip(t - (S - 1), 0, M - 1)
        valid = t >= S - 1
        if collect:
            acc = jax.lax.cond(
                valid,
                lambda a: jax.lax.dynamic_update_index_in_dim(
                    a, y, idx, 0),
                lambda a: a, acc)
        else:
            def reduce(a):
                ex = None
                if extras_mb is not None:
                    ex = jax.tree.map(
                        lambda e: jax.lax.dynamic_index_in_dim(
                            e, idx, 0, keepdims=False), extras_mb)
                return out_fn(a, y, ex)
            acc = jax.lax.cond(valid, reduce, lambda a: a, acc)

        # advance the pipeline: stage s+1's next input is stage s's
        # output — GSPMD lowers this roll over the pp-sharded axis to
        # a collective-permute (the reference's NCCL P2P send/recv)
        state = jnp.roll(processed, 1, axis=0)
        return (state, acc), None

    (_, acc), _ = jax.lax.scan(tick, (state0, acc0),
                               jnp.arange(M + S - 1))
    if collect:
        return acc.reshape(B, *x.shape[1:])
    return acc
