"""Logical-axis sharding rules: TP / SP / ZeRO from annotations.

The reference implements tensor parallelism with hand-written layers
(``ColumnParallelLinear/RowParallelLinear/VocabParallelEmbedding``,
reference ``hybrid_model.py:125-163,590``), Megatron sequence
parallelism with explicit all-gather/reduce-scatter PyLayers
(``sequence_parallel_utils.py:36-326``), and ZeRO via
``group_sharded_parallel`` flat buffers (``eager_engine.py:233-247``).

TPU-native design: the model annotates every parameter and key
activation with *logical* axis names; a single rule table maps logical
axes to mesh axes, and GSPMD inserts the identity/all-reduce/
all-gather/reduce-scatter collectives those hand-written layers
performed. Changing parallelism strategy = changing the rule table,
not the model.

Logical axes used across models:
  params:     ``vocab``, ``embed``, ``mlp``, ``heads``, ``kv``,
              ``layers`` (scan-over-layers leading axis)
  activations: ``batch``, ``seq``, ``act_embed``, ``act_heads``
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import (
    CP_AXIS, DATA_AXES, DP_AXIS, FSDP_AXIS, MP_AXIS, PP_AXIS,
    TopologyConfig,
)

Rules = Tuple[Tuple[str, Any], ...]

#: Logical WEIGHT axes that map onto the mp mesh axis in the rule
#: table below ("vocab" too, but embeddings/logits have no decoder
#: linear). The collective-matmul dispatch
#: (models/gpt/model.py::_CollectiveDense) keys on these to locate the
#: ring-sharded dim of a kernel — kept here so the rules and the
#: dispatch cannot drift apart.
MP_WEIGHT_AXES = ("heads", "mlp")


def make_sharding_rules(topo: TopologyConfig) -> Rules:
    """Build the logical→mesh rule table for a topology.

    - TP (Megatron column/row split): ``vocab``/``heads``/``mlp`` → mp.
    - ZeRO: parameters' ``embed`` axis shards over fsdp when
      sharding_stage == 3 (param sharding, reference "p_g_os"); for
      stages 1/2 only optimizer state shards (handled by the engine's
      optimizer-state out-shardings), params stay replicated.
    - SP: the activation ``seq`` axis shards over mp, reproducing the
      ``[s/mp, b, h]`` layout of ``sequence_parallel_utils.py`` without
      explicit collectives.
    """
    embed_axis = FSDP_AXIS if topo.sharding_stage == 3 else None
    # EP (MoE): the stacked expert axis shards over dataflow devices —
    # ep_degree selects how much of the dp x fsdp plane it uses. With
    # ep == 1 under ZeRO-3 the expert stack still shards over fsdp
    # (that IS the natural param-sharding of expert weights; GSPMD
    # gathers/all-to-alls as the dispatch einsums demand either way).
    if topo.ep_degree == 1:
        expert_axis = FSDP_AXIS if topo.sharding_stage == 3 else None
    elif topo.ep_degree == topo.dp_degree * topo.sharding_degree:
        expert_axis = DATA_AXES
    elif topo.ep_degree == topo.sharding_degree:
        expert_axis = FSDP_AXIS
    elif topo.ep_degree == topo.dp_degree:
        expert_axis = DP_AXIS
    else:
        raise ValueError(
            f"ep_degree ({topo.ep_degree}) must equal dp_degree "
            f"({topo.dp_degree}), sharding_degree "
            f"({topo.sharding_degree}), or their product — expert "
            f"parallelism rides the dataflow axes")
    if topo.cp_degree > 1:
        # context parallel: activations flow sequence-sharded over cp;
        # attention runs the ring (ops/ring_attention.py)
        seq_axis = CP_AXIS
    elif topo.sequence_parallel and topo.mp_degree > 1:
        seq_axis = MP_AXIS
    else:
        seq_axis = None
    # PP: stage s owns the contiguous layer block [s*L/pp, (s+1)*L/pp)
    # of the scan-stacked params — the LayerDesc segmentation of
    # reference hybrid_model.py:955, expressed as a sharding
    layers_axis = PP_AXIS if topo.pp_degree > 1 else None
    return (
        ("vocab", MP_AXIS),
        ("heads", MP_AXIS),
        ("mlp", MP_AXIS),
        ("kv", None),
        ("embed", embed_axis),
        ("pos", None),
        ("norm", None),
        ("layers", layers_axis),
        ("batch", DATA_AXES),
        ("seq", seq_axis),
        ("act_embed", None),
        ("act_heads", MP_AXIS),
        # Ulysses all-to-all CP: during attention the heads dim takes
        # the cp axis on top of mp while seq gathers (models/gpt/
        # model.py routes via context_parallel_algo="ulysses")
        ("act_heads_cp", (CP_AXIS, MP_AXIS)),
        ("act_mlp", MP_AXIS),
        ("act_vocab", MP_AXIS),
        # MoE expert stack (models/gpt/moe.py): expert dim over the
        # dataflow plane, inner FFN dim over mp (EP x TP); the
        # "expert_embed" hidden dim stays unsharded — ZeRO-3 coverage
        # of expert params comes from the expert axis itself
        ("expert", expert_axis),
        ("expert_embed", None),
        ("expert_mlp", MP_AXIS),
        ("act_expert", expert_axis),
        # batch dim of the dispatched [E, b, C, h] tokens: the
        # dataflow axes the expert axis does NOT consume — without
        # this, ep < dp*fsdp would silently replicate expert compute
        # over the uncovered axes
        ("act_expert_batch", _residual_data_axes(expert_axis)),
        # slot dim of the sort-dispatch [b, E*C, h] grouped buffer
        # (moe_dispatch="sort*"): it interleaves EVERY expert's
        # capacity block, so it must not shard over the expert axis —
        # the reshape+transpose to the ep-sharded [E, b, C, h] layout
        # under "act_expert" is where GSPMD places the all-to-all
        ("act_expert_slot", None),
        # slot axis of the serving KV cache ([slots, heads, d, S],
        # core/serving.py): slots are the decode batch, so they ride
        # the dataflow plane like "batch" while mp stays over the
        # cache's heads dim ("act_heads") — a slot server under mp
        # shards every slot's cache by head, never by slot content.
        # Under the paged cache the same name carries the POOL axis
        # of the global [kv_pool_pages, heads, d, page] KV store:
        # pages, like slots, are dataflow-plane content mp must not
        # split (the page-table indirection is per-row host state)
        ("cache_slots", DATA_AXES),
        # Multi-tenant LoRA adapter banks (models/gpt/model.py
        # _LoRADelta, docs/lora.md): [A, K, r] / [A, r, N] stacked
        # pairs. They are small (rank x hidden per adapter), so every
        # axis stays replicated: the adapter dim is serving-side
        # content (bank rows are swapped by the adapter cache, like
        # KV pages — sharding it would turn every cache fill into a
        # collective), and rank is far below the lane width. The
        # grouped GEMM then runs fully local per chip.
        ("adapters", None),
        ("lora_in", None),
        ("lora_rank", None),
        ("lora_out", None),
    )


def _residual_data_axes(expert_axis):
    used = set()
    if isinstance(expert_axis, str):
        used.add(expert_axis)
    elif expert_axis:
        used.update(expert_axis)
    residual = tuple(a for a in DATA_AXES if a not in used)
    return residual or None


def logical_to_mesh_spec(logical_axes: Sequence[Optional[str]],
                         rules: Rules) -> P:
    table = dict(rules)
    return P(*[table.get(a) if a is not None else None
               for a in logical_axes])


def shard_logical(mesh: Mesh, logical_axes: Sequence[Optional[str]],
                  rules: Rules) -> NamedSharding:
    return NamedSharding(mesh, logical_to_mesh_spec(logical_axes, rules))


def param_shardings(abstract_variables, mesh: Mesh, rules: Rules):
    """Map a tree of flax ``Partitioned`` metadata to NamedShardings."""
    return nn.logical_to_mesh_sharding(
        nn.get_partition_spec(abstract_variables), mesh, list(rules))


def with_logical_constraint(x, logical_axes: Sequence[Optional[str]]):
    """Constrain an activation's sharding by logical axes.

    No-op outside a mesh context (single-device runs, ``jax.eval_shape``).
    Requires ``nn.logical_axis_rules``/``set_logical_axis_rules`` to be
    active, which the engine establishes around jit-traced functions.
    """
    return nn.with_logical_constraint(x, tuple(logical_axes))


def optimizer_state_shardings(opt_state_shapes, param_specs, mesh: Mesh,
                              topo: TopologyConfig):
    """Shardings for optimizer state: ZeRO shards moments over fsdp.

    Mirrors reference sharding stages (``eager_engine.py:233-247``):
    stage >= 1 partitions optimizer states over the sharding axis.
    Param-shaped leaves (Adam moments, master weights) inherit the
    param's PartitionSpec — matched by path suffix, since optax moment
    subtrees replicate the param tree structure — and, for stages 1/2
    where params stay replicated over fsdp, additionally shard their
    largest still-unsharded divisible dim over fsdp. Non-param leaves
    (step counts) are replicated.

    ``param_specs`` is a pytree of ``PartitionSpec`` congruent with the
    params pytree.
    """
    flat_params = jax.tree_util.tree_flatten_with_path(param_specs)[0]
    by_suffix = {tuple(str(k) for k in path): spec
                 for path, spec in flat_params}
    max_suffix = max((len(k) for k in by_suffix), default=0)

    def _inherited_spec(path):
        keys = tuple(str(k) for k in path)
        for cut in range(max(0, len(keys) - max_suffix), len(keys)):
            spec = by_suffix.get(keys[cut:])
            if spec is not None:
                return spec
        return None

    def _leaf_sharding(path, shape_dtype):
        spec = _inherited_spec(path)
        if spec is None or shape_dtype.ndim == 0 or \
                len(spec) > shape_dtype.ndim:
            # unmatched leaves and factored-optimizer leaves whose rank
            # differs from the param's (e.g. adafactor row stats) stay
            # replicated
            return NamedSharding(mesh, P())
        dims = list(spec) + [None] * (shape_dtype.ndim - len(spec))
        if topo.sharding_degree > 1 and topo.sharding_stage < 3:
            used = {a for d in dims if d is not None
                    for a in ((d,) if isinstance(d, str) else d)}
            if FSDP_AXIS not in used:
                for d in sorted(range(shape_dtype.ndim),
                                key=lambda i: -shape_dtype.shape[i]):
                    if dims[d] is None and \
                            shape_dtype.shape[d] % topo.sharding_degree == 0:
                        dims[d] = FSDP_AXIS
                        break
        return NamedSharding(mesh, P(*dims))

    return jax.tree_util.tree_map_with_path(_leaf_sharding,
                                            opt_state_shapes)


def offload_to_host(shardings, shapes):
    """ZeRO offload (reference ``sharding_offload``,
    ``eager_engine.py:233-247``): place optimizer-state arrays in
    ``pinned_host`` memory; the train step streams them through HBM
    during the update. Only leaves actually partitioned over the mesh
    are offloaded — the SPMD partitioner rejects host placement of
    REPLICATED values (step counters, indivisible moments), and a
    replicated leaf gains nothing from ZeRO offload anyway.
    """
    del shapes  # placement depends on the spec, not the rank

    def _host(s):
        partitioned = any(d is not None for d in (s.spec or ()))
        return s.with_memory_kind("pinned_host") if partitioned else s

    return jax.tree.map(_host, shardings)


def device_memory_kinds(shardings):
    """The device-memory twin of an offloaded sharding tree (what the
    train step device_puts onto before the optimizer update)."""
    return jax.tree.map(lambda s: s.with_memory_kind("device"),
                        shardings)
