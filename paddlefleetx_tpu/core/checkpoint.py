"""Sharded checkpoint save/restore with step/RNG/dataloader metadata.

Parity: reference ``eager_engine.py:586-665`` writes per-rank dirs
``mp_XX_sharding_XX_pp_XX`` with model / optimizer / meta files and
fast-forwards the dataloader on resume. TPU-native replacement: one
Orbax/TensorStore sharded checkpoint per step — topology-independent
(save on mesh A, restore on mesh B; rank dirs are an artifact of NCCL
that GSPMD checkpointing removes), plus a JSON meta payload carrying
``{epoch, step, consumed_samples, rng_seed}``.

Layout: ``<output>/epoch_{E}_step_{S}/{state,meta}``.

Crash consistency (docs/robustness.md): every completed save commits a
``pfx_manifest.json`` inside the step dir LAST — file list + sizes,
with content hashes for the small metadata files. A dir without a
committed manifest is a torn write (the process died mid-save) and is
never selected by :func:`latest_checkpoint`; a dir whose contents
disagree with its manifest is corruption and :func:`load_checkpoint`
falls back to the newest older verified checkpoint, recording a
``ckpt_fallback`` event. The manifest is also the deletion gate for
:func:`gc_checkpoints` — an uncommitted dir might be an in-flight
async save, so GC never touches it.
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import re
import shutil
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from ..utils.log import logger

_STEP_DIR = re.compile(r"epoch_(\d+)_step_(\d+)$")

#: commit marker written last; its presence == "this save completed"
MANIFEST_NAME = "pfx_manifest.json"

#: files at or under this size get a content hash in the manifest
#: (Orbax metadata / zarray descriptors / the JSON meta payload —
#: the files whose silent corruption a size check cannot catch);
#: hashing multi-GB array shards on every resolve would make
#: latest_checkpoint O(checkpoint bytes)
_HASH_MAX_BYTES = 1 << 20


class CheckpointCorrupt(RuntimeError):
    """A checkpoint failed manifest verification (or restore) and no
    verified fallback existed."""


def _checkpointer() -> ocp.Checkpointer:
    return ocp.Checkpointer(ocp.CompositeCheckpointHandler())


_ASYNC_CKPTR: Optional[ocp.AsyncCheckpointer] = None

#: guards the process-wide async-save state below — serving worker
#: threads reach it through a replica's prefix-store export while the
#: training loop saves; the blocking drain itself runs outside it
_CKPT_STATE_LOCK = threading.Lock()


def _async_checkpointer() -> ocp.AsyncCheckpointer:
    """Process-wide async checkpointer (holds the background write
    thread pool); drained at interpreter exit so a fast-exiting run
    cannot truncate its last checkpoint."""
    global _ASYNC_CKPTR
    with _CKPT_STATE_LOCK:
        if _ASYNC_CKPTR is None:
            _ASYNC_CKPTR = ocp.AsyncCheckpointer(
                ocp.CompositeCheckpointHandler())
            atexit.register(wait_for_pending_save)
        return _ASYNC_CKPTR


#: (path, meta) of the async save whose manifest is not committed yet
_PENDING_MANIFEST: Optional[Tuple[str, Dict[str, Any]]] = None


def wait_for_pending_save() -> None:
    """Block until an in-flight async save (if any) is durable, then
    commit its manifest — the marker must postdate every byte it
    attests to."""
    ckptr, pending = _take_pending()
    _drain_pending(ckptr, pending)


def _take_pending() -> Tuple[Optional[ocp.AsyncCheckpointer],
                             Optional[Tuple[str, Dict[str, Any]]]]:
    """Claim the in-flight save under the state lock; a claimed
    manifest either commits in :func:`_drain_pending` or dies with
    the failed save — a later wait must never re-commit it."""
    global _PENDING_MANIFEST
    with _CKPT_STATE_LOCK:
        ckptr = _ASYNC_CKPTR
        pending = _PENDING_MANIFEST
        _PENDING_MANIFEST = None
        return ckptr, pending


def _drain_pending(ckptr, pending) -> None:
    """The blocking half: wait for durability, then commit."""
    if ckptr is not None:
        ckptr.wait_until_finished()
    if pending is not None:
        path, meta = pending
        write_manifest(path, meta)


def write_manifest(path: str, meta: Optional[Dict[str, Any]] = None
                   ) -> str:
    """Walk a completed step dir and commit its manifest: relative
    file list + byte sizes, content hashes for small files, written
    to a temp name and renamed into place (the rename IS the commit),
    then the directory fsynced so the marker survives power loss."""
    files: Dict[str, int] = {}
    hashes: Dict[str, str] = {}
    for root, _dirs, names in os.walk(path):
        for name in names:
            if name == MANIFEST_NAME or name.endswith(".tmp"):
                continue
            full = os.path.join(root, name)
            rel = os.path.relpath(full, path)
            size = os.path.getsize(full)
            files[rel] = size
            if size <= _HASH_MAX_BYTES:
                with open(full, "rb") as f:
                    hashes[rel] = hashlib.sha256(f.read()).hexdigest()
    payload = {"format": 1, "meta": meta or {}, "files": files,
               "sha256": hashes}
    final = os.path.join(path, MANIFEST_NAME)
    tmp = final + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)
    dirfd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)
    return final


def verify_checkpoint(path: str) -> Optional[str]:
    """None when ``path`` holds a committed, intact checkpoint;
    otherwise the human-readable reason it must not be restored
    (missing manifest == torn write, disagreeing contents ==
    corruption)."""
    mpath = os.path.join(path, MANIFEST_NAME)
    try:
        with open(mpath) as f:
            payload = json.load(f)
    except FileNotFoundError:
        return "no committed manifest (save did not complete)"
    except (OSError, ValueError) as err:
        return f"unreadable manifest: {err}"
    for rel, size in payload.get("files", {}).items():
        full = os.path.join(path, rel)
        try:
            actual = os.path.getsize(full)
        except OSError:
            return f"missing file {rel}"
        if actual != int(size):
            return (f"size mismatch on {rel}: manifest says {size}, "
                    f"found {actual}")
    for rel, digest in payload.get("sha256", {}).items():
        full = os.path.join(path, rel)
        try:
            with open(full, "rb") as f:
                actual = hashlib.sha256(f.read()).hexdigest()
        except OSError:
            return f"missing file {rel}"
        if actual != digest:
            return f"content hash mismatch on {rel}"
    return None


def save_prefix_store(path: str, store: Dict[str, Any]) -> str:
    """Persist a serving prefix store
    (``GenerationServer.export_prefix_store``) as a committed-last
    directory: page bytes as one ``.npz``, registry structure as
    JSON, then the :func:`write_manifest` rename commit — a torn
    write leaves no manifest and :func:`load_prefix_store` refuses
    it. Returns the manifest path."""
    os.makedirs(path, exist_ok=True)
    # overwrite-in-place safety: decommit any stale manifest FIRST so
    # a crash mid-rewrite cannot leave a marker attesting to half-new
    # bytes (same discipline as save_checkpoint)
    stale = os.path.join(path, MANIFEST_NAME)
    if os.path.exists(stale):
        os.remove(stale)
    arrays: Dict[str, Any] = {}
    pages_index: Dict[str, int] = {}
    for hpid, leaves in store.get("pages", {}).items():
        pages_index[str(int(hpid))] = len(leaves)
        for i, leaf in enumerate(leaves):
            arrays[f"p{int(hpid)}_{i}"] = np.asarray(leaf)
    prompts = []
    for key, (pages, payload) in store.get("prompts", {}).items():
        idx = None
        if payload is not None:
            idx = len([k for k in arrays if k.startswith("payload")])
            arrays[f"payload{idx}"] = np.asarray(payload)
        prompts.append([key, [int(p) for p in pages], idx])
    np.savez(os.path.join(path, "host_pages.npz"), **arrays)
    meta = {"kind": "prefix_store",
            "page_size": int(store["page_size"]),
            "kv_cache_dtype": store["kv_cache_dtype"],
            "model_fingerprint": store.get("model_fingerprint"),
            "pages": pages_index,
            "prefixes": [[k, int(p)]
                         for k, p in store.get("prefixes", {}).items()],
            "prompts": prompts}
    with open(os.path.join(path, "prefix_store.json"), "w") as f:
        json.dump(meta, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    return write_manifest(path, {"kind": "prefix_store",
                                 "pages": len(pages_index)})


def load_prefix_store(path: str, recorder=None
                      ) -> Optional[Dict[str, Any]]:
    """Load a :func:`save_prefix_store` directory back into the dict
    shape ``GenerationServer.import_prefix_store`` consumes. Refuses
    — returns None, the caller starts cold — when the directory was
    never committed or fails verification: a warm start from torn KV
    bytes would serve silently wrong attention."""
    reason = verify_checkpoint(path)
    if reason is not None:
        logger.warning("prefix store at %s refused: %s", path, reason)
        if recorder is not None:
            recorder.emit("prefix_store_rejected", path=path,
                          reason=reason)
        return None
    try:
        with open(os.path.join(path, "prefix_store.json")) as f:
            meta = json.load(f)
        # all arrays materialize eagerly inside the context so the
        # NpzFile's descriptor closes here rather than at GC
        with np.load(os.path.join(path, "host_pages.npz")) as npz:
            pages = {int(h): [npz[f"p{int(h)}_{i}"] for i in range(n)]
                     for h, n in meta.get("pages", {}).items()}
            prompts = {k: (
                [int(p) for p in pids],
                npz[f"payload{idx}"] if idx is not None else None)
                for k, pids, idx in meta.get("prompts", [])}
    except (OSError, ValueError) as err:
        logger.warning("prefix store at %s unreadable: %s", path, err)
        return None
    return {
        "page_size": meta["page_size"],
        "kv_cache_dtype": meta["kv_cache_dtype"],
        "model_fingerprint": meta.get("model_fingerprint"),
        "pages": pages,
        "prefixes": {k: int(p) for k, p in meta.get("prefixes", [])},
        "prompts": prompts,
    }


def save_adapter(path: str, tree: Dict[str, Any],
                 meta: Optional[Dict[str, Any]] = None) -> str:
    """Persist one canonical LoRA adapter tree (``core/adapters.py``:
    ``{"site/leaf": [num_layers, ...]}``) as a committed-last
    directory: all leaves in one ``.npz`` plus a JSON descriptor
    carrying per-key shapes/dtypes, then the :func:`write_manifest`
    rename commit. ``meta`` rides along verbatim (adapter id, base
    model fingerprint, training step...). Returns the manifest path."""
    os.makedirs(path, exist_ok=True)
    # same overwrite-in-place discipline as save_prefix_store: a crash
    # mid-rewrite must not leave a marker attesting to half-new bytes
    stale = os.path.join(path, MANIFEST_NAME)
    if os.path.exists(stale):
        os.remove(stale)
    arrays: Dict[str, Any] = {}
    index: Dict[str, Dict[str, Any]] = {}
    for i, key in enumerate(sorted(tree)):
        arr = np.asarray(tree[key])
        arrays[f"leaf{i}"] = arr
        index[key] = {"npz": f"leaf{i}", "shape": list(arr.shape),
                      "dtype": str(arr.dtype)}
    if not index:
        raise ValueError("refusing to save an empty adapter tree")
    np.savez(os.path.join(path, "adapter.npz"), **arrays)
    desc = {"kind": "lora_adapter", "meta": meta or {}, "leaves": index}
    with open(os.path.join(path, "adapter.json"), "w") as f:
        json.dump(desc, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    return write_manifest(path, {"kind": "lora_adapter",
                                 "leaves": len(index)})


def load_adapter(path: str) -> Tuple[Dict[str, np.ndarray],
                                     Dict[str, Any]]:
    """Load a :func:`save_adapter` directory back into ``(tree,
    meta)``. Raises :class:`CheckpointCorrupt` when the directory was
    never committed, fails manifest verification, or a leaf's
    shape/dtype disagrees with the descriptor — a torn adapter
    silently serves wrong deltas, so unlike the prefix store (a pure
    cache) there is no cold-start fallback here."""
    reason = verify_checkpoint(path)
    if reason is not None:
        raise CheckpointCorrupt(f"adapter at {path} refused: {reason}")
    try:
        with open(os.path.join(path, "adapter.json")) as f:
            desc = json.load(f)
        if desc.get("kind") != "lora_adapter":
            raise CheckpointCorrupt(
                f"{path} is not an adapter dir "
                f"(kind={desc.get('kind')!r})")
        tree: Dict[str, np.ndarray] = {}
        with np.load(os.path.join(path, "adapter.npz")) as npz:
            for key, ent in desc.get("leaves", {}).items():
                arr = npz[ent["npz"]]
                if list(arr.shape) != list(ent["shape"]) or \
                        str(arr.dtype) != ent["dtype"]:
                    raise CheckpointCorrupt(
                        f"adapter leaf {key} at {path}: descriptor "
                        f"says {ent['shape']}/{ent['dtype']}, npz "
                        f"holds {list(arr.shape)}/{arr.dtype}")
                tree[key] = arr
    except (OSError, ValueError, KeyError) as err:
        raise CheckpointCorrupt(
            f"adapter at {path} unreadable: {err}") from err
    if not tree:
        raise CheckpointCorrupt(f"adapter at {path} holds no leaves")
    return tree, desc.get("meta", {})


def save_checkpoint(output_dir: str, epoch: int, step: int, state,
                    meta: Dict[str, Any],
                    async_save: bool = False) -> str:
    """Write ``<output>/epoch_{E}_step_{S}``. With ``async_save`` the
    device arrays are snapshotted and the TensorStore write proceeds
    on background threads while training continues (the reference
    serializes training behind ``paddle.save``); the next save — or
    process exit — waits for the previous one. Either way the dir's
    manifest commits only after every byte is durable — synchronously
    here, or from :func:`wait_for_pending_save` for async saves."""
    global _PENDING_MANIFEST
    path = os.path.abspath(
        os.path.join(output_dir, f"epoch_{epoch}_step_{step}"))
    # at most one save (and manifest) in flight — and the previous
    # save's manifest must commit before this save may start
    # overwriting the very bytes it attests to
    wait_for_pending_save()
    # re-saving the same step (repeated preemption saves) overwrites
    # in place: decommit the old manifest FIRST so a crash mid-rewrite
    # cannot leave a stale marker attesting to half-new bytes
    stale = os.path.join(path, MANIFEST_NAME)
    if os.path.exists(stale):
        os.remove(stale)
        logger.info("decommitted %s before re-save", stale)
    args = ocp.args.Composite(
        state=ocp.args.StandardSave(state),
        meta=ocp.args.JsonSave(meta))
    if async_save:
        ckptr = _async_checkpointer()
        ckptr.save(path, args=args, force=True)
        with _CKPT_STATE_LOCK:
            _PENDING_MANIFEST = (path, dict(meta))
        logger.info("async checkpoint save started to %s", path)
    else:
        with _checkpointer() as ckptr:
            ckptr.save(path, args=args, force=True)
        write_manifest(path, meta)
        logger.info("saved checkpoint to %s", path)
    return path


def _step_dirs(ckpt_dir: str) -> List[Tuple[Tuple[int, int], str]]:
    """``((epoch, step), path)`` for every name-matching step dir
    below ``ckpt_dir``, newest first."""
    out = []
    for name in os.listdir(ckpt_dir):
        m = _STEP_DIR.match(name)
        if m and os.path.isdir(os.path.join(ckpt_dir, name)):
            key = (int(m.group(1)), int(m.group(2)))
            out.append((key, os.path.join(ckpt_dir, name)))
    out.sort(reverse=True)
    return out


def latest_checkpoint(ckpt_dir: str, recorder=None) -> Optional[str]:
    """Resolve a checkpoint path: either a step dir itself or the
    newest VERIFIED ``epoch_*_step_*`` below ``ckpt_dir``.

    The name regex alone is not trusted: a dir left by a mid-write
    kill matches it but holds torn bytes. Unverified dirs are skipped;
    when that demotes the resolution past newer-named dirs, a
    ``ckpt_fallback`` event records which artifacts were distrusted
    and why (``recorder`` optional — skipping is always logged)."""
    # an in-flight async save only gets its final (regex-matching)
    # name at commit; resolving before that would miss it or silently
    # pick the previous step
    wait_for_pending_save()
    if ckpt_dir is None or not os.path.isdir(ckpt_dir):
        return None
    if _STEP_DIR.search(ckpt_dir):
        return ckpt_dir   # explicit step dir: load_checkpoint verifies
    skipped: List[Dict[str, str]] = []
    for _key, path in _step_dirs(ckpt_dir):
        reason = verify_checkpoint(path)
        if reason is None:
            if skipped and recorder is not None:
                recorder.emit("ckpt_fallback", to=path,
                              skipped=skipped, stage="resolve")
            return path
        logger.warning("skipping unverified checkpoint %s: %s",
                       path, reason)
        skipped.append({"path": path, "reason": reason})
    if skipped and recorder is not None:
        recorder.emit("ckpt_fallback", to=None, skipped=skipped,
                      stage="resolve")
    return None


def load_checkpoint(path: str, abstract_state, fallback_dir=None,
                    recorder=None):
    """Restore (state, meta); ``abstract_state`` carries target
    shardings so arrays land directly on the current mesh.

    Verified restore with fallback: the manifest is checked before any
    byte is read, and with ``fallback_dir`` set a corrupt (or
    restore-failing) checkpoint demotes to the newest OLDER verified
    step dir under it, each demotion emitting a ``ckpt_fallback``
    event to ``recorder`` (and always logging). Without
    ``fallback_dir`` a verification failure raises
    :class:`CheckpointCorrupt` — resuming from torn bytes must never
    be silent.

    Layer-layout portability: ``Model.scan_layers`` changes the param
    pytree — scanned models stack the decoder under one ``decoder``
    subtree, unrolled models carry ``decoder_0..N`` — and the
    optimizer moments mirror whichever layout trained. A checkpoint
    written under one layout restores into a model built with the
    other: on a structure mismatch the restore is retried against the
    layout-toggled template and the result converted
    (stack <-> unstack) to the live model's layout, keeping
    ``scan_layers`` a pure performance knob rather than a checkpoint
    format fork.
    """
    wait_for_pending_save()   # same-process restore-after-async-save
    path = os.path.abspath(path)
    candidates = [path]
    if fallback_dir is not None and os.path.isdir(fallback_dir):
        mine = _STEP_DIR.search(path)
        my_key = (int(mine.group(1)), int(mine.group(2))) if mine \
            else None
        for key, p in _step_dirs(fallback_dir):
            if os.path.abspath(p) == path:
                continue
            if my_key is not None and key >= my_key:
                continue   # fall BACK, never forward past the target
            candidates.append(os.path.abspath(p))
    last_reason = None
    for i, cand in enumerate(candidates):
        reason = verify_checkpoint(cand)
        if reason is None:
            try:
                state, meta = _restore(cand, abstract_state)
            except Exception as err:   # intact manifest, failed read
                reason = f"restore failed: {err!r}"
                if fallback_dir is None or i == len(candidates) - 1:
                    raise
            else:
                if i > 0:
                    logger.warning(
                        "restored FALLBACK checkpoint %s (newest was "
                        "%s: %s)", cand, candidates[0], last_reason)
                return state, meta
        last_reason = reason
        logger.error("checkpoint %s failed verification: %s", cand,
                     reason)
        if recorder is not None:
            recorder.emit("ckpt_fallback", rejected=cand,
                          reason=reason, stage="load",
                          remaining=len(candidates) - 1 - i)
        if fallback_dir is None:
            raise CheckpointCorrupt(f"{cand}: {reason}")
    raise CheckpointCorrupt(
        f"no verified checkpoint among {len(candidates)} candidates "
        f"(newest: {candidates[0]}: {last_reason})")


def _restore(path: str, abstract_state):
    """One verified step dir -> (state, meta), including the
    scan_layers layout-toggle retry documented on
    :func:`load_checkpoint`."""
    with _checkpointer() as ckptr:
        try:
            restored = ckptr.restore(
                path,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(abstract_state),
                    meta=ocp.args.JsonRestore()))
            state = restored.state
        except (ValueError, KeyError, TypeError) as primary_err:
            # tree-structure mismatches surface as these; I/O or
            # device failures must NOT trigger a full re-read of a
            # possibly multi-GB checkpoint
            toggled = _toggle_layer_stack_template(abstract_state)
            if toggled is None:
                raise
            alt_abstract, convert = toggled
            try:
                restored = ckptr.restore(
                    path,
                    args=ocp.args.Composite(
                        state=ocp.args.StandardRestore(alt_abstract),
                        meta=ocp.args.JsonRestore()))
            except Exception:
                raise primary_err   # alt failed too: original error
            logger.info(
                "checkpoint layer layout differs from the model's "
                "(scan_layers toggled between save and load); "
                "converting")
            state = convert(restored.state)
    logger.info("restored checkpoint from %s", path)
    return state, restored.meta


def gc_checkpoints(output_dir: str, keep_last_k: int,
                   recorder=None) -> List[str]:
    """Delete all but the newest ``keep_last_k`` VERIFIED step dirs
    under ``output_dir``; returns the deleted paths.

    The manifest is the deletion gate: an unverified dir is either an
    in-flight async save (its manifest commits later) or torn garbage
    that :func:`latest_checkpoint` already refuses — GC leaves both
    alone rather than racing a background writer. Because only dirs
    OLDER than the ``keep_last_k`` newest verified ones are deleted,
    every checkpoint a live fallback could demote to survives (with
    ``keep_last_k >= 2``, even a post-commit corruption of the newest
    still finds its predecessor). ``keep_last_k < 1`` means unlimited
    retention and deletes nothing.

    Deliberately does NOT wait for an in-flight async save: the
    pending dir has no manifest yet, so it is not a candidate either
    way, and blocking here would serialize training behind the
    TensorStore write the async path exists to hide."""
    if keep_last_k is None or keep_last_k < 1:
        return []
    if not os.path.isdir(output_dir):
        return []
    verified = [p for _key, p in _step_dirs(output_dir)
                if verify_checkpoint(p) is None]
    deleted = []
    for path in verified[keep_last_k:]:
        # decommit first: a kill mid-rmtree leaves an unverifiable
        # stub, not a manifest over missing files
        try:
            os.remove(os.path.join(path, MANIFEST_NAME))
        except OSError as err:
            logger.warning("ckpt gc: cannot decommit %s (%s); "
                           "leaving it", path, err)
            continue
        shutil.rmtree(path, ignore_errors=True)
        deleted.append(path)
        logger.info("ckpt gc: deleted %s (keep_last_k=%d)", path,
                    keep_last_k)
    if deleted and recorder is not None:
        recorder.emit("ckpt_gc", deleted=deleted,
                      keep_last_k=keep_last_k,
                      kept=verified[:keep_last_k])
    return deleted


# -- scan_layers layout adapter ----------------------------------------


def _is_mapping(x) -> bool:
    return isinstance(x, dict)


_LAYER_KEY = re.compile(r"^decoder_(\d+)$")


def _toggle_layer_stack_template(abstract):
    """(alt_abstract, convert_fn) for the opposite ``scan_layers``
    layout of every ``decoder``/``decoder_N`` subtree in
    ``abstract`` (params and the optimizer-moment trees that mirror
    them), or None when no such subtree exists. ``alt_abstract``
    carries an explicit single-device sharding on every leaf — left
    unset, Orbax would fall back to the sharding RECORDED IN THE
    CHECKPOINT, which it warns is unsafe when the restoring topology
    differs from the saving one (the exact cross-topology case this
    module guarantees). The conversion then re-places every leaf
    onto the model's own shardings with ``device_put``. Fully
    materializing each leaf on one device is fine for the model
    sizes where layouts ever toggle: pipeline topologies require the
    scanned layout on both sides."""
    toggled = [False]
    from jax.sharding import SingleDeviceSharding
    host_sharding = SingleDeviceSharding(jax.local_devices()[0])

    def _leaf(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype,
                                    sharding=host_sharding)

    def walk_template(node):
        """Mirror the tree into ShapeDtypeStructs, unrolling any
        stacked-layer ``decoder`` block into per-layer leaves."""
        if _is_mapping(node):
            layer_keys = sorted(
                (k for k in node if _LAYER_KEY.match(k)),
                key=lambda k: int(_LAYER_KEY.match(k).group(1)))
            out = {}
            if "decoder" in node and _is_mapping(node["decoder"]):
                # stacked -> unrolled template: leaf[i] per layer
                sub = node["decoder"]
                lengths = {x.shape[0] for x in jax.tree.leaves(sub)}
                if len(lengths) == 1:
                    # only a uniform stack counts as a layout toggle —
                    # flagging anything else would let an unrelated
                    # restore failure retry through a layout-identical
                    # (but unsharded) template and mask the real error
                    toggled[0] = True
                    (num_layers,) = lengths
                    for i in range(num_layers):
                        out[f"decoder_{i}"] = jax.tree.map(
                            lambda x: _leaf(x.shape[1:], x.dtype),
                            sub)
                else:   # not a uniform stack; leave untouched
                    out["decoder"] = walk_template(sub)
            elif layer_keys:
                # unrolled -> stacked template: leading layer axis
                toggled[0] = True
                first = node[layer_keys[0]]
                out["decoder"] = jax.tree.map(
                    lambda x: _leaf(
                        (len(layer_keys),) + tuple(x.shape), x.dtype),
                    first)
            for k, v in node.items():
                if k == "decoder" and "decoder" not in out:
                    continue
                if _LAYER_KEY.match(k) and layer_keys:
                    continue
                if k not in out:
                    out[k] = walk_template(v)
            return out
        if isinstance(node, (list, tuple)):
            mapped = [walk_template(v) for v in node]
            if hasattr(node, "_fields"):       # NamedTuple (optax)
                return type(node)(*mapped)
            return type(node)(mapped)
        return _leaf(node.shape, node.dtype) \
            if hasattr(node, "shape") else node

    def convert(alt, template):
        """Restored-alt tree -> the layout+shardings of template."""
        if _is_mapping(template):
            out = {}
            for k, v in template.items():
                if k == "decoder" and _is_mapping(v) and \
                        any(_LAYER_KEY.match(a) for a in alt):
                    layer_keys = sorted(
                        (a for a in alt if _LAYER_KEY.match(a)),
                        key=lambda a: int(_LAYER_KEY.match(a).group(1)))
                    import jax.numpy as jnp
                    stacked = jax.tree.map(
                        lambda *xs: jnp.stack(xs),
                        *[alt[a] for a in layer_keys])
                    out[k] = _replace_leaves(stacked, v)
                elif _LAYER_KEY.match(k) and "decoder" in alt:
                    i = int(_LAYER_KEY.match(k).group(1))
                    sliced = jax.tree.map(lambda x: x[i],
                                          alt["decoder"])
                    out[k] = _replace_leaves(sliced, v)
                else:
                    out[k] = convert(alt[k], v)
            return out
        if isinstance(template, (list, tuple)):
            mapped = [convert(a, t) for a, t in zip(alt, template)]
            if hasattr(template, "_fields"):
                return type(template)(*mapped)
            return type(template)(mapped)
        return _place(alt, template)

    def _place(value, abstract_leaf):
        sharding = getattr(abstract_leaf, "sharding", None)
        if sharding is not None:
            return jax.device_put(value, sharding)
        return value

    def _replace_leaves(value_tree, abstract_tree):
        return jax.tree.map(_place, value_tree, abstract_tree)

    alt = walk_template(abstract)
    if not toggled[0]:
        return None
    return alt, (lambda restored: convert(restored, abstract))
