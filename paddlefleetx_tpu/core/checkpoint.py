"""Sharded checkpoint save/restore with step/RNG/dataloader metadata.

Parity: reference ``eager_engine.py:586-665`` writes per-rank dirs
``mp_XX_sharding_XX_pp_XX`` with model / optimizer / meta files and
fast-forwards the dataloader on resume. TPU-native replacement: one
Orbax/TensorStore sharded checkpoint per step — topology-independent
(save on mesh A, restore on mesh B; rank dirs are an artifact of NCCL
that GSPMD checkpointing removes), plus a JSON meta payload carrying
``{epoch, step, consumed_samples, rng_seed}``.

Layout: ``<output>/epoch_{E}_step_{S}/{state,meta}``.
"""

from __future__ import annotations

import atexit
import os
import re
from typing import Any, Dict, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from ..utils.log import logger

_STEP_DIR = re.compile(r"epoch_(\d+)_step_(\d+)$")


def _checkpointer() -> ocp.Checkpointer:
    return ocp.Checkpointer(ocp.CompositeCheckpointHandler())


_ASYNC_CKPTR: Optional[ocp.AsyncCheckpointer] = None


def _async_checkpointer() -> ocp.AsyncCheckpointer:
    """Process-wide async checkpointer (holds the background write
    thread pool); drained at interpreter exit so a fast-exiting run
    cannot truncate its last checkpoint."""
    global _ASYNC_CKPTR
    if _ASYNC_CKPTR is None:
        _ASYNC_CKPTR = ocp.AsyncCheckpointer(
            ocp.CompositeCheckpointHandler())
        atexit.register(wait_for_pending_save)
    return _ASYNC_CKPTR


def wait_for_pending_save() -> None:
    """Block until an in-flight async save (if any) is durable."""
    if _ASYNC_CKPTR is not None:
        _ASYNC_CKPTR.wait_until_finished()


def save_checkpoint(output_dir: str, epoch: int, step: int, state,
                    meta: Dict[str, Any],
                    async_save: bool = False) -> str:
    """Write ``<output>/epoch_{E}_step_{S}``. With ``async_save`` the
    device arrays are snapshotted and the TensorStore write proceeds
    on background threads while training continues (the reference
    serializes training behind ``paddle.save``); the next save — or
    process exit — waits for the previous one."""
    path = os.path.abspath(
        os.path.join(output_dir, f"epoch_{epoch}_step_{step}"))
    args = ocp.args.Composite(
        state=ocp.args.StandardSave(state),
        meta=ocp.args.JsonSave(meta))
    if async_save:
        ckptr = _async_checkpointer()
        ckptr.wait_until_finished()   # at most one save in flight
        ckptr.save(path, args=args, force=True)
        logger.info("async checkpoint save started to %s", path)
    else:
        with _checkpointer() as ckptr:
            ckptr.save(path, args=args, force=True)
        logger.info("saved checkpoint to %s", path)
    return path


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Resolve a checkpoint path: either a step dir itself or the
    newest ``epoch_*_step_*`` below ``ckpt_dir``."""
    # an in-flight async save only gets its final (regex-matching)
    # name at commit; resolving before that would miss it or silently
    # pick the previous step
    wait_for_pending_save()
    if ckpt_dir is None or not os.path.isdir(ckpt_dir):
        return None
    if _STEP_DIR.search(ckpt_dir):
        return ckpt_dir
    best: Tuple[int, int] = (-1, -1)
    best_path = None
    for name in os.listdir(ckpt_dir):
        m = _STEP_DIR.match(name)
        if m:
            key = (int(m.group(1)), int(m.group(2)))
            if key > best:
                best, best_path = key, os.path.join(ckpt_dir, name)
    return best_path


def load_checkpoint(path: str, abstract_state):
    """Restore (state, meta); ``abstract_state`` carries target
    shardings so arrays land directly on the current mesh."""
    wait_for_pending_save()   # same-process restore-after-async-save
    path = os.path.abspath(path)
    with _checkpointer() as ckptr:
        restored = ckptr.restore(
            path,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract_state),
                meta=ocp.args.JsonRestore()))
    logger.info("restored checkpoint from %s", path)
    return restored.state, restored.meta
