"""Sharded checkpoint save/restore with step/RNG/dataloader metadata.

Parity: reference ``eager_engine.py:586-665`` writes per-rank dirs
``mp_XX_sharding_XX_pp_XX`` with model / optimizer / meta files and
fast-forwards the dataloader on resume. TPU-native replacement: one
Orbax/TensorStore sharded checkpoint per step — topology-independent
(save on mesh A, restore on mesh B; rank dirs are an artifact of NCCL
that GSPMD checkpointing removes), plus a JSON meta payload carrying
``{epoch, step, consumed_samples, rng_seed}``.

Layout: ``<output>/epoch_{E}_step_{S}/{state,meta}``.
"""

from __future__ import annotations

import atexit
import os
import re
from typing import Any, Dict, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from ..utils.log import logger

_STEP_DIR = re.compile(r"epoch_(\d+)_step_(\d+)$")


def _checkpointer() -> ocp.Checkpointer:
    return ocp.Checkpointer(ocp.CompositeCheckpointHandler())


_ASYNC_CKPTR: Optional[ocp.AsyncCheckpointer] = None


def _async_checkpointer() -> ocp.AsyncCheckpointer:
    """Process-wide async checkpointer (holds the background write
    thread pool); drained at interpreter exit so a fast-exiting run
    cannot truncate its last checkpoint."""
    global _ASYNC_CKPTR
    if _ASYNC_CKPTR is None:
        _ASYNC_CKPTR = ocp.AsyncCheckpointer(
            ocp.CompositeCheckpointHandler())
        atexit.register(wait_for_pending_save)
    return _ASYNC_CKPTR


def wait_for_pending_save() -> None:
    """Block until an in-flight async save (if any) is durable."""
    if _ASYNC_CKPTR is not None:
        _ASYNC_CKPTR.wait_until_finished()


def save_checkpoint(output_dir: str, epoch: int, step: int, state,
                    meta: Dict[str, Any],
                    async_save: bool = False) -> str:
    """Write ``<output>/epoch_{E}_step_{S}``. With ``async_save`` the
    device arrays are snapshotted and the TensorStore write proceeds
    on background threads while training continues (the reference
    serializes training behind ``paddle.save``); the next save — or
    process exit — waits for the previous one."""
    path = os.path.abspath(
        os.path.join(output_dir, f"epoch_{epoch}_step_{step}"))
    args = ocp.args.Composite(
        state=ocp.args.StandardSave(state),
        meta=ocp.args.JsonSave(meta))
    if async_save:
        ckptr = _async_checkpointer()
        ckptr.wait_until_finished()   # at most one save in flight
        ckptr.save(path, args=args, force=True)
        logger.info("async checkpoint save started to %s", path)
    else:
        with _checkpointer() as ckptr:
            ckptr.save(path, args=args, force=True)
        logger.info("saved checkpoint to %s", path)
    return path


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Resolve a checkpoint path: either a step dir itself or the
    newest ``epoch_*_step_*`` below ``ckpt_dir``."""
    # an in-flight async save only gets its final (regex-matching)
    # name at commit; resolving before that would miss it or silently
    # pick the previous step
    wait_for_pending_save()
    if ckpt_dir is None or not os.path.isdir(ckpt_dir):
        return None
    if _STEP_DIR.search(ckpt_dir):
        return ckpt_dir
    best: Tuple[int, int] = (-1, -1)
    best_path = None
    for name in os.listdir(ckpt_dir):
        m = _STEP_DIR.match(name)
        if m:
            key = (int(m.group(1)), int(m.group(2)))
            if key > best:
                best, best_path = key, os.path.join(ckpt_dir, name)
    return best_path


def load_checkpoint(path: str, abstract_state):
    """Restore (state, meta); ``abstract_state`` carries target
    shardings so arrays land directly on the current mesh.

    Layer-layout portability: ``Model.scan_layers`` changes the param
    pytree — scanned models stack the decoder under one ``decoder``
    subtree, unrolled models carry ``decoder_0..N`` — and the
    optimizer moments mirror whichever layout trained. A checkpoint
    written under one layout restores into a model built with the
    other: on a structure mismatch the restore is retried against the
    layout-toggled template and the result converted
    (stack <-> unstack) to the live model's layout, keeping
    ``scan_layers`` a pure performance knob rather than a checkpoint
    format fork.
    """
    wait_for_pending_save()   # same-process restore-after-async-save
    path = os.path.abspath(path)
    with _checkpointer() as ckptr:
        try:
            restored = ckptr.restore(
                path,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(abstract_state),
                    meta=ocp.args.JsonRestore()))
            state = restored.state
        except (ValueError, KeyError, TypeError) as primary_err:
            # tree-structure mismatches surface as these; I/O or
            # device failures must NOT trigger a full re-read of a
            # possibly multi-GB checkpoint
            toggled = _toggle_layer_stack_template(abstract_state)
            if toggled is None:
                raise
            alt_abstract, convert = toggled
            try:
                restored = ckptr.restore(
                    path,
                    args=ocp.args.Composite(
                        state=ocp.args.StandardRestore(alt_abstract),
                        meta=ocp.args.JsonRestore()))
            except Exception:
                raise primary_err   # alt failed too: original error
            logger.info(
                "checkpoint layer layout differs from the model's "
                "(scan_layers toggled between save and load); "
                "converting")
            state = convert(restored.state)
    logger.info("restored checkpoint from %s", path)
    return state, restored.meta


# -- scan_layers layout adapter ----------------------------------------


def _is_mapping(x) -> bool:
    return isinstance(x, dict)


_LAYER_KEY = re.compile(r"^decoder_(\d+)$")


def _toggle_layer_stack_template(abstract):
    """(alt_abstract, convert_fn) for the opposite ``scan_layers``
    layout of every ``decoder``/``decoder_N`` subtree in
    ``abstract`` (params and the optimizer-moment trees that mirror
    them), or None when no such subtree exists. ``alt_abstract``
    carries an explicit single-device sharding on every leaf — left
    unset, Orbax would fall back to the sharding RECORDED IN THE
    CHECKPOINT, which it warns is unsafe when the restoring topology
    differs from the saving one (the exact cross-topology case this
    module guarantees). The conversion then re-places every leaf
    onto the model's own shardings with ``device_put``. Fully
    materializing each leaf on one device is fine for the model
    sizes where layouts ever toggle: pipeline topologies require the
    scanned layout on both sides."""
    toggled = [False]
    from jax.sharding import SingleDeviceSharding
    host_sharding = SingleDeviceSharding(jax.local_devices()[0])

    def _leaf(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype,
                                    sharding=host_sharding)

    def walk_template(node):
        """Mirror the tree into ShapeDtypeStructs, unrolling any
        stacked-layer ``decoder`` block into per-layer leaves."""
        if _is_mapping(node):
            layer_keys = sorted(
                (k for k in node if _LAYER_KEY.match(k)),
                key=lambda k: int(_LAYER_KEY.match(k).group(1)))
            out = {}
            if "decoder" in node and _is_mapping(node["decoder"]):
                # stacked -> unrolled template: leaf[i] per layer
                sub = node["decoder"]
                lengths = {x.shape[0] for x in jax.tree.leaves(sub)}
                if len(lengths) == 1:
                    # only a uniform stack counts as a layout toggle —
                    # flagging anything else would let an unrelated
                    # restore failure retry through a layout-identical
                    # (but unsharded) template and mask the real error
                    toggled[0] = True
                    (num_layers,) = lengths
                    for i in range(num_layers):
                        out[f"decoder_{i}"] = jax.tree.map(
                            lambda x: _leaf(x.shape[1:], x.dtype),
                            sub)
                else:   # not a uniform stack; leave untouched
                    out["decoder"] = walk_template(sub)
            elif layer_keys:
                # unrolled -> stacked template: leading layer axis
                toggled[0] = True
                first = node[layer_keys[0]]
                out["decoder"] = jax.tree.map(
                    lambda x: _leaf(
                        (len(layer_keys),) + tuple(x.shape), x.dtype),
                    first)
            for k, v in node.items():
                if k == "decoder" and "decoder" not in out:
                    continue
                if _LAYER_KEY.match(k) and layer_keys:
                    continue
                if k not in out:
                    out[k] = walk_template(v)
            return out
        if isinstance(node, (list, tuple)):
            mapped = [walk_template(v) for v in node]
            if hasattr(node, "_fields"):       # NamedTuple (optax)
                return type(node)(*mapped)
            return type(node)(mapped)
        return _leaf(node.shape, node.dtype) \
            if hasattr(node, "shape") else node

    def convert(alt, template):
        """Restored-alt tree -> the layout+shardings of template."""
        if _is_mapping(template):
            out = {}
            for k, v in template.items():
                if k == "decoder" and _is_mapping(v) and \
                        any(_LAYER_KEY.match(a) for a in alt):
                    layer_keys = sorted(
                        (a for a in alt if _LAYER_KEY.match(a)),
                        key=lambda a: int(_LAYER_KEY.match(a).group(1)))
                    import jax.numpy as jnp
                    stacked = jax.tree.map(
                        lambda *xs: jnp.stack(xs),
                        *[alt[a] for a in layer_keys])
                    out[k] = _replace_leaves(stacked, v)
                elif _LAYER_KEY.match(k) and "decoder" in alt:
                    i = int(_LAYER_KEY.match(k).group(1))
                    sliced = jax.tree.map(lambda x: x[i],
                                          alt["decoder"])
                    out[k] = _replace_leaves(sliced, v)
                else:
                    out[k] = convert(alt[k], v)
            return out
        if isinstance(template, (list, tuple)):
            mapped = [convert(a, t) for a, t in zip(alt, template)]
            if hasattr(template, "_fields"):
                return type(template)(*mapped)
            return type(template)(mapped)
        return _place(alt, template)

    def _place(value, abstract_leaf):
        sharding = getattr(abstract_leaf, "sharding", None)
        if sharding is not None:
            return jax.device_put(value, sharding)
        return value

    def _replace_leaves(value_tree, abstract_tree):
        return jax.tree.map(_place, value_tree, abstract_tree)

    alt = walk_template(abstract)
    if not toggled[0]:
        return None
    return alt, (lambda restored: convert(restored, abstract))
