"""Post-training quantization (PTQ) to the weight-only int8 format.

The QAT pass (``ops/quantization.py``) trains THROUGH a simulated
abs-max int8 grid but still materializes fp weights; nothing in the
repo executed real int8 until the ``quant_execution`` path
(``models/gpt/model.py::_QuantDense`` over
``ops/pallas/quantized_matmul.py``). This module is the bridge: it
rewrites a trained GPT parameter tree into that path's storage format
— each dense-site ``kernel`` becomes an int8 leaf plus a sibling fp32
``kernel_scale`` — so a base checkpoint quantizes into exactly the
tree a ``quant_execution="weight_only_int8"`` model abstract-inits,
and restores through the ordinary manifest-verified checkpoint
machinery (``core/checkpoint.py``).

Grid compatibility: scales are symmetric abs-max with ``qmax = 127``,
the same grid ``ops/quantization.py::fake_quant`` simulates (bits=8),
so PTQ of a QAT-trained checkpoint lands on the grid the weights were
trained to tolerate — but per OUTPUT CHANNEL rather than per tensor,
which is strictly finer (every channel of a QAT-optimal tensor is
also representable). Sites and their contraction layout are keyed by
parameter NAME, not by module introspection, so the pass works on a
bare restored pytree with no model object: ``qkv_proj`` / ``q_proj``
/ ``k_proj`` / ``v_proj`` / ``out_proj`` / ``linear1`` / ``linear2``
kernels quantize; embeddings, norms, biases and every other leaf pass
through untouched. Scan-stacked trees (``decoder/...`` leaves with a
leading ``[num_layers]`` axis) are detected by rank and get
independent per-layer scales, matching the QAT pass's
``stacked_module`` handling.

Driven by ``scripts/quantize_checkpoint.py``; numerics pinned in
``tests/test_quantized_matmul.py``; workflow in
``docs/quantization.md``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import traverse_util

#: dense-site kernel layout, keyed by the flax module name the site
#: keeps across the fp / collective / quantized implementations:
#: name -> (contract_ndim, base_ndim). ``base_ndim`` is the kernel
#: rank WITHOUT the nn.scan layer axis; a leaf of rank base_ndim + 1
#: is a stacked ``decoder`` kernel and keeps its leading layer axis
#: out of the scale reduction.
QUANT_SITES: Dict[str, Tuple[int, int]] = {
    "qkv_proj": (1, 4),    # [h, 3, heads, head_dim]
    "q_proj": (1, 3),      # [h, heads, head_dim]
    "k_proj": (1, 3),
    "v_proj": (1, 3),
    "out_proj": (2, 3),    # [heads, head_dim, h]
    "linear1": (1, 2),     # [h, ffn]
    "linear2": (1, 2),     # [ffn, h]
}

#: symmetric int8 grid shared with ``ops/quantization.py::fake_quant``
QMAX = 127.0
_EPS = 1e-8


def quantize_kernel(w, contract_ndim: int,
                    base_ndim: int) -> Tuple[jax.Array, jax.Array]:
    """One kernel -> ``(int8 values, fp32 per-output-channel scales)``.

    The scale reduces over the ``contract_ndim`` axes that the site's
    matmul contracts (skipping a leading nn.scan layer axis when the
    leaf is rank ``base_ndim + 1``), i.e. one scale per output
    channel — the layout ``_QuantDense`` holds in VMEM and
    ``quantized_matmul`` applies at write-out.
    """
    w = jnp.asarray(w)
    if w.ndim == base_ndim + 1:
        lead = 1
    elif w.ndim == base_ndim:
        lead = 0
    else:
        raise ValueError(
            f"kernel rank {w.ndim} matches neither the site's base "
            f"rank {base_ndim} nor its scan-stacked rank "
            f"{base_ndim + 1} (shape {w.shape})")
    axes = tuple(range(lead, lead + contract_ndim))
    f = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(f), axis=axes)
    scale = jnp.maximum(amax / QMAX, _EPS)
    q = jnp.clip(jnp.round(f / jnp.expand_dims(scale, axes)),
                 -QMAX, QMAX).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_kernel(q, scale, contract_ndim: int,
                      base_ndim: int) -> jax.Array:
    """Exact inverse mapping of the storage format back to fp32 —
    the XLA fallback's dequantize-then-dot weight and the oracle the
    parity tests compare the Pallas kernel against."""
    q = jnp.asarray(q)
    lead = 1 if q.ndim == base_ndim + 1 else 0
    axes = tuple(range(lead, lead + contract_ndim))
    return q.astype(jnp.float32) * jnp.expand_dims(
        jnp.asarray(scale, jnp.float32), axes)


def quantize_param_tree(
        params) -> Tuple[Any, List[Dict[str, Any]]]:
    """Rewrite a GPT param tree into the weight-only int8 format.

    Returns ``(quantized_tree, report)``: every ``<site>/kernel``
    with ``<site>`` in :data:`QUANT_SITES` is replaced by its int8
    values plus a new ``<site>/kernel_scale`` sibling; all other
    leaves (biases, norms, embeddings, already-int8 kernels) pass
    through by reference. The report has one row per quantized site
    with the shapes and the compression it bought — callers log it
    and stash it in the checkpoint meta.
    """
    flat = traverse_util.flatten_dict(params)
    out: Dict[Tuple[str, ...], Any] = {}
    report: List[Dict[str, Any]] = []
    for key, leaf in flat.items():
        site = key[-2] if len(key) >= 2 else ""
        if key[-1] != "kernel" or site not in QUANT_SITES \
                or getattr(leaf, "dtype", None) == jnp.int8:
            out[key] = leaf
            continue
        cn, base_ndim = QUANT_SITES[site]
        q, scale = quantize_kernel(leaf, cn, base_ndim)
        out[key] = q
        out[key[:-1] + ("kernel_scale",)] = scale
        report.append({
            "path": "/".join(key),
            "shape": list(np.shape(leaf)),
            "stacked": q.ndim == base_ndim + 1,
            "bytes_fp": int(np.size(leaf)) * jnp.dtype(leaf.dtype).itemsize,
            "bytes_int8": int(np.size(leaf)) + 4 * int(np.size(scale)),
        })
    return traverse_util.unflatten_dict(out), report


def dequantize_param_tree(qparams) -> Any:
    """Inverse of :func:`quantize_param_tree`: fold every
    ``kernel_scale`` back into an fp32 ``kernel`` — the reference
    tree a base (fp) model can apply, used to bound quantized-vs-base
    deviation without a second trained checkpoint."""
    flat = traverse_util.flatten_dict(qparams)
    out: Dict[Tuple[str, ...], Any] = {}
    for key, leaf in flat.items():
        if key[-1] == "kernel_scale":
            continue
        site = key[-2] if len(key) >= 2 else ""
        skey = key[:-1] + ("kernel_scale",)
        if key[-1] == "kernel" and site in QUANT_SITES \
                and skey in flat:
            cn, base_ndim = QUANT_SITES[site]
            out[key] = dequantize_kernel(leaf, flat[skey], cn,
                                         base_ndim)
        else:
            out[key] = leaf
    return traverse_util.unflatten_dict(out)


def calibrate_activation_absmax(model, params, sample_ids,
                                max_records: int = 512
                                ) -> Dict[str, float]:
    """Seed-batch activation calibration: one fp forward with the
    activation abs-max recorded at every module boundary (the
    moving-average abs-max statistic of the QAT config, evaluated at
    its per-batch fixed point — ``ops/quantization.py``). The result
    is a ``path -> absmax`` table the PTQ script stores in the
    checkpoint meta; a future activation-quantized executor consumes
    it, and until then it documents the dynamic range the weights
    were calibrated against."""
    _, inter = model.apply(
        {"params": params}, sample_ids, deterministic=True,
        capture_intermediates=True, mutable=["intermediates"])
    table: Dict[str, float] = {}
    flat = traverse_util.flatten_dict(inter["intermediates"])
    for key, leaf in sorted(flat.items()):
        if len(table) >= max_records:
            break
        for arr in jax.tree_util.tree_leaves(leaf):
            if hasattr(arr, "dtype") and jnp.issubdtype(
                    arr.dtype, jnp.floating):
                path = "/".join(str(k) for k in key)
                cur = float(jnp.max(jnp.abs(arr)))
                table[path] = max(table.get(path, 0.0), cur)
    return table


def quantization_meta(report: List[Dict[str, Any]],
                      calibration: Optional[Dict[str, float]] = None
                      ) -> Dict[str, Any]:
    """The ``meta["quantization"]`` payload written next to a
    quantized checkpoint — enough for a reader (or the chaos drill's
    resume leg) to know the artifact's format without probing dtypes."""
    payload: Dict[str, Any] = {
        "format": "weight_only_int8",
        "qmax": QMAX,
        "sites": sorted({r["path"] for r in report}),
        "report": report,
    }
    if calibration is not None:
        payload["activation_absmax"] = calibration
    return payload
