"""Draft sources for speculative decoding on the slot server.

A draft source proposes, per request, ``k`` guesses for the tokens the
request will emit AFTER the one the current tick samples (``verify_step``
scores the window ``[t0, d_1..d_k]`` in one forward — see
``models/gpt/generation.py``). Drafts only affect throughput, never
output: a wrong draft just wastes its window column.

The shipped source is n-gram self-speculation ("prompt lookup"): match
the request's trailing n-gram against its own earlier history and
propose the continuation that followed last time. It needs no second
model and pays off on the repetitive spans (code, lists, quoted
context) where speculative decoding wins most. The :class:`DraftSource`
protocol is deliberately minimal so a small draft-model source (its own
params + cache, proposing via k greedy steps) can slot in behind the
same ``GenerationConfig.spec_method`` switch later.
"""

from __future__ import annotations

from typing import Protocol, Sequence


class DraftSource(Protocol):
    """Per-request draft proposal interface."""

    def propose(self, history: Sequence[int], k: int) -> list[int]:
        """Return exactly ``k`` guesses for the tokens following
        ``history`` PLUS the one token the verify tick samples itself
        (i.e. guesses for positions ``len(history) + 2 ..``, given that
        position ``len(history) + 1`` is sampled, not drafted).

        ``k`` is not always ``gen_cfg.spec_tokens``: the fused
        multi-tick server (``device_loop_ticks=T`` — docs/inference.md,
        "Device-resident decode") proposes ``spec_tokens * T`` in ONE
        call and verifies chunk ``j`` on device tick ``j``, so later
        chunks guess past tokens the source never saw committed. A
        source only needs to return ``k`` in-vocab ids; staleness
        costs accept rate, never correctness."""
        ...


class NgramDraftSource:
    """Suffix-match the last ``n`` tokens of ``history`` (``n`` from
    ``max_ngram`` down to 1) against earlier history; on a hit at
    position ``i`` the continuation ``history[i + n] ..`` is what
    followed that n-gram last time. Its first token ``g0`` is a guess
    for the tick's own sampled ``t0``, so the k DRAFTS are the
    continuation shifted by one. No match ⇒ zeros (cheap guaranteed
    rejection)."""

    def __init__(self, max_ngram: int = 3):
        if max_ngram < 1:
            raise ValueError(f"max_ngram must be >= 1, got {max_ngram}")
        self.max_ngram = max_ngram

    def propose(self, history: Sequence[int], k: int) -> list[int]:
        """Draft up to ``k`` tokens by replaying the continuation of
        the most recent n-gram match in ``history`` (longest n
        first); empty when nothing matches."""
        hist = list(history)
        L = len(hist)
        for n in range(min(self.max_ngram, L - 1), 0, -1):
            pattern = hist[L - n:]
            # most recent earlier occurrence whose continuation is
            # in-bounds; range end L-n-1 keeps the match strictly
            # before the suffix itself
            for i in range(L - n - 1, -1, -1):
                if hist[i:i + n] == pattern:
                    cont = hist[i + n:i + n + k + 1]
                    drafts = cont[1:k + 1]
                    return drafts + [0] * (k - len(drafts))
        return [0] * k


def make_draft_source(method: str, **kwargs) -> DraftSource:
    """Factory behind ``GenerationConfig.spec_method``."""
    if method == "ngram":
        return NgramDraftSource(**kwargs)
    raise ValueError(
        f"unknown spec_method {method!r} (supported: 'ngram')")
