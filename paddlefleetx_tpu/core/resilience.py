"""Failure as a tested code path: fault injection + step watchdog.

The reference framework recovers only from its last periodic blocking
checkpoint and has no way to PROVE that recovery works (SURVEY §5.3);
here every failure mode the resilience layer claims to survive is
drilled by injected faults (docs/robustness.md):

- :class:`FaultInjector` parses the ``PFX_FAULTS`` spec — e.g.
  ``kill@step=7``, ``corrupt_ckpt@save=2``, ``hang@step=5:0.5s``,
  ``admit_fail@req=3`` — and the Engine step/save loop and the
  serving tick call :meth:`FaultInjector.fire` at the matching sites.
  Chaos tests (tests/test_resilience.py, scripts/chaos_smoke.py) use
  it to drive real kill -> resume loops and assert loss-curve- and
  token-exact continuation.
- :class:`StepWatchdog` is a monitor thread timing train steps /
  decode ticks against an adaptive deadline (a multiple of the
  trailing median); a stall dumps every thread's stack, emits a
  ``watchdog_stall`` event plus the ``engine/watchdog_stalls``
  counter, and optionally aborts (``PFX_WATCHDOG_ACTION=abort``).

Knobs (docs/observability.md): ``PFX_FAULTS``, ``PFX_FAULTS_SEED``,
``PFX_FAULTS_MODE``, ``PFX_WATCHDOG``, ``PFX_WATCHDOG_ACTION``,
``PFX_WATCHDOG_FACTOR``, ``PFX_WATCHDOG_MIN_S``.
"""

from __future__ import annotations

import os
import random
import re
import signal
import statistics
import sys
import threading
import time
import traceback
from collections import deque
from typing import Dict, List, Optional

from ..observability import metrics
from ..observability import timeline
from ..utils.log import logger


class InjectedKill(RuntimeError):
    """The in-process stand-in for a kill fault
    (``PFX_FAULTS_MODE=raise``): unit tests on the tier-1 mesh drill
    the save -> die -> resume loop without paying a subprocess per
    case, while the default mode delivers a real ``SIGKILL`` for
    end-to-end chaos runs (scripts/chaos_smoke.py)."""


#: ``kind@site=trigger[:durations]`` — trigger is a 1-based ordinal
#: (``kill@step=7``) or a seeded probability (``hang@tick=p0.05``);
#: the optional suffix is a duration in seconds (``hang@step=5:30s``)
_FAULT_RE = re.compile(
    r"^(?P<kind>[a-z_]+)@(?P<site>[a-z_]+)="
    r"(?P<trigger>p?\d+(?:\.\d+)?)"
    r"(?::(?P<duration>\d+(?:\.\d+)?)s?)?$")

_KINDS = ("kill", "hang", "corrupt_ckpt", "admit_fail")
_SITES = ("step", "save", "tick", "req")


class _Fault:
    """One parsed ``PFX_FAULTS`` entry; one-shot once fired."""

    def __init__(self, spec: str):
        m = _FAULT_RE.match(spec.strip())
        if not m:
            raise ValueError(
                f"bad PFX_FAULTS entry {spec!r}: expected "
                f"kind@site=N[:SECONDSs], e.g. kill@step=7 or "
                f"hang@tick=p0.1:2s")
        self.kind = m.group("kind")
        self.site = m.group("site")
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} in "
                             f"{spec!r}; known: {_KINDS}")
        if self.site not in _SITES:
            raise ValueError(f"unknown fault site {self.site!r} in "
                             f"{spec!r}; known: {_SITES}")
        trig = m.group("trigger")
        self.prob: Optional[float] = None
        self.at: Optional[int] = None
        if trig.startswith("p"):
            self.prob = float(trig[1:])
        else:
            self.at = int(float(trig))
        self.duration = float(m.group("duration") or 30.0)
        self.fired = False
        self.spec = spec.strip()


class FaultInjector:
    """Deterministic fault injection driven by a ``PFX_FAULTS`` spec.

    Call sites pass a monotonically increasing 1-based ``count`` per
    site (step number, save ordinal, tick ordinal, submit ordinal);
    ordinal triggers fire when they match, probabilistic triggers
    (``p0.05``) draw from a ``PFX_FAULTS_SEED``-seeded stream so a
    chaos run replays bit-identically. Every fault is one-shot and
    emits a ``fault_injected`` recorder event BEFORE acting — the
    flight record must show the fault even when the action is
    ``SIGKILL``."""

    def __init__(self, spec: str, seed: int = 0, recorder=None,
                 kill_mode: Optional[str] = None):
        self._faults = [_Fault(s) for s in spec.split(",")
                        if s.strip()]
        self._rng = random.Random(seed)
        self._recorder = recorder
        # hang faults wait on this rather than time.sleep so an
        # injected stall stays interruptible (and, on a serving
        # surface, sleeps inside the lock the way a real stalled
        # step would — the watchdog must see the lock held)
        self._hang_cv = threading.Condition()
        self.kill_mode = kill_mode or os.environ.get(
            "PFX_FAULTS_MODE", "kill")
        if self.kill_mode not in ("kill", "raise"):
            raise ValueError(
                f"PFX_FAULTS_MODE must be 'kill' or 'raise', got "
                f"{self.kill_mode!r}")

    @classmethod
    def from_env(cls, recorder=None) -> Optional["FaultInjector"]:
        """The process-configured injector, or None when ``PFX_FAULTS``
        is unset/empty (the production default: zero overhead)."""
        spec = os.environ.get("PFX_FAULTS", "").strip()
        if not spec:
            return None
        seed = int(os.environ.get("PFX_FAULTS_SEED", "0"))
        return cls(spec, seed=seed, recorder=recorder)

    def fire(self, site: str, count: int, **ctx) -> Optional[str]:
        """Evaluate every armed fault at ``site`` for this ``count``;
        acts on a match and returns the fault kind (``admit_fail`` is
        returned for the CALLER to act on — the injector cannot shed a
        request). None when nothing fired."""
        for f in self._faults:
            if f.fired or f.site != site:
                continue
            if f.prob is not None:
                if self._rng.random() >= f.prob:
                    continue
            elif f.at != count:
                continue
            f.fired = True
            logger.error("FAULT INJECTED: %s (site=%s count=%d)",
                         f.spec, site, count)
            if self._recorder is not None:
                self._recorder.emit("fault_injected", kind=f.kind,
                                    site=site, count=count,
                                    spec=f.spec)
            return self._act(f, ctx)
        return None

    def _act(self, f: _Fault, ctx: Dict) -> str:
        if f.kind == "kill":
            if self.kill_mode == "raise":
                raise InjectedKill(f.spec)
            os.kill(os.getpid(), signal.SIGKILL)
        elif f.kind == "hang":
            with self._hang_cv:
                self._hang_cv.wait(timeout=f.duration)
        elif f.kind == "corrupt_ckpt":
            self._corrupt(ctx.get("path"))
        return f.kind

    def _corrupt(self, path: Optional[str]) -> None:
        """Garble the just-written checkpoint at ``path``: wait for
        any in-flight async write (corrupting a half-written dir
        proves nothing — the manifest never commits), then truncate
        one byte off the largest payload file so the committed
        manifest disagrees with the bytes on disk."""
        from . import checkpoint as ckpt
        if path is None or not os.path.isdir(path):
            logger.error("corrupt_ckpt fault: no checkpoint dir in "
                         "context (path=%r); nothing corrupted", path)
            return
        ckpt.wait_for_pending_save()
        victim, size = None, -1
        for root, _dirs, names in os.walk(path):
            for name in names:
                if name == ckpt.MANIFEST_NAME:
                    continue
                full = os.path.join(root, name)
                n = os.path.getsize(full)
                if n > size:
                    victim, size = full, n
        if victim is None:
            logger.error("corrupt_ckpt fault: %s holds no files", path)
            return
        with open(victim, "ab") as fh:
            fh.truncate(max(size - 1, 0))
        logger.error("corrupt_ckpt fault: truncated %s (%d -> %d "
                     "bytes)", victim, size, max(size - 1, 0))


# -- step watchdog ------------------------------------------------------


def dump_all_stacks() -> str:
    """Every live thread's Python stack, formatted — the first thing
    an engineer needs from a hung step and the last thing a stuck
    process can still produce."""
    frames = sys._current_frames()
    lines: List[str] = []
    for t in threading.enumerate():
        frame = frames.get(t.ident)
        if frame is None:
            continue
        lines.append(f'--- thread "{t.name}" (daemon={t.daemon}) ---')
        lines.extend(x.rstrip("\n")
                     for x in traceback.format_stack(frame))
    return "\n".join(lines)


class StepWatchdog:
    """Monitor thread timing armed phases (train steps, decode ticks)
    against an adaptive deadline.

    The loop brackets each unit of work with :meth:`arm` /
    :meth:`disarm`; completed durations feed a trailing window and the
    deadline is ``max(min_interval, factor * trailing median)`` — a
    step 10x slower than its recent peers is a stall, but a cold
    compile before any history only trips the absolute floor. On a
    stall the watchdog dumps all-thread stacks, emits a
    ``watchdog_stall`` event, bumps ``engine/watchdog_stalls`` and —
    under ``action='abort'`` — exits the process with status 134 so an
    external supervisor restarts it instead of burning a TPU
    reservation on a wedged collective. One stall fires at most once
    per armed phase."""

    def __init__(self, name: str = "train_step",
                 factor: Optional[float] = None,
                 min_interval_s: Optional[float] = None,
                 action: Optional[str] = None,
                 recorder=None, history: int = 32):
        self.name = name
        self.factor = float(
            factor if factor is not None
            else os.environ.get("PFX_WATCHDOG_FACTOR", 10.0))
        self.min_interval_s = float(
            min_interval_s if min_interval_s is not None
            else os.environ.get("PFX_WATCHDOG_MIN_S", 60.0))
        self.action = (action or os.environ.get(
            "PFX_WATCHDOG_ACTION", "log")).strip().lower()
        if self.action not in ("log", "abort"):
            raise ValueError(
                f"PFX_WATCHDOG_ACTION must be 'log' or 'abort', got "
                f"{self.action!r}")
        self._recorder = recorder
        self._durations: deque = deque(maxlen=history)
        self._lock = threading.Lock()
        self._armed_at: Optional[float] = None
        self._tag: Optional[str] = None
        self._gen = 0            # arm generation, guards stall dedup
        self._stalled_gen = -1   # last generation that already stalled
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stalls = 0
        # swappable in tests; 134 = 128 + SIGABRT, what a supervisor
        # expects from a self-aborted worker
        self._abort_fn = lambda: os._exit(134)

    @classmethod
    def from_env(cls, name: str = "train_step", recorder=None
                 ) -> Optional["StepWatchdog"]:
        """A started watchdog when ``PFX_WATCHDOG`` is truthy, else
        None (the default: no monitor thread at all)."""
        if os.environ.get("PFX_WATCHDOG", "").strip().lower() \
                not in ("1", "true", "on", "yes"):
            return None
        dog = cls(name=name, recorder=recorder)
        dog.start()
        return dog

    def deadline_s(self) -> float:
        """Current stall threshold for an armed phase."""
        with self._lock:
            med = statistics.median(self._durations) \
                if self._durations else 0.0
        return max(self.min_interval_s, self.factor * med)

    def arm(self, tag: Optional[str] = None) -> None:
        """Mark the start of one timed phase."""
        with self._lock:
            self._armed_at = time.monotonic()
            self._tag = tag
            self._gen += 1

    def disarm(self) -> None:
        """Mark the phase complete; its duration joins the trailing
        window that sets future deadlines."""
        with self._lock:
            if self._armed_at is not None:
                self._durations.append(
                    time.monotonic() - self._armed_at)
            self._armed_at = None
            self._tag = None

    def start(self) -> None:
        """Spawn the monitor thread (daemon — it must never keep a
        dying process alive)."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"watchdog:{self.name}",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Stop and join the monitor thread."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _run(self) -> None:
        tl = timeline.track(f"watchdog:{self.name}")
        poll = min(1.0, max(0.02, self.min_interval_s / 5.0))
        while True:
            t0 = tl.begin()
            stopped = self._stop.wait(poll)
            tl.add("poll", t0)
            if stopped:
                return
            t0 = tl.begin()
            self._run_once()
            tl.add("check", t0)

    def _run_once(self) -> None:
        """One deadline check (the body of each monitor poll)."""
        with self._lock:
            armed_at, tag, gen = self._armed_at, self._tag, \
                self._gen
            already = gen == self._stalled_gen
        if armed_at is None or already:
            return
        waited = time.monotonic() - armed_at
        deadline = self.deadline_s()
        if waited <= deadline:
            return
        with self._lock:
            if self._gen != gen:   # phase ended while we decided
                return
            self._stalled_gen = gen
        self._on_stall(tag, waited, deadline)

    def _on_stall(self, tag: Optional[str], waited: float,
                  deadline: float) -> None:
        self.stalls += 1
        metrics.inc("engine/watchdog_stalls")
        stacks = dump_all_stacks()
        logger.error(
            "WATCHDOG: %s%s stalled for %.1fs (deadline %.1fs = "
            "max(%.1fs, %.1f x trailing median)); all-thread "
            "stacks:\n%s", self.name,
            f" [{tag}]" if tag else "", waited, deadline,
            self.min_interval_s, self.factor, stacks)
        if self._recorder is not None:
            # tail-bounded: the event stream is line-oriented JSON and
            # a deep stack must not balloon it past usefulness
            self._recorder.emit(
                "watchdog_stall", name=self.name, tag=tag,
                waited_s=round(waited, 3),
                deadline_s=round(deadline, 3),
                action=self.action, stacks=stacks[-8000:])
        if self.action == "abort":
            logger.error("WATCHDOG: aborting (PFX_WATCHDOG_ACTION="
                         "abort)")
            self._abort_fn()
