"""Inference engine over an exported AOT artifact.

Parity: reference ``core/engine/inference_engine.py:34-158`` — loads
per-rank static-graph models, writes a comm-topology CSV and drives
``paddle.inference`` with a distributed config. TPU-native: the
artifact is one ``jax.export`` directory (see ``utils/export.py``).

Distribution modes:

- **Model/tensor parallel**: an artifact exported under an ``mp > 1``
  mesh records its device count and parameter partition specs
  (``spec.json`` metadata); loading it requires an active mesh (see
  ``parallel.mesh.set_mesh``) with the same axis names and total size,
  onto which the parameters are re-partitioned and the computation
  jitted — one directory replaces the reference's per-rank
  ``rank_{i}`` model files, and the loader's mesh may be a different
  physical device assignment than the exporter's.
- **Data parallel** (reference ``inference_gpt_345M_dp8.yaml``): every
  rank constructs its own ``InferenceEngine`` over the same
  single-device artifact and serves its shard of the requests —
  embarrassingly parallel, no collectives (this is also what the
  reference's dp inference does: one predictor per rank).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List

import jax
import numpy as np

from ..observability import metrics
from ..observability.trace import annotate
from ..utils.export import (
    load_inference_model, load_spec, pad_to_spec,
)
from ..utils.log import logger


class InferenceEngine:
    """Loads a ``jax.export`` artifact and serves ``predict`` calls,
    re-partitioned onto the requested mesh."""

    def __init__(self, model_dir: str, mp_degree: int = 1, mesh=None):
        self.model_dir = model_dir
        t_load = time.time()
        meta = load_spec(model_dir)["metadata"]

        n_export = int(meta.get("num_export_devices", 1))
        axes = {k: int(v) for k, v in
                (meta.get("mesh_axes") or {}).items()}
        if mesh is None and n_export > 1:
            from ..parallel.mesh import get_mesh
            mesh = get_mesh()
            if mesh is None:
                mesh = self._build_mesh_from_metadata(axes, n_export)
        if n_export > 1:
            if mesh is None or mesh.devices.size != n_export:
                have = "no mesh" if mesh is None else \
                    f"a {mesh.devices.size}-device mesh"
                raise ValueError(
                    f"artifact {model_dir} was exported for {n_export} "
                    f"devices (mesh axes {axes}); the caller must "
                    f"activate a matching mesh (parallel.mesh."
                    f"set_mesh), but {have} is active")
            # size alone is not enough: a dp4 mesh has 4 devices too,
            # but loading an mp4 artifact on it would silently
            # replicate every parameter the export partitioned
            mismatched = {
                name: (size, mesh.shape.get(name))
                for name, size in axes.items()
                if mesh.shape.get(name) != size}
            if mismatched:
                raise ValueError(
                    f"artifact {model_dir} was exported on mesh axes "
                    f"{axes}; the active mesh {dict(mesh.shape)} "
                    f"differs on {sorted(mismatched)}")
        else:
            if mp_degree != 1:
                logger.info(
                    "mp_degree=%d requested but the artifact was "
                    "exported single-device; run tools/export.py under "
                    "the mp mesh to bake a partitioned artifact",
                    mp_degree)
            mesh = None

        # params restore sharded directly when a mesh is resolved — no
        # full-tree host materialization followed by a re-shard
        self.call, self.params, self.spec = \
            load_inference_model(model_dir, mesh=mesh)
        self.pad_values = meta.get("pad_values")
        self.pad_sides = meta.get("pad_sides")
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            replicated = NamedSharding(mesh, PartitionSpec())
            exported_call = self.call
            self.call = jax.jit(
                lambda p, *inputs: exported_call(p, *inputs),
                out_shardings=replicated)
            self._input_sharding = replicated
            logger.info(
                "inference artifact re-partitioned onto %d-device mesh "
                "%s", n_export, axes)
        else:
            self._input_sharding = None
        metrics.inc("inference/loads")
        metrics.get_registry().add_time("inference/load",
                                        time.time() - t_load)

    @staticmethod
    def _build_mesh_from_metadata(axes: Dict[str, int], n_export: int):
        """When no mesh is active, rebuild one from the artifact's own
        recorded axis names/sizes over the first ``n_export`` local
        devices — the serving entry points (``tasks/gpt/inference.py``)
        need no topology plumbing to load an mp artifact."""
        if not axes or n_export > len(jax.devices()):
            return None
        from jax.sharding import Mesh
        devs = np.asarray(jax.devices()[:n_export]).reshape(
            tuple(axes.values()))
        logger.info("no active mesh; rebuilding %s from artifact "
                    "metadata", axes)
        return Mesh(devs, tuple(axes))

    def predict(self, data: List[Any]) -> Dict[str, np.ndarray]:
        """Feed ``data`` (one array-like per exported input), run, and
        return outputs keyed by position (the reference returns the
        predictor's named output handles; positions are the stable
        equivalent here). Each call accumulates wall time under the
        ``inference/predict`` timer, the ``inference/predict_ms``
        latency histogram (p50/p99 on ``/metrics``), and bumps
        ``inference/predict_calls`` and ``inference/output_tokens``
        (total output elements) — docs/observability.md."""
        metrics.inc("inference/predict_calls")
        t_call = time.time()
        pads = self.pad_values or [0] * len(data)
        inputs = pad_to_spec([np.asarray(d) for d in data], self.spec,
                             pads, self.pad_sides)
        if self._input_sharding is not None:
            inputs = [jax.device_put(x, self._input_sharding)
                      for x in inputs]
        with metrics.get_registry().timer("inference/predict"):
            with annotate("predict"):
                outputs = self.call(self.params, *inputs)
            if not isinstance(outputs, (tuple, list)):
                outputs = (outputs,)
            # np.asarray blocks on the device result, so the transfer
            # lands inside the per-call latency timer
            result = {str(i): np.asarray(o)
                      for i, o in enumerate(outputs)}
        metrics.observe("inference/predict_ms",
                        (time.time() - t_call) * 1000.0)
        metrics.inc("inference/output_tokens",
                    sum(o.size for o in result.values()))
        return result

    @staticmethod
    def serve_generation(model, params, gen_cfg, num_slots: int = 4,
                         **kwargs):
        """Build a continuous-batching :class:`~paddlefleetx_tpu.core.
        serving.GenerationServer` over a live model (slot-managed KV
        cache + ragged flash decode) — the serving counterpart of the
        artifact-driven ``predict`` path. Extra ``kwargs`` pass through
        to the server (``prefill_buckets``, ``rng``, ``events_path``,
        the paged-KV knobs ``page_size`` / ``pool_pages`` /
        ``prefill_chunk_pages`` / ``prefix_sharing`` —
        docs/inference.md, "Paged KV cache" — the fused-decode knob
        ``device_loop_ticks`` (up to T ticks per host round-trip —
        docs/inference.md, "Device-resident decode") and the
        graceful-degradation knobs ``request_ttl_s`` /
        ``max_queue_depth`` / ``drain_on_sigterm`` —
        docs/robustness.md). With
        ``events_path`` the server traces every request
        (docs/observability.md, "Request tracing"); with
        ``PFX_METRICS_PORT`` set it serves live ``/metrics`` +
        ``/healthz``."""
        from .serving import GenerationServer
        return GenerationServer(model, params, gen_cfg,
                                num_slots=num_slots, **kwargs)
