"""Inference engine over an exported AOT artifact.

Parity: reference ``core/engine/inference_engine.py:34-158`` — loads
per-rank static-graph models, writes a comm-topology CSV and drives
``paddle.inference`` with a distributed config. TPU-native: the
artifact is one ``jax.export`` directory (see ``utils/export.py``);
distribution is whatever mesh the *caller* runs the deserialized
computation under (GSPMD re-partitions automatically), so there is no
rank bookkeeping or ring CSV to manage. ``mp_degree`` is accepted for
config compatibility.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ..utils.export import load_inference_model, pad_to_spec
from ..utils.log import logger


class InferenceEngine:
    def __init__(self, model_dir: str, mp_degree: int = 1):
        if mp_degree != 1:
            logger.info(
                "mp_degree=%d accepted for config parity; the exported "
                "computation repartitions under the active mesh instead "
                "of per-rank model files", mp_degree)
        self.model_dir = model_dir
        self.call, self.params, self.spec = \
            load_inference_model(model_dir)
        self.pad_values = self.spec["metadata"].get("pad_values")
        self.pad_sides = self.spec["metadata"].get("pad_sides")

    def predict(self, data: List[Any]) -> Dict[str, np.ndarray]:
        """Feed ``data`` (one array-like per exported input), run, and
        return outputs keyed by position (the reference returns the
        predictor's named output handles; positions are the stable
        equivalent here)."""
        pads = self.pad_values or [0] * len(data)
        inputs = pad_to_spec([np.asarray(d) for d in data], self.spec,
                             pads, self.pad_sides)
        outputs = self.call(self.params, *inputs)
        if not isinstance(outputs, (tuple, list)):
            outputs = (outputs,)
        return {str(i): np.asarray(o) for i, o in enumerate(outputs)}
