"""The Lightning-style module contract between engine and models.

Parity: reference ``ppfleetx/core/module/basic_module.py:29-86``
(``BasicModule``: get_model / training_step / validation_step /
``*_step_end`` hooks / input_spec) and
``ppfleetx/models/language_model/language_module.py:31-110``
(``LanguageModule``: loss + tokens/s throughput logging in the exact
``ips:`` line grammar the TIPC harness greps).

JAX twist: ``training_step`` must be a pure function traced under jit,
so the contract splits into pure parts (``loss_fn``) the engine jits,
and host-side hooks (``*_step_end``) for logging.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

from ..utils.log import logger


class BasicModule:
    """Subclasses implement ``get_model``/``loss_fn``; the engine owns
    the step loop and calls the hooks."""

    #: set True when the model handles cp-sharded sequences (ring
    #: attention); the engine rejects cp_degree > 1 otherwise
    supports_context_parallel = False

    def __init__(self, configs):
        self.configs = configs
        self.nranks = None  # filled by the engine with mesh world size
        self.model = self.get_model()

    # -- pure (jit-traced) ---------------------------------------------
    def get_model(self):
        raise NotImplementedError

    def loss_fn(self, params, batch, rng, train: bool = True):
        """Return scalar loss. ``batch`` is the collated tuple."""
        raise NotImplementedError

    def predict_step(self, params, batch, rng):
        """Pure per-batch test output for ``Engine.predict`` (reference
        ``test_step``, ``language_module.py:83-88``: eval-mode loss);
        override to return custom predictions (scalar, array, or a
        dict with a ``loss`` entry for logging)."""
        return self.loss_fn(params, batch, rng, train=False)

    # -- host-side hooks -----------------------------------------------
    def pretreating_batch(self, batch):
        return batch

    def training_step_end(self, log_dict: Dict[str, Any]) -> None:
        logger.train(
            "[train] epoch: %d, batch: %d, loss: %.9f, avg_batch_cost: "
            "%.5f sec", log_dict["epoch"], log_dict["batch"],
            log_dict["loss"], log_dict["train_cost"])

    def validation_step_end(self, log_dict: Dict[str, Any]) -> None:
        logger.eval(
            "[eval] epoch: %d, batch: %d, loss: %.9f, avg_eval_cost: "
            "%.5f sec", log_dict["epoch"], log_dict["batch"],
            log_dict["loss"], log_dict["eval_cost"])

    def validation_epoch_end(self, log_dict: Dict[str, Any]) -> None:
        pass

    def test_step_end(self, log_dict: Dict[str, Any]) -> None:
        pass

    def training_epoch_end(self, log_dict: Dict[str, Any]) -> None:
        logger.info("[Training] epoch: %d, total time: %.5f sec",
                    log_dict["epoch"], log_dict["train_cost"])

    def input_spec(self):
        """Abstract input shapes/dtypes for export (AOT compile)."""
        return None

    def init_model_variables(self, model, rngs, samples):
        """Parameter init call — override when the model needs extra
        static arguments so the created tree matches what ``loss_fn``
        will apply (e.g. Imagen's cascade stage selection)."""
        return model.init(rngs, *samples)

    def _data_section(self):
        """First present Data mode section, or None (eval-only configs
        have no Train; dry-run configs may have no Data at all)."""
        data = self.configs.get("Data") or {}
        return data.get("Train") or data.get("Eval") or \
            data.get("Test")


class LanguageModule(BasicModule):
    """Adds the LM throughput logging contract
    (reference ``language_module.py:58-95``)."""

    def training_step_end(self, log_dict: Dict[str, Any]) -> None:
        """Emit the TIPC-scraped ``[train]`` line (see
        ``utils/log.py:TRAIN_LINE_RE`` for the pinned grammar)."""
        speed = 1.0 / log_dict["train_cost"]
        default_global_tokens_num = (
            self.configs.Global.global_batch_size *
            log_dict["max_seq_len"])
        # the HBM suffix rides AFTER the TIPC-pinned fields so the
        # ``loss:``/``ips:`` grammar (tests/test_log_grammar.py) stays
        # grep-compatible; present only when the engine sampled
        # device-memory stats (telemetry on, TPU backend)
        hbm = ""
        if log_dict.get("hbm_bytes_in_use") is not None:
            hbm = ", hbm: %.2fG (peak %.2fG)" % (
                log_dict["hbm_bytes_in_use"] / 2**30,
                (log_dict.get("hbm_peak_bytes") or 0) / 2**30)
        logger.train(
            "[train] epoch: %d, batch: %d, loss: %.9f, "
            "avg_batch_cost: %.5f sec, speed: %.2f step/s, "
            "ips_total: %.0f tokens/s, ips: %.0f tokens/s, "
            "learning rate: %.5e%s",
            log_dict["epoch"], log_dict["batch"], log_dict["loss"],
            log_dict["train_cost"], speed,
            speed * default_global_tokens_num,
            speed * default_global_tokens_num / max(self.nranks or 1, 1),
            log_dict["lr"], hbm)

    def validation_step_end(self, log_dict: Dict[str, Any]) -> None:
        speed = 1.0 / log_dict["eval_cost"]
        logger.eval(
            "[eval] epoch: %d, batch: %d, loss: %.9f, avg_eval_cost: "
            "%.5f sec, speed: %.2f step/s", log_dict["epoch"],
            log_dict["batch"], log_dict["loss"], log_dict["eval_cost"],
            speed)

    def test_step_end(self, log_dict: Dict[str, Any]) -> None:
        speed = 1.0 / log_dict["test_cost"]
        logger.info(
            "[test] epoch: %d, batch: %d, loss: %.9f, avg_test_cost: "
            "%.5f sec, speed: %.2f step/s", log_dict["epoch"],
            log_dict["batch"], log_dict["loss"], log_dict["test_cost"],
            speed)
