"""Core subpackage."""
from .engine import BasicEngine, Engine  # noqa: F401
from .module import BasicModule, LanguageModule  # noqa: F401
from .serving import Completion, GenerationServer  # noqa: F401
