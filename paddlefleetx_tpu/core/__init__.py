"""Core subpackage."""
from .engine import BasicEngine, Engine  # noqa: F401
from .module import BasicModule, LanguageModule  # noqa: F401
from .resilience import (  # noqa: F401
    FaultInjector, InjectedKill, StepWatchdog,
)
from .serving import (  # noqa: F401
    Completion, GenerationServer, RequestShed,
)
