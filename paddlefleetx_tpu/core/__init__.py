"""Core subpackage."""
from .engine import BasicEngine, Engine  # noqa: F401
from .module import BasicModule, LanguageModule  # noqa: F401
from .resilience import (  # noqa: F401
    FaultInjector, InjectedKill, StepWatchdog,
)
from .fleet import FleetReplica, FleetRouter  # noqa: F401
from .serving import (  # noqa: F401
    Completion, GenerationServer, RequestShed,
)
