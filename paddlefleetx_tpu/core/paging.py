"""Paged KV-cache bookkeeping: the host side of the serving cache.

The contiguous slot cache (PR 5) provisions every slot for the
worst-case length — ``cache_capacity`` KV columns per slot whether the
request is 16 tokens or 500. This module replaces that with the
vLLM-style paged design: the physical KV store is one global pool of
fixed-size pages (``[kv_pool_pages, heads, head_dim, kv_page_size]``
per layer, device-resident), and each slot reaches its tokens through a
``page_table [slots, max_pages]`` int32 indirection the flash-decode
kernel walks via scalar prefetch (``flash_decode_paged``) and the XLA
fallback resolves with a gather (``ops/attention.py``).

Everything HERE is host-side and cheap: which physical page holds which
logical page of which request, reference counts for pages shared
between requests, and two content-addressed registries that make the
sharing happen:

- the **prefix registry** keys each FULL page of a prompt by the chain
  hash of every token up to and including that page, so two requests
  with the same system-prompt prefix map the same physical pages and
  prefill the shared region once;
- the **prompt registry** keys a whole finished prefill (pages + the
  final-token logits), so an identical prompt admits with ZERO prefill
  — the fork case of parallel sampling — and the forks share even the
  partial last page until their first divergent decode write triggers
  a copy-on-write split (the server checks ``refcount > 1`` before
  every write and copies the page first).

Page 0 is reserved as the null page: empty ``page_table`` entries point
at it, so an inactive slot's dead decode writes land in a dedicated
garbage page instead of corrupting live data.

Invariants (asserted by :meth:`PageAllocator.check` under the
randomized trace tests): ``free + in_use == num_pages - 1``; every
refcount is positive; every registered page is live; releasing a page
to refcount 0 returns it to the free list and drops every registry
entry that mentions it.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

#: the reserved garbage page every empty page_table entry points at
NULL_PAGE = 0


def page_prefix_keys(tokens: Sequence[int], page_size: int) -> List[str]:
    """Chain-hash key per FULL page of ``tokens``: key ``j`` digests
    every token in pages ``0..j``, so equal keys mean equal prompt
    prefixes (KV at position ``i`` depends only on tokens ``<= i``
    under causal attention — the PagedAttention sharing argument)."""
    h = hashlib.sha1()
    out: List[str] = []
    for j in range(len(tokens) // page_size):
        chunk = np.asarray(
            tokens[j * page_size:(j + 1) * page_size], np.int64)
        h.update(chunk.tobytes())
        out.append(h.hexdigest())
    return out


def prompt_key(tokens: Sequence[int]) -> str:
    """Content key for a WHOLE prompt (length-tagged so a prefix never
    collides with its extension)."""
    h = hashlib.sha1(np.asarray(tokens, np.int64).tobytes())
    return f"L{len(tokens)}:{h.hexdigest()}"


class PagePoolExhausted(RuntimeError):
    """Raised by :meth:`PageAllocator.alloc` when no free page exists;
    the server preempts a slot and retries."""


class PageAllocator:
    """Refcounted allocator over ``num_pages`` physical KV pages.

    Pure host bookkeeping — device traffic (pool writes, COW page
    copies, page-table uploads) stays with the caller
    (``core/serving.py``), which consults this object between decode
    ticks. Page 0 (:data:`NULL_PAGE`) is reserved and never allocated.
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved null "
                f"page), got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = num_pages
        self.page_size = page_size
        # LIFO free list, low page ids first (deterministic traces)
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._ref: Dict[int, int] = {}
        #: chain-hash key -> physical page (full prompt pages only)
        self._prefix: Dict[str, int] = {}
        #: whole-prompt key -> (pages tuple, opaque payload — the
        #: server stores the final-token logits row here)
        self._prompt: Dict[str, Tuple[Tuple[int, ...], object]] = {}
        #: reverse maps so releasing a page drops its registry entries
        self._page_prefix_keys: Dict[int, str] = {}
        self._page_prompt_keys: Dict[int, set] = {}
        self.stats = {"allocs": 0, "frees": 0, "prefix_hits": 0,
                      "prompt_hits": 0, "cow_splits": 0}

    # -- pool accounting ----------------------------------------------

    @property
    def free_pages(self) -> int:
        """Pages available for allocation right now."""
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        """Live (refcount > 0) pages, null page excluded."""
        return self.num_pages - 1 - len(self._free)

    def refcount(self, pid: int) -> int:
        """Current reference count of ``pid`` (0 when free)."""
        return self._ref.get(pid, 0)

    def alloc(self) -> int:
        """Take a free page at refcount 1."""
        if not self._free:
            raise PagePoolExhausted(
                f"page pool exhausted ({self.num_pages - 1} usable "
                f"pages, all referenced)")
        pid = self._free.pop()
        self._ref[pid] = 1
        self.stats["allocs"] += 1
        return pid

    def try_alloc(self) -> Optional[int]:
        """Like :meth:`alloc`, but None instead of raising on an
        empty pool."""
        try:
            return self.alloc()
        except PagePoolExhausted:
            return None

    def retain(self, pid: int) -> int:
        """Add a reference to a live page; returns the new refcount."""
        if self._ref.get(pid, 0) < 1:
            raise ValueError(f"retain of free/unknown page {pid}")
        self._ref[pid] += 1
        return self._ref[pid]

    def release(self, pid: int) -> bool:
        """Drop one reference; at zero the page returns to the free
        list and every registry entry naming it is dropped. Returns
        True when the page was actually freed."""
        if self._ref.get(pid, 0) < 1:
            raise ValueError(f"release of free/unknown page {pid}")
        self._ref[pid] -= 1
        if self._ref[pid]:
            return False
        del self._ref[pid]
        key = self._page_prefix_keys.pop(pid, None)
        if key is not None:
            self._prefix.pop(key, None)
        for pk in self._page_prompt_keys.pop(pid, set()):
            entry = self._prompt.pop(pk, None)
            if entry is not None:
                for other in entry[0]:
                    if other != pid:
                        keys = self._page_prompt_keys.get(other)
                        if keys is not None:
                            keys.discard(pk)
        self._free.append(pid)
        self.stats["frees"] += 1
        return True

    # -- content-addressed sharing ------------------------------------

    def lookup_prefix(self, key: str) -> Optional[int]:
        """Physical page holding this full-page prefix, or None."""
        return self._prefix.get(key)

    def register_prefix(self, key: str, pid: int) -> None:
        """Publish a full prompt page for prefix sharing. First writer
        wins — an already-registered key keeps its page (both copies
        hold identical KV, deduping them after the fact is not worth
        the device copy)."""
        if self._ref.get(pid, 0) < 1:
            raise ValueError(f"register_prefix of free page {pid}")
        if key not in self._prefix:
            self._prefix[key] = pid
            self._page_prefix_keys[pid] = key

    def lookup_prompt(self, key: str):
        """``(pages, payload)`` of an identical finished prefill, or
        None. The caller must :meth:`retain` every page it maps."""
        return self._prompt.get(key)

    def register_prompt(self, key: str, pages: Sequence[int],
                        payload) -> None:
        """Publish a whole finished prefill (its page list plus an
        opaque payload — the server stores the final-token logits) so
        an identical prompt can admit with zero prefill compute."""
        pages = tuple(int(p) for p in pages)
        for pid in pages:
            if self._ref.get(pid, 0) < 1:
                raise ValueError(
                    f"register_prompt names free page {pid}")
        if key in self._prompt:
            return
        self._prompt[key] = (pages, payload)
        for pid in pages:
            self._page_prompt_keys.setdefault(pid, set()).add(key)

    # -- invariants ----------------------------------------------------

    def check(self) -> None:
        """Assert the allocator invariants (test hook)."""
        assert NULL_PAGE not in self._ref and NULL_PAGE not in self._free
        assert len(self._free) + len(self._ref) == self.num_pages - 1
        assert not (set(self._free) & set(self._ref))
        assert all(c > 0 for c in self._ref.values())
        for key, pid in self._prefix.items():
            assert self._ref.get(pid, 0) > 0, (key, pid)
            assert self._page_prefix_keys.get(pid) == key
        for key, (pages, _) in self._prompt.items():
            for pid in pages:
                assert self._ref.get(pid, 0) > 0, (key, pid)
                assert key in self._page_prompt_keys.get(pid, set())


# -- pool sizing -------------------------------------------------------

def kv_page_bytes(num_heads: int, head_dim: int, page_size: int,
                  kv_cache_dtype: str = "bf16") -> int:
    """Device bytes ONE K or V page costs per layer.

    ``bf16``: 2 bytes per element. ``int8``: 1 byte per element plus
    one fp32 scale per (head, position) — the ``cached_*_scale`` pool
    leaves of ``models/gpt/model.py`` — i.e. ``head_dim + 4`` bytes
    per head-token instead of ``2 * head_dim``: a 1.88x density win at
    head_dim 64 (docs/quantization.md)."""
    if kv_cache_dtype == "int8":
        per_token = num_heads * (head_dim + 4)
    elif kv_cache_dtype == "bf16":
        per_token = num_heads * head_dim * 2
    else:
        raise ValueError(
            f"unknown kv_cache_dtype {kv_cache_dtype!r} "
            f"(expected 'bf16' or 'int8')")
    return per_token * page_size


def pool_bytes(num_layers: int, num_heads: int, head_dim: int,
               page_size: int, num_pages: int,
               kv_cache_dtype: str = "bf16") -> int:
    """Total device bytes of a ``num_pages`` KV pool (K and V, all
    layers) — the figure the serving summary reports and the A/B
    bench divides slot counts by."""
    return 2 * num_layers * num_pages * kv_page_bytes(
        num_heads, head_dim, page_size, kv_cache_dtype)


def pool_pages_for_bytes(budget_bytes: int, num_layers: int,
                         num_heads: int, head_dim: int,
                         page_size: int,
                         kv_cache_dtype: str = "bf16") -> int:
    """Largest pool (in pages) fitting ``budget_bytes`` of HBM —
    the inverse of :func:`pool_bytes`, used to hold pool BYTES fixed
    while switching ``kv_cache_dtype`` (int8 admits ~1.9x the pages,
    hence ~1.9x the resident slots on the same memory)."""
    per_page = 2 * num_layers * kv_page_bytes(
        num_heads, head_dim, page_size, kv_cache_dtype)
    return int(budget_bytes) // max(per_page, 1)
