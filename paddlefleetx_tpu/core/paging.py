"""Paged KV-cache bookkeeping: the host side of the serving cache.

The contiguous slot cache (PR 5) provisions every slot for the
worst-case length — ``cache_capacity`` KV columns per slot whether the
request is 16 tokens or 500. This module replaces that with the
vLLM-style paged design: the physical KV store is one global pool of
fixed-size pages (``[kv_pool_pages, heads, head_dim, kv_page_size]``
per layer, device-resident), and each slot reaches its tokens through a
``page_table [slots, max_pages]`` int32 indirection the flash-decode
kernel walks via scalar prefetch (``flash_decode_paged``) and the XLA
fallback resolves with a gather (``ops/attention.py``).

Everything HERE is host-side and cheap: which physical page holds which
logical page of which request, reference counts for pages shared
between requests, and two content-addressed registries that make the
sharing happen:

- the **prefix registry** keys each FULL page of a prompt by the chain
  hash of every token up to and including that page, so two requests
  with the same system-prompt prefix map the same physical pages and
  prefill the shared region once;
- the **prompt registry** keys a whole finished prefill (pages + the
  final-token logits), so an identical prompt admits with ZERO prefill
  — the fork case of parallel sampling — and the forks share even the
  partial last page until their first divergent decode write triggers
  a copy-on-write split (the server checks ``refcount > 1`` before
  every write and copies the page first).

Page 0 is reserved as the null page: empty ``page_table`` entries point
at it, so an inactive slot's dead decode writes land in a dedicated
garbage page instead of corrupting live data.

PR 16 adds a second tier: constructed with ``host_pages > 0`` the
allocator also tracks a bounded pinned-host-DRAM pool occupying the id
range ``num_pages .. num_pages + host_pages - 1``. A registered page
whose refcount drops to its last reference can be **spilled** — its
registry entries move onto a host id and the HBM page frees — and a
later registry hit **promotes** it back onto a freshly allocated HBM
page (the server scatters the saved bytes first). Both registries span
the tiers transparently: a lookup may return a host id, which the
caller detects with :meth:`PageAllocator.is_host`. Host ids are never
mapped in any page table, so COW semantics are preserved structurally:
a divergent write can only target an HBM page, and splitting it leaves
the host copy untouched.

Invariants (asserted by :meth:`PageAllocator.check` under the
randomized trace tests): ``free + in_use == num_pages - 1``; every
refcount is positive; every registered page is live or host-resident;
releasing a page to refcount 0 returns it to the free list and drops
every registry entry that mentions it; no id is simultaneously free,
live, and host-resident (the cross-tier partition); every
host-resident page carries at least one registration (orphans are
evicted eagerly — an unreachable host page is pure leak).
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

#: the reserved garbage page every empty page_table entry points at
NULL_PAGE = 0


def page_prefix_keys(tokens: Sequence[int], page_size: int) -> List[str]:
    """Chain-hash key per FULL page of ``tokens``: key ``j`` digests
    every token in pages ``0..j``, so equal keys mean equal prompt
    prefixes (KV at position ``i`` depends only on tokens ``<= i``
    under causal attention — the PagedAttention sharing argument)."""
    h = hashlib.sha1()
    out: List[str] = []
    for j in range(len(tokens) // page_size):
        chunk = np.asarray(
            tokens[j * page_size:(j + 1) * page_size], np.int64)
        h.update(chunk.tobytes())
        out.append(h.hexdigest())
    return out


def prompt_key(tokens: Sequence[int]) -> str:
    """Content key for a WHOLE prompt (length-tagged so a prefix never
    collides with its extension)."""
    h = hashlib.sha1(np.asarray(tokens, np.int64).tobytes())
    return f"L{len(tokens)}:{h.hexdigest()}"


class PagePoolExhausted(RuntimeError):
    """Raised by :meth:`PageAllocator.alloc` when no free page exists;
    the server preempts a slot and retries."""


class PageAllocator:
    """Refcounted allocator over ``num_pages`` physical KV pages.

    Pure host bookkeeping — device traffic (pool writes, COW page
    copies, page-table uploads, spill gathers, rehydrate scatters)
    stays with the caller (``core/serving.py``), which consults this
    object between decode ticks. Page 0 (:data:`NULL_PAGE`) is
    reserved and never allocated.

    With ``host_pages > 0`` a second id range (``num_pages ..
    num_pages + host_pages - 1``) models the pinned-host spill tier:
    :meth:`spill` moves a dying page's registrations onto a host id,
    :meth:`promote` moves them back onto a fresh HBM id on a registry
    hit, and a full host tier evicts its least-recently-spilled
    resident to make room. The allocator never touches the page BYTES
    — the caller keeps the host copies and drains
    :meth:`pop_host_evicted` after every mutating call so its byte
    store tracks this bookkeeping exactly.
    """

    def __init__(self, num_pages: int, page_size: int,
                 host_pages: int = 0):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved null "
                f"page), got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if host_pages < 0:
            raise ValueError(
                f"host_pages must be >= 0, got {host_pages}")
        self.num_pages = num_pages
        self.page_size = page_size
        self.host_pages = host_pages
        # LIFO free list, low page ids first (deterministic traces)
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._ref: Dict[int, int] = {}
        #: chain-hash key -> physical page (full prompt pages only)
        self._prefix: Dict[str, int] = {}
        #: whole-prompt key -> (pages tuple, opaque payload — the
        #: server stores the final-token logits row here)
        self._prompt: Dict[str, Tuple[Tuple[int, ...], object]] = {}
        #: reverse maps so releasing a page drops its registry entries
        self._page_prefix_keys: Dict[int, str] = {}
        self._page_prompt_keys: Dict[int, set] = {}
        # -- host tier (ids >= num_pages) --
        self._host_free: List[int] = list(
            range(num_pages + host_pages - 1, num_pages - 1, -1))
        #: resident host id -> monotone spill sequence (LRU order)
        self._hosted: Dict[int, int] = {}
        self._host_seq = 0
        #: host ids the allocator evicted since the caller last drained
        #: them (the caller drops its byte copies for these)
        self._host_evicted: List[int] = []
        self.stats = {"allocs": 0, "frees": 0, "prefix_hits": 0,
                      "prompt_hits": 0, "cow_splits": 0, "spills": 0,
                      "rehydrates": 0, "host_evictions": 0}

    # -- pool accounting ----------------------------------------------

    @property
    def free_pages(self) -> int:
        """Pages available for allocation right now."""
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        """Live (refcount > 0) pages, null page excluded."""
        return self.num_pages - 1 - len(self._free)

    def refcount(self, pid: int) -> int:
        """Current reference count of ``pid`` (0 when free)."""
        return self._ref.get(pid, 0)

    def alloc(self) -> int:
        """Take a free page at refcount 1."""
        if not self._free:
            raise PagePoolExhausted(
                f"page pool exhausted ({self.num_pages - 1} usable "
                f"pages, all referenced)")
        pid = self._free.pop()
        self._ref[pid] = 1
        self.stats["allocs"] += 1
        return pid

    def try_alloc(self) -> Optional[int]:
        """Like :meth:`alloc`, but None instead of raising on an
        empty pool."""
        try:
            return self.alloc()
        except PagePoolExhausted:
            return None

    def alloc_many(self, n: int) -> List[int]:
        """Take ``n`` free pages at refcount 1 in one call — the
        import half of a batched KV handoff (``kv_import`` scatters
        all destination pages in one dispatch). All-or-nothing: an
        exhausted pool raises before any page is taken."""
        if n > len(self._free):
            raise PagePoolExhausted(
                f"page pool exhausted ({len(self._free)} free of "
                f"{self.num_pages - 1} usable, {n} requested)")
        return [self.alloc() for _ in range(n)]

    def retain(self, pid: int) -> int:
        """Add a reference to a live page; returns the new refcount."""
        if self._ref.get(pid, 0) < 1:
            raise ValueError(f"retain of free/unknown page {pid}")
        self._ref[pid] += 1
        return self._ref[pid]

    def retain_many(self, pids: Sequence[int]) -> None:
        """Pin a whole page set in one call — the export half of a
        batched KV handoff. All-or-nothing: validates every id before
        taking the first reference, so a bad id never leaves a
        partially pinned set."""
        for pid in pids:
            if self._ref.get(pid, 0) < 1:
                raise ValueError(f"retain of free/unknown page {pid}")
        for pid in pids:
            self._ref[pid] += 1

    def release(self, pid: int) -> bool:
        """Drop one reference; at zero the page returns to the free
        list and every registry entry naming it is dropped. Returns
        True when the page was actually freed."""
        if self._ref.get(pid, 0) < 1:
            raise ValueError(f"release of free/unknown page {pid}")
        self._ref[pid] -= 1
        if self._ref[pid]:
            return False
        del self._ref[pid]
        self._drop_registrations(pid)
        self._free.append(pid)
        self.stats["frees"] += 1
        return True

    def _drop_registrations(self, pid: int) -> None:
        """Remove every registry entry naming ``pid`` — the single
        teardown shared by every way a page leaves a tier: an HBM page
        freeing to the pool (:meth:`release`) and a host-resident page
        evicted to make room. Dropping a prompt entry can strand a
        hosted co-member with no surviving registration; such orphans
        are unreachable by any lookup, so they are evicted here too
        (recorded in :meth:`pop_host_evicted` for the byte store)."""
        key = self._page_prefix_keys.pop(pid, None)
        if key is not None:
            self._prefix.pop(key, None)
        affected = set()
        for pk in self._page_prompt_keys.pop(pid, set()):
            entry = self._prompt.pop(pk, None)
            if entry is not None:
                for other in entry[0]:
                    if other != pid:
                        keys = self._page_prompt_keys.get(other)
                        if keys is not None:
                            keys.discard(pk)
                            if not keys:
                                # an empty reverse-map set would make
                                # page_registered() lie True
                                del self._page_prompt_keys[other]
                            affected.add(other)
        for other in affected:
            if other in self._hosted and not self.page_registered(other):
                self._evict_host(other)

    # -- content-addressed sharing ------------------------------------

    def lookup_prefix(self, key: str) -> Optional[int]:
        """Physical page holding this full-page prefix, or None."""
        return self._prefix.get(key)

    def register_prefix(self, key: str, pid: int) -> None:
        """Publish a full prompt page for prefix sharing. First writer
        wins — an already-registered key keeps its page (both copies
        hold identical KV, deduping them after the fact is not worth
        the device copy). Host-resident pages may be (re)registered —
        the restart warm-start import path does exactly that."""
        if self._ref.get(pid, 0) < 1 and pid not in self._hosted:
            raise ValueError(f"register_prefix of free page {pid}")
        if key not in self._prefix:
            self._prefix[key] = pid
            self._page_prefix_keys[pid] = key

    def lookup_prompt(self, key: str):
        """``(pages, payload)`` of an identical finished prefill, or
        None. The caller must :meth:`retain` every page it maps."""
        return self._prompt.get(key)

    def register_prompt(self, key: str, pages: Sequence[int],
                        payload) -> None:
        """Publish a whole finished prefill (its page list plus an
        opaque payload — the server stores the final-token logits) so
        an identical prompt can admit with zero prefill compute.
        Members may live in either tier (live HBM or host-resident)."""
        pages = tuple(int(p) for p in pages)
        for pid in pages:
            if self._ref.get(pid, 0) < 1 and pid not in self._hosted:
                raise ValueError(
                    f"register_prompt names free page {pid}")
        if key in self._prompt:
            return
        self._prompt[key] = (pages, payload)
        for pid in pages:
            self._page_prompt_keys.setdefault(pid, set()).add(key)

    # -- host spill tier ----------------------------------------------

    @property
    def host_pages_resident(self) -> int:
        """Host-tier pages currently holding spilled KV."""
        return len(self._hosted)

    def is_host(self, pid: int) -> bool:
        """True when ``pid`` is a resident host-tier id (a registry
        lookup returned a spilled page the caller must rehydrate)."""
        return pid in self._hosted

    def page_registered(self, pid: int) -> bool:
        """True when any registry entry (prefix or prompt) names
        ``pid`` — the spill-eligibility gate: an unregistered page can
        never be found again, so spilling it would be pure leak."""
        return pid in self._page_prefix_keys or \
            pid in self._page_prompt_keys

    def spill(self, pid: int) -> Optional[int]:
        """Move a refcount-1 page's registrations onto a fresh host id
        and free the HBM page — the bookkeeping half of a spill; the
        caller gathers the page's KV (before calling this) and stages
        it to host memory under the returned id. A full host tier
        evicts its least-recently-spilled resident first. Returns None
        — page NOT freed, caller falls back to a plain release — when
        no host tier exists or ``pid`` carries no registration."""
        if self._ref.get(pid, 0) != 1:
            raise ValueError(
                f"spill of page {pid} with refcount "
                f"{self._ref.get(pid, 0)} != 1")
        if not self.host_pages or not self.page_registered(pid):
            return None
        hpid = self._host_alloc()
        if not self.page_registered(pid):
            # the LRU eviction inside _host_alloc cascaded through a
            # prompt entry this page co-membered with the victim and
            # took its last registration — nothing left to keep warm
            del self._hosted[hpid]
            self._host_free.append(hpid)
            return None
        self._move_registrations(pid, hpid)
        del self._ref[pid]
        self._free.append(pid)
        self.stats["frees"] += 1
        self.stats["spills"] += 1
        return hpid

    def promote(self, hpid: int, pid: int) -> None:
        """Move a host-resident page's registrations onto live HBM
        page ``pid`` and free the host slot — the bookkeeping half of
        rehydration; the caller allocates ``pid`` (its refcount-1
        reference belongs to the admitting request) and scatters the
        saved bytes into it BEFORE calling this."""
        if hpid not in self._hosted:
            raise ValueError(f"promote of non-resident host id {hpid}")
        if self._ref.get(pid, 0) < 1:
            raise ValueError(f"promote onto free page {pid}")
        self._move_registrations(hpid, pid)
        del self._hosted[hpid]
        self._host_free.append(hpid)
        self.stats["rehydrates"] += 1

    def host_import(self) -> Optional[int]:
        """A fresh resident host id with NO eviction — the restart
        warm-start import fills free host slots and stops; evicting
        this replica's own spills to adopt another's would be a wash.
        The caller registers content keys against the returned id."""
        if not self._host_free:
            return None
        hpid = self._host_free.pop()
        self._host_seq += 1
        self._hosted[hpid] = self._host_seq
        return hpid

    def host_generation(self, hpid: int) -> Optional[int]:
        """Monotone residency generation of a host id (its spill
        sequence), or None when not resident. A recycled id gets a
        NEW generation, so the byte-store owner can tell staged bytes
        of an evicted earlier residency from the live one's — the ids
        alone are ambiguous the moment the LRU recycles them."""
        return self._hosted.get(hpid)

    def evict_host(self, hpid: int) -> None:
        """Evict one resident host page by id — the caller lost its
        byte copy (e.g. the spill stage failed on the writer thread),
        so the registrations pointing at it must die before a lookup
        hands out a page that can never rehydrate. The id shows up in
        :meth:`pop_host_evicted` like any other eviction; a
        non-resident id is a no-op (it may already have been LRU'd)."""
        if hpid in self._hosted:
            self._evict_host(hpid)

    def pop_host_evicted(self) -> List[int]:
        """Host ids this allocator evicted (LRU pressure, orphan
        sweep) since the last call — returned once so the caller can
        drop its byte copies before the ids are reused."""
        out, self._host_evicted = self._host_evicted, []
        return out

    def sweep_host_orphans(self) -> None:
        """Evict every host-resident page with no surviving
        registration (partial-import leftovers); the evicted ids show
        up in :meth:`pop_host_evicted` like any other eviction."""
        for hpid in [h for h in self._hosted
                     if not self.page_registered(h)]:
            self._evict_host(hpid)

    def host_snapshot(self):
        """``(prefixes, prompts)`` restricted to the host tier —
        prefix key -> host id, prompt key -> (ids list, payload) for
        entries whose EVERY member is host-resident (a mixed entry
        pins live HBM pages a restart cannot carry). This is the
        registry half of the restart-persistent prefix store."""
        prefixes = {k: p for k, p in self._prefix.items()
                    if p in self._hosted}
        prompts = {k: (list(pages), payload)
                   for k, (pages, payload) in self._prompt.items()
                   if all(p in self._hosted for p in pages)}
        return prefixes, prompts

    def _host_alloc(self) -> int:
        """A resident host id, evicting the least-recently-spilled
        page (registrations dropped, id recycled) when the tier is
        full — the boundedness contract of ``host_pool_bytes``."""
        if not self._host_free:
            victim = min(self._hosted, key=self._hosted.get)
            self._evict_host(victim)
        hpid = self._host_free.pop()
        self._host_seq += 1
        self._hosted[hpid] = self._host_seq
        return hpid

    def _evict_host(self, hpid: int) -> None:
        """Drop a resident host page: registrations die, the slot
        frees, and the id is queued for :meth:`pop_host_evicted`."""
        del self._hosted[hpid]
        self._drop_registrations(hpid)
        self._host_free.append(hpid)
        self._host_evicted.append(hpid)
        self.stats["host_evictions"] += 1

    def _move_registrations(self, src: int, dst: int) -> None:
        """Re-point every registry entry from ``src`` to ``dst`` —
        the cross-tier move both :meth:`spill` and :meth:`promote`
        reduce to. ``dst`` must carry no registrations of its own
        (always true: spill targets a fresh host id, promote a fresh
        HBM page)."""
        key = self._page_prefix_keys.pop(src, None)
        if key is not None:
            self._prefix[key] = dst
            self._page_prefix_keys[dst] = key
        pks = self._page_prompt_keys.pop(src, set())
        if pks:
            self._page_prompt_keys.setdefault(dst, set()).update(pks)
            for pk in pks:
                pages, payload = self._prompt[pk]
                self._prompt[pk] = (tuple(
                    dst if p == src else p for p in pages), payload)

    # -- invariants ----------------------------------------------------

    def check(self) -> None:
        """Assert the allocator invariants (test hook)."""
        assert NULL_PAGE not in self._ref and NULL_PAGE not in self._free
        assert len(self._free) + len(self._ref) == self.num_pages - 1
        assert not (set(self._free) & set(self._ref))
        assert all(c > 0 for c in self._ref.values())
        # cross-tier partition: HBM ids below num_pages, host ids at or
        # above it, and no id is simultaneously free, live, and
        # host-resident — the three states are mutually exclusive
        host_ids = set(self._host_free) | set(self._hosted)
        assert not (set(self._free) | set(self._ref)) & host_ids
        assert not set(self._host_free) & set(self._hosted)
        assert len(self._host_free) + len(self._hosted) == \
            self.host_pages
        assert all(h >= self.num_pages for h in host_ids)
        assert all(p < self.num_pages
                   for p in list(self._free) + list(self._ref))
        # every host-resident page is reachable through a registry
        for hpid in self._hosted:
            assert self.page_registered(hpid), hpid
        for key, pid in self._prefix.items():
            assert self._ref.get(pid, 0) > 0 or pid in self._hosted, \
                (key, pid)
            assert self._page_prefix_keys.get(pid) == key
        for key, (pages, _) in self._prompt.items():
            for pid in pages:
                assert self._ref.get(pid, 0) > 0 or \
                    pid in self._hosted, (key, pid)
                assert key in self._page_prompt_keys.get(pid, set())
        # the reverse maps never hold dead weight: an empty prompt-key
        # set would make page_registered() (the spill gate) lie True
        assert all(self._page_prompt_keys.values())
        for pid, keys in self._page_prompt_keys.items():
            for key in keys:
                assert key in self._prompt, (pid, key)


# -- pool sizing -------------------------------------------------------

def kv_page_bytes(num_heads: int, head_dim: int, page_size: int,
                  kv_cache_dtype: str = "bf16") -> int:
    """Device bytes ONE K or V page costs per layer.

    ``bf16``: 2 bytes per element. ``int8``: 1 byte per element plus
    one fp32 scale per (head, position) — the ``cached_*_scale`` pool
    leaves of ``models/gpt/model.py`` — i.e. ``head_dim + 4`` bytes
    per head-token instead of ``2 * head_dim``: a 1.88x density win at
    head_dim 64 (docs/quantization.md)."""
    if kv_cache_dtype == "int8":
        per_token = num_heads * (head_dim + 4)
    elif kv_cache_dtype == "bf16":
        per_token = num_heads * head_dim * 2
    else:
        raise ValueError(
            f"unknown kv_cache_dtype {kv_cache_dtype!r} "
            f"(expected 'bf16' or 'int8')")
    return per_token * page_size


def pool_bytes(num_layers: int, num_heads: int, head_dim: int,
               page_size: int, num_pages: int,
               kv_cache_dtype: str = "bf16") -> int:
    """Total device bytes of a ``num_pages`` KV pool (K and V, all
    layers) — the figure the serving summary reports and the A/B
    bench divides slot counts by."""
    return 2 * num_layers * num_pages * kv_page_bytes(
        num_heads, head_dim, page_size, kv_cache_dtype)


def pool_pages_for_bytes(budget_bytes: int, num_layers: int,
                         num_heads: int, head_dim: int,
                         page_size: int,
                         kv_cache_dtype: str = "bf16") -> int:
    """Largest pool (in pages) fitting ``budget_bytes`` of HBM —
    the inverse of :func:`pool_bytes`, used to hold pool BYTES fixed
    while switching ``kv_cache_dtype`` (int8 admits ~1.9x the pages,
    hence ~1.9x the resident slots on the same memory)."""
    per_page = 2 * num_layers * kv_page_bytes(
        num_heads, head_dim, page_size, kv_cache_dtype)
    return int(budget_bytes) // max(per_page, 1)
