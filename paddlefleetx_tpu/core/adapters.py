"""Multi-tenant LoRA adapter trees and the serving-side HBM bank cache.

The model holds every resident adapter in stacked per-site banks —
``{site}_lora/lora_a [A, K, r]`` / ``lora_b [A, r, N]`` parameters
created by ``models/gpt/model.py::_LoRADelta`` (scanned training params
carry a leading ``[num_layers, ...]`` axis; the serving server's
unrolled twin splits that into per-layer ``decoder_{i}`` leaves). Bank
row 0 is the reserved zero adapter; rows ``1..A-1`` are cache capacity
the serving layer fills and evicts at runtime.

Two pieces live here:

- **Adapter trees** — the canonical single-adapter format:
  ``{"<site>/<leaf>": [num_layers, ...]}`` keyed by the eight
  ``(site, leaf)`` pairs (``qkv_proj_lora``/``out_proj_lora``/
  ``linear1_lora``/``linear2_lora`` x ``lora_a``/``lora_b``), each
  value stacked over layers. :func:`extract_adapter` /
  :func:`insert_adapter` convert between this format and a live params
  tree in EITHER layout (scanned ``[L, A, ...]`` or unrolled
  ``decoder_{i} [A, ...]``), so an adapter fine-tuned on the scanned
  training model drops straight into an unrolled serving bank.
  ``core/checkpoint.py`` persists the format with the same npz +
  fingerprinted-manifest discipline as any checkpoint.

- **:class:`AdapterCache`** — host bookkeeping mapping adapter id ->
  bank row with KV-page-style refcounts (docs/lora.md): a row is
  PINNED while any slot serves its adapter and only refcount-0
  residents are LRU-evictable; a miss loads the tree from the
  ``source`` and claims a free or evicted row. The cache owns no
  device state — the server applies :func:`insert_adapter` to its
  live params when a lease reports a load. Counted
  ``serving/adapter_{hits,misses,evictions}`` with the
  ``serving/adapters_resident`` gauge.
"""

from __future__ import annotations

import re
import threading
from collections import OrderedDict
from typing import (
    Any, Callable, Dict, Mapping, NamedTuple, Optional, Tuple,
)

import jax
import jax.numpy as jnp

from ..observability import metrics

#: leaf names a LoRA site module owns (models/gpt/model.py _LoRADelta)
LORA_LEAVES = ("lora_a", "lora_b")

_LAYER_IDX = re.compile(r"_(\d+)$")


def _lora_path(path) -> Optional[Tuple[str, str, Optional[int]]]:
    """``(site, leaf, layer_index)`` when ``path`` names a LoRA bank
    leaf, else None. ``layer_index`` comes from the nearest enclosing
    ``decoder_{i}``-style component (None for scanned params, whose
    layer axis is in the array itself)."""
    keys = [str(getattr(k, "key", k)) for k in path]
    if len(keys) < 2 or keys[-1] not in LORA_LEAVES or \
            not keys[-2].endswith("_lora"):
        return None
    layer = None
    for k in reversed(keys[:-2]):
        m = _LAYER_IDX.search(k)
        if m:
            layer = int(m.group(1))
            break
    return keys[-2], keys[-1], layer


def extract_adapter(params, row: int) -> Dict[str, jax.Array]:
    """One bank row as a canonical adapter tree: ``{"site/leaf":
    [num_layers, ...]}`` stacked over layers, whatever layout
    ``params`` is in (scanned ``[L, A, ...]`` leaves slice axis 1 of
    the stack; unrolled per-layer ``[A, ...]`` leaves stack over their
    ``decoder_{i}`` indices). Raises ``ValueError`` when ``params``
    holds no LoRA banks or ``row`` is out of range."""
    per_layer: Dict[str, Dict[int, jax.Array]] = {}
    stacked: Dict[str, jax.Array] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        hit = _lora_path(path)
        if hit is None:
            continue
        site, name, layer = hit
        key = f"{site}/{name}"
        if leaf.ndim == 4:       # scanned: [L, A, K, r] / [L, A, r, N]
            if not 0 <= row < leaf.shape[1]:
                raise ValueError(
                    f"adapter row {row} out of range for bank "
                    f"{key} with {leaf.shape[1]} rows")
            stacked[key] = leaf[:, row]
        else:                    # unrolled per layer: [A, K, r]
            if not 0 <= row < leaf.shape[0]:
                raise ValueError(
                    f"adapter row {row} out of range for bank "
                    f"{key} with {leaf.shape[0]} rows")
            per_layer.setdefault(key, {})[layer or 0] = leaf[row]
    for key, rows in per_layer.items():
        stacked[key] = jnp.stack(
            [rows[i] for i in sorted(rows)], axis=0)
    if not stacked:
        raise ValueError(
            "params hold no LoRA banks (lora_rank is off?)")
    return stacked


def insert_adapter(params, tree: Mapping[str, Any], row: int):
    """Functionally write a canonical adapter tree into bank row
    ``row`` of ``params`` (either layout), casting values to each
    leaf's dtype. Every ``tree`` entry must land somewhere and shapes
    must match — a silent partial insert would serve a chimera
    adapter."""
    used = set()

    def put(path, leaf):
        """Write the tree's matching slice into this leaf's row."""
        hit = _lora_path(path)
        if hit is None:
            return leaf
        site, name, layer = hit
        key = f"{site}/{name}"
        if key not in tree:
            raise ValueError(f"adapter tree missing {key}")
        val = jnp.asarray(tree[key], leaf.dtype)
        used.add(key)
        if leaf.ndim == 4:       # scanned stack
            if val.shape != (leaf.shape[0],) + leaf.shape[2:]:
                raise ValueError(
                    f"adapter {key} shape {val.shape} does not fit "
                    f"bank {leaf.shape}")
            return leaf.at[:, row].set(val)
        li = layer or 0
        if li >= val.shape[0] or val.shape[1:] != leaf.shape[1:]:
            raise ValueError(
                f"adapter {key} shape {val.shape} does not fit "
                f"layer {li} bank {leaf.shape}")
        return leaf.at[row].set(val[li])

    out = jax.tree_util.tree_map_with_path(put, params)
    if not used:
        raise ValueError(
            "params hold no LoRA banks (lora_rank is off?)")
    missing = set(tree) - used
    if missing:
        raise ValueError(
            f"adapter tree keys matched no bank: {sorted(missing)}")
    return out


class AdapterCacheFull(RuntimeError):
    """Every bank row is pinned by a live slot — admission must wait
    for a release (the queue-head blocking rule, like page
    starvation)."""


class AdapterLease(NamedTuple):
    """Result of :meth:`AdapterCache.acquire`. ``tree`` is non-None on
    a miss — the caller must :func:`insert_adapter` it into row
    ``row`` before serving. ``evicted`` names the refcount-0 resident
    whose row was reclaimed, if any."""
    row: int
    tree: Optional[Dict[str, Any]]
    evicted: Optional[Any]


class AdapterCache:
    """Adapter id -> bank row with refcounts and LRU eviction.

    ``num_rows`` is the bank's adapter axis (``lora_num_adapters``);
    usable capacity is ``num_rows - 1`` (row 0 = reserved zero
    adapter). ``source`` maps adapter id -> canonical adapter tree —
    a Mapping or a callable; unknown ids raise ``KeyError``. Pure
    host bookkeeping behind its own lock: admission mutates the map
    under the serving surface lock while ``summary()`` and the fleet's
    affinity probes read it from router threads.

    Invariants (pinned by tests/test_lora.py):
    - a row is never reassigned while its adapter's refcount > 0;
    - eviction only ever takes the LRU refcount-0 resident;
    - ``acquire`` with no free and no evictable row raises
      :class:`AdapterCacheFull` and changes nothing.
    """

    def __init__(self, num_rows: int,
                 source: Callable[[Any], Mapping[str, Any]]):
        if num_rows < 2:
            raise ValueError(
                f"num_rows must be >= 2 (row 0 is the reserved zero "
                f"adapter), got {num_rows}")
        self._lock = threading.Lock()
        self._free = list(range(num_rows - 1, 0, -1))   # pop() -> row 1
        self._source = source
        self._rows: Dict[Any, int] = {}        # adapter id -> row
        self._refs: Dict[Any, int] = {}        # adapter id -> pins
        #: refcount-0 residents, least recently released first
        self._lru: "OrderedDict[Any, None]" = OrderedDict()
        self.stats = {"adapter_hits": 0, "adapter_misses": 0,
                      "adapter_evictions": 0}

    @property
    def resident(self) -> int:
        with self._lock:
            return len(self._rows)

    @property
    def capacity(self) -> int:
        """Total usable bank rows (free + resident)."""
        with self._lock:
            return len(self._free) + len(self._rows)

    def resident_ids(self):
        with self._lock:
            return list(self._rows)

    def is_resident(self, adapter_id) -> bool:
        with self._lock:
            return adapter_id in self._rows

    def refcount(self, adapter_id) -> int:
        with self._lock:
            return self._refs.get(adapter_id, 0)

    def can_admit(self, adapter_id) -> bool:
        """Would :meth:`acquire` find a row right now? (Source errors
        still surface from acquire itself.)"""
        with self._lock:
            return adapter_id in self._rows or bool(self._free) or \
                bool(self._lru)

    def _load(self, adapter_id) -> Mapping[str, Any]:
        if callable(self._source):
            return self._source(adapter_id)
        return self._source[adapter_id]

    def acquire(self, adapter_id) -> AdapterLease:
        """Pin ``adapter_id`` to a bank row. Hit: bump the refcount.
        Miss: load the tree from the source FIRST (an unknown id must
        not evict anyone), then claim a free row or evict the LRU
        refcount-0 resident. Raises :class:`AdapterCacheFull` when
        every row is pinned, ``KeyError`` from the source for unknown
        ids."""
        with self._lock:
            if adapter_id in self._rows:
                self._refs[adapter_id] += 1
                self._lru.pop(adapter_id, None)
                self.stats["adapter_hits"] += 1
                metrics.inc("serving/adapter_hits")
                self._gauge()
                return AdapterLease(self._rows[adapter_id], None, None)
            if not self._free and not self._lru:
                raise AdapterCacheFull(
                    f"all {len(self._rows)} adapter rows pinned by "
                    f"live slots")
            tree = self._load(adapter_id)
            evicted = None
            if self._free:
                row = self._free.pop()
            else:
                evicted, _ = self._lru.popitem(last=False)
                row = self._rows.pop(evicted)
                del self._refs[evicted]
                self.stats["adapter_evictions"] += 1
                metrics.inc("serving/adapter_evictions")
            self._rows[adapter_id] = row
            self._refs[adapter_id] = 1
            self.stats["adapter_misses"] += 1
            metrics.inc("serving/adapter_misses")
            self._gauge()
            return AdapterLease(row, dict(tree), evicted)

    def release(self, adapter_id) -> None:
        """Drop one pin. At refcount 0 the adapter STAYS resident (its
        weights keep their row — the warm-cache win) but becomes LRU
        eviction fodder."""
        with self._lock:
            refs = self._refs.get(adapter_id)
            if refs is None:
                raise KeyError(f"release of non-resident adapter "
                               f"{adapter_id!r}")
            if refs < 1:
                raise AssertionError(
                    f"adapter {adapter_id!r} refcount underflow")
            self._refs[adapter_id] = refs - 1
            if refs == 1:
                self._lru[adapter_id] = None
            self._gauge()

    def check(self) -> None:
        """Test hook: internal invariants."""
        with self._lock:
            assert set(self._lru) <= set(self._rows)
            assert set(self._refs) == set(self._rows)
            for aid, refs in self._refs.items():
                assert refs >= 0
                assert (refs == 0) == (aid in self._lru), \
                    f"{aid!r}: refs={refs}, lru={aid in self._lru}"
            rows = list(self._rows.values()) + self._free
            assert len(rows) == len(set(rows)), \
                "row leaked or double-used"
            assert 0 not in rows, "reserved row 0 entered circulation"

    def _gauge(self) -> None:
        metrics.get_registry().set_gauge("serving/adapters_resident",
                                         len(self._rows))
