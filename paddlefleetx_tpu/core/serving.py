"""Continuous-batching generation server over slot-managed KV cache.

The lockstep ``generate()`` path (``models/gpt/generation.py``) runs a
batch at the speed of its longest request and admits nothing until the
whole batch drains. ``GenerationServer`` keeps decode rolling instead:
a persistent ``[slots, ...]`` KV cache lives on device, the host owns a
request queue and admits each request into a free slot (a bucketed
``prefill_into_slots`` — one compiled shape per prompt-length bucket),
and ONE jitted SPMD ``decode_step`` ticks every occupied slot forward a
token with per-slot lengths/sampling state through the ragged attention
dispatch (``flash_decode_ragged`` or the XLA per-row-offset fallback —
dispatch matrix in docs/inference.md). Finished slots are evicted
between ticks and their completions returned, so new requests ride in
as soon as capacity frees and throughput never drops to the slowest
request.

Slot-for-slot parity: greedy completions match the lockstep
``generate()`` exactly, whatever the admission order or prompt-length
mix (pinned by tests/test_serving.py's parity matrix).

Telemetry (docs/observability.md): ``serving/slot_occupancy`` gauge,
``serving/admitted`` / ``serving/evicted`` / ``serving/preempted``
counters, a ``serving/decode_tick`` timer, and a tokens/s summary; an
optional flight recorder mirrors admissions/evictions to an
``events.jsonl`` stream CI's failure-diagnostics artifact collects.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gpt.generation import (
    GenerationConfig, _unrolled_twin, decode_step, init_slot_cache,
    init_slot_state, prefill_into_slots,
)
from ..observability import metrics
from ..observability.recorder import FlightRecorder
from ..utils.log import logger


def default_prefill_buckets(max_prompt_len: int) -> Tuple[int, ...]:
    """Powers of two from 16 up to ``max_prompt_len``, which is always
    included — a handful of compiled prefill shapes covers every
    admissible prompt length."""
    out = []
    b = 16
    while b < max_prompt_len:
        out.append(b)
        b *= 2
    out.append(max_prompt_len)
    return tuple(out)


@dataclass
class Completion:
    """One finished request as returned by :meth:`GenerationServer.step`."""
    request_id: int
    prompt: List[int]
    #: emitted tokens in order, EOS included when hit (identical to the
    #: lockstep ``generate()`` row before its pad tail)
    tokens: List[int]
    #: "eos" | "length" (hit max_dec_len) | "preempted"
    finish_reason: str


class GenerationServer:
    """Host-side queue/admit/evict loop around the jitted slot
    primitives (``models/gpt/generation.py``).

    ``model``/``params`` are the live flax model and its parameters
    (the layer loop is unrolled and params cast to the compute dtype
    once, exactly as ``generate()`` prepares them). Sampling and greedy
    strategies are served; beam search stays on the lockstep path.
    """

    def __init__(self, model, params, gen_cfg: GenerationConfig,
                 num_slots: int = 4,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 rng: Optional[jax.Array] = None,
                 events_path: Optional[str] = None):
        if gen_cfg.decode_strategy == "beam_search":
            raise ValueError(
                "GenerationServer serves sampling/greedy_search; beam "
                "search reorders the batch every step and stays on the "
                "lockstep generate() path")
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        model, params = _unrolled_twin(model, params)
        cfg = model.config
        compute_dtype = jnp.dtype(cfg.dtype)
        if compute_dtype != jnp.float32:
            # same one-time cast as generate(): halve the per-token
            # parameter bandwidth of the decode tick
            params = jax.tree.map(
                lambda p: p.astype(compute_dtype)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
        self.model, self.params = model, params
        self.gen_cfg = gen_cfg
        self.num_slots = num_slots
        self._max_prompt = cfg.max_position_embeddings - gen_cfg.max_dec_len
        if self._max_prompt < 1:
            raise ValueError(
                f"max_dec_len ({gen_cfg.max_dec_len}) leaves no room "
                f"for prompts under max_position_embeddings "
                f"{cfg.max_position_embeddings}")
        buckets = tuple(sorted(set(
            prefill_buckets or default_prefill_buckets(self._max_prompt))))
        if buckets[-1] < self._max_prompt:
            buckets = buckets + (self._max_prompt,)
        self._buckets = buckets
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._cache = init_slot_cache(model, params, num_slots)
        self._state = init_slot_state(num_slots, cfg.vocab_size)
        self._queue: deque = deque()
        self._slots: List[Optional[dict]] = [None] * num_slots
        self._next_id = 0
        self._nonce = 0
        self._counts = {"admitted": 0, "evicted": 0, "preempted": 0}
        self._ticks = 0
        self._decode_tokens = 0
        self._tick_time = 0.0
        self._recorder = FlightRecorder(events_path) if events_path \
            else None
        self._emit("serving_start", slots=num_slots,
                   buckets=list(buckets),
                   max_dec_len=gen_cfg.max_dec_len)
        logger.info(
            "GenerationServer: %d slots, prefill buckets %s, "
            "capacity %d (max_position_embeddings %d)", num_slots,
            list(buckets), cfg.cache_capacity,
            cfg.max_position_embeddings)

    # -- host bookkeeping ---------------------------------------------

    def _emit(self, event: str, **fields) -> None:
        if self._recorder is not None:
            self._recorder.emit(event, **fields)

    @property
    def occupancy(self) -> int:
        """Number of slots currently holding a live request."""
        return sum(s is not None for s in self._slots)

    @property
    def pending(self) -> int:
        """Number of submitted requests still waiting for a slot."""
        return len(self._queue)

    def submit(self, prompt: Sequence[int]) -> int:
        """Queue a request; returns its id. Raises when the prompt can
        never fit (``prompt + max_dec_len > max_position_embeddings``)
        — an oversized request must fail loudly at the door, not stall
        the queue."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self._max_prompt:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_dec_len "
                f"({self.gen_cfg.max_dec_len}) exceeds "
                f"max_position_embeddings "
                f"{self.model.config.max_position_embeddings}")
        rid = self._next_id
        self._next_id += 1
        self._queue.append({"id": rid, "prompt": prompt, "tokens": []})
        return rid

    def _bucket_for(self, n: int) -> int:
        for b in self._buckets:
            if b >= n:
                return b
        return self._buckets[-1]

    def _admit(self) -> None:
        """Move queued requests into free slots (bucketed prefill)."""
        while self._queue and None in self._slots:
            req = self._queue.popleft()
            slot = self._slots.index(None)
            bucket = self._bucket_for(len(req["prompt"]))
            row = np.full((1, bucket), self.gen_cfg.pad_token_id,
                          np.int32)
            row[0, :len(req["prompt"])] = req["prompt"]
            nonce = self._nonce
            self._nonce += 1
            self._cache, self._state = prefill_into_slots(
                self.model, self.params, self._cache, self._state,
                jnp.asarray([slot], jnp.int32), jnp.asarray(row),
                jnp.asarray([len(req["prompt"])], jnp.int32),
                jnp.asarray([nonce], jnp.int32))
            self._slots[slot] = req
            self._counts["admitted"] += 1
            metrics.inc("serving/admitted")
            self._emit("serving_admit", request=req["id"], slot=slot,
                       prompt_len=len(req["prompt"]), bucket=bucket)

    def _evict(self, slot: int, reason: str) -> Completion:
        req = self._slots[slot]
        self._slots[slot] = None
        self._state = self._state._replace(
            active=self._state.active.at[slot].set(False),
            finished=self._state.finished.at[slot].set(False))
        self._counts["evicted"] += 1
        metrics.inc("serving/evicted")
        if reason == "preempted":
            self._counts["preempted"] += 1
            metrics.inc("serving/preempted")
        self._emit("serving_evict", request=req["id"], slot=slot,
                   reason=reason, tokens=len(req["tokens"]))
        return Completion(request_id=req["id"], prompt=req["prompt"],
                          tokens=req["tokens"], finish_reason=reason)

    def preempt(self, request_id: int) -> Optional[Completion]:
        """Cancel a request (client abort / scheduler decision): evict
        its slot — or drop it from the queue — and return the partial
        completion. None when the id is unknown/already finished."""
        for slot, req in enumerate(self._slots):
            if req is not None and req["id"] == request_id:
                return self._evict(slot, "preempted")
        for i, req in enumerate(self._queue):
            if req["id"] == request_id:
                del self._queue[i]
                self._counts["preempted"] += 1
                metrics.inc("serving/preempted")
                self._emit("serving_evict", request=request_id,
                           slot=-1, reason="preempted", tokens=0)
                return Completion(request_id=request_id,
                                  prompt=req["prompt"], tokens=[],
                                  finish_reason="preempted")
        return None

    # -- the serving loop ---------------------------------------------

    def step(self) -> List[Completion]:
        """Admit what fits, tick every occupied slot one token, evict
        and return whatever finished."""
        self._admit()
        reg = metrics.get_registry()
        if self.occupancy == 0:
            reg.set_gauge("serving/slot_occupancy", 0)
            return []
        t0 = time.time()
        with reg.timer("serving/decode_tick"):
            self._cache, self._state, tok = decode_step(
                self.model, self.params, self._cache, self._state,
                self._rng, self.gen_cfg)
            tok = np.asarray(tok)   # device sync inside the timer
        self._tick_time += time.time() - t0
        self._ticks += 1
        finished = np.asarray(self._state.finished)
        dec_count = np.asarray(self._state.dec_count)
        done: List[Completion] = []
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            req["tokens"].append(int(tok[slot]))
            self._decode_tokens += 1
            if finished[slot]:
                done.append(self._evict(slot, "eos"))
            elif dec_count[slot] >= self.gen_cfg.max_dec_len:
                done.append(self._evict(slot, "length"))
        reg.set_gauge("serving/slot_occupancy", self.occupancy)
        return done

    def run(self, prompts: Sequence[Sequence[int]]) -> List[Completion]:
        """Serve a batch of prompts to completion; completions return
        in SUBMISSION order (slot/finish order is an implementation
        detail the caller should not see)."""
        ids = [self.submit(p) for p in prompts]
        done: Dict[int, Completion] = {}
        while self._queue or self.occupancy:
            for c in self.step():
                done[c.request_id] = c
        return [done[i] for i in ids]

    def summary(self) -> dict:
        """Counters + decode tokens/s for the server's lifetime so far
        (also emitted to the flight recorder)."""
        tps = self._decode_tokens / self._tick_time \
            if self._tick_time > 0 else 0.0
        s = {"slots": self.num_slots, "occupancy": self.occupancy,
             "pending": self.pending, "decode_ticks": self._ticks,
             "decode_tokens": self._decode_tokens,
             "decode_time_sec": round(self._tick_time, 4),
             "tokens_per_sec": round(tps, 2), **self._counts}
        self._emit("serving_summary", **s)
        return s
